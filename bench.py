"""Headline benchmark: ViT-B/16 trainer samples/sec/chip (BASELINE.json).

The reference publishes no performance numbers (BASELINE.md), so this
establishes the framework's own baseline: full training step
(fwd + bwd + adamw) on the flagship ViT-B/16 config, bf16 compute, one
chip. Prints ONE JSON line. ``vs_baseline`` is measured/baseline against
the recorded number in BASELINE.md §measured (1.0 when none exists yet).

Env knobs: UNIONML_TPU_BENCH_PRESET=tiny for a CPU smoke run;
UNIONML_TPU_BENCH_BATCH to override the per-chip batch size.
"""

from __future__ import annotations

import json
import os
import time

# Recorded result of a previous round on the target hardware (one TPU
# v5e chip via tunnel). Update when a round improves it; vs_baseline is
# computed against this so the driver sees round-over-round progress.
# Round 1: ViT-B/16 batch=64 bf16, xla attention, re-measured under the
# 100-step methodology → 1025 samples/sec/chip (the originally recorded
# 982 came from a 20-step window with ±40% tunnel jitter).
RECORDED_BASELINE_SAMPLES_PER_SEC = 1025.0


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # pre-registered TPU plugins can override the env var; config wins
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import ViT, ViTConfig, classification_step, create_train_state

    backend = jax.default_backend()
    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET", "tiny" if backend == "cpu" else "vit_b16"
    )
    if preset == "tiny":
        cfg = ViTConfig.tiny(image_size=32, num_classes=10)
        batch = int(os.environ.get("UNIONML_TPU_BENCH_BATCH", 32))
        steps, warmup = 10, 3
    else:
        cfg = ViTConfig.base16(num_classes=1000)
        batch = int(os.environ.get("UNIONML_TPU_BENCH_BATCH", 64))
        # tunnel dispatch is jittery at short windows: 100 timed steps
        # gives run-to-run spread < 1% (20 steps gave ±40%)
        steps, warmup = 100, 10

    module = ViT(cfg)
    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.normal(size=(batch, cfg.image_size, cfg.image_size, 3)), jnp.bfloat16
    )
    labels = jnp.asarray(rng.integers(0, cfg.num_classes, size=(batch,)), jnp.int32)

    state = create_train_state(module, images[:1], learning_rate=1e-3)
    step = jax.jit(classification_step(module), donate_argnums=0)

    # NOTE: timing ends with a host readback of a value data-dependent on
    # the last step (which chains through every donated state) —
    # jax.block_until_ready alone does not block on tunneled TPU backends
    for _ in range(warmup):
        state, metrics = step(state, (images, labels))
    # drain with a param element — a loss readback does not gate through
    # the tunnel and would leave warmup backlog inside window 1 (window 2
    # was already protected: it starts after window 1's param readback)
    from benchmarks._timing import drain

    drain(state)

    # best of two windows: the tunneled backend occasionally hits external
    # contention that halves a single window's throughput (observed 658
    # vs a stable ~1117 samples/sec); contention is noise, not a property
    # of the program, so the better window is the honest measurement.
    # Comparability with the single-window recorded baseline: under
    # normal conditions the two estimators agree within jitter (measured
    # 1111 best-of-two vs 1117/1118 single-window, <1%), so this guards
    # against outliers without inflating vs_baseline
    best_dt = None
    for _window in range(2):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, (images, labels))
        # read back a post-update param element: data-dependent on the
        # final step's bwd+adamw, which chains through every donated state
        drain(state)
        dt = time.perf_counter() - t0
        best_dt = dt if best_dt is None else min(best_dt, dt)

    samples_per_sec = batch * steps / best_dt
    # the recorded baseline is a TPU ViT-B number; comparing any other
    # preset/backend against it would be meaningless
    comparable = preset == "vit_b16" and backend == "tpu"
    vs = (
        samples_per_sec / RECORDED_BASELINE_SAMPLES_PER_SEC
        if RECORDED_BASELINE_SAMPLES_PER_SEC and comparable
        else 1.0
    )
    print(
        json.dumps(
            {
                "metric": f"{preset}_train_samples_per_sec_per_chip",
                "value": round(samples_per_sec, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
