# Developer entry points (reference analog: the upstream Makefile).
# Tests force the CPU-simulated 8-device mesh via tests/conftest.py.

.PHONY: test test-quick lint docs docs-site bench bench-all notebooks dryrun

docs:
	python scripts/gen_api_reference.py
	python scripts/build_docs_site.py

docs-site:
	python scripts/build_docs_site.py

test:
	python -m pytest tests/ -x -q

# the measured sub-minute spec-path modules (<5 min total on the 1-core
# simulated mesh) — the iteration/CI-sharding tier; `make test` remains
# the full matrix of record
test-quick:
	python -m pytest tests/ -m quick -q

lint:
	python scripts/lint_basics.py
	@if python -c "import ruff" 2>/dev/null; then \
		python -m ruff check unionml_tpu tests benchmarks scripts; \
	elif python -c "import flake8" 2>/dev/null; then \
		python -m flake8 --max-line-length 110 \
			--extend-ignore=E203,W503,E731,E741 \
			unionml_tpu tests benchmarks scripts; \
	else \
		echo "flake8/ruff not installed; lint_basics covered the correctness subset"; \
	fi

bench:
	python bench.py

bench-all: bench
	python benchmarks/train_throughput.py
	UNIONML_TPU_BENCH_PRESET=train_goodput python benchmarks/train_throughput.py
	UNIONML_TPU_BENCH_PRESET=train_overlap python benchmarks/train_throughput.py
	python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_moe python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_8b python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_paged python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_usage python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_preempt python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_router python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_disagg python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_autoscale python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_fleet_obs python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_perf python benchmarks/serve_latency.py
	UNIONML_TPU_BENCH_PRESET=serve_rollout python benchmarks/serve_latency.py
	python benchmarks/serve_http.py
	UNIONML_TPU_BENCH_PRESET=serve_8b python benchmarks/serve_http.py
	python benchmarks/attn_kernels.py
	PYTHONPATH=.:$$PYTHONPATH python benchmarks/remote_bert/app.py

notebooks:
	python scripts/myst_to_ipynb.py docs/tutorials/*.md

dryrun:
	JAX_PLATFORMS=cpu python __graft_entry__.py 8
