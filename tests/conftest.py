"""Test harness: CPU-simulated 8-device mesh (SURVEY.md §4.3).

The reference tests against a dockerized single-node Flyte sandbox
(reference: tests/integration/test_flyte_remote.py:33-57); the TPU-native
equivalent is the JAX CPU backend with a forced 8-device host platform so
DP/FSDP/TP/SP sharding is exercised without hardware. Env must be set
before the first jax import, hence at conftest import time.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# keep stage caches inside the test tmp area, not the user cache
os.environ.setdefault("UNIONML_TPU_CACHE_DIR", "/tmp/unionml_tpu_test_cache")

# The env var JAX_PLATFORMS can be overridden by pre-registered TPU plugins
# (e.g. the axon tunnel); the config API takes precedence over both.
import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the test suite: dozens of tests
# build fresh DecodeEngines/trainers over the SAME tiny-model geometry,
# and each re-jits byte-identical HLO (the in-memory jit cache is
# per-closure, so engine instances never share it). The persistent
# cache keys on HLO hash, so repeats hit even WITHIN one cold suite
# run, and the whole suite warms across runs. Scoped to the test
# harness — production code paths never see this config.
_JAX_TEST_CACHE = os.environ.get(
    "UNIONML_TPU_TEST_JAX_CACHE", "/tmp/unionml_tpu_test_jax_cache"
)
jax.config.update("jax_compilation_cache_dir", _JAX_TEST_CACHE)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
# SUBPROCESS jax runs share the same cache via jax's env-var config
# defaults: the CLI scaffold tests and the tutorial executors each
# spawn child pytest/python processes that otherwise cold-compile the
# same tiny models on every suite run (~100 s of repeat XLA work).
# setdefault so an outer override (UNIONML_TPU_TEST_JAX_CACHE unset
# but JAX_COMPILATION_CACHE_DIR exported) still wins.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _JAX_TEST_CACHE)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")

import os.path

import pytest

# Modules whose executables are UNSAFE under a WARM persistent cache on
# this jax 0.4.37/CPU stack: the elastic/trainer family's donated step
# functions intermittently crash (SIGSEGV/SIGBUS) or return garbage in
# donated outputs when the process has read warm cache entries —
# reproduced at clean HEAD with a 3-line repro (warm dir + one early
# dispatch + the elastic chaos tests; a fresh dir passes 100%). The
# fixture below turns the persistent cache OFF for these modules only.
# It must also call compilation_cache.reset_cache(): jax's
# is_cache_used() FREEZES its decision process-wide on first use
# (_cache_checked is sticky), so a config flip alone is ignored once
# any earlier test — or a collection-time jnp dispatch — touched the
# cache. These modules are all tiny-MLP suites that re-compile in
# seconds; everything else keeps the warm-cache speed the suite budget
# depends on.
_PERSISTENT_CACHE_UNSAFE = (
    "test_async_checkpoint.py",
    "test_train_step.py",
    "test_diagnostics.py",
    "test_goodput.py",
    "test_overlap_training.py",
    "test_data_pipeline.py",
    "test_grad_accumulation.py",
)


@pytest.fixture(autouse=True)
def _elastic_family_skips_persistent_cache(request):
    path = os.path.basename(str(getattr(request.node, "fspath", "")))
    if path not in _PERSISTENT_CACHE_UNSAFE:
        yield
        return
    from jax._src import compilation_cache as _cc

    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    _cc.reset_cache()  # un-stick the frozen is_cache_used() decision
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    _cc.reset_cache()  # re-arm the cache for the modules that keep it
