"""Test harness: CPU-simulated 8-device mesh (SURVEY.md §4.3).

The reference tests against a dockerized single-node Flyte sandbox
(reference: tests/integration/test_flyte_remote.py:33-57); the TPU-native
equivalent is the JAX CPU backend with a forced 8-device host platform so
DP/FSDP/TP/SP sharding is exercised without hardware. Env must be set
before the first jax import, hence at conftest import time.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# keep stage caches inside the test tmp area, not the user cache
os.environ.setdefault("UNIONML_TPU_CACHE_DIR", "/tmp/unionml_tpu_test_cache")

# The env var JAX_PLATFORMS can be overridden by pre-registered TPU plugins
# (e.g. the axon tunnel); the config API takes precedence over both.
import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the test suite: dozens of tests
# build fresh DecodeEngines/trainers over the SAME tiny-model geometry,
# and each re-jits byte-identical HLO (the in-memory jit cache is
# per-closure, so engine instances never share it). The persistent
# cache keys on HLO hash, so repeats hit even WITHIN one cold suite
# run, and the whole suite warms across runs. Scoped to the test
# harness — production code paths never see this config.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get(
        "UNIONML_TPU_TEST_JAX_CACHE", "/tmp/unionml_tpu_test_jax_cache"
    ),
)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
