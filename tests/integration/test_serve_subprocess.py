"""Process-boundary serving: boot the real CLI server in a subprocess.

Reference analog: tests/integration/test_fastapi.py:14-26 (subprocess
``unionml serve`` + health polling) and :116-121 (the missing
``--model-path`` error surface). The in-process transport tests live in
tests/unit/test_serving.py; THIS file is the only place the `serve`
command's process path (CLI arg parsing -> env handoff -> app resolution
-> HTTP loop) runs end-to-end.
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).parent.parent.parent
APPS_DIR = REPO_ROOT / "tests" / "apps"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _serve_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO_ROOT), str(APPS_DIR), env.get("PYTHONPATH", "")]
    )
    return env


@pytest.fixture(scope="module")
def model_artifact_path(tmp_path_factory):
    """Train the fixture app once and save its artifact to disk."""
    sys.path.insert(0, str(APPS_DIR))
    try:
        import sklearn_app

        sklearn_app.model.train(hyperparameters={"max_iter": 200}, n=200)
        path = tmp_path_factory.mktemp("artifact") / "model.joblib"
        sklearn_app.model.save(str(path))
        return path
    finally:
        sys.path.remove(str(APPS_DIR))


def _get(url: str):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return resp.status, json.loads(resp.read() or b"null")


def _post(url: str, payload: dict):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.status, json.loads(resp.read())


def _wait_live(port: int, proc: subprocess.Popen, timeout: float = 60.0):
    """Poll / until the server answers (reference: test_fastapi.py:29-44)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server exited early rc={proc.returncode}")
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=2)
            return
        except (urllib.error.URLError, ConnectionError):
            time.sleep(0.3)
    raise TimeoutError("server did not come up")


def test_serve_subprocess_lifecycle(model_artifact_path, tmp_path):
    port = _free_port()
    log = open(tmp_path / "serve.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "unionml_tpu.cli", "serve", "sklearn_app:model",
         "--model-path", str(model_artifact_path), "--port", str(port)],
        env=_serve_env(), stdout=log, stderr=log,
    )
    try:
        _wait_live(port, proc)
        status, health = _get(f"http://127.0.0.1:{port}/health")
        assert status == 200
        assert health == {
            "status": "ok", "model_loaded": True,
            "queue_depth": 0, "breaker_open": False,
        }

        # predict from raw features
        status, preds = _post(
            f"http://127.0.0.1:{port}/predict",
            {"features": [{"x1": 5.0, "x2": 5.0}, {"x1": -5.0, "x2": -5.0}]},
        )
        assert status == 200
        assert preds == [1.0, 0.0]

        # predict through the reader-kwargs path
        status, preds = _post(
            f"http://127.0.0.1:{port}/predict", {"inputs": {"n": 10}}
        )
        assert status == 200
        assert isinstance(preds, list) and len(preds) == 10
    finally:
        proc.terminate()
        proc.wait(timeout=15)
        log.close()


def test_serve_subprocess_missing_model_path_errors(tmp_path):
    """Nonexistent --model-path fails fast with a helpful CLI error
    (reference: test_fastapi.py:116-121)."""
    proc = subprocess.run(
        [sys.executable, "-m", "unionml_tpu.cli", "serve", "sklearn_app:model",
         "--model-path", str(tmp_path / "nope.joblib"), "--port", "0"],
        env=_serve_env(), capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "does not exist" in proc.stderr


def test_serve_subprocess_unloaded_model_fails_fast(tmp_path):
    """No --model-path and no artifact: the server refuses to start with a
    named remedy instead of serving a dead /predict."""
    env = _serve_env()
    env.pop("UNIONML_MODEL_PATH", None)
    proc = subprocess.run(
        [sys.executable, "-m", "unionml_tpu.cli", "serve", "sklearn_app:model",
         "--port", str(_free_port())],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode != 0
    assert "UNIONML_MODEL_PATH" in proc.stderr
