"""True multi-process validation (SURVEY.md §5.8): a 2-process × 4-device
``jax.distributed`` CPU run executes a dp×fsdp train step end-to-end with
process-local data feeding, and lands on the same loss as the
single-process 8-device run.

This is the TPU-native analogue of the reference's prove-it-with-a-real-
control-plane integration test (reference:
tests/integration/test_flyte_remote.py:33-57): ``multihost_initialize``,
Gloo cross-process collectives, ``make_array_from_process_local_data``
batch assembly, and the per-process row slicing all run for real — no
fakes anywhere in the leg.
"""

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
sys.path.insert(0, str(REPO / "scripts"))

from unionml_tpu.parallel import cpu_multiprocess_supported  # noqa: E402

# CPU-simulated multi-process runs need a jax build with Gloo CPU
# collectives (multihost_initialize selects them); a build without the
# capability must SKIP — a red "environment failure" every run is
# indistinguishable from a real regression
pytestmark = pytest.mark.skipif(
    not cpu_multiprocess_supported(),
    reason="this jax build has no multi-process CPU collectives (gloo)",
)

from multihost_smoke import launch_pair, launch_single  # noqa: E402


def test_two_process_run_matches_single_process():
    single = launch_single(local_devices=8)
    pair = launch_pair(local_devices=4)
    assert single["processes"] == 1 and single["devices"] == 8
    assert pair["processes"] == 2 and pair["devices"] == 8
    # same seeds, same global batches, same step count — cross-process
    # Gloo reductions may reassociate floating-point sums, hence the
    # tight-but-not-bitwise tolerance
    assert abs(pair["loss"] - single["loss"]) <= 1e-5 * max(1.0, abs(single["loss"]))
    assert abs(pair["checksum"] - single["checksum"]) <= 1e-5 * abs(single["checksum"])
    # and training actually trained
    assert pair["loss"] < 1.0


from multihost_serving_smoke import (  # noqa: E402
    launch_pair as serving_pair,
    launch_single as serving_single,
)


def test_two_process_tp_serving_matches_single_process():
    """Multi-host TP SERVING (round-5 gap): parameters tensor-sharded
    ACROSS two processes, host 0 fronting HTTP and broadcasting each
    prompt so both controllers enter the sharded generate in lockstep —
    greedy tokens must be identical to the single-process TP run."""
    single = serving_single(local_devices=8)
    pair = serving_pair(local_devices=4)
    assert single["processes"] == 1 and single["devices"] == 8
    assert pair["processes"] == 2 and pair["devices"] == 8
    assert pair["via"] == "http"
    assert pair["tokens"] == single["tokens"]
