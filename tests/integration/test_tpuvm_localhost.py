"""TPUVM choreography over the REAL transport: two localhost "hosts".

The round-4 verdict's remaining transport gap: `TPUVMBackend`'s SSH/scp
choreography had only ever run against monkeypatched transport methods
(tests/unit/test_remote.py), which share the test filesystem and skip the
literal argv paths. Here nothing on the backend is patched:

- the backend shells out to `ssh`/`scp` binaries found on PATH — shim
  executables that map each hostname to its own PRIVATE directory root
  (every absolute path under the control base is rewritten to
  ``{fauxroot}/{host}{path}``), then execute the command string under a
  real shell. Two hosts therefore have genuinely disjoint filesystems on
  one machine — the property the faked transport cannot model (it needs
  a same-path no-op special case precisely because it shares the FS);
- the two SSH-launched runner processes bring up ONE real
  ``jax.distributed`` world (Gloo over loopback, coordinator = host 0),
  proven by a cross-process ``process_allgather`` inside the trainer;
- with ``shared_fs: false``, inputs are scp-staged to each host's
  private root, host 0's outputs are scp-fetched back, and the predict
  workflow exercises ``_stage_model_registry``'s exec-dir rewrite
  against hosts that really cannot see the deployer's registry.

Reference analog: tests/integration/test_flyte_remote.py:33-57 (prove
the control plane against a real local stand-in, not mocks).
"""

import json
import os
import socket
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[2]

SSH_SHIM = textwrap.dedent(
    """\
    #!/usr/bin/env python3
    # ssh shim: `ssh [-o opt]... user@host command` -> run the command
    # locally with every control-base path rewritten into the host's
    # private root. Exit code passes through (failure aggregation).
    import json, os, subprocess, sys

    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i] == "-o":
            i += 2
        elif args[i].startswith("-"):
            i += 1
        else:
            break
    dest, command = args[i], " ".join(args[i + 1:])
    host = dest.split("@", 1)[1]
    base = os.environ["UNIONML_TPU_FAUXHOST_BASE"]
    hostroot = os.path.join(os.environ["UNIONML_TPU_FAUXHOST_ROOT"], host)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the "host" sets its own device count
    env.update(json.loads(os.environ.get("UNIONML_TPU_FAUXHOST_ENV", "{}")))
    sys.exit(subprocess.call(
        ["bash", "-c", command.replace(base, hostroot + base)], env=env))
    """
)

SCP_SHIM = textwrap.dedent(
    """\
    #!/usr/bin/env python3
    # scp shim: rewrite the remote side's path into the host's private
    # root, then cp -r. `src/.` copies contents, like scp.
    import os, subprocess, sys

    paths = []
    args = sys.argv[1:]
    i = 0
    while i < len(args):
        if args[i] == "-o":
            i += 2
        elif args[i].startswith("-"):
            i += 1
        else:
            paths.append(args[i])
            i += 1
    base = os.environ["UNIONML_TPU_FAUXHOST_BASE"]

    def map_path(p):
        if "@" in p and ":" in p.split("@", 1)[1]:
            host, path = p.split("@", 1)[1].split(":", 1)
            hostroot = os.path.join(
                os.environ["UNIONML_TPU_FAUXHOST_ROOT"], host)
            return path.replace(base, hostroot + base)
        return p

    src, dst = map_path(paths[0]), map_path(paths[1])
    sys.exit(subprocess.call(["cp", "-r", src, dst]))
    """
)

# The deployed app. The trainer runs once per host under the coordinator
# env TPUVMBackend sets; the allgather proves the two SSH-launched
# processes joined one distributed runtime (not two isolated ones).
MH_APP = textwrap.dedent(
    '''\
    """Two-host fixture app (deployed over the shim transport)."""

    import numpy as np
    import pandas as pd

    from unionml_tpu import Dataset, Model
    from unionml_tpu.defaults import Resources

    dataset = Dataset(name="mh_dataset", test_size=0.25, shuffle=True,
                      targets=["y"])


    def make_model(scale: float = 1.0) -> dict:
        return {"scale": scale}


    model = Model(name="mh_model", init=make_model, dataset=dataset)


    @dataset.reader
    def reader(n: int = 64) -> pd.DataFrame:
        rng = np.random.default_rng(11)
        x1 = rng.normal(size=n)
        x2 = rng.normal(size=n)
        y = x1 * 2.0 - x2
        return pd.DataFrame({"x1": x1, "x2": x2, "y": y})


    @model.trainer(resources=Resources(cpu="4", chips=0))
    def trainer(m: dict, features: pd.DataFrame, target: pd.DataFrame) -> dict:
        import jax
        from jax.experimental import multihost_utils

        peers = multihost_utils.process_allgather(
            np.asarray([jax.process_index()], dtype=np.int32))
        w, *_ = np.linalg.lstsq(features.to_numpy(),
                                target.to_numpy().ravel(), rcond=None)
        m["w"] = [float(v) for v in w]
        m["world"] = int(jax.process_count())
        m["peers"] = sorted(int(p) for p in np.asarray(peers).ravel())
        return m


    @model.predictor
    def predictor(m: dict, features: pd.DataFrame) -> list:
        w = np.asarray(m["w"])
        return [float(v) for v in features.to_numpy() @ w]


    @model.evaluator
    def evaluator(m: dict, features: pd.DataFrame, target: pd.DataFrame) -> float:
        # surfaces the distributed world size through the metrics path
        return float(m["world"])
    '''
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def shim_world(tmp_path, monkeypatch):
    """PATH shims + private host roots + the deployable app module."""
    base = tmp_path / "ctl"  # control side: backend root + vm workdir
    base.mkdir()
    fauxroot = tmp_path / "hosts"
    fauxroot.mkdir()
    shims = tmp_path / "bin"
    shims.mkdir()
    for name, body in (("ssh", SSH_SHIM), ("scp", SCP_SHIM)):
        p = shims / name
        p.write_text(body)
        os.chmod(p, 0o755)
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "mh_app.py").write_text(MH_APP)

    monkeypatch.setenv("PATH", f"{shims}{os.pathsep}{os.environ['PATH']}")
    monkeypatch.setenv("UNIONML_TPU_FAUXHOST_BASE", str(base))
    monkeypatch.setenv("UNIONML_TPU_FAUXHOST_ROOT", str(fauxroot))
    # each "host" runs one single-device CPU jax process; the framework
    # must be importable there (a real VM gets it from provisioning)
    monkeypatch.setenv(
        "UNIONML_TPU_FAUXHOST_ENV",
        json.dumps({
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "PYTHONPATH": str(REPO_ROOT),
        }),
    )
    monkeypatch.setenv("UNIONML_TPU_HOME", str(base / "backend"))
    sys.path.insert(0, str(app_dir))
    sys.modules.pop("mh_app", None)
    try:
        import mh_app

        yield mh_app.model, base, fauxroot
    finally:
        sys.path.remove(str(app_dir))
        sys.modules.pop("mh_app", None)


def _cpu_multiprocess_supported() -> bool:
    from unionml_tpu.parallel import cpu_multiprocess_supported

    return cpu_multiprocess_supported()


@pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="this jax build has no multi-process CPU collectives (gloo) "
    "— the two SSH-launched runners form a real jax.distributed world",
)
def test_two_private_hosts_real_transport(shim_world):
    from unionml_tpu.remote import TPUVMBackend

    model, base, fauxroot = shim_world
    hosts = ["127.0.0.1", "localhost"]  # distinct identities, one machine
    backend = TPUVMBackend(
        hosts=hosts,
        project="mh-project",
        root=str(base / "backend"),
        workdir=str(base / "vm_work"),
        shared_fs=False,
        provision=False,
        coordinator_port=_free_port(),
    )
    model.remote(project="mh-project")
    model._backend = backend

    model.remote_deploy(app_version="v1")
    artifact = model.remote_train(app_version="v1", n=64)

    # the two SSH-launched runners formed ONE jax.distributed world
    assert artifact.model_object["world"] == 2
    assert artifact.model_object["peers"] == [0, 1]
    assert artifact.metrics["test"] == 2.0
    # the fit itself ran (y = 2*x1 - x2)
    w = artifact.model_object["w"]
    assert abs(w[0] - 2.0) < 1e-6 and abs(w[1] + 1.0) < 1e-6

    # filesystem privacy: each host got its own pushed tree under its
    # own root; the runner wrote its record in the host-private exec dir
    for host in hosts:
        pushed = Path(f"{fauxroot}/{host}{base}/vm_work/v1")
        assert (pushed / "mh_app.py").exists(), host
        exec_dirs = list((pushed / "_exec").iterdir())
        assert len(exec_dirs) == 1, host
        assert (exec_dirs[0] / "record.json").exists(), host
    # ...and host 0's outputs were scp-fetched back to the control side
    rec_dir = Path(f"{fauxroot}/{hosts[0]}{base}/vm_work/v1/_exec")
    exec_id = next(rec_dir.iterdir()).name
    local_exec = base / "backend" / "executions" / "mh-project" / exec_id
    assert (local_exec / "outputs.pkl").exists()
    # host 1 never wrote outputs (runner: only process 0 dumps)
    host1_exec = Path(f"{fauxroot}/{hosts[1]}{base}/vm_work/v1/_exec") / exec_id
    assert not (host1_exec / "outputs.pkl").exists()

    # per-host runner logs landed on the control side
    for i in range(2):
        assert (local_exec / f"runner.host{i}.log").exists()

    # predict: hosts cannot see the deployer's registry, so the backend
    # must stage the train execution (with host-side exec_dir rewritten)
    # before the runner can resolve model_version="latest"
    preds = model.remote_predict(
        app_version="v1",
        features=[{"x1": 1.0, "x2": 0.0}, {"x1": 0.0, "x2": 1.0}],
    )
    assert len(preds) == 2
    assert abs(preds[0] - 2.0) < 1e-6 and abs(preds[1] + 1.0) < 1e-6
    # the backend really staged the train execution into each host's
    # private registry (the exec-dir rewrite itself is asserted in
    # tests/unit/test_remote.py — here control and host path STRINGS
    # coincide by design, so only the push is observable)
    for host in hosts:
        staged = Path(
            f"{fauxroot}/{host}{base}/backend/executions/mh-project/{exec_id}"
        )
        assert (staged / "record.json").exists(), host
        assert (staged / "outputs.pkl").exists(), host
