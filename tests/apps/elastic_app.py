"""Fixture app: TPU-native train_step with preemption-safe checkpointing.

The test seam: with UNIONML_TEST_DIE_AT=N set AND no checkpoint yet on
disk, the elastic trainer's fault hook hard-kills the process
(``os._exit``) at global step N — a faithful slice preemption (no
cleanup, no terminal status). A relaunch finds checkpoints, disarms,
and resumes to completion.
"""

import os

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the env var alone does not out-rank a pre-registered TPU plugin;
    # the config API does (same trick as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

import glob

import jax.numpy as jnp
import numpy as np
import pandas as pd

from unionml_tpu import Dataset, Model
from unionml_tpu.defaults import Resources

_die_at = int(os.environ.get("UNIONML_TEST_DIE_AT", "0"))
_ckpt_dir = "elastic_ckpts"   # relative: resolves against the runner cwd
if _die_at and not glob.glob(os.path.join(_ckpt_dir, "step_*")):
    # arm the preemption bomb only on a FRESH run (no checkpoints):
    # the relaunch must resume, not die again at the same step
    import unionml_tpu.elastic as _elastic

    _real = _elastic.run_elastic_trainer

    def _with_fault(**kwargs):
        def hook(step):
            if step == _die_at:
                os._exit(17)  # hard kill: no finally blocks, like SIGKILL

        return _real(fault_hook=hook, **kwargs)

    _elastic.run_elastic_trainer = _with_fault

dataset = Dataset(name="elastic_dataset", test_size=0.25, shuffle=True,
                  random_state=11, targets=["y"])
model = Model(name="elastic_model", dataset=dataset)


@model.init
def init(hyperparameters: dict) -> dict:
    return {"w": jnp.zeros((2,), jnp.float32),
            "b": jnp.zeros((), jnp.float32)}


@dataset.reader
def reader(n: int = 64) -> pd.DataFrame:
    rng = np.random.default_rng(3)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = 2.0 * x1 - x2 + 0.1 * rng.normal(size=n)
    return pd.DataFrame({"x1": x1, "x2": x2, "y": y})


@model.train_step(
    checkpoint_dir=_ckpt_dir, save_every=2,
    resources=Resources(cpu="1", mem="1Gi", chips=0),
)
def step(state: dict, batch: tuple) -> tuple:
    x, y = batch
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32).reshape(-1)

    def loss_fn(params):
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(state)
    new_state = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, state, grads)
    return new_state, {"loss": loss}


@model.predictor
def predictor(params: dict, features: pd.DataFrame) -> list:
    x = jnp.asarray(np.asarray(features), jnp.float32)
    return np.asarray(x @ params["w"] + params["b"]).tolist()


@model.evaluator
def evaluator(params: dict, features: pd.DataFrame, target: pd.DataFrame) -> float:
    x = jnp.asarray(np.asarray(features), jnp.float32)
    y = jnp.asarray(np.asarray(target), jnp.float32).reshape(-1)
    return float(jnp.mean((x @ params["w"] + params["b"] - y) ** 2))
