"""Fixture app for remote/serving tests
(reference analog: tests/integration/sklearn_app/quickstart.py)."""

import numpy as np
import pandas as pd
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model

dataset = Dataset(name="fixture_dataset", test_size=0.2, shuffle=True, targets=["y"])
model = Model(name="fixture_model", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader(n: int = 200) -> pd.DataFrame:
    rng = np.random.default_rng(17)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = ((x1 + x2) > 0).astype(int)
    return pd.DataFrame({"x1": x1, "x2": x2, "y": y})


@model.trainer
def trainer(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> list:
    return [float(p) for p in estimator.predict(features)]


@model.evaluator
def evaluator(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> float:
    return float(estimator.score(features, target.squeeze()))
