"""Fixture app with a JAX TrainState model object (remote-transport test:
train states are not picklable — optax closures — so the backend moves
them as saver bytes, remote/artifacts.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import Mlp, MlpConfig, classification_step, create_train_state
from unionml_tpu.models.train import TrainState

dataset = Dataset(name="flax_fixture_data", test_size=0.25)
model = Model(name="flax_fixture_model", dataset=dataset)

_module = Mlp(MlpConfig(num_classes=2, hidden_dims=(16,)))


@dataset.reader
def reader(n: int = 64) -> dict:
    rng = np.random.default_rng(3)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    return {"features": x, "targets": y}


@dataset.splitter
def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
    k = int(len(data["features"]) * (1 - test_size))
    return (
        {"features": data["features"][:k], "targets": data["targets"][:k]},
        {"features": data["features"][k:], "targets": data["targets"][k:]},
    )


@dataset.parser
def parser(data: dict, features, targets):
    return (data["features"], data["targets"])


@model.init
def init(hyperparameters: dict) -> TrainState:
    return create_train_state(
        _module, jnp.zeros((1, 8)),
        learning_rate=hyperparameters.get("learning_rate", 1e-2),
    )


@model.trainer
def trainer(state: TrainState, features: np.ndarray, targets: np.ndarray,
            *, epochs: int = 30) -> TrainState:
    step = jax.jit(classification_step(_module))
    batch = (jnp.asarray(features), jnp.asarray(targets))
    for _ in range(epochs):
        state, _ = step(state, batch)
    return state


@model.predictor
def predictor(state: TrainState, features: np.ndarray) -> list:
    logits = state.apply_fn({"params": state.params}, jnp.asarray(features))
    return [int(i) for i in jnp.argmax(logits, axis=-1)]


@model.evaluator
def evaluator(state: TrainState, features: np.ndarray, targets: np.ndarray) -> float:
    logits = state.apply_fn({"params": state.params}, jnp.asarray(features))
    return float((jnp.argmax(logits, -1) == jnp.asarray(targets)).mean())
