"""Unified telemetry layer: registry thread-safety, histogram bucket
math, Prometheus exposition, trace-span export, and the tier-1-safe
``/metrics`` smoke over a ServingApp with a stub predictor (no TPU,
``JAX_PLATFORMS=cpu`` — the CI scrape check)."""

import json
import re
import threading
import time

import numpy as np
import pytest

from unionml_tpu import telemetry
from unionml_tpu.telemetry import MetricsRegistry, TraceRecorder

# measured sub-minute module: part of the `-m quick` tier
pytestmark = pytest.mark.quick


# ----------------------------------------------------------------- registry


def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests", ("route",))
    c.labels(route="/predict").inc()
    c.labels("/predict").inc(2)
    assert c.labels(route="/predict").value == 3
    with pytest.raises(ValueError):
        c.labels(route="/x").inc(-1)  # counters only go up

    g = reg.gauge("depth", "queue depth")
    g.set(4)
    g.inc()
    g.dec(2)
    assert g.value == 3
    g.set_function(lambda: 99)
    assert g.value == 99

    # same name + schema returns the same family; a changed schema raises
    assert reg.counter("req_total", "requests", ("route",)) is c
    with pytest.raises(ValueError):
        reg.counter("req_total", "requests", ("other",))
    with pytest.raises(ValueError):
        reg.gauge("req_total", "now a gauge", ("route",))


def test_registry_thread_safety_under_concurrent_increments():
    reg = MetricsRegistry()
    c = reg.counter("n_total", "count")
    h = reg.histogram("v_ms", "values")
    n_threads, per_thread = 8, 2000

    def work():
        for i in range(per_thread):
            c.inc()
            h.observe(float(i % 50))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # exact totals: no lost updates
    assert c.value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert h.buckets()[-1][1] == n_threads * per_thread  # +Inf cumulative


def test_histogram_bucket_math():
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms", "latency", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 99.0, 1000.0):
        h.observe(v)
    cum = dict(h.buckets())
    # le is inclusive: the observation at exactly 1.0 lands in le="1"
    assert cum[1.0] == 2
    assert cum[10.0] == 3
    assert cum[100.0] == 4
    assert cum[float("inf")] == 5
    assert h.count == 5 and h.sum == pytest.approx(1105.5)
    s = h.summary()
    assert s["n"] == 5 and s["p50"] == 5.0
    assert s["p99"] >= s["p95"] >= s["p50"]
    h.reset()
    assert h.count == 0 and h.summary() == {}


def test_default_ms_buckets_are_log_spaced_and_sorted():
    b = telemetry.DEFAULT_MS_BUCKETS
    assert list(b) == sorted(b)
    # log-spaced: each decade is covered by a bounded ratio step
    ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
    assert max(ratios) <= 5.0 and min(ratios) >= 1.5


def test_histogram_window_is_bounded():
    reg = MetricsRegistry()
    h = reg.histogram("w_ms", "window").labels()
    for i in range(h.WINDOW_CAP + 100):
        h.observe(float(i))
    assert len(h._window) <= h.WINDOW_CAP
    assert h.count == h.WINDOW_CAP + 100  # buckets never forget


# ------------------------------------------------------------- exposition


def parse_prometheus_text(text: str) -> dict:
    """Minimal exposition parser: {family: {"type": ..., "samples":
    [(name, labels_dict, value)]}}. Raises on malformed lines — the
    validation the CI smoke check leans on."""
    families: dict = {}
    current = None
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})? ([^ ]+)$"
    )
    label_re = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="(.*)"$')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            current = line.split(" ", 3)[2]
            families.setdefault(current, {"type": None, "samples": []})
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name == current, f"TYPE line out of order: {line!r}"
            families[name]["type"] = kind
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = sample_re.match(line)
        assert m, f"malformed sample line {line!r}"
        name, _, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            for pair in re.split(r",(?=[a-zA-Z_])", labelstr):
                lm = label_re.match(pair)
                assert lm, f"malformed label {pair!r} in {line!r}"
                labels[lm.group(1)] = re.sub(
                    r"\\(.)",
                    lambda e: {"n": "\n"}.get(e.group(1), e.group(1)),
                    lm.group(2),
                )
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        family = families.get(base) or families.get(name)
        assert family is not None, f"sample {name!r} without HELP/TYPE"
        float(value.replace("+Inf", "inf"))  # value must parse
        family["samples"].append((name, labels, value))
    return families


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("a_total", "with \"quotes\" and\nnewline", ("k",)).labels(
        k='va"l\\ue'
    ).inc(3)
    reg.gauge("b_gauge", "plain").set(2.5)
    reg.histogram("c_ms", "hist", buckets=(1.0, 10.0)).observe(4.0)
    text = reg.exposition()
    fams = parse_prometheus_text(text)
    assert fams["a_total"]["type"] == "counter"
    assert fams["b_gauge"]["type"] == "gauge"
    assert fams["c_ms"]["type"] == "histogram"
    # histogram renders cumulative buckets + sum + count, +Inf last
    names = [s[0] for s in fams["c_ms"]["samples"]]
    assert names.count("c_ms_bucket") == 3  # 1, 10, +Inf
    assert "c_ms_sum" in names and "c_ms_count" in names
    inf_rows = [
        s for s in fams["c_ms"]["samples"]
        if s[0] == "c_ms_bucket" and s[1]["le"] == "+Inf"
    ]
    assert inf_rows and inf_rows[0][2] == "1"
    # label escaping round-trips
    (name, labels, value), = fams["a_total"]["samples"]
    assert labels["k"] == 'va"l\\ue' and value == "3"


def test_instance_labels_are_unique():
    a, b = telemetry.instance_label("x"), telemetry.instance_label("x")
    assert a != b and a.startswith("x-")


# ------------------------------------------------------------ trace spans


def test_trace_span_export_round_trip():
    tr = TraceRecorder()
    rid = tr.new_request("generate")
    tr.record_span(rid, "queue", 1.000, 1.010)
    tr.record_span(rid, "prefill", 1.010, 1.050, tokens=1)
    with tr.span(rid, "decode-chunk[0]", tokens=8):
        pass
    tr.finish_request(rid)

    chrome = tr.export_chrome()
    # must be valid JSON that Perfetto/chrome://tracing accepts
    parsed = json.loads(json.dumps(chrome))
    assert parsed["displayTimeUnit"] == "ms"
    events = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in events][:2] == ["queue", "prefill"]
    for e in events:
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert e["args"]["request_id"] == rid
        assert {"pid", "tid", "cat"} <= set(e)
    queue_ev = events[0]
    assert queue_ev["ts"] == pytest.approx(1.000 * 1e6)
    assert queue_ev["dur"] == pytest.approx(0.010 * 1e6, rel=1e-6)

    lines = tr.export_jsonl().strip().splitlines()
    records = [json.loads(line) for line in lines]
    assert len(records) == 3
    assert records[1]["name"] == "prefill" and records[1]["tokens"] == 1
    assert all(r["request_id"] == rid for r in records)

    # late span for a finished request is ignored, not an error
    tr.record_span(rid, "ghost", 2.0, 3.0)
    assert len(json.loads(json.dumps(tr.export_chrome()))["traceEvents"]) == 4


def test_trace_recorder_bounds_completed_ring():
    tr = TraceRecorder(max_requests=3)
    for i in range(6):
        rid = tr.new_request("r")
        tr.record_span(rid, "s", 0.0, 1.0)
        tr.finish_request(rid)
    assert len(tr._done) == 3


def test_engine_request_spans_reach_tracer(tiny_llama_engine):
    """A served request's spans follow queue → prefill → decode-chunk[i]
    → harvest, and the Chrome export is structurally Perfetto-valid."""
    engine, params, tracer = tiny_llama_engine
    engine.generate(params, [[1, 2, 3]])
    chrome = json.loads(json.dumps(tracer.export_chrome()))
    names = [e["name"] for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert names[0] == "queue" and names[1] == "prefill"
    assert any(n.startswith("decode-chunk[") for n in names)
    assert names[-1] == "harvest"


@pytest.fixture
def tiny_llama_engine():
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    tracer = TraceRecorder()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        chunk_steps=2, registry=MetricsRegistry(), tracer=tracer,
    )
    try:
        yield engine, params, tracer
    finally:
        engine.close()


# -------------------------------------------------- layer integration


def test_engine_metrics_in_registry(tiny_llama_engine):
    """The engine's stats() is a thin view over its registry series."""
    engine, params, _ = tiny_llama_engine
    engine.generate(params, [[1, 2, 3], [4, 5, 6]])
    text = engine._registry.exposition()
    fams = parse_prometheus_text(text)
    for name in (
        "unionml_engine_requests_total",
        "unionml_engine_decode_steps_total",
        "unionml_engine_slots_in_use",
        "unionml_engine_queue_wait_ms",
        "unionml_engine_prefill_ms",
        "unionml_engine_chunk_dispatch_ms",
        "unionml_engine_chunk_harvest_ms",
    ):
        assert name in fams, name
    sample = fams["unionml_engine_requests_total"]["samples"][0]
    assert sample[1]["engine"].startswith("engine-") and sample[2] == "2"
    assert engine.stats()["completed_requests"] == 2
    engine.reset_stats()
    assert engine.stats()["completed_requests"] == 0


def test_batcher_metrics_in_registry():
    from unionml_tpu.serving.batcher import MicroBatcher

    reg = MetricsRegistry()
    batcher = MicroBatcher(
        lambda f: f.sum(axis=1), max_batch_size=8, max_wait_ms=5.0,
        registry=reg,
    )
    try:
        batcher.submit(np.ones((2, 3)))
        fams = parse_prometheus_text(reg.exposition())
        for name in (
            "unionml_batcher_requests_total",
            "unionml_batcher_batches_total",
            "unionml_batcher_batch_rows",
            "unionml_batcher_queue_wait_ms",
            "unionml_batcher_device_ms",
            "unionml_batcher_abandoned_total",
        ):
            assert name in fams, name
        s = batcher.stats()
        assert s["completed_requests"] == 1 and s["batches"] == 1
    finally:
        batcher.close()


def test_batcher_abandoned_submit_skipped_at_drain():
    """A submit() that times out while queued is marked abandoned: the
    worker never burns a device call on it and counts it."""
    import time

    from unionml_tpu.serving.batcher import MicroBatcher

    calls = []

    def slow(feats):
        calls.append(feats.shape[0])
        time.sleep(0.4)
        return feats

    reg = MetricsRegistry()
    batcher = MicroBatcher(
        slow, max_batch_size=1, max_wait_ms=1.0, registry=reg
    )
    try:
        # req1 occupies the worker; req2 times out while still queued
        t1 = threading.Thread(
            target=lambda: batcher.submit(np.ones((1, 2)), timeout=10)
        )
        t1.start()
        time.sleep(0.1)
        with pytest.raises(TimeoutError):
            batcher.submit(np.full((1, 2), 2.0), timeout=0.05)
        t1.join()
        batcher.submit(np.full((1, 2), 3.0), timeout=10)
        assert batcher._m_abandoned.value == 1
        assert len(calls) == 2  # the abandoned request never ran
        assert "abandoned" not in str(calls)
    finally:
        batcher.close()


def test_trainer_publishes_through_registry():
    import jax.numpy as jnp

    from unionml_tpu.execution import run_step_trainer

    reg = MetricsRegistry()

    def step(state, batch):
        x, y = batch
        return state, {"loss": jnp.mean((x.sum(axis=1) - y) ** 2)}

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64,)).astype(np.float32)
    run_step_trainer(
        step_fn=step, state={"w": jnp.zeros(4)}, features=x, targets=y,
        num_epochs=5, batch_size=4, donate_state=False, registry=reg,
    )
    fams = parse_prometheus_text(reg.exposition())
    assert "unionml_trainer_step_ms" in fams
    assert "unionml_trainer_steps_total" in fams
    steps_sample = fams["unionml_trainer_steps_total"]["samples"][0]
    assert float(steps_sample[2]) == 80  # 5 epochs * 16 batches
    # loss gauge was published at a window boundary (window=50 < 80)
    assert "unionml_trainer_loss" in fams
    assert "unionml_trainer_samples_per_sec" in fams


# ------------------------------------------------------ /metrics smoke


def validate_exposition_strict(text: str) -> dict:
    """Line-by-line exposition-format validation (beyond the substring
    checks this module started with): HELP precedes TYPE precedes
    samples for every family, no family appears twice, labels parse
    with escaping, every value parses, and histogram series are
    internally consistent per labelset — cumulative bucket counts
    nondecreasing, ``+Inf`` last and equal to ``_count``, ``_sum``
    present. Returns the parsed families."""
    families = parse_prometheus_text(text)  # raises on malformed lines
    seen_help = []
    state: dict = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert name not in seen_help, f"family {name} repeated"
            seen_help.append(name)
            state[name] = "help"
            continue
        if line.startswith("# TYPE "):
            name = line.split(" ", 3)[2]
            assert state.get(name) == "help", f"TYPE before HELP: {line!r}"
            state[name] = "type"
            continue
        base = re.sub(r"_(bucket|sum|count)$", "", line.split("{")[0].split(" ")[0])
        owner = base if base in state else line.split("{")[0].split(" ")[0]
        assert state.get(owner) == "type", f"sample before TYPE: {line!r}"
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # group by labelset minus 'le'
        series: dict = {}
        for sample, labels, value in fam["samples"]:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            series.setdefault(key, {})[
                (sample, labels.get("le"))
            ] = float(value.replace("+Inf", "inf"))
        for key, samples in series.items():
            buckets = [
                (float(le.replace("+Inf", "inf")), v)
                for (s, le), v in samples.items()
                if s == f"{name}_bucket"
            ]
            assert buckets, f"{name}{key}: no buckets"
            buckets.sort()
            counts = [v for _, v in buckets]
            assert counts == sorted(counts), (
                f"{name}{key}: non-monotonic buckets {counts}"
            )
            assert buckets[-1][0] == float("inf"), f"{name}{key}: no +Inf"
            count = samples.get((f"{name}_count", None))
            assert count == buckets[-1][1], (
                f"{name}{key}: _count {count} != +Inf {buckets[-1][1]}"
            )
            assert (f"{name}_sum", None) in samples, f"{name}{key}: no _sum"
    return families


def test_exposition_strict_validation_catches_defects():
    """The validator itself must reject broken expositions, or the
    concurrency smoke below is vacuous."""
    good = "# HELP a_total x\n# TYPE a_total counter\na_total 1\n"
    validate_exposition_strict(good)
    with pytest.raises(AssertionError):  # sample before TYPE
        validate_exposition_strict("# HELP a_total x\na_total 1\n")
    with pytest.raises(AssertionError):  # family repeated
        validate_exposition_strict(good + good)
    with pytest.raises(AssertionError):  # non-monotonic histogram
        validate_exposition_strict(
            "# HELP h_ms x\n# TYPE h_ms histogram\n"
            'h_ms_bucket{le="1"} 5\nh_ms_bucket{le="+Inf"} 3\n'
            "h_ms_sum 1\nh_ms_count 3\n"
        )


def test_metrics_scrape_under_concurrent_traffic():
    """Concurrency smoke: scrape /metrics repeatedly while request
    threads stream predicts, validating the exposition line-by-line
    each time — a torn render (half-updated histogram, interleaved
    family) must never reach a scraper."""
    import urllib.request

    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    dataset = Dataset(name="concurrency_smoke_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    stub = Model(name="concurrency_smoke", init=lambda: {"w": 1}, dataset=dataset)

    @stub.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @stub.predictor
    def predictor(p: dict, feats: list) -> list:
        return [float(np.asarray(f).sum()) for f in feats]

    stub.artifact = ModelArtifact({"w": 1}, {}, {})
    app = ServingApp(stub, registry=MetricsRegistry())
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    stop = threading.Event()
    errors: list = []

    def client(i):
        body = json.dumps({"features": [[float(i), 1.0]]}).encode()
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    f"{base}/predict", data=body,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=30).read()
            except Exception as exc:  # surfaced after the join
                errors.append(f"client: {exc!r}")
                return

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(4)
    ]
    try:
        for t in threads:
            t.start()
        for _ in range(15):
            with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
                text = resp.read().decode()
            fams = validate_exposition_strict(text)
            # the standard process gauges ride every scrape
            assert "process_start_time_seconds" in fams
            assert "unionml_tpu_build_info" in fams
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        app.shutdown()
    assert not errors, errors
    # traffic actually flowed while we scraped
    rows = [
        s for s in fams["unionml_http_requests_total"]["samples"]
        if s[1].get("path") == "/predict"
    ]
    assert rows and float(rows[0][2]) > 0


def test_process_and_build_info_gauges():
    """Satellite: process_start_time_seconds + build_info on the
    default registry (standard Prometheus conventions), and published
    into isolated registries on demand."""
    import time as _time

    text = telemetry.get_registry().exposition()
    row = next(
        line for line in text.splitlines()
        if line.startswith("process_start_time_seconds ")
    )
    start_s = float(row.split(" ", 1)[1])
    assert 0 < start_s <= _time.time()

    import jax  # noqa: F401 — backend label resolves once jax is loaded

    reg = MetricsRegistry()
    telemetry.publish_process_metrics(reg)
    fams = parse_prometheus_text(reg.exposition())
    assert fams["process_start_time_seconds"]["type"] == "gauge"
    sample = fams["unionml_tpu_build_info"]["samples"][0]
    assert set(sample[1]) == {"version", "jax_version", "backend"}
    assert sample[2] == "1"
    # jax is loaded in the test process: the backend label is real
    assert sample[1]["backend"] == "cpu"
    # republishing with the same labels never duplicates the child
    telemetry.publish_process_metrics(reg)
    fams = parse_prometheus_text(reg.exposition())
    live = [
        s for s in fams["unionml_tpu_build_info"]["samples"]
        if s[2] == "1"
    ]
    assert len(live) == 1


def test_percentile_summary_moved_to_telemetry_with_compat_shim():
    """Satellite: percentile_summary lives in telemetry; the old
    serving._stats import keeps working."""
    from unionml_tpu.serving._stats import percentile_summary as compat
    from unionml_tpu.telemetry import percentile_summary

    assert compat is percentile_summary
    s = percentile_summary([3.0, 1.0, 2.0])
    assert s == {"p50": 2.0, "p95": 3.0, "p99": 3.0, "mean": 2.0, "n": 3}
    # StepTimer shares it: summary() carries the full summary dict
    from unionml_tpu.diagnostics import StepTimer

    t = StepTimer(window=2)
    for _ in range(7):
        t.tick(4)
    s = t.summary()
    assert s["samples_per_sec"]["n"] == len(t.rates)
    assert s["samples_per_sec_median"] == s["samples_per_sec"]["p50"]


def test_sliding_samples_quantiles():
    """SlidingSamples (the router's hedge-delay tracker): bounded
    window, nearest-rank percentiles (the repo-wide formula), default
    before any sample, old regimes age out."""
    from unionml_tpu.telemetry import SlidingSamples

    with pytest.raises(ValueError):
        SlidingSamples(maxlen=0)
    s = SlidingSamples(maxlen=4)
    assert s.percentile(0.95, default=1.5) == 1.5
    with pytest.raises(ValueError):
        s.percentile(0.0)
    for v in (10.0, 20.0, 30.0, 40.0):
        s.add(v)
    assert len(s) == 4
    assert s.percentile(0.5) == 20.0      # ceil(0.5*4)-1 = index 1
    assert s.percentile(0.95) == 40.0
    # a new regime pushes the old one out of the bounded window
    for v in (1.0, 1.0, 1.0, 1.0):
        s.add(v)
    assert s.percentile(0.95) == 1.0


def test_metrics_smoke_servingapp_scrape():
    """CI smoke (tier-1-safe, JAX_PLATFORMS=cpu, no TPU): start a
    ServingApp over a stub predictor, scrape GET /metrics on a real
    socket, and validate the exposition parses end to end."""
    import urllib.request

    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    dataset = Dataset(name="metrics_smoke_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    stub = Model(name="metrics_smoke", init=lambda: {"w": 1}, dataset=dataset)

    @stub.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @stub.predictor
    def predictor(p: dict, feats: list) -> list:
        return [float(np.asarray(f).sum()) for f in feats]

    stub.artifact = ModelArtifact({"w": 1}, {}, {})
    app = ServingApp(stub, registry=MetricsRegistry())
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        body = json.dumps({"features": [[1.0, 2.0]]}).encode()
        req = urllib.request.Request(
            f"{base}/predict", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            assert json.loads(resp.read()) == [3.0]
        # the handler records its request series in a `finally` AFTER
        # the response bytes are flushed, so a scrape racing the
        # /predict handler thread can observe the registry a beat
        # before the sample lands — retry briefly (the race window is
        # microseconds; this bounds the wait, it never masks a missing
        # series)
        predict_rows: list = []
        deadline = time.monotonic() + 5.0
        while True:
            with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
                assert resp.headers["Content-Type"].startswith("text/plain")
                text = resp.read().decode()
            fams = parse_prometheus_text(text)  # raises on malformed lines
            assert fams["unionml_http_requests_total"]["type"] == "counter"
            predict_rows = [
                s for s in fams["unionml_http_requests_total"]["samples"]
                if s[1]["path"] == "/predict"
            ]
            if predict_rows or time.monotonic() > deadline:
                break
            time.sleep(0.01)
        assert predict_rows and predict_rows[0][1]["status"] == "200"
        assert fams["unionml_http_request_ms"]["type"] == "histogram"
    finally:
        app.shutdown()
