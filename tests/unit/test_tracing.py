"""Distributed-tracing tier-1 tests (docs/observability.md
"Distributed tracing & SLOs"): W3C ``traceparent`` parse/format/scope,
recorder parent links forming a connected tree, span-cap truncation
accounting, the OTLP exporter's golden encoding and retry/overflow
behavior against the collector stub, and the transport round trip —
inbound header → engine/batcher span parentage → response echo, with a
malformed header minting a root instead of erroring."""

import json
import threading

import httpx
import pytest

from unionml_tpu import telemetry
from unionml_tpu.exporters import (
    OtlpCollectorStub,
    OtlpExporter,
    encode_metrics,
    encode_spans,
)
from unionml_tpu.serving.batcher import MicroBatcher
from unionml_tpu.serving.http import KNOWN_ROUTES, ServingApp
from unionml_tpu.serving.serverless import gateway_handler
from unionml_tpu.telemetry import (
    MetricsRegistry,
    TraceContext,
    TraceRecorder,
    format_traceparent,
    parse_traceparent,
    trace_scope,
)

TP = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
TRACE_ID = "4bf92f3577b34da6a3ce929d0e0e4736"
PARENT_SPAN = "00f067aa0ba902b7"


# ------------------------------------------------------------ traceparent


def test_parse_traceparent_valid():
    ctx = parse_traceparent(TP)
    assert ctx == TraceContext(TRACE_ID, PARENT_SPAN, sampled=True)
    # not-sampled flag and surrounding whitespace
    ctx = parse_traceparent(f"  00-{TRACE_ID}-{PARENT_SPAN}-00  ")
    assert ctx is not None and ctx.sampled is False
    # future version parses leniently (the spec's forward-compat rule)
    assert parse_traceparent(f"01-{TRACE_ID}-{PARENT_SPAN}-01") is not None


@pytest.mark.parametrize("header", [
    None,
    "",
    "garbage",
    "00-short-00f067aa0ba902b7-01",
    f"00-{TRACE_ID}-{PARENT_SPAN}",          # missing flags
    f"00-{'0' * 32}-{PARENT_SPAN}-01",       # all-zero trace id
    f"00-{TRACE_ID}-{'0' * 16}-01",          # all-zero span id
    f"ff-{TRACE_ID}-{PARENT_SPAN}-01",       # forbidden version
    f"00-{TRACE_ID.upper()}Z-{PARENT_SPAN}-01",
])
def test_parse_traceparent_rejects_malformed(header):
    assert parse_traceparent(header) is None


def test_format_traceparent_round_trip():
    ctx = TraceContext(telemetry.new_trace_id(), telemetry.new_span_id())
    assert parse_traceparent(format_traceparent(ctx)) == ctx
    off = TraceContext(ctx.trace_id, ctx.span_id, sampled=False)
    assert format_traceparent(off).endswith("-00")


def test_trace_scope_nests_and_restores():
    a = TraceContext(telemetry.new_trace_id(), telemetry.new_span_id())
    b = TraceContext(telemetry.new_trace_id(), telemetry.new_span_id())
    assert telemetry.current_trace_context() is None
    with trace_scope(a):
        assert telemetry.current_trace_context() == a
        with trace_scope(b):
            assert telemetry.current_trace_context() == b
        assert telemetry.current_trace_context() == a
    assert telemetry.current_trace_context() is None


# ------------------------------------------------------------ recorder


def test_recorder_parent_links_form_connected_tree():
    tr = TraceRecorder(registry=MetricsRegistry())
    inbound = parse_traceparent(TP)
    with trace_scope(inbound):
        rid = tr.new_request("generate")
    tr.record_span(rid, "queue", 1.0, 1.1)
    tr.record_span(rid, "prefill", 1.1, 1.3)
    ctx = tr.trace_context(rid)
    assert ctx.trace_id == TRACE_ID
    meta = tr._meta[rid]
    assert meta["parent_span_id"] == PARENT_SPAN
    tr.finish_request(rid)
    # jsonl carries the ids: every span parents to the request root
    records = [json.loads(x) for x in tr.export_jsonl().splitlines()]
    assert all(r["trace_id"] == TRACE_ID for r in records)
    assert all(r["parent_span_id"] == ctx.span_id for r in records)
    span_ids = {r["span_id"] for r in records}
    assert len(span_ids) == 2 and ctx.span_id not in span_ids


def test_recorder_mints_root_without_scope():
    tr = TraceRecorder(registry=MetricsRegistry())
    rid = tr.new_request("generate")
    meta = tr._meta[rid]
    assert meta["parent_span_id"] is None
    assert parse_traceparent(
        f"00-{meta['trace_id']}-{meta['span_id']}-01"
    ) is not None  # minted ids are valid W3C ids


def test_span_cap_counts_drops_and_flags_truncated():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    tr.MAX_SPANS_PER_REQUEST = 3  # instance override
    rid = tr.new_request("generate")
    for i in range(5):
        tr.record_span(rid, f"s{i}", 0.0, 1.0)
    dropped = reg.counter("unionml_trace_spans_dropped_total")
    assert dropped.value == 2
    assert tr._meta[rid]["truncated"] is True
    tr.finish_request(rid)
    records = [json.loads(x) for x in tr.export_jsonl().splitlines()]
    assert len(records) == 3 and all(r["truncated"] for r in records)
    # unknown rid is still silently ignored, not counted as a drop
    tr.record_span("nope", "ghost", 0.0, 1.0)
    assert dropped.value == 2


def test_finish_listener_sees_request_once():
    tr = TraceRecorder(registry=MetricsRegistry())
    seen = []
    tr.add_listener(lambda rid, meta, spans: seen.append(rid))
    rid = tr.new_request("r")
    tr.record_span(rid, "s", 0.0, 1.0)
    tr.finish_request(rid)
    tr.finish_request(rid)  # double finish: no second event
    assert seen == [rid]
    tr.remove_listener(seen.append)  # unknown fn: no-op


# ------------------------------------------------------------ OTLP encoding


def test_otlp_span_encoding_golden():
    meta = {
        "kind": "generate", "trace_id": TRACE_ID, "span_id": "aa" * 8,
        "parent_span_id": PARENT_SPAN, "start_s": 1.0, "end_s": 3.0,
        "truncated": True, "prompt": 7,
    }
    spans = [{
        "name": "prefill", "start_s": 1.5, "end_s": 2.0,
        "span_id": "bb" * 8, "args": {"tokens": 3},
    }]
    payload = encode_spans([("rid0", meta, spans)], {"service.name": "svc"},
                           wall_offset_s=0.0)
    scope = payload["resourceSpans"][0]
    res_attrs = {a["key"]: a["value"] for a in scope["resource"]["attributes"]}
    assert res_attrs == {"service.name": {"stringValue": "svc"}}
    root, child = scope["scopeSpans"][0]["spans"]
    assert root == {
        "traceId": TRACE_ID, "spanId": "aa" * 8, "name": "generate",
        "kind": 2, "startTimeUnixNano": "1000000000",
        "endTimeUnixNano": "3000000000",
        "attributes": [
            {"key": "unionml.request_id", "value": {"stringValue": "rid0"}},
            {"key": "unionml.truncated", "value": {"boolValue": True}},
            {"key": "unionml.prompt", "value": {"intValue": "7"}},
        ],
        "parentSpanId": PARENT_SPAN,
    }
    assert child["parentSpanId"] == "aa" * 8
    assert child["spanId"] == "bb" * 8
    assert child["startTimeUnixNano"] == "1500000000"
    assert child["attributes"] == [
        {"key": "tokens", "value": {"intValue": "3"}},
    ]


def test_otlp_metrics_encoding_golden():
    reg = MetricsRegistry()
    reg.counter("unionml_t_total", "help c", ("k",)).labels("v").inc(3)
    reg.gauge("unionml_t_gauge", "help g").set(1.5)
    h = reg.histogram("unionml_t_ms", "help h", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    payload = encode_metrics(reg, {"service.name": "svc"}, now_unix_ns=42)
    metrics = {
        m["name"]: m
        for m in payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    }
    ctr = metrics["unionml_t_total"]["sum"]
    assert ctr["isMonotonic"] is True and ctr["aggregationTemporality"] == 2
    point = ctr["dataPoints"][0]
    assert point["asDouble"] == 3.0 and point["timeUnixNano"] == "42"
    assert point["attributes"] == [
        {"key": "k", "value": {"stringValue": "v"}},
    ]
    assert metrics["unionml_t_gauge"]["gauge"]["dataPoints"][0]["asDouble"] == 1.5
    hist = metrics["unionml_t_ms"]["histogram"]["dataPoints"][0]
    assert hist["explicitBounds"] == [1.0, 10.0]
    assert hist["bucketCounts"] == ["1", "1", "0"]
    assert hist["count"] == "2" and hist["sum"] == 5.5


# ------------------------------------------------------------ exporter


def _finish_one(tr, kind="generate"):
    rid = tr.new_request(kind)
    tr.record_span(rid, "queue", 1.0, 2.0)
    tr.finish_request(rid)
    return rid


def test_exporter_ships_spans_and_metrics_to_stub():
    stub = OtlpCollectorStub()
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    exp = OtlpExporter(stub.endpoint, registry=reg, tracer=tr,
                       interval_s=60.0, seed=0)
    try:
        _finish_one(tr)
        assert exp.pending() == 1
        exp.flush()
        assert exp.pending() == 0
        traces = stub.payloads("/v1/traces")
        assert len(traces) == 1
        spans = traces[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len(spans) == 2  # synthesized root + the queue span
        res = {
            a["key"]
            for a in traces[0]["resourceSpans"][0]["resource"]["attributes"]
        }
        assert {"service.name", "host.name", "service.version",
                "unionml_tpu.backend"} <= res
        assert stub.payloads("/v1/metrics")
        assert exp._m_exported.value == 2
    finally:
        exp.close(flush=False)
        stub.close()


def test_exporter_retries_then_succeeds():
    stub = OtlpCollectorStub()
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    exp = OtlpExporter(stub.endpoint, registry=reg, tracer=tr,
                       interval_s=60.0, max_retries=3, backoff_s=0.01,
                       export_metrics=False, seed=0)
    try:
        stub.fail(2)  # two 503s, then healthy: the POST must survive
        _finish_one(tr)
        exp.flush()
        assert stub.failures_served == 2
        assert exp._m_retries.value == 2
        assert exp._m_failures["traces"].value == 0
        assert len(stub.payloads("/v1/traces")) == 1
    finally:
        exp.close(flush=False)
        stub.close()


def test_exporter_drops_batch_after_exhausted_retries():
    stub = OtlpCollectorStub()
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    exp = OtlpExporter(stub.endpoint, registry=reg, tracer=tr,
                       interval_s=60.0, max_retries=1, backoff_s=0.01,
                       export_metrics=False, seed=0)
    try:
        stub.fail(10)
        _finish_one(tr)
        exp.flush()
        assert exp._m_failures["traces"].value == 1
        assert not stub.payloads("/v1/traces")
        # a non-retryable 4xx gives up immediately (no retry storm)
        stub.fail(10, status=400)
        retries_before = exp._m_retries.value
        _finish_one(tr)
        exp.flush()
        assert exp._m_retries.value == retries_before
        assert exp._m_failures["traces"].value == 2
    finally:
        exp.close(flush=False)
        stub.close()


def test_exporter_bounded_queue_drops_oldest():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    # endpoint never dialed: we only exercise the queue bound
    exp = OtlpExporter("http://127.0.0.1:9", registry=reg, tracer=tr,
                       interval_s=60.0, max_queue=3, export_metrics=False,
                       max_retries=0, backoff_s=0.01, seed=0)
    try:
        for _ in range(5):
            _finish_one(tr)
        assert exp.pending() == 3
        assert exp._m_dropped.value == 2
    finally:
        exp.close(flush=False)


# ------------------------------------------------- transport round trips


class _Artifact:
    model_object = "obj"


class _Dataset:
    def get_features(self, features):
        return features


class _StubModel:
    """The minimal object ServingApp needs: rows of floats in, sums out
    (through the batcher when batch=True)."""

    name = "tracing-stub"
    artifact = _Artifact()
    dataset = _Dataset()
    _predictor = staticmethod(lambda mo, feats: [float(sum(x)) for x in feats])
    _predict_step_options: dict = {}

    def predict_from_features_workflow(self):
        return lambda model_object, features: [
            float(sum(x)) for x in features
        ]


@pytest.fixture
def traced_app():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    stub = OtlpCollectorStub()
    app = ServingApp(
        _StubModel(), batch=True, row_lists=True, max_wait_ms=1.0,
        registry=reg, tracer=tr, otlp_endpoint=stub.endpoint,
        flight=telemetry.FlightRecorder(),
    )
    host, port = app.serve(port=0, blocking=False)
    yield f"http://{host}:{port}", app, tr, stub
    app.shutdown()
    stub.close()


def test_http_traceparent_round_trip_batcher_tree(traced_app):
    """The acceptance path: inbound traceparent → transport server span
    → batcher request root → queue/predict children, one connected
    tree under the caller's ids, echoed on the response and exported
    via OTLP to the collector stub."""
    url, app, tr, stub = traced_app
    r = httpx.post(f"{url}/predict", json={"features": [[1.0, 2.0]]},
                   headers={"traceparent": TP})
    assert r.status_code == 200 and r.json() == [3.0]
    echo = parse_traceparent(r.headers["traceparent"])
    assert echo is not None and echo.trace_id == TRACE_ID
    app._otlp.flush()
    spans = (
        stub.payloads("/v1/traces")[0]
        ["resourceSpans"][0]["scopeSpans"][0]["spans"]
    )
    assert all(s["traceId"] == TRACE_ID for s in spans)
    by_id = {s["spanId"]: s for s in spans}
    # the echoed span is the transport's recorded server span, parented
    # to the caller
    http_root = by_id[echo.span_id]
    assert http_root["parentSpanId"] == PARENT_SPAN
    assert http_root["name"] == "http"
    # the batcher timeline parents to the transport span, its children
    # (queue, predict) to it — a connected tree (the transport's own
    # "http /predict" server span is a sibling under the same parent)
    under_http = [
        s for s in spans if s.get("parentSpanId") == echo.span_id
    ]
    assert {s["name"] for s in under_http} == {"batch", "http /predict"}
    batch_roots = [s for s in under_http if s["name"] == "batch"]
    children = {
        s["name"] for s in spans
        if s.get("parentSpanId") == batch_roots[0]["spanId"]
    }
    assert children == {"queue", "predict"}


def test_http_malformed_traceparent_mints_root_never_errors(traced_app):
    url, _, _, _ = traced_app
    r = httpx.post(f"{url}/predict", json={"features": [[1.0]]},
                   headers={"traceparent": "not-a-context"})
    assert r.status_code == 200
    minted = parse_traceparent(r.headers["traceparent"])
    assert minted is not None and minted.trace_id != TRACE_ID


def test_http_every_route_echoes_traceparent(traced_app):
    url, _, _, _ = traced_app
    for path in ("/health", "/stats", "/metrics", "/debug/flight"):
        r = httpx.get(f"{url}{path}", headers={"traceparent": TP})
        echoed = parse_traceparent(r.headers.get("traceparent"))
        assert echoed is not None and echoed.trace_id == TRACE_ID, path


def test_http_echo_preserves_not_sampled_flag(traced_app):
    """The caller's sampling decision (-00) must ride through the echo
    on both traced and untraced routes."""
    url, _, _, _ = traced_app
    not_sampled = f"00-{TRACE_ID}-{PARENT_SPAN}-00"
    r = httpx.post(f"{url}/predict", json={"features": [[1.0]]},
                   headers={"traceparent": not_sampled})
    assert r.headers["traceparent"].endswith("-00")
    r = httpx.get(f"{url}/health", headers={"traceparent": not_sampled})
    assert r.headers["traceparent"].endswith("-00")


def test_http_get_probe_of_predict_stays_untraced(traced_app):
    """A GET scan of /predict 404s without opening a recorded timeline
    (only POSTs on the predict routes are traced)."""
    url, _, tr, _ = traced_app
    before = len(tr._done) + len(tr._live)
    r = httpx.get(f"{url}/predict", headers={"traceparent": TP})
    assert r.status_code == 404
    assert len(tr._done) + len(tr._live) == before


def test_debug_trace_endpoint_chrome_and_jsonl(traced_app):
    url, _, _, _ = traced_app
    assert "/debug/trace" in KNOWN_ROUTES and "/debug/slo" in KNOWN_ROUTES
    httpx.post(f"{url}/predict", json={"features": [[1.0]]},
               headers={"traceparent": TP})
    chrome = httpx.get(f"{url}/debug/trace")
    assert chrome.status_code == 200
    assert any(
        e.get("name") == "predict"
        for e in chrome.json()["traceEvents"]
    )
    jsonl = httpx.get(f"{url}/debug/trace?format=jsonl")
    assert jsonl.status_code == 200
    assert "ndjson" in jsonl.headers["content-type"]
    records = [json.loads(x) for x in jsonl.text.splitlines() if x]
    assert any(r["trace_id"] == TRACE_ID for r in records)
    assert httpx.get(f"{url}/debug/trace?format=nope").status_code == 422
    # /debug/slo without a watchdog is a 422, not a 500
    assert httpx.get(f"{url}/debug/slo").status_code == 422
    # both debug routes land in their own metric series, not <other>
    text = httpx.get(f"{url}/metrics").text
    assert 'path="/debug/trace"' in text


def test_metrics_route_stays_untraced(traced_app):
    """Scrapes and probes echo a context but must not churn the trace
    ring (an OTLP exporter would otherwise ship a span per scrape)."""
    url, _, tr, _ = traced_app
    before = len(tr._done) + len(tr._live)
    for _ in range(3):
        httpx.get(f"{url}/metrics")
        httpx.get(f"{url}/health")
    assert len(tr._done) + len(tr._live) == before


# ------------------------------------------------------------ batcher


def test_batcher_spans_inherit_scope_and_finish():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    batcher = MicroBatcher(
        lambda feats: [sum(x) for x in feats], row_lists=True,
        max_wait_ms=1.0, registry=reg, tracer=tr,
        flight=telemetry.FlightRecorder(),
    )
    try:
        inbound = parse_traceparent(TP)
        with trace_scope(inbound):
            out = batcher.submit([[1.0, 2.0]])
        assert out == [3.0]
        assert not tr._live, "batcher leaked a live trace timeline"
        (rid, meta, spans) = tr._done[-1]
        assert meta["trace_id"] == TRACE_ID
        assert meta["parent_span_id"] == PARENT_SPAN
        assert [s["name"] for s in spans] == ["queue", "predict"]
    finally:
        batcher.close()


def test_batcher_error_path_finishes_timeline():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)

    def boom(feats):
        raise RuntimeError("boom")

    batcher = MicroBatcher(boom, row_lists=True, max_wait_ms=1.0,
                           registry=reg, tracer=tr,
                           flight=telemetry.FlightRecorder())
    try:
        with pytest.raises(RuntimeError, match="boom"):
            batcher.submit([[1.0]])
        assert not tr._live, "errored submit leaked a live timeline"
    finally:
        batcher.close()


# ------------------------------------------------------------ serverless


def test_serverless_gateway_traceparent_and_debug_trace():
    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    handler = gateway_handler(
        _StubModel(), registry=reg, tracer=tr,
        flight=telemetry.FlightRecorder(),
    )
    resp = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"traceparent": TP},
        "body": json.dumps({"features": [[2.0, 3.0]]}),
    })
    assert resp["statusCode"] == 200
    echo = parse_traceparent(resp["headers"]["traceparent"])
    assert echo is not None and echo.trace_id == TRACE_ID
    # the recorded server span parents to the caller
    assert tr._done and tr._done[-1][1]["parent_span_id"] == PARENT_SPAN
    # probes echo a minted/propagated context without recording
    done_before = len(tr._done)
    health = handler({"httpMethod": "GET", "path": "/health", "headers": {}})
    assert parse_traceparent(health["headers"]["traceparent"]) is not None
    assert len(tr._done) == done_before
    # trace export over the gateway
    trace = handler({
        "httpMethod": "GET", "path": "/debug/trace",
        "queryStringParameters": {"format": "jsonl"}, "headers": {},
    })
    assert trace["statusCode"] == 200
    records = [json.loads(x) for x in trace["body"].splitlines() if x]
    assert any(r["trace_id"] == TRACE_ID for r in records)
    chrome = handler({"httpMethod": "GET", "path": "/debug/trace",
                      "headers": {}})
    assert "traceEvents" in json.loads(chrome["body"])
    bad = handler({
        "httpMethod": "GET", "path": "/debug/trace",
        "queryStringParameters": {"format": "nope"}, "headers": {},
    })
    assert bad["statusCode"] == 422


# ------------------------------------------------------------ engine


@pytest.fixture(scope="module")
def tiny_engine():
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    reg = MetricsRegistry()
    tracer = TraceRecorder(registry=reg)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        chunk_steps=2, registry=reg, tracer=tracer,
        flight=telemetry.FlightRecorder(),
    )
    try:
        yield engine, params, tracer
    finally:
        engine.close()


def test_engine_spans_join_inbound_trace(tiny_engine):
    """generate() inside a trace_scope: every engine span shares the
    inbound trace id and the parent links form a connected tree
    (engine root → queue/prefill/decode-chunk/harvest)."""
    engine, params, tracer = tiny_engine
    inbound = parse_traceparent(TP)
    with trace_scope(inbound):
        engine.generate(params, [[1, 2, 3]])
    rid, meta, spans = tracer._done[-1]
    assert meta["kind"] == "generate"
    assert meta["trace_id"] == TRACE_ID
    assert meta["parent_span_id"] == PARENT_SPAN
    names = [s["name"] for s in spans]
    assert names[0] == "queue" and names[1] == "prefill"
    assert names[-1] == "harvest"
    # connected: every span has its own id; jsonl parents them to root
    assert len({s["span_id"] for s in spans}) == len(spans)
    records = [
        json.loads(x) for x in tracer.export_jsonl().splitlines()
        if json.loads(x)["request_id"] == rid
    ]
    assert all(r["parent_span_id"] == meta["span_id"] for r in records)


def test_engine_streams_and_concurrent_traces_stay_separate(tiny_engine):
    """Two concurrent generates under different inbound contexts must
    not cross-contaminate trace ids (thread-local scope isolation)."""
    engine, params, tracer = tiny_engine
    ctxs = [
        TraceContext(telemetry.new_trace_id(), telemetry.new_span_id())
        for _ in range(2)
    ]
    done = []

    def worker(ctx, prompt):
        with trace_scope(ctx):
            engine.generate(params, [prompt])
        done.append(ctx)

    threads = [
        threading.Thread(target=worker, args=(ctxs[i], [i + 1, i + 2]))
        for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(done) == 2
    recent = {meta["trace_id"]: meta for _, meta, _ in tracer._done[-2:]}
    assert set(recent) == {c.trace_id for c in ctxs}
    for ctx in ctxs:
        assert recent[ctx.trace_id]["parent_span_id"] == ctx.span_id


# ------------------------------------------------------------ fastapi


def test_fastapi_traceparent_parity():
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    from unionml_tpu.serving.fastapi import serving_app

    reg = MetricsRegistry()
    tr = TraceRecorder(registry=reg)
    app = fastapi.FastAPI()
    serving_app(
        _StubModel(), app, registry=reg, tracer=tr,
        flight=telemetry.FlightRecorder(),
    )
    with TestClient(app) as client:
        r = client.post("/predict", json={"features": [[1.0, 2.0]]},
                        headers={"traceparent": TP})
        assert r.status_code == 200
        echo = parse_traceparent(r.headers["traceparent"])
        assert echo is not None and echo.trace_id == TRACE_ID
        assert tr._done[-1][1]["parent_span_id"] == PARENT_SPAN
        # malformed header → 200 + minted root (never a 5xx)
        bad = client.post("/predict", json={"features": [[1.0]]},
                          headers={"traceparent": "zzz"})
        assert bad.status_code == 200
        assert parse_traceparent(bad.headers["traceparent"]) is not None
        # untraced routes echo through the middleware
        h = client.get("/health", headers={"traceparent": TP})
        assert parse_traceparent(h.headers["traceparent"]).trace_id == TRACE_ID
        # the debug surface is mounted
        assert "traceEvents" in client.get("/debug/trace").json()
        assert client.get("/debug/trace?format=nope").status_code == 422
        assert client.get("/debug/slo").status_code == 422
