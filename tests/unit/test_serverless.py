"""Serverless adapters (reference analog: tests/unit/test_aws_lambda_handler.py
— synthetic API-Gateway and S3 events invoked directly as functions, with
object I/O against a local store instead of mocked boto3)."""

import json

import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.serving.serverless import (
    LocalObjectStore,
    gateway_handler,
    object_event_handler,
)


@pytest.fixture
def trained_model(model):
    model.train(hyperparameters={"max_iter": 500}, sample_frac=1.0, random_state=123)
    return model


def test_gateway_routes(trained_model):
    handler = gateway_handler(trained_model)
    assert handler({"httpMethod": "GET", "path": "/"})["statusCode"] == 200
    health = handler({"httpMethod": "GET", "path": "/health"})
    assert json.loads(health["body"])["model_loaded"] is True
    assert handler({"httpMethod": "GET", "path": "/nope"})["statusCode"] == 404


def test_gateway_predict_and_validation(trained_model, dataset):
    handler = gateway_handler(trained_model)
    features = [[0.1, 0.2], [1.5, -0.3], [0.0, 0.9]]
    resp = handler({
        "httpMethod": "POST", "path": "/predict",
        "body": json.dumps({"features": features}),
    })
    assert resp["statusCode"] == 200
    preds = json.loads(resp["body"])
    assert len(preds) == 3
    # both inputs and features -> 422, the same status the HTTP
    # transports answer for the identical payload (transport parity;
    # this was 400 before the contract was unified)
    bad = handler({
        "httpMethod": "POST", "path": "/predict",
        "body": json.dumps({"features": features, "inputs": {}}),
    })
    assert bad["statusCode"] == 422
    assert "exactly one" in json.loads(bad["body"])["error"]


def test_gateway_http_api_v2_event_shape(trained_model):
    # API-Gateway v2 events carry method/path differently
    handler = gateway_handler(trained_model)
    resp = handler({
        "requestContext": {"http": {"method": "GET"}},
        "rawPath": "/health",
    })
    assert resp["statusCode"] == 200


def test_gateway_metrics_and_request_id(trained_model):
    """Transport parity (PR-1 contract): GET /metrics serves the
    Prometheus exposition and every response carries X-Request-ID —
    echoed when the gateway forwarded one, minted otherwise."""
    handler = gateway_handler(trained_model)
    r = handler({"httpMethod": "GET", "path": "/health"})
    rid = r["headers"]["X-Request-ID"]
    assert rid and len(rid) == 16 and int(rid, 16) >= 0
    # an incoming id is echoed back (gateways forward client ids)
    r = handler({
        "httpMethod": "GET", "path": "/health",
        "headers": {"X-Request-Id": "trace-me-123"},
    })
    assert r["headers"]["X-Request-ID"] == "trace-me-123"
    # /metrics: exposition body + content type + serverless series
    handler({
        "httpMethod": "POST", "path": "/predict",
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    m = handler({"httpMethod": "GET", "path": "/metrics"})
    assert m["statusCode"] == 200
    assert m["headers"]["Content-Type"].startswith("text/plain")
    assert "unionml_http_requests_total" in m["body"]
    assert 'transport="serverless"' in m["body"]
    assert 'path="/predict"' in m["body"]
    # standard process gauges ride along (PR conventions)
    assert "process_start_time_seconds" in m["body"]
    assert "unionml_tpu_build_info" in m["body"]
    # /stats parity with the HTTP transports
    s = handler({"httpMethod": "GET", "path": "/stats"})
    assert s["statusCode"] == 200
    assert json.loads(s["body"])["engine"] == "direct"


def test_gateway_health_non_ok_maps_503(trained_model):
    """The PR-3 readiness contract: any non-ok health answers 503 so
    gateway health checks stop routing here; draining predicts get the
    typed 503 + Retry-After."""
    handler = gateway_handler(trained_model)
    app = handler.serving_app
    assert handler({"httpMethod": "GET", "path": "/health"})["statusCode"] == 200
    app.drain()
    try:
        h = handler({"httpMethod": "GET", "path": "/health"})
        assert h["statusCode"] == 503
        assert json.loads(h["body"])["status"] == "draining"
        r = handler({
            "httpMethod": "POST", "path": "/predict",
            "body": json.dumps({"features": [[0.1, 0.2]]}),
        })
        assert r["statusCode"] == 503
        assert json.loads(r["body"])["reason"] == "draining"
        assert int(r["headers"]["Retry-After"]) >= 1
    finally:
        app.resume()
    ok = handler({
        "httpMethod": "POST", "path": "/predict",
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert ok["statusCode"] == 200


def test_gateway_deadline_header_contract(trained_model):
    """X-Deadline-Ms flows through the shared parser: malformed values
    are a 422 (not a silently-ignored no-deadline), valid ones open the
    deadline scope around the predictor call."""
    handler = gateway_handler(trained_model)
    bad = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"X-Deadline-Ms": "banana"},
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert bad["statusCode"] == 422
    assert "X-Deadline-Ms" in json.loads(bad["body"])["error"]
    ok = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"X-Deadline-Ms": "30000"},
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert ok["statusCode"] == 200


def test_object_event_batch_prediction(trained_model, tmp_path):
    store = LocalObjectStore(str(tmp_path))
    features = [[0.1, 0.2], [1.5, -0.3]]
    store.put("uploads", "batch-001.json", json.dumps(features).encode())

    handler = object_event_handler(trained_model, store)
    event = {"Records": [{"s3": {"bucket": {"name": "uploads"},
                                 "object": {"key": "batch-001.json"}}}]}
    resp = handler(event)
    assert resp["statusCode"] == 200
    out = json.loads(resp["body"])["outputs"]
    assert out == [{"bucket": "uploads", "key": "batch-001.json.predictions.json"}]
    preds = json.loads(store.get("uploads", "batch-001.json.predictions.json"))
    assert len(preds) == 2
    # malformed records are skipped, not fatal
    assert handler({"Records": [{"s3": {}}]})["statusCode"] == 200


def test_object_event_url_encoded_keys_and_partial_errors(trained_model, tmp_path):
    store = LocalObjectStore(str(tmp_path))
    features = [[0.1, 0.2]]
    store.put("uploads", "my batch.json", json.dumps(features).encode())

    handler = object_event_handler(trained_model, store)
    rec = lambda key: {"s3": {"bucket": {"name": "uploads"}, "object": {"key": key}}}  # noqa: E731
    # S3 notifications URL-encode keys; one missing object must not abort
    # the good record's output
    resp = handler({"Records": [rec("my+batch.json"), rec("missing.json")]})
    assert resp["statusCode"] == 207
    body = json.loads(resp["body"])
    assert body["outputs"] == [
        {"bucket": "uploads", "key": "my batch.json.predictions.json"}
    ]
    assert body["errors"][0]["key"] == "missing.json"


def test_local_object_store_rejects_traversal(tmp_path):
    store = LocalObjectStore(str(tmp_path / "store"))
    with pytest.raises(ValueError, match="escapes store root"):
        store.get("uploads", "../../secrets.txt")
    with pytest.raises(ValueError, match="escapes store root"):
        store.put("..", "x.json", b"{}")
