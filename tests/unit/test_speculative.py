"""Speculative decoding: the greedy acceptance rule must make the output
token-identical to plain greedy decoding of the TARGET, for any draft —
acceptance only changes speed, never tokens."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig, make_generator
from unionml_tpu.models.speculative import make_speculative_generator


@pytest.fixture(scope="module")
def pair():
    t_cfg = LlamaConfig.tiny(vocab_size=97)
    d_cfg = LlamaConfig.tiny(vocab_size=97, hidden_dim=32, num_layers=1,
                             num_heads=2, num_kv_heads=1, mlp_dim=64)
    t = Llama(t_cfg)
    d = Llama(d_cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    tp = t.init(jax.random.PRNGKey(0), toks)["params"]
    dp = d.init(jax.random.PRNGKey(1), toks)["params"]
    return t, d, tp, dp


def _target_greedy(target, tp, prompts, n_new):
    gen = make_generator(target, max_new_tokens=n_new, max_len=128)
    return np.asarray(gen(tp, jnp.asarray(prompts, jnp.int32)))


def test_arbitrary_draft_is_token_identical(pair):
    """An unrelated random draft (low acceptance) must not change a
    single output token."""
    target, draft, tp, dp = pair
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, 97, size=(3, 10))
    spec = make_speculative_generator(
        target, draft, max_new_tokens=12, speculate_k=3, max_len=64
    )
    got = np.asarray(spec(tp, dp, jnp.asarray(prompts, jnp.int32)))
    want = _target_greedy(target, tp, prompts, 12)
    np.testing.assert_array_equal(got, want)


def test_self_speculation_full_acceptance(pair):
    """draft == target: every proposal accepted; output still identical."""
    target, _, tp, _ = pair
    rng = np.random.default_rng(1)
    prompts = rng.integers(1, 97, size=(2, 6))
    spec = make_speculative_generator(
        target, target, max_new_tokens=10, speculate_k=4, max_len=64
    )
    got = np.asarray(spec(tp, tp, jnp.asarray(prompts, jnp.int32)))
    want = _target_greedy(target, tp, prompts, 10)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("k", [1, 2, 5])
def test_speculate_k_never_changes_tokens(pair, k):
    target, draft, tp, dp = pair
    rng = np.random.default_rng(2)
    prompts = rng.integers(1, 97, size=(2, 7))
    spec = make_speculative_generator(
        target, draft, max_new_tokens=8, speculate_k=k, max_len=64
    )
    got = np.asarray(spec(tp, dp, jnp.asarray(prompts, jnp.int32)))
    want = _target_greedy(target, tp, prompts, 8)
    np.testing.assert_array_equal(got, want)


def test_eos_stops_like_plain_decoding(pair):
    target, draft, tp, dp = pair
    prompt = np.arange(1, 9)[None]
    plain = _target_greedy(target, tp, prompt, 8)[0]
    eos = int(plain[2])  # force an eos hit on the third generated token
    gen = make_generator(target, max_new_tokens=8, max_len=128, eos_id=eos, pad_id=0)
    want = np.asarray(gen(tp, jnp.asarray(prompt, jnp.int32)))[0]
    spec = make_speculative_generator(
        target, draft, max_new_tokens=8, speculate_k=3, max_len=64,
        eos_id=eos, pad_id=0,
    )
    got = np.asarray(spec(tp, dp, jnp.asarray(prompt, jnp.int32)))[0]
    np.testing.assert_array_equal(got, want)


def test_config_validation(pair):
    target, draft, *_ = pair
    other = Llama(LlamaConfig.tiny(vocab_size=64))
    with pytest.raises(ValueError, match="vocabularies differ"):
        make_speculative_generator(target, other, max_new_tokens=4)
    with pytest.raises(ValueError, match="speculate_k"):
        make_speculative_generator(target, draft, max_new_tokens=4, speculate_k=0)


def test_full_acceptance_round_count_no_draft_cache_hole(pair):
    """Self-speculation must keep accepting across rounds: a draft-cache
    hole after a fully-accepted round would collapse acceptance from
    round 2 (the regression this pins). 10 tokens at k=4 means 1 prefill
    token + 2 rounds of 5, with 4 drafts accepted per live round."""
    target, _, tp, _ = pair
    rng = np.random.default_rng(3)
    prompts = rng.integers(1, 97, size=(2, 6))
    spec = make_speculative_generator(
        target, target, max_new_tokens=10, speculate_k=4, max_len=64,
        with_stats=True,
    )
    toks, stats = spec(tp, tp, jnp.asarray(prompts, jnp.int32))
    rounds = np.asarray(stats["rounds"])
    accepted = np.asarray(stats["accepted"])
    np.testing.assert_array_equal(rounds, [2, 2])
    np.testing.assert_array_equal(accepted, [8, 8])  # 4 per round
    want = _target_greedy(target, tp, prompts, 10)
    np.testing.assert_array_equal(np.asarray(toks), want)


def test_chance_draft_low_acceptance_stats(pair):
    target, draft, tp, dp = pair
    rng = np.random.default_rng(4)
    prompts = rng.integers(1, 97, size=(1, 8))
    spec = make_speculative_generator(
        target, draft, max_new_tokens=8, speculate_k=3, max_len=64,
        with_stats=True,
    )
    toks, stats = spec(tp, dp, jnp.asarray(prompts, jnp.int32))
    assert int(np.asarray(stats["rounds"])[0]) >= 3  # mostly rejected


def test_speculative_predictor_buckets_pads_and_trims(pair):
    """The serving wrapper: ragged prompts right-pad into ONE bucketed
    call (bounded executables), per-row outputs equal plain target
    decoding, FrozenDict state accepted, warmup counts executables."""
    from flax.core import freeze

    from unionml_tpu.models.speculative import make_speculative_predictor

    target, draft, tp, dp = pair
    pred = make_speculative_predictor(
        target, draft, max_new_tokens=6, bucket_lens=(8, 16), speculate_k=2
    )
    state = {"target": tp, "draft": dp}
    prompts = [[1, 2, 3, 4], [9, 8, 7], [5, 6, 7, 8]]
    out = pred(state, prompts)
    for p, got in zip(prompts, out):
        want = _target_greedy(target, tp, np.asarray([p], np.int32), 6)[0].tolist()
        assert got == want, (p, got, want)

    # frozen mappings are valid state (checkpoint-restored trees)
    out2 = pred(freeze(state), prompts[:1])
    assert out2[0] == out[0]

    n = pred.warmup(state, max_batch=4)
    assert n == 2 * 3  # buckets {8,16} x batches {1,2,4}
    with pytest.raises(ValueError, match="empty bucket tuple"):
        pred.warmup(state, buckets=())

    with pytest.raises(ValueError, match="mapping"):
        pred(tp, prompts)
    with pytest.raises(ValueError, match="largest bucket"):
        pred(state, [list(range(40))])


def test_speculative_with_kv_quant_cache(pair):
    """Speculation on int8 KV caches (kv_quant=True target AND draft):
    still token-identical to plain greedy decoding of the quantized-cache
    target — per-position quantization is write-order independent, so the
    multi-token verify forward writes the same int8 rows a one-token
    decode would."""
    import dataclasses

    target, draft, tp, dp = pair
    q_target = Llama(dataclasses.replace(target.config, kv_quant=True))
    q_draft = Llama(dataclasses.replace(draft.config, kv_quant=True))
    rng = np.random.default_rng(5)
    prompts = rng.integers(1, 97, size=(2, 10))
    spec = make_speculative_generator(
        q_target, q_draft, max_new_tokens=10, speculate_k=3, max_len=64
    )
    got = np.asarray(spec(tp, dp, jnp.asarray(prompts, jnp.int32)))
    want = _target_greedy(q_target, tp, prompts, 10)
    np.testing.assert_array_equal(got, want)
