"""Zero-downtime model lifecycle tests (docs/robustness.md "Rollouts &
rollback").

The contract under test: the version registry publishes weights through
the checkpoint manager's crash-safe commit protocol and refuses torn
dirs exactly as restore does; the RolloutController choreographs canary
provisioning → shadow-diffed baking → rolling promotion through the
router's existing actuators and auto-rolls back on SLO burn / parity
regression / dead canaries under hysteresis; ``X-Model-Version`` is
validated at every transport boundary (closed grammar, 422 on garbage,
echoed on every response, carried across the router hop); ``bind()``
under fleet pressure refuses to swap weights under in-flight disagg
handoffs and preemption-resume limbo without stranding host KV or
leaking leases; and — THE chaos acceptance — an engine-backed fleet on
the stdlib transport has a version rolled forward and auto-rolled back
mid-flood with a canary OOM-killed mid-shadow, with zero caller-visible
failures, live tokens bit-identical to the solo oracle, the canary pool
reaped, and every decision reconstructible from ``/debug/flight`` plus
stitched ``/debug/trace?rid=`` timelines.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.autoscaler import (
    EngineReplicaProvisioner,
    ReplicaProvisioner,
)
from unionml_tpu.serving.disagg import DisaggRouter
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    EngineUnavailable,
    FaultInjector,
    xla_oom_error,
)
from unionml_tpu.serving.prefix_cache import RadixPrefixCache
from unionml_tpu.serving.rollout import (
    ROLLOUT_DECISIONS,
    ROLLOUT_REASONS,
    RolloutController,
    RolloutPolicy,
    VersionRegistry,
)
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
    RouterPolicy,
    make_router_app,
)
from unionml_tpu.serving.scheduler import (
    model_version_scope,
    validate_model_version,
)
from unionml_tpu.serving.usage import UsageLedger, tenant_scope


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


@pytest.fixture
def trained_model(model):
    model.train(
        hyperparameters={"max_iter": 500}, sample_frac=1.0, random_state=123
    )
    return model


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _copy_params(params):
    """Same values, new object identity — exercises bind()'s swap
    machinery without changing a single emitted token."""
    return jax.tree_util.tree_map(lambda x: jnp.array(x), params)


def _walk_refcounts(cache):
    """Every node's live lease refcount — must be all-zero at rest."""
    bad = []
    stack = list(cache._root.children.values())
    while stack:
        node = stack.pop()
        stack.extend(node.children.values())
        if node.refcount != 0:
            bad.append((node.depth, node.refcount))
    return bad


def _bind_when_idle(engine, params, timeout=30.0):
    """Swap weights the way an operator does: wait out the engine's
    trailing in-flight work (harvest/insert pipeline entries settle a
    beat after the caller's event fires), then bind."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            engine.bind(params)
            return
        except RuntimeError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.005)


def _wait_for(cond, timeout=60.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    pytest.fail(f"timed out waiting for {what}")


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeReplica(ReplicaHandle):
    """Scriptable replica (the autoscaler test pattern): settable burn
    and death, optional REAL prefix cache for warm-join."""

    def __init__(self, name, tokens=(1, 2, 3, 4), *, chunk=2, burn=0.0,
                 status="ok", cache=None):
        self.name = name
        self.tokens = list(tokens)
        self.chunk = chunk
        self.burn = burn
        self.status = status
        self.cache = cache
        self.dead = False
        self.dispatches = 0

    def generate_stream(self, prompt, *, max_new_tokens=None):
        if self.dead:
            raise EngineUnavailable(
                f"{self.name} is dead", reason="unreachable",
            )
        self.dispatches += 1
        for i in range(0, len(self.tokens), self.chunk):
            yield self.tokens[i:i + self.chunk]

    def health(self):
        if self.dead:
            raise ConnectionError(f"{self.name} is dead")
        return {"status": self.status, "queue_depth": 0, "burn": self.burn}

    def cached_prefix_len(self, prompt):
        return 0 if self.cache is None else self.cache.peek(prompt)

    def cache_blocks(self):
        return 0 if self.cache is None else self.cache.entries

    def export_hot_blocks(self, max_blocks=64):
        return [] if self.cache is None else self.cache.export_hot(
            max_blocks=max_blocks
        )

    def import_cache_blocks(self, entries):
        return 0 if self.cache is None else self.cache.import_blocks(entries)


class FakeProvisioner(ReplicaProvisioner):
    def __init__(self, *, fail_times=0, with_cache=False, tokens=(9, 9)):
        self.fail_times = fail_times
        self.with_cache = with_cache
        self.tokens = tokens
        self.attempts = 0
        self.provisioned = []
        self.released = []

    def provision(self, name):
        self.attempts += 1
        if self.attempts <= self.fail_times:
            raise RuntimeError(f"provision boom #{self.attempts}")
        cache = (
            RadixPrefixCache(
                block_size=4, registry=telemetry.MetricsRegistry(),
            )
            if self.with_cache else None
        )
        replica = FakeReplica(name, tokens=self.tokens, cache=cache)
        self.provisioned.append(replica)
        return replica

    def release(self, handle):
        self.released.append(handle.name)


def _fleet(replicas, **router_kw):
    router_kw.setdefault("health_ttl_s", 0.0)
    router_kw.setdefault("jitter_s", 0.0)
    router_kw.setdefault("backoff_base_s", 0.0)
    return FleetRouter(
        replicas,
        policy=RouterPolicy(**router_kw),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        sleep=lambda s: None,
    )


def _registry(tmp_path, *versions):
    vreg = VersionRegistry(tmp_path / "versions")
    for i, v in enumerate(versions):
        vreg.publish(v, {"w": np.full(4, float(i), np.float32)})
    return vreg


def _controller(router, prov, vreg, clock, **policy_kw):
    policy_kw.setdefault("canary_replicas", 1)
    policy_kw.setdefault("warm_blocks", 0)
    policy_kw.setdefault("shadow", False)
    policy_kw.setdefault("bake_evals", 2)
    policy_kw.setdefault("sustain_evals", 2)
    return RolloutController(
        router, prov, vreg,
        policy=RolloutPolicy(**policy_kw),
        params_loader=lambda v: {"which": v},
        registry=router._registry,
        flight=router._flight,
        clock=clock,
    )


# ------------------------------------------------------ version registry


def test_registry_publish_resolve_load_roundtrip(tmp_path):
    vreg = VersionRegistry(tmp_path)
    try:
        assert vreg.latest() is None
        vreg.publish("rel-1", {"w": np.arange(4, dtype=np.float32)})
        vreg.publish(
            "rel-2", {"w": np.arange(4, 8, dtype=np.float32)},
            metadata={"notes": "retrained"},
        )
        assert list(vreg.versions()) == ["rel-1", "rel-2"]
        assert vreg.latest() == "rel-2"
        assert vreg.resolve("rel-2")["metadata"] == {"notes": "retrained"}
        restored = vreg.load("rel-1", {"w": np.zeros(4, np.float32)})
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(4, dtype=np.float32)
        )
        # duplicates, the reserved sentinel, and grammar violations all
        # refuse with the deterministic 422 class
        with pytest.raises(ValueError, match="already published"):
            vreg.publish("rel-1", {"w": np.zeros(4, np.float32)})
        with pytest.raises(ValueError, match="reserved"):
            vreg.publish("auto", {"w": np.zeros(4, np.float32)})
        with pytest.raises(ValueError):
            vreg.publish("Not Valid!!", {"w": np.zeros(4, np.float32)})
        with pytest.raises(ValueError, match="unknown model version"):
            vreg.resolve("ghost")
    finally:
        vreg.close()


def test_registry_refuses_torn_dirs(tmp_path):
    """A step dir without its commit marker (crashed publish, partial
    rsync) is invisible to versions()/latest() and refused by load —
    exactly the restore contract, so a rollout can never pick up
    half-written weights."""
    vreg = VersionRegistry(tmp_path)
    try:
        vreg.publish("rel-1", {"w": np.arange(4, dtype=np.float32)})
        torn = tmp_path / "step_9"
        torn.mkdir()
        (torn / "state.msgpack").write_bytes(b"partial garbage")
        assert list(vreg.versions()) == ["rel-1"]
        assert vreg.latest() == "rel-1"
        with pytest.raises(ValueError, match="unknown model version"):
            vreg.load("v9", {"w": np.zeros(4, np.float32)})
    finally:
        vreg.close()


def test_registry_derived_ids_and_corrupt_sidecar(tmp_path):
    """A committed checkpoint saved outside publish() lists under the
    derived ``v<step>`` id; a corrupt metadata sidecar degrades to the
    derived id instead of hiding commit-protected weights."""
    vreg = VersionRegistry(tmp_path)
    try:
        vreg._manager.save(1, {"w": np.arange(4, dtype=np.float32)})
        vreg._manager.wait()
        assert list(vreg.versions()) == ["v1"]
        vreg.publish("rel-2", {"w": np.arange(4, 8, dtype=np.float32)})
        (tmp_path / "step_2" / "version.json").write_text("{not json")
        assert list(vreg.versions()) == ["v1", "v2"]
        restored = vreg.load("v2", {"w": np.zeros(4, np.float32)})
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(4, 8, dtype=np.float32)
        )
    finally:
        vreg.close()


# --------------------------------------------------------------- policy


def test_rollout_policy_validation():
    with pytest.raises(ValueError, match="canary_replicas"):
        RolloutPolicy(canary_replicas=0)
    with pytest.raises(ValueError, match="canary_percent"):
        RolloutPolicy(canary_percent=101.0)
    with pytest.raises(ValueError, match="shadow_sample"):
        RolloutPolicy(shadow_sample=1.5)
    with pytest.raises(ValueError, match="shadow_queue"):
        RolloutPolicy(shadow_queue=0)
    with pytest.raises(ValueError, match="divergence_tolerance"):
        RolloutPolicy(divergence_tolerance=-1)
    with pytest.raises(ValueError, match="sustain_evals"):
        RolloutPolicy(sustain_evals=0)
    with pytest.raises(ValueError, match="bake_evals"):
        RolloutPolicy(bake_evals=0)
    with pytest.raises(ValueError, match="warm_blocks"):
        RolloutPolicy(warm_blocks=-1)
    # the vocabularies the lint pins to docs/robustness.md stay closed
    assert ROLLOUT_DECISIONS == (
        "rollout_advance", "rollout_hold", "rollout_rollback",
    )
    assert len(set(ROLLOUT_REASONS)) == len(ROLLOUT_REASONS)


# -------------------------------------------------------- state machine


def test_rollout_provision_bake_promote_complete(tmp_path):
    """The clean path: canary joins (warm from the hottest live donor),
    bake accrues clean evaluations, promotion walks live replicas one
    per tick through drain → bind → rejoin, canaries reap, the fleet's
    live_version flips — and every transition is a flight event."""
    clock = _Clock()
    donor_cache = RadixPrefixCache(
        block_size=4, registry=telemetry.MetricsRegistry(),
    )
    tokens = list(range(100, 112))
    donor_cache.insert(
        tokens, 0,
        [((np.full((1, 4, 2), i, np.float32),),) for i in range(3)],
    )
    live = [
        FakeReplica("r0", cache=donor_cache),
        FakeReplica("r1"),
    ]
    router = _fleet(live)
    prov = FakeProvisioner(with_cache=True)
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(router, prov, vreg, clock, warm_blocks=8)
    try:
        d = ctl.start_rollout("rel-1", percent=25.0)
        assert (d["decision"], d["reason"]) == ("rollout_advance", "operator")
        d = ctl.evaluate()
        assert d["reason"] == "canary_ready"
        assert d["warmed_blocks"] > 0   # fleet-warmed from r0's cache
        assert ctl.dashboard()["stage"] == "baking"
        assert router.version_split()["percent"] == 25.0
        assert "canary-rel-1-0" in router.members()
        # two clean evaluations bake; the third promotes
        assert ctl.evaluate()["reason"] == "baking"
        assert ctl.evaluate()["reason"] == "bake_complete"
        promoted = {ctl.evaluate()["replica"], ctl.evaluate()["replica"]}
        assert promoted == {"r0", "r1"}
        assert live[0].version == "rel-1" and live[1].version == "rel-1"
        assert ctl.evaluate()["reason"] == "reap_canary"
        d = ctl.evaluate()
        assert (d["decision"], d["reason"]) == ("rollout_advance", "complete")
        assert router.live_version == "rel-1"
        assert router.version_split() is None
        assert ctl.dashboard()["stage"] == "idle"
        assert prov.released == ["canary-rel-1-0"]
        assert "canary-rel-1-0" not in router.members()
        # reconstructible: the flight ring carries the whole release
        reasons = [
            e.get("reason") for e in router._flight.dump()
            if e["kind"] in ROLLOUT_DECISIONS
        ]
        for want in ("operator", "canary_ready", "bake_complete",
                     "promote_replica", "reap_canary", "complete"):
            assert want in reasons, (want, reasons)
        snap = router._registry.snapshot()
        assert any(
            "reason=complete" in k
            for k in snap["unionml_rollout_decisions_total"]
        )
    finally:
        ctl.close()


def test_rollout_slo_burn_rolls_back_with_hysteresis(tmp_path):
    """One hot evaluation holds (hysteresis), a sustained burn rolls
    back: canaries drained + released, split cleared, live capacity
    untouched."""
    clock = _Clock()
    live = [FakeReplica("r0"), FakeReplica("r1")]
    router = _fleet(live)
    prov = FakeProvisioner()
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(
        router, prov, vreg, clock, canary_burn_threshold=1.0,
    )
    try:
        ctl.start_rollout("rel-1")
        assert ctl.evaluate()["reason"] == "canary_ready"
        canary = prov.provisioned[0]
        canary.burn = 5.0
        d = ctl.evaluate()
        assert (d["decision"], d["reason"]) == ("rollout_hold", "hysteresis")
        canary.burn = 0.0   # a blip clears the streak
        assert ctl.evaluate()["reason"] == "baking"
        canary.burn = 5.0
        ctl.evaluate()
        d = ctl.evaluate()
        assert (d["decision"], d["reason"]) == ("rollout_rollback", "slo_burn")
        assert ctl.dashboard()["stage"] == "idle"
        assert router.version_split() is None
        assert prov.released == ["canary-rel-1-0"]
        assert set(router.members()) == {"r0", "r1"}
        assert live[0].version is None   # live replicas never touched
    finally:
        ctl.close()


def test_rollout_dead_canary_degrades_shadow_then_rolls_back(tmp_path):
    """An unreachable canary degrades shadowing OFF immediately (the
    flight ring shows rollout_hold{shadow_degraded} exactly once) and
    rolls the release back after its own hysteresis window."""
    clock = _Clock()
    router = _fleet([FakeReplica("r0")])
    prov = FakeProvisioner()
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(
        router, prov, vreg, clock, shadow=True, canary_dead_evals=2,
    )
    try:
        ctl.start_rollout("rel-1")
        assert ctl.evaluate()["reason"] == "canary_ready"
        prov.provisioned[0].dead = True
        d = ctl.evaluate()
        assert d["reason"] == "hysteresis" and d["signal"] == "canary_dead"
        d = ctl.evaluate()
        assert (d["decision"], d["reason"]) == (
            "rollout_hold", "shadow_degraded",
        )
        assert ctl.dashboard()["shadow"]["degraded"] is True
        d = ctl.evaluate()
        assert (d["decision"], d["reason"]) == (
            "rollout_rollback", "canary_dead",
        )
        kinds = [
            (e["kind"], e.get("reason")) for e in router._flight.dump()
        ]
        assert kinds.count(("rollout_hold", "shadow_degraded")) == 1
    finally:
        ctl.close()


def test_rollout_provision_failure_backs_off_exponentially(tmp_path):
    clock = _Clock()
    router = _fleet([FakeReplica("r0")])
    prov = FakeProvisioner(fail_times=2)
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(
        router, prov, vreg, clock,
        provision_backoff_s=1.0, provision_backoff_max_s=30.0,
    )
    try:
        ctl.start_rollout("rel-1")
        assert ctl.evaluate()["reason"] == "provision_failed"
        # inside the backoff window: held, no new attempt burned
        clock.advance(0.5)
        assert ctl.evaluate()["reason"] == "provision_backoff"
        assert prov.attempts == 1
        clock.advance(1.0)
        d = ctl.evaluate()
        assert d["reason"] == "provision_failed"
        assert d["retry_in_s"] == 2.0   # doubled
        clock.advance(2.5)
        assert ctl.evaluate()["reason"] == "canary_ready"
    finally:
        ctl.close()


def test_rollout_abort_mid_promote_walks_fleet_back(tmp_path):
    """abort() after a replica promoted restores it to the old weights
    through the same drain → bind → rejoin step — the fleet is never
    left split-brained across versions."""
    clock = _Clock()
    live = [FakeReplica("r0"), FakeReplica("r1")]
    router = _fleet(live)
    prov = FakeProvisioner()
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(router, prov, vreg, clock)
    try:
        ctl.start_rollout("rel-1")
        ctl.evaluate()              # canary_ready
        ctl.promote()               # operator skips the bake
        d = ctl.evaluate()
        assert d["reason"] == "promote_replica" and d["replica"] == "r0"
        assert live[0].version == "rel-1"
        d = ctl.abort()
        assert (d["decision"], d["reason"]) == ("rollout_rollback", "operator")
        assert d["restored"] == ["r0"]
        assert live[0].version is None   # back on the implicit live version
        assert ctl.dashboard()["stage"] == "idle"
        # a fresh rollout can start after the abort
        ctl.start_rollout("rel-1")
        assert ctl.evaluate()["reason"] == "canary_ready"
    finally:
        ctl.close()


def test_rollout_shadow_diff_drives_parity_rollback(tmp_path):
    """The shadow lane end-to-end on fake replicas: live dispatches
    duplicate onto the canary, token diffs count as divergences, and a
    sustained parity regression auto-rolls back."""
    clock = _Clock()
    live = [FakeReplica("r0", tokens=(1, 2, 3, 4))]
    router = _fleet(live)
    prov = FakeProvisioner(tokens=(9, 9))   # the canary disagrees
    vreg = _registry(tmp_path, "rel-1")
    ctl = _controller(router, prov, vreg, clock, shadow=True)
    try:
        ctl.start_rollout("rel-1", percent=0.0)
        assert ctl.evaluate()["reason"] == "canary_ready"
        decision = None
        for _ in range(2):
            before = ctl.dashboard()["shadow"]["diverged"]
            assert router.generate([5, 6, 7]) == [1, 2, 3, 4]
            _wait_for(
                lambda: ctl.dashboard()["shadow"]["diverged"] > before,
                what="shadow divergence",
            )
            decision = ctl.evaluate()
        assert (decision["decision"], decision["reason"]) == (
            "rollout_rollback", "parity_regression",
        )
        # the divergence is attributable: first differing position and
        # the live rid land in the flight ring
        diffs = [
            e for e in router._flight.dump()
            if e["kind"] == "rollout_shadow"
        ]
        assert diffs and diffs[0]["first_diff"] == 0 and diffs[0]["rid"]
        snap = router._registry.snapshot()
        shadow = snap["unionml_rollout_shadow_requests_total"]
        assert shadow.get("result=diverged", 0) >= 2
    finally:
        ctl.close()


# ------------------------------------------------ version-aware routing


def test_version_split_and_pin_routing(tmp_path):
    """The router's version-aware pick: deterministic percentage stride
    on unpinned traffic, tenant pins, hard X-Model-Version pins (422
    for unknown, 503-class when known but unroutable), soft fallback
    when the canary version loses capacity."""
    live = FakeReplica("r0", tokens=(1, 2))
    canary = FakeReplica("c0", tokens=(9, 9))
    canary.version = "rel-1"
    router = _fleet([live, canary])
    router.set_version_split("rel-1", percent=50.0)
    outs = [router.generate([1, 2, 3]) for _ in range(4)]
    assert outs.count([9, 9]) == 2 and outs.count([1, 2]) == 2
    # tenant pin: all of acme's traffic goes to the canary version
    router.set_version_split("rel-1", percent=0.0, tenants={"acme": "rel-1"})
    with tenant_scope("acme"):
        assert router.generate([1, 2, 3]) == [9, 9]
    assert router.generate([1, 2, 3]) == [1, 2]
    # hard pin beats the split; unknown version is the 422 class
    with model_version_scope("rel-1"):
        assert router.generate([1, 2, 3]) == [9, 9]
    with model_version_scope("ghost"):
        with pytest.raises(ValueError, match="unknown model version"):
            router.generate([1, 2, 3])
    # known-but-unroutable pin: retryable 503 class, not a 422
    assert router.drain_replica("c0", timeout=1.0)
    with model_version_scope("rel-1"):
        with pytest.raises(EngineUnavailable):
            router.generate([1, 2, 3])
    # the soft split sheds the dying canary's share instead of failing
    router.set_version_split("rel-1", percent=100.0)
    assert router.generate([1, 2, 3]) == [1, 2]


# ------------------------------------------------- transport round-trip


def test_stdlib_transport_model_version_round_trip(trained_model):
    import httpx

    from unionml_tpu.serving.http import ServingApp

    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict",
            json={"features": [{"x": 1.0, "x2": 1.0}]},
            headers={"X-Model-Version": "rel-1"},
        )
        assert r.status_code == 200
        assert r.headers["x-model-version"] == "rel-1"
        # default + echo on non-predict routes too
        h = httpx.get(f"{base}/health")
        assert h.headers["x-model-version"] == "auto"
        # outside the closed grammar: 422, and the ERROR response still
        # carries the (defaulted) header
        bad = httpx.post(
            f"{base}/predict", json={"features": []},
            headers={"X-Model-Version": "Not Valid!!"},
        )
        assert bad.status_code == 422
        assert bad.headers["x-model-version"] == "auto"
        # /debug/rollout without a controller is a deterministic 422
        nr = httpx.get(f"{base}/debug/rollout")
        assert nr.status_code == 422
    finally:
        app.shutdown()


def test_fastapi_transport_model_version_round_trip(trained_model):
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        r = client.post(
            "/predict", json={"features": [[0.1, 0.2]]},
            headers={"X-Model-Version": "rel-1"},
        )
        assert r.status_code == 200
        assert r.headers["x-model-version"] == "rel-1"
        h = client.get("/health")
        assert h.headers["x-model-version"] == "auto"
        bad = client.get("/health", headers={"X-Model-Version": "NOPE!"})
        assert bad.status_code == 422


def test_serverless_transport_model_version_round_trip(trained_model):
    from unionml_tpu.serving.serverless import gateway_handler

    handler = gateway_handler(trained_model)
    r = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"X-Model-Version": "rel-1"},
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert r["statusCode"] == 200
    assert r["headers"]["X-Model-Version"] == "rel-1"
    h = handler({"httpMethod": "GET", "path": "/health"})
    assert h["headers"]["X-Model-Version"] == "auto"
    bad = handler({
        "httpMethod": "GET", "path": "/health",
        "headers": {"X-Model-Version": "NOPE!"},
    })
    assert bad["statusCode"] == 422


def test_http_replica_forwards_model_version():
    """The router hop: HttpReplica re-emits the ambient pin as
    X-Model-Version so a routed request stays pinned on the remote
    replica; the no-pin default adds no header at all."""
    replica = HttpReplica("http://127.0.0.1:9")
    with model_version_scope("rel-1"):
        assert replica._headers()["X-Model-Version"] == "rel-1"
    assert "X-Model-Version" not in replica._headers()
    # boundary validation is the shared closed grammar
    with pytest.raises(ValueError, match="model version too long"):
        validate_model_version("x" * 65)


def test_engine_version_tag_rides_usage_vectors(tiny_llama):
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(16,),
        chunk_steps=2, usage=ledger, registry=registry,
    )
    try:
        engine.generate(params, [[1, 2, 3]], tenant="acme")
        engine.model_version = "rel-1"
        engine.generate(params, [[4, 5, 6]], tenant="acme")
        vec = ledger.report()["tenants"]["acme"]
        # unversioned requests add no key; versioned ones bill under it
        assert vec["requests_by_version"] == {"rel-1": 1}
    finally:
        engine.close()


# ------------------------------------------- bind() under fleet pressure


@pytest.mark.chaos
def test_bind_racing_disagg_handoff_holds_guards(tiny_llama):
    """A weight swap racing an in-flight disaggregated handoff: the
    decode engine's busy guard refuses mid-stream, the prefill-side
    swap drops the exported host KV (stale blocks can never serve the
    new tree), the held lease stays release-idempotent, and the next
    request degrades to recompute with full token parity — refcounts
    back to baseline throughout."""
    module, params = tiny_llama
    params2 = _copy_params(params)
    reg = telemetry.MetricsRegistry()
    shared = RadixPrefixCache(registry=reg)
    fi = FaultInjector()
    kw = dict(
        slots=2, max_new_tokens=48, prompt_buckets=(32,), chunk_steps=2,
        prefix_cache=shared, registry=reg,
    )
    pre = DecodeEngine(module, phase="prefill", **kw)
    dec = DecodeEngine(module, phase="decode", fault_injector=fi, **kw)
    router = DisaggRouter(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        policy=RouterPolicy(
            health_ttl_s=0.0, backoff_base_s=0.0, jitter_s=0.0,
        ),
        registry=reg, flight=telemetry.FlightRecorder(),
    )
    prompt = list(range(1, 21))
    solo = _solo(module, params, prompt, 48, max_len=dec.cache_len)
    try:
        # -- prefill side: swap while the export lease is still held
        handle = pre.prefill_export(params, prompt)
        assert handle["cached_tokens"] > 0 and shared.entries > 0
        _bind_when_idle(pre, params2)   # idle engine: the swap lands...
        assert shared.entries == 0      # ...stranding NO old-weights KV
        handle["lease"].release()    # idempotent against cleared store
        assert _walk_refcounts(shared) == []
        # -- decode side: swap mid-stream must refuse
        fi.arm("engine.dispatch", nth=1, count=8, delay_s=0.1)
        stream = router.generate_stream(prompt)
        got = list(next(stream))     # the prefill leg's TTFT emission
        got.extend(next(stream))     # first DECODE chunk: leg in flight
        with pytest.raises(RuntimeError, match="while requests are in"):
            dec.bind(params2)
        got.extend(t for chunk in stream for t in chunk)
        assert got == solo
        # -- after the stream drains, the swap lands and the handoff
        #    path keeps exact parity on recompute
        _wait_for(lambda: _walk_refcounts(shared) == [],
                  what="leases released")
        _bind_when_idle(dec, params2)
        assert shared.entries == 0
        out = [t for c in router.generate_stream(prompt) for t in c]
        assert out == solo
        _wait_for(lambda: _walk_refcounts(shared) == [],
                  what="leases released")
    finally:
        router.close()
        pre.close()
        dec.close()


@pytest.mark.chaos
def test_bind_racing_preemption_resume_holds_guards(tiny_llama):
    """A weight swap racing a preempted stream's evict→resume limbo:
    the victim's host KV belongs to the OLD weights, so bind() refuses
    until the stream resumed and finished — then the swap lands, the
    old KV is dropped, and the pool/lease ledgers are at baseline."""
    module, params = tiny_llama
    params2 = _copy_params(params)
    reg = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    engine = DecodeEngine(
        module, paged=True, slots=2, max_new_tokens=48,
        prompt_buckets=(64,), chunk_steps=2, pipeline_depth=2,
        kv_block_size=16, kv_pool_blocks=5,
        prefix_cache=RadixPrefixCache(block_size=16, registry=reg),
        registry=reg, flight=flight,
    )
    try:
        rng = np.random.default_rng(0)
        low_prompt = rng.integers(1, 97, 8).tolist()
        high_prompt = rng.integers(1, 97, 8).tolist()
        low_out, errors, high_out = [], [], []

        def low_client():
            try:
                for chunk in engine.generate_stream(
                    params, low_prompt, priority="low"
                ):
                    low_out.extend(chunk)
            except BaseException as exc:
                errors.append(exc)

        def high_client():
            try:
                high_out.append(engine.generate(
                    params, [high_prompt], max_new_tokens=8,
                    priority="high",
                )[0])
            except BaseException as exc:
                errors.append(exc)

        t_low = threading.Thread(target=low_client)
        t_low.start()
        _wait_for(lambda: low_out, what="first low token")
        t_high = threading.Thread(target=high_client)
        t_high.start()
        _wait_for(
            lambda: any(e["kind"] == "preempt" for e in flight.dump()),
            what="preemption",
        )
        # the victim sits in evict→resume limbo: its host KV was built
        # under the CURRENT weights — the swap must wait
        with pytest.raises(RuntimeError, match="while requests are in"):
            engine.bind(params2)
        t_low.join(timeout=120)
        t_high.join(timeout=120)
        assert not t_low.is_alive() and not t_high.is_alive()
        assert not errors, f"caller-visible failure: {errors}"
        assert low_out == _solo(
            module, params, low_prompt, 48, max_len=engine.cache_len
        )
        assert high_out[0] == _solo(
            module, params, high_prompt, 8, max_len=engine.cache_len
        )
        # idle now: the swap lands, drops the old-weights KV, and the
        # pool + lease ledgers are back to baseline
        _wait_for(
            lambda: engine.stats()["kv_pool"]["blocks_in_use"] == 0,
            what="pool drained",
        )
        _bind_when_idle(engine, params2)
        assert engine.prefix_cache.entries == 0
        assert _walk_refcounts(engine.prefix_cache) == []
        probe = rng.integers(1, 97, 8).tolist()
        assert engine.generate(params2, [probe])[0] == _solo(
            module, params, probe, 48, max_len=engine.cache_len
        )
        st = engine.stats()["kv_pool"]
        assert st["blocks_in_use"] == 0 and st["blocks_reserved"] == 0
    finally:
        engine.close()


# ------------------------------------------------------ chaos acceptance


@pytest.mark.chaos
def test_rollout_chaos_fleet_under_flood(tiny_llama, tmp_path):
    """THE acceptance: an engine-backed fleet on the stdlib transport
    has a bad version rolled forward and auto-rolled back mid-flood
    (shadow parity regression), then a clean version baked through a
    canary OOM-kill mid-shadow and promoted — zero caller-visible
    failures, every live token bit-identical to the solo oracle, the
    canary pool reaped with lease refcounts at baseline, and the whole
    release reconstructible from /debug/flight + /debug/rollout +
    stitched /debug/trace?rid= timelines."""
    httpx = pytest.importorskip("httpx")
    module, params = tiny_llama
    params_good = _copy_params(params)
    params_bad = jax.tree_util.tree_map(lambda x: -x, params)
    reg = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    tracer = telemetry.TraceRecorder()
    fi = FaultInjector()

    def make_engine(**extra):
        return DecodeEngine(
            module, slots=4, max_new_tokens=8, prompt_buckets=(16,),
            chunk_steps=4, registry=reg,
            prefix_cache=RadixPrefixCache(registry=reg),
            **extra,
        )

    engines = [make_engine() for _ in range(2)]
    canary_engines = []

    def factory():
        e = make_engine(fault_injector=fi)
        canary_engines.append(e)
        return e, params

    router = FleetRouter(
        [EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)],
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.0,
        ),
        registry=reg, flight=flight, tracer=tracer,
    )
    app = make_router_app(router, registry=reg)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"

    vreg = VersionRegistry(tmp_path / "versions")
    vreg.publish("good", {"w": np.zeros(2, np.float32)})
    vreg.publish("bad", {"w": np.ones(2, np.float32)})
    ctl = RolloutController(
        router, EngineReplicaProvisioner(factory), vreg,
        policy=RolloutPolicy(
            canary_replicas=1, canary_percent=0.0, shadow=True,
            shadow_queue=64, bake_evals=2, sustain_evals=2,
            warm_blocks=0, drain_timeout_s=60.0,
        ),
        params_loader=lambda v: {"good": params_good, "bad": params_bad}[v],
        registry=reg, flight=flight,
    )

    # the solo oracle's cache length must MATCH the engines' — a padded
    # -length mismatch reorders attention reductions and a near-tie
    # argmax flip would read as lost token parity
    oracle_len = engines[0].cache_len
    gen = make_generator(module, max_new_tokens=8, max_len=oracle_len)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 97, n).tolist() for n in (5, 8, 11, 14)]
    solo = {
        tuple(p): np.asarray(
            gen(params, jnp.asarray([p], jnp.int32))
        )[0].tolist()
        for p in prompts
    }
    # the bad weights genuinely change behavior, so the shadow diff has
    # a real signal to catch
    assert _solo(
        module, params_bad, prompts[0], 8, max_len=oracle_len
    ) != solo[tuple(prompts[0])]

    failures, results = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def flood(idx):
        j = 0
        while not stop.is_set():
            p = prompts[(idx + j) % len(prompts)]
            j += 1
            try:
                if j % 2:
                    r = httpx.post(
                        f"{base}/predict", json={"features": [p]},
                        timeout=120,
                    )
                    assert r.status_code == 200, r.text
                    assert r.headers["x-model-version"] == "auto"
                    toks = r.json()[0]
                else:
                    toks = []
                    with httpx.stream(
                        "POST", f"{base}/predict/stream",
                        json={"features": p}, timeout=120,
                    ) as resp:
                        assert resp.status_code == 200
                        # the SSE path echoes the version header too
                        assert resp.headers["x-model-version"] == "auto"
                        for line in resp.iter_lines():
                            if line.startswith("data: "):
                                ev = json.loads(line[len("data: "):])
                                if not ev.get("done"):
                                    toks.extend(ev["tokens"])
                with lock:
                    results.append((tuple(p), toks))
            except BaseException as exc:
                with lock:
                    failures.append(exc)
                return
            time.sleep(0.01)

    threads = [threading.Thread(target=flood, args=(i,)) for i in range(4)]
    deadline = time.monotonic() + 240
    try:
        for e in engines:
            e.warmup(params)
        for t in threads:
            t.start()

        # ---- phase A: the bad version rolls forward, shadows diverge,
        #      the controller auto-rolls back mid-flood
        ctl.start_rollout("bad")
        while (ctl.dashboard()["stage"] == "provisioning"
               and time.monotonic() < deadline):
            ctl.evaluate()
            time.sleep(0.02)
        assert ctl.dashboard()["stage"] == "baking"
        assert "canary-bad-0" in router.members()
        decision, last = None, 0
        while time.monotonic() < deadline:
            d = ctl.dashboard()["shadow"]["diverged"]
            if d > last:
                last = d
                decision = ctl.evaluate()
                if decision["decision"] == "rollout_rollback":
                    break
            time.sleep(0.02)
        assert decision is not None and (
            decision["decision"], decision["reason"],
        ) == ("rollout_rollback", "parity_regression")
        assert ctl.dashboard()["stage"] == "idle"
        assert set(router.members()) == {"r0", "r1"}

        # ---- phase B: the clean version bakes through a canary
        #      OOM-kill mid-shadow and promotes — zero downtime
        ctl.start_rollout("good")
        while (ctl.dashboard()["stage"] == "provisioning"
               and time.monotonic() < deadline):
            ctl.evaluate()
            time.sleep(0.02)
        assert ctl.dashboard()["stage"] == "baking"
        assert canary_engines[1].cache_len == oracle_len
        fi.arm("engine.dispatch", exc=xla_oom_error())
        _wait_for(
            lambda: ctl.dashboard()["shadow"]["error"] >= 1,
            timeout=120, what="OOM-killed shadow dispatch",
        )
        matched = ctl.dashboard()["shadow"]["match"]
        _wait_for(
            lambda: ctl.dashboard()["shadow"]["match"] > matched,
            timeout=120, what="shadow match after canary recovery",
        )
        while (ctl.dashboard()["stage"] != "idle"
               and time.monotonic() < deadline):
            ctl.evaluate()
            time.sleep(0.05)
        assert ctl.dashboard()["stage"] == "idle"
        assert router.live_version == "good"
        for i in range(2):
            assert router.replica_handle(f"r{i}").version == "good"

        # a hard pin on the promoted version routes (and echoes)
        r = httpx.post(
            f"{base}/predict", json={"features": [prompts[0]]},
            headers={"X-Model-Version": "good"}, timeout=120,
        )
        assert r.status_code == 200
        assert r.headers["x-model-version"] == "good"
        assert r.json()[0] == solo[tuple(prompts[0])]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=120)

    try:
        # zero caller-visible failures; every live token bit-identical
        assert not failures, f"caller-visible failures: {failures[:3]}"
        assert len(results) > 20
        for p, toks in results:
            assert toks == solo[p], (p, toks, solo[p])
        # the canary pool is reaped, its engines torn down, and the
        # lease ledgers everywhere are back to baseline
        assert set(router.members()) == {"r0", "r1"}
        assert len(canary_engines) == 2
        for e in engines:
            _wait_for(
                lambda e=e: _walk_refcounts(e.prefix_cache) == [],
                what="live leases released",
            )
        snap = reg.snapshot()
        assert snap["unionml_rollout_canary_replicas"] == {"": 0.0}
        # reconstructible: counters, the flight ring, /debug/rollout,
        # and a stitched per-request trace for a shadowed request
        decisions = snap["unionml_rollout_decisions_total"]
        for key in ("reason=parity_regression", "reason=complete",
                    "reason=canary_ready", "reason=promote_replica"):
            assert any(key in k for k in decisions), (key, decisions)
        dump = flight.dump()
        shadow_events = [e for e in dump if e["kind"] == "rollout_shadow"]
        assert shadow_events, "diverged shadows must land in the ring"
        fl = httpx.get(f"{base}/debug/flight", timeout=30).text
        assert "rollout_rollback" in fl and "rollout_advance" in fl
        dash = httpx.get(f"{base}/debug/rollout", timeout=30).json()
        assert dash["stage"] == "idle"
        assert dash["live_version"] == "good"
        assert dash["shadow"]["diverged"] >= 2
        assert any(
            h["reason"] == "parity_regression" for h in dash["history"]
        )
        rid = shadow_events[0]["rid"]
        tr = httpx.get(
            f"{base}/debug/trace?rid={rid}", timeout=30,
        ).text
        assert "shadow" in tr, "the shadow span must stitch under the rid"
    finally:
        ctl.close()
        app.shutdown()
        vreg.close()
        for e in engines + canary_engines:
            e.close()
