"""Disaggregated prefill/decode serving (docs/serving.md
"Disaggregated serving"): phase-split pools, the prefix-cache KV
handoff, cross-host transfer over /debug/kv/export ↔ /debug/kv/import,
degrade-never-error, per-pool operations — and THE chaos acceptance:
an engine-backed 1-prefill + 2-decode fleet over the stdlib transport
with the prefill replica killed between export and splice."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.autoscaler import (
    AutoscalerPolicy,
    FleetAutoscaler,
    ReplicaProvisioner,
)
from unionml_tpu.serving.disagg import DisaggRouter
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.prefix_cache import (
    RadixPrefixCache,
    decode_entries,
    encode_entries,
)
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
    RouterPolicy,
    make_router_app,
)
from unionml_tpu.serving.scheduler import validate_phase

pytestmark = pytest.mark.chaos

N_NEW = 12
BUCKET = 32


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _solo(module, params, prompt, n_new=N_NEW, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(
        gen(params, jnp.asarray([prompt], jnp.int32))
    )[0].tolist()


def _engine(module, reg, *, phase, cache=None, paged=False, **kw):
    if cache is None:
        cache = RadixPrefixCache(registry=reg)
    return DecodeEngine(
        module, slots=2, max_new_tokens=N_NEW, prompt_buckets=(BUCKET,),
        chunk_steps=4, prefix_cache=cache, phase=phase, registry=reg,
        paged=paged, **kw,
    )


def _disagg(replicas, reg=None, **kw):
    kw.setdefault("policy", RouterPolicy(
        health_ttl_s=0.0, backoff_base_s=0.0, jitter_s=0.0,
    ))
    kw.setdefault("registry", reg or telemetry.MetricsRegistry())
    kw.setdefault("flight", telemetry.FlightRecorder())
    return DisaggRouter(replicas, **kw)


def _collect(stream):
    return [t for chunk in stream for t in chunk]


def _walk_refcounts(cache):
    """Every node's live lease refcount — must be all-zero at rest."""
    bad = []
    stack = list(cache._root.children.values())
    while stack:
        node = stack.pop()
        stack.extend(node.children.values())
        if node.refcount != 0:
            bad.append((node.depth, node.refcount))
    return bad


# ----------------------------------------------------------- vocabulary


def test_phase_vocabulary_and_construction(tiny_llama):
    module, params = tiny_llama
    assert validate_phase(None) == "colocated"
    assert validate_phase("PREFILL") == "prefill"
    with pytest.raises(ValueError, match="phase"):
        validate_phase("warmup")
    with pytest.raises(ValueError, match="phase"):
        DecodeEngine(module, prompt_buckets=(8,), phase="warmup")
    # a prefill-only fleet cannot serve streams
    class _P(ReplicaHandle):
        name, phase = "p", "prefill"
    with pytest.raises(ValueError, match="decode-capable"):
        DisaggRouter([_P()], registry=telemetry.MetricsRegistry(),
                     flight=telemetry.FlightRecorder())
    with pytest.raises(ValueError, match="handoff_min_tokens"):
        DisaggRouter(
            [_P(), type("_D", (ReplicaHandle,), {"name": "d",
                                                 "phase": "decode"})()],
            handoff_min_tokens=0, registry=telemetry.MetricsRegistry(),
            flight=telemetry.FlightRecorder(),
        )


def test_engine_phase_surfaces(tiny_llama):
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    eng = _engine(module, reg, phase="prefill")
    try:
        assert eng.health()["phase"] == "prefill"
        assert eng.stats()["phase"] == "prefill"
        assert eng.stats()["scheduler"]["phase"] == "prefill"
        # EngineReplica inherits the engine's declaration
        rep = EngineReplica(eng, params, name="r0")
        assert rep.phase == "prefill"
        # explicit wins
        assert EngineReplica(eng, params, name="r1",
                             phase="colocated").phase == "colocated"
    finally:
        eng.close()
    # a colocated engine keeps the historical health shape
    reg2 = telemetry.MetricsRegistry()
    eng2 = _engine(module, reg2, phase=None)
    try:
        assert "phase" not in eng2.health()
    finally:
        eng2.close()


# ------------------------------------------------------- prefill export


def test_prefill_export_handle_and_lease(tiny_llama):
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    eng = _engine(module, reg, phase="prefill")
    prompt = list(range(1, 21))  # 20 tokens -> one full 16-block
    try:
        solo = _solo(module, params, prompt, max_len=eng.cache_len)
        handle = eng.prefill_export(params, prompt)
        assert handle["tokens"] == [solo[0]]
        blk = eng.prefix_cache.block_size
        full = (len(prompt) // blk) * blk
        assert handle["cached_tokens"] == full
        # the exported path is resident AND pinned until release
        assert eng.prefix_cache.peek(prompt) == full
        assert _walk_refcounts(eng.prefix_cache), (
            "the handle's lease must pin the exported path"
        )
        handle["lease"].release()
        handle["lease"].release()  # idempotent
        assert _walk_refcounts(eng.prefix_cache) == []
        # kv_export serves the same blocks as importable entries
        entries = eng.kv_export(prompt)
        assert len(entries) == full // blk
    finally:
        eng.close()


def test_prefill_export_requires_cache(tiny_llama):
    module, params = tiny_llama
    eng = DecodeEngine(
        module, slots=2, max_new_tokens=N_NEW, prompt_buckets=(BUCKET,),
        chunk_steps=4, registry=telemetry.MetricsRegistry(),
    )
    try:
        with pytest.raises(ValueError, match="prefix cache"):
            eng.prefill_export(params, [1, 2, 3])
        with pytest.raises(ValueError, match="prefix cache"):
            eng.kv_export([1, 2, 3])
        with pytest.raises(ValueError, match="prefix cache"):
            eng.kv_import([])
    finally:
        eng.close()


# -------------------------------------------------- two-leg dispatch


def test_two_leg_shared_store_parity(tiny_llama):
    """Same-host pools over ONE host block store: the handoff is a
    pointer handoff (result=shared), the decode admission splices the
    prefill leg's blocks, tokens are bit-identical to solo, and both
    legs' spans land under one routing rid."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    rec = telemetry.TraceRecorder()
    flight = telemetry.FlightRecorder()
    shared = RadixPrefixCache(registry=reg)
    pre = _engine(module, reg, phase="prefill", cache=shared, tracer=rec,
                  flight=flight)
    dec = _engine(module, reg, phase="decode", cache=shared, tracer=rec,
                  flight=flight)
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg, tracer=rec, flight=flight,
    )
    prompt = list(range(1, 21))
    try:
        solo = _solo(module, params, prompt, max_len=dec.cache_len)
        out = _collect(router.generate_stream(prompt))
        assert out == solo
        # the prefill engine served the 1-token leg; the decode engine
        # spliced instead of re-prefilling
        assert pre.stats()["completed_requests"] == 1
        assert dec.stats()["prefix_cache"]["prefill_tokens_saved"] > 0
        snap = reg.snapshot()
        assert snap["unionml_disagg_handoffs_total"] == {
            "result=shared": 1.0
        }
        assert snap["unionml_disagg_requests_total"] == {
            "path=two_leg": 1.0
        }
        # both legs under ONE routing rid: handoff event names both
        # pools, and the stitched trace holds the three joining spans
        handoffs = flight.dump(kind="handoff")
        assert len(handoffs) == 1
        rid = handoffs[0]["rid"]
        assert handoffs[0]["phases"] == ["prefill", "decode"]
        trace_id = rec.find_trace_id(rid)
        doc = telemetry.stitched_trace(
            trace_id, rec.requests_for_trace(trace_id),
        )
        names = {s["name"] for s in doc["spans"]}
        assert {"prefill-leg", "handoff", "decode-leg"} <= names, names
        # the engine legs' own spans joined the same trace
        assert "prefill" in names
        # no leaked pins anywhere
        assert _walk_refcounts(shared) == []
        # blocking surface rides the same pipeline
        assert router.generate(prompt) == solo
    finally:
        pre.close()
        dec.close()


def test_short_prompt_stays_single_leg(tiny_llama):
    """Below handoff_min_tokens the prefill pool is bypassed entirely
    — colocated still wins for short prompts, and the decode pool
    (freed of long prefills) serves them directly."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg, handoff_min_tokens=16,
    )
    prompt = [1, 2, 3, 4, 5]
    try:
        assert _collect(router.generate_stream(prompt)) == _solo(
            module, params, prompt, max_len=dec.cache_len,
        )
        assert pre.stats()["completed_requests"] == 0
        assert dec.stats()["completed_requests"] == 1
        assert reg.snapshot()["unionml_disagg_requests_total"] == {
            "path=single_leg": 1.0
        }
    finally:
        pre.close()
        dec.close()


def test_cross_store_transfer_warms_decode(tiny_llama):
    """Distinct host stores (the cross-process shape): the prefill
    leg's blocks transfer into the decode replica's store before its
    dispatch, so the decode admission still splices instead of
    recomputing — and the transferred bytes are the same pointers
    in-process (no copy)."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    prompt = list(range(1, 21))
    try:
        assert _collect(router.generate_stream(prompt)) == _solo(
            module, params, prompt, max_len=dec.cache_len,
        )
        assert dec.stats()["prefix_cache"]["prefill_tokens_saved"] > 0
        snap = reg.snapshot()
        assert snap["unionml_disagg_handoffs_total"] == {
            "result=transfer": 1.0
        }
        assert snap["unionml_disagg_kv_blocks_transferred_total"][""] >= 1
        assert _walk_refcounts(pre.prefix_cache) == []
        assert _walk_refcounts(dec.prefix_cache) == []
    finally:
        pre.close()
        dec.close()


def test_transfer_disabled_decodes_cold(tiny_llama):
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg, transfer=False,
    )
    prompt = list(range(1, 21))
    try:
        assert _collect(router.generate_stream(prompt)) == _solo(
            module, params, prompt, max_len=dec.cache_len,
        )
        assert reg.snapshot()["unionml_disagg_handoffs_total"] == {
            "result=skipped": 1.0
        }
        # cold decode: the decode engine prefilled the prompt itself
        assert dec.stats()["prefix_cache"]["prefill_tokens_saved"] == 0
    finally:
        pre.close()
        dec.close()


def test_early_close_releases_the_handoff_lease(tiny_llama):
    """A caller abandoning the stream right after its TTFT chunk —
    GeneratorExit at the first yield, before the decode leg ever ran —
    must still release the prefill leg's lease (the code-review
    regression: the finally used to see only the post-loop handle)."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    shared = RadixPrefixCache(registry=reg)
    pre = _engine(module, reg, phase="prefill", cache=shared)
    dec = _engine(module, reg, phase="decode", cache=shared)
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    prompt = list(range(1, 21))
    try:
        stream = router.generate_stream(prompt)
        first = next(iter(stream))
        assert len(first) == 1
        stream.close()  # client disconnected after the TTFT token
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and _walk_refcounts(shared):
            time.sleep(0.02)
        assert _walk_refcounts(shared) == [], (
            "abandoning the stream after the prefill leg leaked the "
            "handoff lease"
        )
    finally:
        pre.close()
        dec.close()


def test_caller_faults_surface_instead_of_degrading(tiny_llama):
    """Deterministic caller faults (bad request, expired deadline)
    from the prefill leg must SURFACE — a second dispatch is doomed
    work wearing a 'degraded' label; only infra-class failures
    degrade (incl. a misconfigured cache-less prefill replica)."""
    from unionml_tpu.serving.faults import DeadlineExceeded

    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    prompt = list(range(1, 21))
    try:
        p0 = router.replica_handle("p0")
        p0.prefill_export = lambda *a, **k: (_ for _ in ()).throw(
            DeadlineExceeded("expired while queued")
        )
        with pytest.raises(DeadlineExceeded):
            _collect(router.generate_stream(prompt))
        # the doomed decode dispatch never happened
        assert dec.stats()["completed_requests"] == 0
        snap = reg.snapshot()
        assert "path=degraded" not in snap.get(
            "unionml_disagg_requests_total", {}
        )
    finally:
        pre.close()
        dec.close()
    # a cache-less prefill replica is a POOL misconfiguration: the
    # EngineReplica hook speaks the infra vocabulary, so the request
    # degrades to a cold decode prefill instead of erroring
    reg2 = telemetry.MetricsRegistry()
    bare = DecodeEngine(
        module, slots=2, max_new_tokens=N_NEW, prompt_buckets=(BUCKET,),
        chunk_steps=4, registry=reg2, phase="prefill",
    )
    dec2 = _engine(module, reg2, phase="decode")
    router2 = _disagg(
        [EngineReplica(bare, params, name="p0"),
         EngineReplica(dec2, params, name="d0")],
        reg=reg2,
    )
    try:
        assert _collect(router2.generate_stream(prompt)) == _solo(
            module, params, prompt, max_len=dec2.cache_len,
        )
        assert reg2.snapshot()["unionml_disagg_requests_total"] == {
            "path=degraded": 1.0
        }
    finally:
        bare.close()
        dec2.close()


def test_dead_prefill_pool_degrades_not_errors(tiny_llama):
    """The prefill leg exhausting its whole retry envelope is NOT a
    caller-visible failure: the decode pool prefills cold, tokens
    identical."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    prompt = list(range(1, 21))
    try:
        router.replica_handle("p0").prefill_export = (
            lambda *a, **k: (_ for _ in ()).throw(
                RuntimeError("prefill replica dead")
            )
        )
        assert _collect(router.generate_stream(prompt)) == _solo(
            module, params, prompt, max_len=dec.cache_len,
        )
        snap = reg.snapshot()
        assert snap["unionml_disagg_requests_total"] == {
            "path=degraded": 1.0
        }
        degrade = [
            e for e in router._flight.dump(kind="handoff")
            if e.get("degraded")
        ]
        assert degrade and degrade[0]["result"] == "cold"
    finally:
        pre.close()
        dec.close()


def test_token_cap_rides_the_two_leg_pipeline(tiny_llama):
    """max_new_tokens caps BOTH legs consistently; a 1-token request
    is answered by the prefill leg alone."""
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    prompt = list(range(1, 21))
    try:
        solo = _solo(module, params, prompt, max_len=dec.cache_len)
        assert router.generate(prompt, max_new_tokens=3) == solo[:3]
        assert router.generate(prompt, max_new_tokens=1) == solo[:1]
        # the 1-token request never touched the decode pool
        assert dec.stats()["completed_requests"] == 1  # the 3-token one
    finally:
        pre.close()
        dec.close()


# --------------------------------------------- HTTP transport surfaces


def _lm_app(engine, params, module):
    """An engine-backed ServingApp with the full disagg wiring (the
    test_serving _lm_serving_app pattern + kv hooks)."""
    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    dataset = Dataset(name=f"d_{id(engine)}", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    lm = Model(name=f"m_{id(engine)}", init=lambda: params,
               dataset=dataset)

    @lm.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @lm.predictor
    def predictor(p: dict, prompts: list) -> list:
        return engine.generate(p, prompts)

    lm.artifact = ModelArtifact(params, {}, {})
    return ServingApp(
        lm,
        stats=engine.stats, health=engine.health, drain=engine.drain,
        stream=lambda p, prompts: engine.generate_stream(p, prompts[0]),
        cache_peek=engine.prefix_cache.peek,
        kv_export=engine.kv_export, kv_import=engine.kv_import,
        registry=engine.registry, tracer=engine.tracer,
        flight=engine.flight,
    )


def test_max_new_tokens_survives_the_http_hop(tiny_llama):
    """Satellite contract: the cap rides the /predict payload on the
    stdlib transport and HttpReplica forwards it — remote responses
    honor the caller's cap exactly (token parity with the solo
    prefix)."""
    httpx = pytest.importorskip("httpx")
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    eng = _engine(module, reg, phase=None, tracer=telemetry.TraceRecorder(),
                  flight=telemetry.FlightRecorder())
    app = _lm_app(eng, params, module)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    prompt = list(range(1, 9))
    try:
        solo = _solo(module, params, prompt, max_len=eng.cache_len)
        remote = HttpReplica(base, name="r")
        assert remote.generate(prompt, max_new_tokens=3) == solo[:3]
        assert _collect(
            remote.generate_stream(prompt, max_new_tokens=5)
        ) == solo[:5]
        # raw payload field on both routes
        resp = httpx.post(
            f"{base}/predict",
            json={"features": [prompt], "max_new_tokens": 4}, timeout=120,
        )
        assert resp.status_code == 200 and resp.json() == [solo[:4]]
        # the PUBLIC predict_stream surface honors the cap standalone
        # (its wrapper covers the lazy generator's first pull — the
        # code-review regression: the old scope closed before the
        # engine body ever ran)
        out = _collect(app.predict_stream(
            {"features": prompt, "max_new_tokens": 3}
        ))
        assert out == solo[:3], out
        # garbage caps answer 422 at the boundary
        for bad in ("nope", 0, -3, 1.5, True):
            resp = httpx.post(
                f"{base}/predict",
                json={"features": [prompt], "max_new_tokens": bad},
                timeout=30,
            )
            assert resp.status_code == 422, (bad, resp.status_code)
    finally:
        app.shutdown()
        eng.close()


def test_kv_export_import_http_roundtrip(tiny_llama):
    """The cross-host handoff wire: blocks exported from host A over
    POST /debug/kv/export import into host B over POST
    /debug/kv/import, numerically identical, after which B's peek
    covers the prompt; unwired apps answer 422."""
    httpx = pytest.importorskip("httpx")
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    a = _engine(module, reg, phase="prefill",
                tracer=telemetry.TraceRecorder(),
                flight=telemetry.FlightRecorder())
    b = _engine(module, reg, phase="decode",
                tracer=telemetry.TraceRecorder(),
                flight=telemetry.FlightRecorder())
    app_a = _lm_app(a, params, module)
    app_b = _lm_app(b, params, module)
    ha, pa = app_a.serve(port=0, blocking=False)
    hb, pb = app_b.serve(port=0, blocking=False)
    prompt = list(range(1, 21))
    try:
        a.prefill_export(params, prompt)["lease"].release()
        ra = HttpReplica(f"http://{ha}:{pa}", name="a", phase="prefill")
        rb = HttpReplica(f"http://{hb}:{pb}", name="b", phase="decode")
        entries = ra.export_request_blocks(prompt)
        assert entries, "export must cover the prefilled prompt"
        # wire codec round-trips bit-exactly (bf16 KV included)
        for orig, back in zip(
            a.kv_export(prompt), decode_entries(encode_entries(entries)),
        ):
            assert np.array_equal(orig["tokens"], back["tokens"])
            for lo, lb in zip(orig["rows"], back["rows"]):
                for bo, bb in zip(lo, lb):
                    assert np.asarray(bo).dtype == np.asarray(bb).dtype
                    assert np.array_equal(np.asarray(bo), np.asarray(bb))
        attached = rb.import_cache_blocks(entries)
        assert attached == len(entries)
        blk = b.prefix_cache.block_size
        assert b.prefix_cache.peek(
            a._canonical_row(prompt)
        ) == (len(prompt) // blk) * blk
        # unwired surfaces: 422, not 500
        resp = httpx.post(
            f"http://{ha}:{pa}/debug/kv/export", json={"prompt": []},
            timeout=30,
        )
        assert resp.status_code == 422
        resp = httpx.post(
            f"http://{ha}:{pa}/debug/kv/import", json={"entries": "x"},
            timeout=30,
        )
        assert resp.status_code == 422
    finally:
        app_a.shutdown()
        app_b.shutdown()
        a.close()
        b.close()


# --------------------------------------------- per-pool fleet surfaces


def test_fleet_report_and_flight_carry_phase(tiny_llama):
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    pre = _engine(module, reg, phase="prefill")
    dec = _engine(module, reg, phase="decode")
    router = _disagg(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        reg=reg,
    )
    app = make_router_app(router, registry=reg)
    prompt = list(range(1, 21))
    try:
        _collect(router.generate_stream(prompt))
        report = app.debug_fleet()
        assert report["replicas"]["p0"]["phase"] == "prefill"
        assert report["replicas"]["d0"]["phase"] == "decode"
        assert report["phases"]["prefill"]["replicas"] == 1
        assert report["phases"]["decode"]["routable"] == 1
        # pool gauges track membership
        snap = reg.snapshot()
        assert snap["unionml_disagg_pool_replicas"]["phase=prefill"] == 1.0
        assert snap["unionml_disagg_pool_replicas"]["phase=decode"] == 1.0
        # /debug/flight?phase= isolates one pool; handoff matches both
        body = app.debug_flight(phase="prefill")
        kinds = {e["kind"] for e in body["events"]}
        assert "handoff" in kinds
        assert all(
            e.get("phase") == "prefill"
            or "prefill" in e.get("phases", ())
            for e in body["events"]
        )
        decode_body = app.debug_flight(phase="decode")
        assert any(
            e["kind"] == "prefill" and e.get("phase") == "decode"
            for e in decode_body["events"]
        ), "the decode engine's lifecycle events carry its pool tag"
    finally:
        pre.close()
        dec.close()


def test_usage_vector_splits_by_phase(tiny_llama):
    module, params = tiny_llama
    reg = telemetry.MetricsRegistry()
    eng = _engine(module, reg, phase="decode", usage=True)
    try:
        eng.generate(params, [[1, 2, 3, 4]], tenant="acme")
        vec = eng.usage.report()["tenants"]["acme"]
        assert vec["requests_by_phase"] == {"decode": 1}
    finally:
        eng.close()


class _PoolStub(ReplicaHandle):
    def __init__(self, name, phase, queue_depth=0, blocks=0):
        self.name = name
        self.phase = phase
        self._qd = queue_depth
        self._blocks = blocks

    def health(self):
        return {"status": "ok", "queue_depth": self._qd}

    def cache_blocks(self):
        return self._blocks


class _StubProvisioner(ReplicaProvisioner):
    def __init__(self):
        self.provisioned = []
        self.released = []

    def provision(self, name):
        handle = _PoolStub(name, "colocated")
        self.provisioned.append(handle)
        return handle

    def release(self, handle):
        self.released.append(handle.name)


def test_autoscaler_scales_one_pool(tiny_llama):
    """FleetAutoscaler(phase=...) observes its pool (shared colocated
    members included — they serve either leg), acts only on owned
    exact-phase members: repair counts pool capacity, the joiner is
    stamped with the pool's phase, scale-in victims never cross pools
    or drain shared colocated replicas, and both pool autoscalers
    register on the router for the dashboard."""
    clock_t = [1000.0]
    router = FleetRouter(
        [_PoolStub("p0", "prefill", blocks=0),
         _PoolStub("c0", "colocated", blocks=0),  # coldest of all
         _PoolStub("d0", "decode", blocks=5),
         _PoolStub("d1", "decode", blocks=9)],
        policy=RouterPolicy(health_ttl_s=0.0, min_live=1),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        clock=lambda: clock_t[0],
    )
    prov = _StubProvisioner()
    reg = telemetry.MetricsRegistry()
    auto_d = FleetAutoscaler(
        router, prov,
        policy=AutoscalerPolicy(min_replicas=4, max_replicas=5,
                                cooldown_in_s=0.0),
        registry=reg, flight=telemetry.FlightRecorder(),
        clock=lambda: clock_t[0], phase="decode",
    )
    auto_p = FleetAutoscaler(
        router, prov,
        # floor 2: the pool's capacity INCLUDES the shared colocated
        # member, so p0 + c0 sits exactly at the floor — steady
        policy=AutoscalerPolicy(min_replicas=2, max_replicas=3),
        registry=reg, flight=telemetry.FlightRecorder(),
        clock=lambda: clock_t[0], phase="prefill",
    )
    # pool registration: both visible for the dashboard
    assert set(router.autoscalers) == {"prefill", "decode"}
    # the decode pool counts d0 + d1 + the shared c0 = 3 < 4: repair —
    # and the joiner is phase-stamped + pool-named
    out = auto_d.evaluate()
    assert (out["decision"], out["reason"]) == ("scale_out", "below_min")
    assert out["live"] == 3  # colocated capacity observed
    joiner = router.members()[out["replica"]]
    assert joiner.phase == "decode"
    assert out["replica"].startswith("auto-decode-")
    # the prefill pool reads its OWN capacity (p0 + shared c0): steady
    out = auto_p.evaluate()
    assert (out["decision"], out["reason"]) == ("scale_hold", "steady")
    assert auto_p.dashboard()["phase"] == "prefill"
    # decode scale-in (idle: no ledger, empty queues) drains the
    # coldest OWNED decode replica — never p0, and never the shared
    # colocated c0 even though it is the globally coldest cache
    auto_d.policy.min_replicas = 2  # the repaired pool (4) has surplus
    clock_t[0] += 1.0
    out = auto_d.evaluate()
    assert (out["decision"], out["reason"]) == ("scale_in", "idle")
    assert out["replica"] not in ("p0", "c0")
    assert "p0" in router.members() and "c0" in router.members()
    # a pool whose only drainable capacity is SHARED colocated holds
    # with no_pool_victim instead of stealing it from the peer pool
    router2 = FleetRouter(
        [_PoolStub("c0", "colocated"), _PoolStub("c1", "colocated")],
        policy=RouterPolicy(health_ttl_s=0.0, min_live=1),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        clock=lambda: clock_t[0],
    )
    auto2 = FleetAutoscaler(
        router2, _StubProvisioner(),
        policy=AutoscalerPolicy(min_replicas=1, max_replicas=2,
                                cooldown_in_s=0.0),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        clock=lambda: clock_t[0], phase="prefill",
    )
    out = auto2.evaluate()
    assert (out["decision"], out["reason"]) == (
        "scale_hold", "no_pool_victim",
    )
    assert set(router2.members()) == {"c0", "c1"}


# ------------------------------------------------- THE chaos acceptance


class _KillAfterExport(HttpReplica):
    """The deterministic chaos window: the prefill replica dies AFTER
    its prefill leg exported (the handle exists, the KV sits in the
    dead process's store) and BEFORE the decode leg splices."""

    def __init__(self, *args, kill=None, kill_on_call=1, **kwargs):
        super().__init__(*args, **kwargs)
        self._kill = kill
        self._calls = 0
        self._kill_on_call = kill_on_call

    def prefill_export(self, prompt, *, max_new_tokens=None):
        handle = super().prefill_export(
            prompt, max_new_tokens=max_new_tokens,
        )
        self._calls += 1
        if self._calls == self._kill_on_call and self._kill is not None:
            kill, self._kill = self._kill, None
            kill()  # between export and splice
        return handle


def test_disagg_chaos_prefill_killed_between_export_and_splice(tiny_llama):
    """THE acceptance (ISSUE 15): engine-backed 1-prefill + 2-decode
    fleet over the stdlib transport; the prefill replica is OOM-killed
    between one request's KV export and its decode-side splice. Zero
    caller-visible failures, every completion bit-identical to the
    colocated solo oracle, no leaked PrefixLease refcounts or pool
    blocks, and GET /debug/trace?rid= stitches both legs under one
    trace."""
    httpx = pytest.importorskip("httpx")
    from unionml_tpu.serving.faults import FaultInjector, xla_oom_error

    module, params = tiny_llama
    fi = FaultInjector()
    engines, apps, bases = [], [], []
    for i, phase in enumerate(["prefill", "decode", "decode"]):
        reg = telemetry.MetricsRegistry()
        eng = _engine(
            module, reg, phase=phase, paged=True,
            tracer=telemetry.TraceRecorder(),
            flight=telemetry.FlightRecorder(),
            **({"fault_injector": fi} if phase == "prefill" else {}),
        )
        app = _lm_app(eng, params, module)
        host, port = app.serve(port=0, blocking=False)
        engines.append(eng)
        apps.append(app)
        bases.append(f"http://{host}:{port}")
    pre = engines[0]

    def kill_prefill():
        # OOM-poison the prefill engine's next device dispatch and take
        # the whole process off the network — the dead-process shape
        # the fleet tier is built for
        fi.arm("engine.prefill", exc=xla_oom_error())
        apps[0].shutdown()

    replicas = [
        _KillAfterExport(bases[0], name="p0", phase="prefill",
                         kill=kill_prefill, kill_on_call=2,
                         obs_timeout_s=2.0),
        HttpReplica(bases[1], name="d0", phase="decode"),
        HttpReplica(bases[2], name="d1", phase="decode"),
    ]
    router = _disagg(replicas, policy=RouterPolicy(
        health_ttl_s=0.0, backoff_base_s=0.001, jitter_s=0.0,
    ))
    front = make_router_app(router, registry=router._registry)
    fhost, fport = front.serve(port=0, blocking=False)
    fbase = f"http://{fhost}:{fport}"

    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(1, 97, 20).tolist() for _ in range(6)
    ]
    try:
        solo = {
            tuple(p): _solo(
                module, params, p, max_len=engines[0].cache_len,
            ) for p in prompts
        }

        def sse(prompt):
            out, rid = [], None
            with httpx.stream(
                "POST", f"{fbase}/predict/stream",
                json={"features": prompt}, timeout=240,
            ) as resp:
                assert resp.status_code == 200
                rid = resp.headers.get("x-request-id")
                for line in resp.iter_lines():
                    if line.startswith("data: "):
                        import json as _json

                        event = _json.loads(line[len("data: "):])
                        if not event.get("done"):
                            out.extend(event["tokens"])
            return out, rid

        # request 0: the full cross-host path works (export → wire →
        # import → splice) BEFORE the kill
        out0, _ = sse(prompts[0])
        assert out0 == solo[tuple(prompts[0])]
        handoffs = router._flight.dump(kind="handoff")
        assert handoffs and handoffs[-1]["result"] == "transfer"

        # request 1: the prefill replica dies between export and
        # splice — the transfer fails against the dead host, the
        # decode leg prefills cold, the caller sees nothing
        kill_rid = None
        out1, kill_rid = sse(prompts[1])
        assert out1 == solo[tuple(prompts[1])]
        handoffs = router._flight.dump(kind="handoff")
        assert handoffs[-1]["result"] == "cold"

        # the rest of the flood (concurrent): prefill pool is gone —
        # requests degrade to the decode pool, ZERO failures
        results, failures, lock = [], [], threading.Lock()

        def client(ps):
            for p in ps:
                try:
                    out, _ = sse(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(prompts[2:][i::2],))
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "clients hung"
        assert not failures, failures
        assert all(out == solo[key] for key, out in results), (
            "token parity lost after the prefill-pool death"
        )

        # stitched trace: the killed-window request's BOTH legs under
        # one trace — prefill-leg (served by p0 before it died),
        # handoff, decode-leg, with attempts on both pools
        doc = httpx.get(
            f"{fbase}/debug/trace?rid={kill_rid}", timeout=30,
        ).json()
        names = {s["name"] for s in doc["spans"]}
        assert {"prefill-leg", "handoff", "decode-leg"} <= names, names
        attempt_replicas = {
            s.get("replica")
            for s in doc["spans"] if s["name"] == "attempt"
        }
        assert "p0" in attempt_replicas
        assert attempt_replicas & {"d0", "d1"}
        assert doc["trace_id"]

        # resource hygiene on the survivors: no leaked lease refcounts,
        # every pool block back
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            stats = [e.kv_pool.stats() for e in engines[1:]]
            if all(
                s["blocks_in_use"] == 0 and s["blocks_reserved"] == 0
                for s in stats
            ):
                break
            time.sleep(0.05)
        for eng in engines[1:]:
            s = eng.kv_pool.stats()
            assert s["blocks_in_use"] == 0, s
            assert s["blocks_reserved"] == 0, s
            assert _walk_refcounts(eng.prefix_cache) == [], eng.instance
        # the kill actually fired as an OOM arm + dead process
        assert router._flight.dump(kind="handoff")
    finally:
        front.shutdown()
        for app in apps[1:]:
            app.shutdown()
        for eng in engines:
            eng.close()
