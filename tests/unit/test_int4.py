"""Packed-int4 weight-only serving (ops/int4_matmul.py +
Int4DenseGeneral): pack/unpack round trip, matmul correctness on both
code paths (Pallas decode shape + XLA fallback), quantize_params bits=4
tree conversion, and the end-to-end tiny-Llama generation surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.models import Llama, LlamaConfig, make_generator
from unionml_tpu.models.quantization import (
    LLAMA_QUANT_PATTERNS,
    quantize_params,
)
from unionml_tpu.ops.int4_matmul import (
    MAX_PALLAS_ROWS,
    int4_matmul,
    pack_int4,
    quantize_kernel_int4,
    tile_for,
    unpack_int4,
)


def int4_cfg(**over):
    """A tiny config whose widths all pack (even N everywhere)."""
    kwargs = dict(
        vocab_size=512, hidden_dim=64, num_layers=2, num_heads=4,
        num_kv_heads=2, mlp_dim=128, max_len=256, rope_theta=10_000.0,
        quantized=True, weight_bits=4,
    )
    kwargs.update(over)
    return LlamaConfig(**kwargs)


def test_tile_selection():
    from unionml_tpu.ops.int4_matmul import _grid_for

    assert _grid_for(14336, 4096) == (512, 4096)   # gate/up: fits unblocked
    assert _grid_for(4096, 14336) == (512, 3584)   # down: K-blocked
    assert tile_for(128256, 4096) in (512, 256)    # lm_head
    assert tile_for(128, 64) == 128                # single-tile small widths
    assert tile_for(97, 64) == 0                   # odd cannot pack


@pytest.mark.parametrize("n,tile", [(512, 512), (1024, 512), (128, 128)])
def test_pack_unpack_roundtrip(n, tile):
    rng = np.random.default_rng(0)
    nib = jnp.asarray(rng.integers(-8, 8, size=(32, n)), jnp.int8)
    packed = pack_int4(nib, tile)
    assert packed.shape == (32, n // 2) and packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed, tile)), np.asarray(nib))


@pytest.mark.parametrize("rows", [1, 8, MAX_PALLAS_ROWS + 1])
def test_int4_matmul_matches_dequant_reference(rows):
    """Pallas path (rows <= MAX) and XLA fallback (rows > MAX) agree
    with the dequantized reference."""
    rng = np.random.default_rng(1)
    k, n = 64, 512
    w = rng.normal(size=(k, n)).astype(np.float32)
    packed, scale = quantize_kernel_int4(jnp.asarray(w), 512)
    x = jnp.asarray(rng.normal(size=(rows, k)), jnp.bfloat16)
    got = np.asarray(
        int4_matmul(x, packed, scale, tile_n=512, dtype=jnp.float32)
    )
    wdq = np.asarray(unpack_int4(packed, 512), np.float32) * np.asarray(scale)
    want = np.asarray(x, np.float32) @ wdq
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_quantize_params_bits4_structure_and_fallback():
    cfg = int4_cfg()
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False, "weight_bits": 8})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    attn_q = q4["block_0"]["attn"]["q"]
    assert set(attn_q) == {"kernel_p", "scale"}
    assert attn_q["kernel_p"].dtype == jnp.int8
    # packed width is half the true width (q: heads*hd = 64 -> 32)
    assert attn_q["kernel_p"].shape == (64, 32)
    assert q4["lm_head"]["kernel_p"].shape == (64, 256)
    # an odd-width layer stays int8 (fallback, not an error)
    odd = {"mlp": {"down": {"kernel": jnp.ones((10, 7), jnp.float32)}}}
    q_odd = quantize_params(odd, (r"mlp/(gate|up|down)$",), bits=4)
    assert "kernel_q" in q_odd["mlp"]["down"]


def test_int4_llama_generates_and_tracks_fp(tmp_path=None):
    """The int4 tree loads into the weight_bits=4 module and greedy
    generation runs; logits stay close to the dequantized-int8 scale of
    agreement (4-bit is lossy — the contract is the pipeline, not
    bit-parity with fp)."""
    cfg = int4_cfg()
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False, "weight_bits": 8})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    module = Llama(cfg)
    prompt = jnp.asarray([[5, 3, 9, 2]], jnp.int32)
    logits = module.apply({"params": q4}, prompt)
    assert logits.shape == (1, 4, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    gen = make_generator(module, max_new_tokens=6, max_len=32)
    out = np.asarray(gen(q4, prompt))
    assert out.shape == (1, 6)
    # int8 and int4 trees of the same weights should broadly agree on
    # next-token ranking at this scale (loose: top-1 of >= half the
    # positions match the int8 tree's)
    q8 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=8)
    cfg8 = LlamaConfig(**{**cfg.__dict__, "weight_bits": 8})
    logits8 = Llama(cfg8).apply({"params": q8}, prompt)
    agree = (np.asarray(logits).argmax(-1) == np.asarray(logits8).argmax(-1)).mean()
    assert agree >= 0.5, f"int4/int8 top-1 agreement {agree}"


def test_lora_with_int4_is_loud():
    cfg = int4_cfg(lora_rank=4)
    with pytest.raises(AssertionError, match="weight_bits=8"):
        Llama(cfg).init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))


def test_int4_tp_compatibility_guard():
    from unionml_tpu.models.llama import assert_int4_tp_compatible

    cfg8b = LlamaConfig(quantized=True, weight_bits=4)
    assert_int4_tp_compatible(cfg8b, 2)   # 8B shards cleanly at tp=2
    with pytest.raises(ValueError, match="packing tile"):
        # the 1024-channel k/v projections (tile 512) split at tp=4
        assert_int4_tp_compatible(cfg8b, 4)
    # int8 configs are never constrained
    assert_int4_tp_compatible(LlamaConfig(quantized=True), 8)


def test_int4_untileable_layer_falls_back_to_int8_module():
    """A mixed int4/int8 tree (odd vocab stays int8 in quantize_params)
    loads into the weight_bits=4 module — the module mirrors the
    per-layer fallback."""
    cfg = int4_cfg(vocab_size=97)   # odd vocab: lm_head cannot pack
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False, "weight_bits": 8})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    assert "kernel_q" in q4["lm_head"]          # fallback artifact...
    assert "kernel_p" in q4["block_0"]["attn"]["q"]
    logits = Llama(cfg).apply({"params": q4}, jnp.zeros((1, 4), jnp.int32))
    assert logits.shape == (1, 4, 97)           # ...and it loads/runs


def test_int4_engine_matches_generator():
    """The continuous-batching engine serves int4 trees (slot-decode rows
    hit the kernel's decode path) token-identically to the solo
    generator."""
    from unionml_tpu.serving.engine import DecodeEngine

    cfg = int4_cfg()
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False, "weight_bits": 8})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    module = Llama(cfg)
    prompt = [7, 3, 9, 2, 5]
    gen = make_generator(module, max_new_tokens=6, max_len=64)
    want = np.asarray(gen(q4, jnp.asarray([prompt], jnp.int32)))[0].tolist()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,), chunk_steps=3
    )
    try:
        got = engine.generate(q4, [prompt])[0]
    finally:
        engine.close()
    assert got == want


def test_streamed_int4_checkpoint_matches_quantize_params(tmp_path):
    """load_llama_checkpoint(quantize=True) with weight_bits=4 streams
    straight to the packed layout, bit-identical to the in-memory
    quantize_params(bits=4) over a direct load."""
    from unionml_tpu.models.convert import (
        export_llama_safetensors,
        load_llama_checkpoint,
    )

    fp_cfg = LlamaConfig.tiny(dtype="float32")
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    export_llama_safetensors(params, fp_cfg, str(tmp_path))
    streamed, cfg = load_llama_checkpoint(
        str(tmp_path), quantize=True, quantized=True, weight_bits=4,
    )
    assert cfg.weight_bits == 4
    direct, _ = load_llama_checkpoint(str(tmp_path), fp_cfg, dtype=jnp.float32)
    reference = quantize_params(direct, LLAMA_QUANT_PATTERNS, bits=4)
    q_attn = streamed["block_0"]["attn"]["q"]
    assert set(q_attn) == {"kernel_p", "scale"}
    np.testing.assert_array_equal(
        np.asarray(q_attn["kernel_p"]),
        np.asarray(reference["block_0"]["attn"]["q"]["kernel_p"]),
    )
    np.testing.assert_array_equal(
        np.asarray(streamed["lm_head"]["kernel_p"]),
        np.asarray(reference["lm_head"]["kernel_p"]),
    )
    # and the streamed tree serves through the weight_bits=4 module
    logits = Llama(cfg).apply({"params": streamed}, jnp.zeros((1, 4), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("rows", [1, 8, MAX_PALLAS_ROWS + 1])
@pytest.mark.parametrize("group", [16, 32, 64])
def test_grouped_int4_matmul_matches_dequant_reference(rows, group):
    """Group-wise scales on both code paths (grouped Pallas kernel at
    decode rows, fp32-dequant XLA fallback above) agree with the
    per-group dequantized reference."""
    rng = np.random.default_rng(2)
    k, n = 64, 512
    w = rng.normal(size=(k, n)).astype(np.float32)
    packed, scale = quantize_kernel_int4(jnp.asarray(w), 512, group_size=group)
    assert scale.shape == (k // group, n)
    x = jnp.asarray(rng.normal(size=(rows, k)), jnp.bfloat16)
    got = np.asarray(
        int4_matmul(
            x, packed, scale, tile_n=512, dtype=jnp.float32, group_size=group
        )
    )
    wdq = np.asarray(unpack_int4(packed, 512), np.float32) * np.repeat(
        np.asarray(scale), group, axis=0
    )
    want = np.asarray(x, np.float32) @ wdq
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_grouped_scales_improve_outlier_quality():
    """The quality argument in one number: with a single outlier row,
    per-channel absmax poisons the whole column's resolution while
    group-wise contains it — reconstruction error must drop (one
    16-row group of 128 poisoned instead of every row: ~8x)."""
    rng = np.random.default_rng(3)
    k, n = 128, 512
    w = rng.normal(size=(k, n)).astype(np.float32) * 0.02
    w[7] *= 100.0                                   # one outlier K-row
    pc_packed, pc_scale = quantize_kernel_int4(jnp.asarray(w), 512)
    g_packed, g_scale = quantize_kernel_int4(jnp.asarray(w), 512, group_size=16)
    dq_pc = np.asarray(unpack_int4(pc_packed, 512), np.float32) * np.asarray(pc_scale)
    dq_g = np.asarray(unpack_int4(g_packed, 512), np.float32) * np.repeat(
        np.asarray(g_scale), 16, axis=0
    )
    mask = np.ones(k, bool)
    mask[7] = False                                 # error on the NORMAL rows
    err_pc = np.abs(dq_pc[mask] - w[mask]).mean()
    err_g = np.abs(dq_g[mask] - w[mask]).mean()
    assert err_g < err_pc / 4, (err_pc, err_g)


def test_tile_selection_with_tp_shards():
    """The shard-aware tile rule: tiles divide the PER-DEVICE width."""
    # 8B k/v (N=1024): tp=4 -> 256-per-device -> no 512 tile; 128 fits...
    assert tile_for(1024, 4096, shards=4) == 256
    assert tile_for(1024, 4096, shards=8) == 128
    # gate/up 14336: 1792 per device at tp=8 -> 7 tiles of 256
    assert tile_for(14336, 4096, shards=8) == 256
    # q 4096 at tp=8 -> 512 per device -> full tile survives
    assert tile_for(4096, 4096, shards=8) == 512
    # no conforming multi-tile split -> 0 (int8 fallback), never a
    # single-tile packing that a shard would split
    assert tile_for(96, 64, shards=2) == 0


def test_int4_tp_packed_tree_passes_guard_and_generates():
    """A tree quantized with tensor=2 + int4_tp=2 config passes the TP
    guard at tp=2 and generates finitely (tile choice consistent between
    quantize_params and the module's sites)."""
    from unionml_tpu.models.llama import assert_int4_tp_compatible

    cfg = int4_cfg(int4_tp=2, hidden_dim=128, num_heads=4, num_kv_heads=2,
                   mlp_dim=256, vocab_size=512)
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False,
                            "weight_bits": 8, "int4_tp": 1})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4, tensor=2)
    assert_int4_tp_compatible(cfg, 2)
    module = Llama(cfg)
    logits = module.apply({"params": q4}, jnp.asarray([[5, 3, 9, 2]], jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()
    # 8B geometry passes every power-of-two degree when packed for tp=8
    cfg8b = LlamaConfig(quantized=True, weight_bits=4, int4_tp=8)
    for tp in (2, 4, 8):
        assert_int4_tp_compatible(cfg8b, tp)


def test_grouped_int4_llama_generates_and_tracks_int8():
    """End-to-end: group-wise int4 tree (scale_g leaves) loads into the
    int4_group module, generates, and tracks the int8 tree's top-1 at
    least as well as per-channel int4 does."""
    group = 16
    cfg = int4_cfg(int4_group=group)
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False,
                            "weight_bits": 8, "int4_group": 0})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q4g = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4, group_size=group)
    attn_q = q4g["block_0"]["attn"]["q"]
    assert set(attn_q) == {"kernel_p", "scale_g"}
    assert attn_q["scale_g"].shape == (64 // group, 64)
    module = Llama(cfg)
    prompt = jnp.asarray([[5, 3, 9, 2]], jnp.int32)
    logits_g = module.apply({"params": q4g}, prompt)
    assert np.isfinite(np.asarray(logits_g)).all()
    gen = make_generator(module, max_new_tokens=6, max_len=32)
    out = np.asarray(gen(q4g, prompt))
    assert out.shape == (1, 6)
    # grouped logits track the FP model within the same band as
    # per-channel int4 (at tiny random-weight scale the two are
    # statistically indistinguishable — the OUTLIER test above carries
    # the quality separation; this pins the e2e pipeline)
    fp_logits = np.asarray(Llama(fp_cfg).apply({"params": params}, prompt))
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    logits_pc = Llama(int4_cfg()).apply({"params": q4}, prompt)
    err_g = np.sqrt(((np.asarray(logits_g) - fp_logits) ** 2).mean())
    err_pc = np.sqrt(((np.asarray(logits_pc) - fp_logits) ** 2).mean())
    assert err_g <= err_pc * 1.5, (err_g, err_pc)


def test_serving_params_preserves_grouped_scales():
    """serving_params must not cast scale_g (fp32 dequant metadata)."""
    from unionml_tpu.models.generate import serving_params

    tree = {
        "mlp": {
            "gate": {
                "kernel_p": jnp.zeros((16, 16), jnp.int8),
                "scale_g": jnp.ones((2, 32), jnp.float32),
            },
            "norm": {"scale": jnp.ones((8,), jnp.float32)},
        }
    }
    out = serving_params(tree)
    assert out["mlp"]["gate"]["scale_g"].dtype == jnp.float32
    assert out["mlp"]["norm"]["scale"].dtype == jnp.bfloat16


def test_streamed_grouped_int4_checkpoint_matches_quantize_params(tmp_path):
    """Streamed loads honor int4_group: scale_g leaves bit-identical to
    the in-memory quantize_params(group_size=...) path."""
    from unionml_tpu.models.convert import (
        export_llama_safetensors,
        load_llama_checkpoint,
    )

    fp_cfg = LlamaConfig.tiny(dtype="float32")
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    export_llama_safetensors(params, fp_cfg, str(tmp_path))
    streamed, cfg = load_llama_checkpoint(
        str(tmp_path), quantize=True, quantized=True, weight_bits=4,
        int4_group=16,
    )
    direct, _ = load_llama_checkpoint(str(tmp_path), fp_cfg, dtype=jnp.float32)
    reference = quantize_params(
        direct, LLAMA_QUANT_PATTERNS, bits=4, group_size=16
    )
    q_attn = streamed["block_0"]["attn"]["q"]
    assert set(q_attn) == {"kernel_p", "scale_g"}
    np.testing.assert_array_equal(
        np.asarray(q_attn["kernel_p"]),
        np.asarray(reference["block_0"]["attn"]["q"]["kernel_p"]),
    )
    np.testing.assert_array_equal(
        np.asarray(q_attn["scale_g"]),
        np.asarray(reference["block_0"]["attn"]["q"]["scale_g"]),
    )
    logits = Llama(cfg).apply({"params": streamed}, jnp.zeros((1, 4), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_group_not_dividing_k_falls_back_int8_consistently():
    """When int4_group doesn't divide a layer's K, quantize_params emits
    the int8 fallback — and the module must declare the SAME structure
    (kernel_q+scale), not kernel_p/scale_g (reviewer repro: mismatched
    fallback raised ScopeParamNotFoundError)."""
    cfg = int4_cfg(int4_group=48)     # 48 divides neither 64 nor 128
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False,
                            "weight_bits": 8, "int4_group": 0})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    q = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4, group_size=48)
    assert "kernel_q" in q["block_0"]["attn"]["q"]      # int8 fallback
    logits = Llama(cfg).apply({"params": q}, jnp.zeros((1, 4), jnp.int32))
    assert np.isfinite(np.asarray(logits)).all()


def test_grouped_cross_attention_o_matches_tree():
    """Encoder-decoder cross attention's o projection must declare the
    grouped scale like the self-attention path (one-line desync found in
    review)."""
    from unionml_tpu.models.layers import Attention

    attn = Attention(num_heads=2, head_dim=16, quantized=True,
                     weight_bits=4, int4_group=16)
    x = jnp.zeros((1, 4, 32), jnp.bfloat16)
    kv = jnp.zeros((1, 6, 32), jnp.bfloat16)
    variables = attn.init(jax.random.PRNGKey(0), x, kv=kv)
    o = variables["params"]["o"]
    assert "scale_g" in o, sorted(o)


def test_group128_keeps_pallas_k_block():
    """group_size=128 must keep a Pallas-eligible k_block (the whole
    point of the grouped kernel); smaller groups return 0 (XLA path)."""
    from unionml_tpu.ops.int4_matmul import _grid_for

    assert _grid_for(4096, 4096, group_size=128)[1] == 128
    assert _grid_for(4096, 4096, group_size=64)[1] == 0
    with pytest.warns(UserWarning, match="multiple of 128"):
        int4_matmul(
            jnp.zeros((1, 64), jnp.bfloat16),
            jnp.zeros((64, 256), jnp.int8),
            jnp.ones((4, 512), jnp.float32), tile_n=512, group_size=16,
        )


def test_mosaic_gate_routes_128_tiles_to_xla(monkeypatch):
    """tile 128 is a valid PACKING (TP-shardable k/v) but its packed
    block width 64 breaks the Mosaic lane rule — the decode call must
    take the XLA path, never the Pallas kernel (review finding: the
    kernel would fail at serve time on real TPU, invisible to the
    interpret-mode CI)."""
    import unionml_tpu.ops.int4_matmul as m

    def boom(*a, **k):
        raise AssertionError("Pallas path engaged for a 128-tile")

    monkeypatch.setattr(m, "_pallas_int4", boom)
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(64, 384)).astype(np.float32))
    packed, scale = quantize_kernel_int4(w, 128)
    x = jnp.asarray(rng.normal(size=(1, 64)), jnp.bfloat16)
    y = int4_matmul(x, packed, scale, tile_n=128, dtype=jnp.float32)
    wdq = np.asarray(unpack_int4(packed, 128), np.float32) * np.asarray(scale)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x, np.float32) @ wdq, rtol=2e-2, atol=2e-2
    )
    # ...while a single FULL-width tile (Mosaic-exempt) and the 256/512
    # tiles stay on the kernel
    assert m._k_block_for(64, 384) == 64
    monkeypatch.undo()


def test_k_block_sized_for_callers_tile():
    """int4_matmul's K grid must be sized for the tile it was CALLED
    with, not a recomputed first-fit candidate (review finding: a
    128-tile paired with a 512-sized k_block fragments the K grid)."""
    from unionml_tpu.ops.int4_matmul import _k_block_for

    # K=14336 at tile 512 must halve to 3584; at tile 256 it fits 7168
    assert _k_block_for(14336, 512) == 3584
    assert _k_block_for(14336, 256) == 7168
    # grouped: k_block pins to the group regardless of tile
    assert _k_block_for(14336, 512, group_size=128) == 128


def test_int4_with_kv_quant_and_chunked_prefill():
    """The long-context serving composition (round-4 gap: no test
    exercised weight_bits=4 together with kv_quant): packed-int4 weights
    + int8 KV cache + chunked prefill, through both the solo generator
    and the engine's chunked admission — token identical."""
    from unionml_tpu.serving.engine import DecodeEngine

    cfg = int4_cfg(kv_quant=True)
    fp_cfg = LlamaConfig(**{**cfg.__dict__, "quantized": False,
                            "weight_bits": 8, "kv_quant": False})
    params = Llama(fp_cfg).init(
        jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q4 = quantize_params(params, LLAMA_QUANT_PATTERNS, bits=4)
    module = Llama(cfg)
    rng = np.random.default_rng(23)
    long_prompt = rng.integers(1, 512, size=40).tolist()
    gen_chunked = make_generator(
        module, max_new_tokens=6, max_len=64, prefill_chunk=16
    )
    gen_mono = make_generator(module, max_new_tokens=6, max_len=64)
    want = np.asarray(gen_mono(q4, jnp.asarray([long_prompt], jnp.int32)))[0]
    got = np.asarray(gen_chunked(q4, jnp.asarray([long_prompt], jnp.int32)))[0]
    np.testing.assert_array_equal(got, want)

    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(48,),
        prefill_chunk=16, chunk_steps=3,
    )
    try:
        eng = engine.generate(q4, [long_prompt])[0]
    finally:
        engine.close()
    assert eng == want.tolist()
