"""Dataset spec tests (reference: tests/unit/test_dataset.py)."""

import json
from typing import Dict, List, Tuple

import numpy as np
import pandas as pd
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu import Dataset
from unionml_tpu.dataset import ReaderReturnTypeSource
from unionml_tpu.stage import Stage


def test_reader_compiles_to_stage(dataset):
    task = dataset.dataset_task()
    assert isinstance(task, Stage)
    assert task.name == "test_dataset.reader"
    assert "sample_frac" in task.input_types
    assert task.output_type is pd.DataFrame
    # direct-callable: the local executor doubles as the test fake
    out = task(sample_frac=1.0, random_state=123)
    assert isinstance(out, pd.DataFrame)
    assert len(out) == 100


def test_reader_requires_return_annotation():
    ds = Dataset(name="bad")
    with pytest.raises(TypeError):

        @ds.reader
        def reader():
            return pd.DataFrame()


def test_get_data_default_pipeline(dataset):
    raw = dataset.dataset_task()(sample_frac=1.0, random_state=123)
    data = dataset.get_data(raw)
    assert set(data) == {"train", "test"}
    X_train, y_train = data["train"]
    X_test, y_test = data["test"]
    assert list(X_train.columns) == ["x", "x2"]
    assert list(y_train.columns) == ["y"]
    assert len(X_train) == 80 and len(X_test) == 20
    # deterministic splits under fixed random_state
    data2 = dataset.get_data(raw)
    pd.testing.assert_frame_equal(data["train"][0], data2["train"][0])


def test_custom_splitter_parser_over_list_dict():
    """Custom splitter/parser over List[Dict] data
    (reference: tests/unit/test_dataset.py:80-115)."""
    ds = Dataset(name="listdict", targets=["y"])

    @ds.reader
    def reader() -> List[Dict]:
        return [{"x": float(i), "y": i % 2} for i in range(10)]

    @ds.splitter
    def splitter(data: List[Dict], test_size: float, shuffle: bool, random_state: int):
        k = int(len(data) * (1 - test_size))
        return data[:k], data[k:]

    Parsed = Tuple[List[List[float]], List[int]]

    @ds.parser
    def parser(data: List[Dict], features, targets) -> Parsed:
        return [[d["x"]] for d in data], [d["y"] for d in data]

    data = ds.get_data(reader())
    X_train, y_train = data["train"]
    assert X_train == [[float(i)] for i in range(8)]
    assert y_train == [i % 2 for i in range(8)]
    assert len(data["test"][0]) == 2


def test_custom_loader_json_str():
    """JSON-string reader + custom loader (reference: tests/unit/test_dataset.py:118-126)."""
    ds = Dataset(name="jsonds", features=["a"], targets=["b"])

    @ds.reader
    def reader() -> str:
        return json.dumps([{"a": 1.0, "b": 0}, {"a": 2.0, "b": 1}, {"a": 3.0, "b": 0}])

    @ds.loader
    def loader(data: str) -> pd.DataFrame:
        return pd.DataFrame.from_records(json.loads(data))

    assert ds.dataset_datatype_source is ReaderReturnTypeSource.LOADER
    assert ds.dataset_datatype["data"] is pd.DataFrame
    data = ds.get_data(reader(), splitter_kwargs={"test_size": 0.34, "shuffle": False})
    assert len(data["train"][0]) == 2


def test_feature_pipeline_defaults(dataset):
    feats = dataset.get_features([{"x": 1.0, "x2": 2.0}])
    assert isinstance(feats, pd.DataFrame)
    assert list(feats.columns) == ["x", "x2"]
    # JSON string path
    feats2 = dataset.get_features(json.dumps([{"x": 1.0, "x2": 2.0}]))
    pd.testing.assert_frame_equal(feats, feats2)


def test_feature_pipeline_custom():
    ds = Dataset(name="custom_feat")

    @ds.reader
    def reader() -> np.ndarray:
        return np.ones((4, 2))

    @ds.feature_loader
    def feature_loader(raw) -> np.ndarray:
        return np.asarray(raw, dtype=np.float32)

    @ds.feature_transformer
    def feature_transformer(x: np.ndarray) -> np.ndarray:
        return x / 2.0

    out = ds.get_features([[2.0, 4.0]])
    np.testing.assert_allclose(out, [[1.0, 2.0]])


def test_kwargs_dataclass_synthesis(dataset):
    sk = dataset.splitter_kwargs_type()
    assert sk.test_size == 0.2 and sk.shuffle is True and sk.random_state == 99
    pk = dataset.parser_kwargs_type()
    assert pk.features == ["x", "x2"] and pk.targets == ["y"]


def test_stage_caching(tmp_path, monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_CACHE_DIR", str(tmp_path))
    calls = {"n": 0}
    ds = Dataset(name="cached")

    @ds.reader(cache=True, cache_version="1")
    def reader(n: int = 3) -> List[float]:
        calls["n"] += 1
        return [float(i) for i in range(n)]

    task = ds.dataset_task()
    assert task(n=3) == [0.0, 1.0, 2.0]
    assert task(n=3) == [0.0, 1.0, 2.0]
    assert calls["n"] == 1  # second call served from cache
    assert task(n=4) == [0.0, 1.0, 2.0, 3.0]
    assert calls["n"] == 2


def test_sqlite_dataset(tmp_path):
    import sqlite3

    db = tmp_path / "data.db"
    with sqlite3.connect(db) as conn:
        conn.execute("CREATE TABLE points (x REAL, y INTEGER)")
        conn.executemany(
            "INSERT INTO points VALUES (?, ?)", [(float(i), i % 2) for i in range(20)]
        )
    ds = Dataset.from_sqlite_task(
        "sqlds",
        db_path=str(db),
        query_template="SELECT * FROM points LIMIT {limit}",
        features=["x"],
        targets=["y"],
    )
    task = ds.dataset_task()
    frame = task(limit=10)
    assert len(frame) == 10
    data = ds.get_data(frame)
    assert len(data["train"][0]) == 8
