"""Preemptive, priority-aware scheduling tests (docs/robustness.md
"Preemption & fairness").

The contract under test: the ``X-Priority`` class is validated at
every transport boundary (closed value set, 422 on garbage, echoed on
responses, carried across the router hop), the waiting room drains
per-(priority, tenant) queues at the configured class weights with a
starvation bound (never strict-priority starvation), and — THE chaos
acceptance — under pool exhaustion with mixed priorities a
lower-priority mid-decode stream is evicted to the host prefix-cache
store, re-admitted via the splice path, and finishes with tokens
bit-identical to its uncontended solo run, with zero caller-visible
failures; preemption composing with ``_recover`` leaks zero pool
blocks or cache leases.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import FaultInjector, xla_oom_error
from unionml_tpu.serving.prefix_cache import RadixPrefixCache
from unionml_tpu.serving.scheduler import (
    DEFAULT_PRIORITY,
    PRIORITIES,
    SchedulerConfig,
    WaitingRoom,
    current_priority,
    priority_scope,
    validate_priority,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


@pytest.fixture
def trained_model(model):
    model.train(
        hyperparameters={"max_iter": 500}, sample_frac=1.0, random_state=123
    )
    return model


def _solo(module, params, prompt, n_new, max_len=256):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _assert_pool_drained(engine, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = engine.stats()["kv_pool"]
        if st["blocks_in_use"] == 0 and st["blocks_reserved"] == 0:
            return st
        time.sleep(0.02)
    raise AssertionError(f"kv pool leaked blocks: {engine.stats()['kv_pool']}")


def _assert_no_live_leases(cache):
    """Every node's refcount back to zero: no admission or resume pin
    outlived its request (the lease-leak acceptance gauge)."""
    stack = list(cache._root.children.values())
    while stack:
        node = stack.pop()
        assert node.refcount == 0, (
            f"leaked lease refcount {node.refcount} at depth {node.depth}"
        )
        stack.extend(node.children.values())


class _FakeReq:
    _n = 0

    def __init__(self, priority="normal", tenant="anonymous", cost=24):
        self.priority = priority
        self.tenant = tenant
        self.prompt = [0] * (cost - 16)
        self.max_new_tokens = 16
        _FakeReq._n += 1
        self.rid = f"r{_FakeReq._n}"


# ---------------------------------------------------------- validator


def test_validate_priority_contract():
    assert validate_priority(None) == DEFAULT_PRIORITY
    assert validate_priority("") == DEFAULT_PRIORITY
    for p in PRIORITIES:
        assert validate_priority(p) == p
        assert validate_priority(p.upper()) == p  # case-insensitive
    for bad in ("urgent", "0", "hi gh", "normal "):
        with pytest.raises(ValueError, match="X-Priority"):
            validate_priority(bad)


def test_priority_scope_nesting():
    assert current_priority() == DEFAULT_PRIORITY
    with priority_scope("low"):
        assert current_priority() == "low"
        with priority_scope("high"):
            assert current_priority() == "high"
        with priority_scope(None):  # None leaves the outer scope visible
            assert current_priority() == "low"
        assert current_priority() == "low"
    assert current_priority() == DEFAULT_PRIORITY


def test_scheduler_config_validation():
    with pytest.raises(ValueError, match="class_weights"):
        SchedulerConfig(class_weights={"high": 1})
    with pytest.raises(ValueError, match="quantum"):
        SchedulerConfig(quantum_tokens=0)
    with pytest.raises(ValueError, match="mix_prefill_tokens"):
        SchedulerConfig(mix_prefill_tokens=0)


# ------------------------------------------------------- waiting room


def test_waiting_room_fifo_within_class_and_tenant():
    room = WaitingRoom()
    reqs = [_FakeReq() for _ in range(5)]
    for r in reqs:
        room.put(r)
    assert room.qsize() == 5
    assert [room.pop() for _ in range(5)] == reqs
    assert room.pop() is None
    assert room.empty()


def test_waiting_room_class_shares_follow_weights():
    """Stride scheduling: under full backlog the admitted-token shares
    converge to class_weights — high dominates, low drains at its
    weight share (the starvation bound: low is slowed, never stopped)."""
    room = WaitingRoom(SchedulerConfig(
        class_weights={"high": 16, "normal": 4, "low": 1},
    ))
    for _ in range(200):
        room.put(_FakeReq("high"))
        room.put(_FakeReq("normal"))
        room.put(_FakeReq("low"))
    popped = [room.pop().priority for _ in range(210)]
    # the most urgent class serves first
    assert popped[0] == "high"
    counts = {p: popped.count(p) for p in PRIORITIES}
    # equal costs -> pop shares == token shares == weight shares (21
    # pops per full cycle: 16 high, 4 normal, 1 low)
    assert counts["high"] == pytest.approx(210 * 16 / 21, abs=2)
    assert counts["normal"] == pytest.approx(210 * 4 / 21, abs=2)
    assert counts["low"] >= 8  # never starved
    # a backlogged low request waits at most one full weight cycle
    first_low = popped.index("low")
    assert first_low <= 21


def test_waiting_room_idle_class_banks_no_credit():
    """A class that was idle joins at the current virtual time: it
    cannot monopolize admissions to 'catch up' on its idle period."""
    room = WaitingRoom(SchedulerConfig(
        class_weights={"high": 4, "normal": 4, "low": 1},
    ))
    for _ in range(50):
        room.put(_FakeReq("high"))
    for _ in range(30):
        room.pop()
    for _ in range(50):
        room.put(_FakeReq("normal"))  # joins late
    window = [room.pop().priority for _ in range(20)]
    # equal weights -> roughly alternating, not 20 straight normals
    assert 5 <= window.count("normal") <= 15


def test_waiting_room_tenant_drr_interleaves():
    """Within one class, two tenants with equal fair weights admit in
    DRR turns — a bulk tenant's deep queue cannot lock out a light
    tenant that arrived later."""
    room = WaitingRoom()
    for _ in range(10):
        room.put(_FakeReq(tenant="bulk"))
    room.put(_FakeReq(tenant="light"))
    first_six = [room.pop().tenant for _ in range(6)]
    assert "light" in first_six


def test_waiting_room_usage_weighted_tenant_quota():
    """The ledger feeds DRR refill: a tenant holding ~all attributed
    device time refills at the floor weight, so the light tenant's
    head request is served first despite arriving second."""

    class _Ledger:
        def fair_share(self, tenant):
            return 0.99 if tenant == "heavy" else 0.0

    room = WaitingRoom(
        SchedulerConfig(quantum_tokens=24), usage=_Ledger()
    )
    for _ in range(4):
        room.put(_FakeReq(tenant="heavy"))
    room.put(_FakeReq(tenant="light"))
    # heavy refills 24 * 0.05 = 1.2/visit (cost 24 -> ~20 visits);
    # light refills 24/visit and serves on its first visit
    assert room.pop().tenant == "light"


def test_waiting_room_parked_blocks_class_and_below():
    room = WaitingRoom()
    parked = _FakeReq("normal")
    room.park(parked)
    room.put(_FakeReq("normal", tenant="b"))
    room.put(_FakeReq("low"))
    high = _FakeReq("high")
    room.put(high)
    # only the strictly-higher class may admit past the parked head
    assert room.pop() is high
    assert room.pop() is None
    # the parked head retries first and unblocks its class when taken
    assert room.take_parked() is parked
    assert room.pop() is not None


def test_waiting_room_front_requeue_resumes_first():
    room = WaitingRoom()
    a, b = _FakeReq(tenant="t"), _FakeReq(tenant="t")
    room.put(a)
    room.put(b)
    resumed = _FakeReq(tenant="t")
    room.put(resumed, front=True)
    assert room.pop() is resumed


# ------------------------------------------------- engine integration


def test_preempt_requires_prerequisites(tiny_llama):
    module, _ = tiny_llama
    with pytest.raises(ValueError, match="preempt"):
        DecodeEngine(
            module, slots=1, max_new_tokens=4, prompt_buckets=(16,),
            scheduler=SchedulerConfig(preempt=True),
            registry=telemetry.MetricsRegistry(),
        )


def _preempt_engine(module, registry=None, **kw):
    registry = registry if registry is not None else telemetry.MetricsRegistry()
    kw.setdefault("slots", 2)
    kw.setdefault("max_new_tokens", 48)
    kw.setdefault("prompt_buckets", (64,))
    kw.setdefault("chunk_steps", 2)
    kw.setdefault("pipeline_depth", 2)
    kw.setdefault("kv_block_size", 16)
    kw.setdefault("kv_pool_blocks", 5)  # capacity 4: ONE resident fits
    return DecodeEngine(
        module, paged=True, registry=registry,
        prefix_cache=RadixPrefixCache(block_size=16, registry=registry),
        **kw,
    )


@pytest.mark.chaos
def test_preempted_stream_resumes_with_token_parity(tiny_llama):
    """THE acceptance: a low-priority mid-decode stream is evicted to
    host (its blocks land in the prefix cache), the high-priority
    waiter admits, the victim re-admits via the splice path — and BOTH
    finish with tokens bit-identical to their uncontended solo runs,
    zero caller-visible failures."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    engine = _preempt_engine(module, registry=registry, flight=flight)
    try:
        rng = np.random.default_rng(0)
        low_prompt = rng.integers(1, 97, 8).tolist()
        high_prompt = rng.integers(1, 97, 8).tolist()
        low_out, low_err = [], []

        def low_client():
            try:
                for chunk in engine.generate_stream(
                    params, low_prompt, priority="low"
                ):
                    low_out.extend(chunk)
            except BaseException as exc:  # pragma: no cover - fail below
                low_err.append(exc)

        t = threading.Thread(target=low_client)
        t.start()
        # wait for the victim's first harvested token (the resume
        # point preemption needs), while its ~22 remaining decode
        # chunks leave a wide submission window
        deadline = time.monotonic() + 60
        while not low_out and time.monotonic() < deadline:
            time.sleep(0.002)
        assert low_out, "low stream never produced a token"
        high_out = engine.generate(
            params, [high_prompt], max_new_tokens=8, priority="high"
        )[0]
        t.join(timeout=120)
        assert not t.is_alive(), "low stream hung"
        assert not low_err, f"caller-visible failure: {low_err}"
        # bit-identical to the uncontended solo runs
        assert high_out == _solo(module, params, high_prompt, 8, max_len=engine.cache_len)
        assert low_out == _solo(module, params, low_prompt, 48, max_len=engine.cache_len)
        sched = engine.stats()["scheduler"]
        assert sched["preemptions"] >= 1
        kinds = [e["kind"] for e in flight.dump()]
        assert "preempt" in kinds and "resume" in kinds
        pre = [e for e in flight.dump() if e["kind"] == "preempt"][0]
        assert pre["priority"] == "low" and pre["by_priority"] == "high"
        st = _assert_pool_drained(engine)
        assert st["preempted_blocks"] >= 1
        _assert_no_live_leases(engine.prefix_cache)
        # the metric series exist under the closed label sets
        text = registry.exposition()
        assert "unionml_preemptions_total" in text
        assert 'cause="priority"' in text
        assert "unionml_sched_waiting_depth" in text
    finally:
        engine.close()


@pytest.mark.chaos
def test_high_priority_promotes_past_parked_head(tiny_llama):
    """The promote path: while a pool-exhausted LOW admission is
    parked (head-of-line for its class), a HIGH request small enough
    to fit the remaining blocks admits PAST it — it must not wait out
    the bulk backlog. The parked stream still completes with parity
    once blocks free."""
    module, params = tiny_llama
    flight = telemetry.FlightRecorder()
    engine = _preempt_engine(module, flight=flight, slots=3)
    try:
        rng = np.random.default_rng(4)
        p_a = rng.integers(1, 97, 8).tolist()   # resident: 3 blocks
        p_b = rng.integers(1, 97, 8).tolist()   # parks: needs 3 > 1 left
        p_c = rng.integers(1, 97, 8).tolist()   # high: 1 block, fits
        results = {}
        lock = threading.Lock()

        errors = []

        def client(name, prompt, priority, n):
            try:
                out = engine.generate(
                    params, [prompt], max_new_tokens=n, priority=priority
                )[0]
                with lock:
                    results[name] = out
            except BaseException as exc:
                with lock:
                    errors.append((name, exc))
        t_a = threading.Thread(target=client, args=("a", p_a, "low", 40))
        t_a.start()
        deadline = time.monotonic() + 60
        while not [
            e for e in flight.dump() if e["kind"] == "decode"
        ] and time.monotonic() < deadline:
            time.sleep(0.002)
        t_b = threading.Thread(target=client, args=("b", p_b, "low", 40))
        t_b.start()
        while not [
            e for e in flight.dump() if e["kind"] == "pool_pressure"
        ] and time.monotonic() < deadline:
            time.sleep(0.002)
        # b is parked; a high request that fits the leftover block
        # admits past it (equal-priority preemption never fires: a
        # and b are both low, c needs no eviction)
        t_c = threading.Thread(target=client, args=("c", p_c, "high", 8))
        t_c.start()
        for t in (t_a, t_b, t_c):
            t.join(timeout=120)
        assert not any(t.is_alive() for t in (t_a, t_b, t_c))
        assert not errors, f"caller-visible failures: {errors}"
        assert results["a"] == _solo(module, params, p_a, 40, max_len=engine.cache_len)
        assert results["b"] == _solo(module, params, p_b, 40, max_len=engine.cache_len)
        assert results["c"] == _solo(module, params, p_c, 8, max_len=engine.cache_len)
        promotes = [e for e in flight.dump() if e["kind"] == "promote"]
        assert promotes and promotes[0]["priority"] == "high"
        assert promotes[0]["past_priority"] == "low"
        _assert_pool_drained(engine)
        _assert_no_live_leases(engine.prefix_cache)
    finally:
        engine.close()


def test_equal_priority_contention_parks_fifo(tiny_llama):
    """Same class never preempts itself: pool pressure within one
    priority parks exactly as before the scheduler (and everything
    still completes token-parity)."""
    module, params = tiny_llama
    engine = _preempt_engine(module)
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, 97, 8).tolist() for _ in range(3)]
        outs = engine.generate(params, prompts, max_new_tokens=8)
        for p, out in zip(prompts, outs):
            assert out == _solo(module, params, p, 8, max_len=engine.cache_len)
        assert engine.stats()["scheduler"]["preemptions"] == 0
        _assert_pool_drained(engine)
    finally:
        engine.close()


@pytest.mark.chaos
def test_preemption_under_recovery_leaks_nothing(tiny_llama):
    """Preemption composed with the PR 3 chaos harness: an OOM-shaped
    device fault lands while a preempted stream is in (or past) its
    evict→resume window. Whatever the interleaving, the engine must
    not hang, must keep serving, and must return the pool AND the host
    cache's lease refcounts to baseline."""
    module, params = tiny_llama
    fi = FaultInjector()
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    engine = _preempt_engine(
        module, registry=registry, flight=flight, fault_injector=fi,
    )
    try:
        rng = np.random.default_rng(2)
        low_prompt = rng.integers(1, 97, 8).tolist()
        high_prompt = rng.integers(1, 97, 8).tolist()
        results, errors = [], []
        lock = threading.Lock()

        def client(prompt, priority, n):
            try:
                out = engine.generate(
                    params, [prompt], max_new_tokens=n, priority=priority
                )[0]
                with lock:
                    results.append((prompt, n, out))
            except Exception as exc:
                with lock:
                    errors.append(exc)  # the poisoned batch

        t_low = threading.Thread(target=client, args=(low_prompt, "low", 48))
        t_low.start()
        deadline = time.monotonic() + 60
        while not [
            e for e in flight.dump() if e["kind"] == "decode"
        ] and time.monotonic() < deadline:
            time.sleep(0.002)
        t_high = threading.Thread(
            target=client, args=(high_prompt, "high", 8)
        )
        t_high.start()
        # once the preemption fired, poison the NEXT decode dispatch:
        # recovery now races the victim's evict→resume window
        while not [
            e for e in flight.dump() if e["kind"] == "preempt"
        ] and time.monotonic() < deadline:
            time.sleep(0.002)
        fi.arm("engine.dispatch", exc=xla_oom_error())
        t_low.join(timeout=120)
        t_high.join(timeout=120)
        assert not t_low.is_alive() and not t_high.is_alive(), (
            "a request hung through preemption + recovery"
        )
        # completed requests (if any) are solo-parity
        for prompt, n, out in results:
            assert out == _solo(module, params, prompt, n, max_len=engine.cache_len)
        # the engine still serves after the storm
        probe = rng.integers(1, 97, 8).tolist()
        assert engine.generate(
            params, [probe], max_new_tokens=8
        )[0] == _solo(module, params, probe, 8, max_len=engine.cache_len)
        _assert_pool_drained(engine)
        _assert_no_live_leases(engine.prefix_cache)
    finally:
        engine.close()


def test_mix_budget_token_parity(tiny_llama):
    """Stall-free mixing: a larger prefill token budget changes only
    scheduling, never tokens (chunked-prefill admissions stay
    bit-identical to solo runs)."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=5, prompt_buckets=(64,),
        prefill_chunk=16, chunk_steps=2, paged=True,
        scheduler=SchedulerConfig(mix_prefill_tokens=48),
        registry=telemetry.MetricsRegistry(),
    )
    try:
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 97, 50).tolist() for _ in range(3)]
        outs = engine.generate(params, prompts)
        for p, out in zip(prompts, outs):
            assert out == _solo(module, params, p, 5, max_len=engine.cache_len)
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_priority_in_usage_vector(tiny_llama):
    module, params = tiny_llama
    from unionml_tpu.serving.usage import UsageLedger

    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(16,),
        chunk_steps=2, usage=ledger, registry=registry,
    )
    try:
        engine.generate(
            params, [[1, 2, 3]], priority="high", tenant="acme"
        )
        engine.generate(params, [[4, 5, 6]], tenant="acme")
        vec = ledger.report()["tenants"]["acme"]
        assert vec["requests_by_priority"] == {"high": 1, "normal": 1}
    finally:
        engine.close()


# ------------------------------------------------------- transports


def test_stdlib_transport_priority_round_trip(trained_model):
    import httpx

    from unionml_tpu.serving.http import ServingApp

    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict",
            json={"features": [{"x": 1.0, "x2": 1.0}]},
            headers={"X-Priority": "high"},
        )
        assert r.status_code == 200
        assert r.headers["x-priority"] == "high"
        # default + echo on non-predict routes too
        h = httpx.get(f"{base}/health")
        assert h.headers["x-priority"] == "normal"
        # outside the closed set: 422, never reaches the scheduler
        bad = httpx.post(
            f"{base}/predict", json={"features": []},
            headers={"X-Priority": "urgent"},
        )
        assert bad.status_code == 422
    finally:
        app.shutdown()


def test_fastapi_transport_priority_round_trip(trained_model):
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        r = client.post(
            "/predict", json={"features": [[0.1, 0.2]]},
            headers={"X-Priority": "LOW"},
        )
        assert r.status_code == 200
        assert r.headers["x-priority"] == "low"
        h = client.get("/health")
        assert h.headers["x-priority"] == "normal"
        bad = client.get("/health", headers={"X-Priority": "urgent"})
        assert bad.status_code == 422


def test_serverless_transport_priority_round_trip(trained_model):
    import json as _json

    from unionml_tpu.serving.serverless import gateway_handler

    handler = gateway_handler(trained_model)
    r = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"X-Priority": "high"},
        "body": _json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert r["statusCode"] == 200
    assert r["headers"]["X-Priority"] == "high"
    h = handler({"httpMethod": "GET", "path": "/health"})
    assert h["headers"]["X-Priority"] == "normal"
    bad = handler({
        "httpMethod": "GET", "path": "/health",
        "headers": {"X-Priority": "urgent"},
    })
    assert bad["statusCode"] == 422


def test_http_replica_forwards_priority():
    """The router hop: HttpReplica re-emits the ambient priority scope
    as X-Priority, so a routed request keeps its preemption rights on
    the remote replica's engine."""
    from unionml_tpu.serving.router import HttpReplica

    replica = HttpReplica("http://127.0.0.1:9")
    with priority_scope("high"):
        assert replica._headers()["X-Priority"] == "high"
    assert replica._headers()["X-Priority"] == "normal"
