"""Shared fixtures (reference: tests/unit/conftest.py + model_fixtures.py)."""

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, Model


@pytest.fixture
def mock_data() -> pd.DataFrame:
    """Synthetic 100-row frame (reference: tests/unit/model_fixtures.py:12-20)."""
    rng = np.random.default_rng(42)
    return pd.DataFrame(
        {
            "x": rng.normal(size=100),
            "x2": rng.normal(size=100),
            "y": rng.integers(0, 2, size=100),
        }
    )


@pytest.fixture
def dataset(mock_data) -> Dataset:
    ds = Dataset(
        name="test_dataset", features=["x", "x2"], targets=["y"],
        test_size=0.2, shuffle=True, random_state=99,
    )

    @ds.reader
    def reader(sample_frac: float = 1.0, random_state: int = 123) -> pd.DataFrame:
        return mock_data.sample(frac=sample_frac, random_state=random_state)

    return ds


@pytest.fixture
def model(dataset) -> Model:
    from sklearn.linear_model import LogisticRegression

    model = Model(name="test_model", init=LogisticRegression, dataset=dataset)

    @model.trainer
    def trainer(m: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return m.fit(features, target.squeeze())

    @model.predictor
    def predictor(m: LogisticRegression, features: pd.DataFrame) -> list:
        return [float(x) for x in m.predict(features)]

    @model.evaluator
    def evaluator(m: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
        return float(m.score(features, target.squeeze()))

    return model
