"""KV block-pool allocator semantics (host-side, no jax).

The reservation contract is what makes paged serving fail CLEANLY:
admission reserves a request's worst case up front, so mid-decode table
growth can never fail — exhaustion surfaces as :class:`PoolExhausted`
at reservation time (the engine parks or sheds), never as a corrupted
decode. Block 0 is the trash block and must never be allocated.
"""

import pytest

from unionml_tpu import telemetry
from unionml_tpu.serving.kv_pool import TRASH_BLOCK, KVBlockPool, PoolExhausted


def make_pool(num_blocks=8, block_size=16, block_nbytes=1024):
    return KVBlockPool(
        num_blocks=num_blocks, block_size=block_size,
        block_nbytes=block_nbytes, registry=telemetry.MetricsRegistry(),
    )


def test_capacity_excludes_trash_block():
    pool = make_pool(num_blocks=8)
    assert pool.capacity == 7
    assert pool.available == 7
    taken = []
    pool.reserve(7)
    for _ in range(7):
        taken.append(pool.take())
    assert TRASH_BLOCK not in taken
    assert sorted(taken) == list(range(1, 8))


def test_reserve_take_give_roundtrip():
    pool = make_pool()
    pool.reserve(3)
    assert pool.reserved == 3
    assert pool.available == pool.capacity - 3
    a, b = pool.take(), pool.take()
    assert pool.in_use == 2
    assert pool.reserved == 1
    pool.give([a, b], unreserve=1)
    assert pool.in_use == 0
    assert pool.reserved == 0
    assert pool.available == pool.capacity
    stats = pool.stats()
    assert stats["allocated_blocks"] == 2
    assert stats["freed_blocks"] == 2


def test_exhaustion_raises_and_counts():
    pool = make_pool(num_blocks=4)  # capacity 3
    pool.reserve(2)
    with pytest.raises(PoolExhausted) as exc:
        pool.reserve(2)
    assert exc.value.needed == 2
    assert exc.value.available == 1
    assert pool.stats()["alloc_failures"] == 1
    # the failed reservation committed nothing
    assert pool.reserved == 2
    pool.reserve(1)  # the remaining block still reservable


def test_take_without_reservation_refused():
    pool = make_pool()
    with pytest.raises(RuntimeError):
        pool.take()


def test_reservation_makes_growth_infallible():
    """Once reserved, every take() succeeds even if another caller
    drains the unreserved remainder first."""
    pool = make_pool(num_blocks=6)  # capacity 5
    pool.reserve(2)                 # request A
    pool.reserve(3)                 # request B takes everything else
    b_ids = [pool.take() for _ in range(3)]
    a_ids = [pool.take() for _ in range(2)]
    assert len(set(a_ids + b_ids)) == 5
    with pytest.raises(PoolExhausted):
        pool.reserve(1)


def test_give_validates_ids_and_unreserve():
    pool = make_pool(num_blocks=4)
    pool.reserve(1)
    bid = pool.take()
    with pytest.raises(ValueError):
        pool.give([0])          # trash block is not allocatable
    with pytest.raises(ValueError):
        pool.give([99])         # outside the pool
    with pytest.raises(ValueError):
        pool.give([], unreserve=1)  # nothing reserved anymore
    pool.give([bid])


def test_reset_returns_everything():
    pool = make_pool(num_blocks=6)
    pool.reserve(4)
    ids = [pool.take() for _ in range(3)]
    assert ids
    pool.reset()
    assert pool.in_use == 0
    assert pool.reserved == 0
    assert pool.available == pool.capacity


def test_occupancy_and_fragmentation_gauges():
    pool = make_pool(num_blocks=5, block_size=16)  # capacity 4
    pool.reserve(3)
    pool.take(), pool.take()
    st = pool.stats()
    # 2 in use + 1 reserved over capacity 4
    assert st["occupancy"] == pytest.approx(0.75)
    # 20 used rows over 2 blocks x 16 rows
    pool.note_used_rows(20)
    st = pool.stats()
    assert st["fragmentation"] == pytest.approx(1 - 20 / 32, abs=1e-3)
    assert st["bytes_in_use"] == 2 * 1024


def test_blocks_for_rows():
    pool = make_pool(block_size=16)
    assert pool.blocks_for_rows(0) == 0
    assert pool.blocks_for_rows(1) == 1
    assert pool.blocks_for_rows(16) == 1
    assert pool.blocks_for_rows(17) == 2


def test_constructor_validation():
    with pytest.raises(ValueError):
        make_pool(num_blocks=1)  # only the trash block
    with pytest.raises(ValueError):
        KVBlockPool(
            num_blocks=4, block_size=0,
            registry=telemetry.MetricsRegistry(),
        )
