"""Cross-implementation fidelity: checkpoints written by HF transformers'
*torch* reference models load through models/convert.py and reproduce the
torch logits.

This is the output-sanity proof for the ingestion path (VERDICT round 3,
Missing #1): the mapping, layout transforms, and the rotary/GELU/norm
conventions are all exercised end-to-end against an independent
implementation — a transposed kernel, permuted head, or mismatched RoPE
convention shifts logits by O(1), far outside the tolerances here. Real
pretrained checkpoints use the exact same tensor names and layouts; only
scale differs.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from unionml_tpu.models import Llama  # noqa: E402
from unionml_tpu.models.bert import BertEncoder  # noqa: E402
from unionml_tpu.models.convert import (  # noqa: E402
    load_bert_checkpoint,
    load_llama_checkpoint,
)
from unionml_tpu.models.generate import make_generator  # noqa: E402


@pytest.fixture(scope="module")
def hf_llama_checkpoint(tmp_path_factory):
    cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10_000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval().to(torch.float32)
    path = tmp_path_factory.mktemp("hf_llama")
    model.save_pretrained(path, safe_serialization=True)
    return model, str(path)


def test_llama_logits_match_torch_reference(hf_llama_checkpoint):
    hf_model, path = hf_llama_checkpoint
    params, cfg = load_llama_checkpoint(path, dtype=jnp.float32, max_len=256)
    # the loader's returned config IS the model config (fp32 compute for
    # a tight comparison against the fp32 torch reference)
    module = Llama(dataclasses.replace(cfg, dtype="float32"))
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 512, size=(2, 16), dtype=np.int32)
    ours = np.asarray(
        module.apply({"params": params}, jnp.asarray(tokens))
    )
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-3, rtol=1e-3)
    # the distributions agree, not just roughly: identical argmax per position
    np.testing.assert_array_equal(
        ours.argmax(-1), theirs.argmax(-1)
    )


def test_llama_greedy_generation_matches_torch(hf_llama_checkpoint):
    hf_model, path = hf_llama_checkpoint
    params, cfg = load_llama_checkpoint(path, dtype=jnp.float32, max_len=256)
    module = Llama(dataclasses.replace(cfg, dtype="float32", max_len=64))
    generate = make_generator(module, max_new_tokens=8, max_len=64)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, 512, size=(2, 12), dtype=np.int32)
    ours = np.asarray(generate(params, jnp.asarray(prompt)))
    with torch.no_grad():
        theirs = hf_model.generate(
            torch.tensor(prompt, dtype=torch.long),
            max_new_tokens=8, do_sample=False,
        ).numpy()[:, 12:]
    np.testing.assert_array_equal(ours, theirs)


def test_llama3_rope_scaling_matches_torch(tmp_path):
    """Llama-3.1/3.2-style checkpoints carry llama3 rope_scaling — the
    frequency rescale must reproduce transformers' torch implementation
    or long-context logits silently drift."""
    cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10_000.0,
        tie_word_embeddings=True,  # 3.2-style: lm_head tied to embed
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 32,
        },
    )
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(cfg).eval().to(torch.float32)
    hf_model.save_pretrained(tmp_path, safe_serialization=True)
    params, loaded = load_llama_checkpoint(str(tmp_path), dtype=jnp.float32)
    assert loaded.rope_scaling == (8.0, 1.0, 4.0, 32)
    module = Llama(dataclasses.replace(loaded, dtype="float32"))
    rng = np.random.default_rng(4)
    # longer than original_max_position_embeddings so the rescaled
    # low-frequency band actually participates
    tokens = rng.integers(0, 512, size=(1, 48), dtype=np.int32)
    ours = np.asarray(module.apply({"params": params}, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-3, rtol=1e-3)
    np.testing.assert_array_equal(ours.argmax(-1), theirs.argmax(-1))


def test_unsupported_rope_scaling_is_loud():
    from unionml_tpu.models.convert import llama_config_from_hf

    with pytest.raises(NotImplementedError, match="rope_scaling"):
        llama_config_from_hf({
            "vocab_size": 512, "hidden_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4, "intermediate_size": 128,
            "rope_scaling": {"rope_type": "yarn", "factor": 4.0},
        })


def test_bert_encoder_matches_torch_reference(tmp_path):
    hf_cfg = transformers.BertConfig(
        vocab_size=1024, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=128, type_vocab_size=2,
        hidden_act="gelu",  # erf GELU — matched by gelu_exact=True
    )
    torch.manual_seed(0)
    hf_model = transformers.BertModel(hf_cfg).eval().to(torch.float32)
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    params, loaded_cfg = load_bert_checkpoint(str(tmp_path), encoder_key="")
    # the loader derives gelu_exact=True from hidden_act="gelu" — the
    # erf form erf-pretrained checkpoints need for faithful inference
    assert loaded_cfg.gelu_exact
    module = BertEncoder(dataclasses.replace(loaded_cfg, dtype="float32"))
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 1024, size=(2, 10), dtype=np.int32)
    mask = np.ones((2, 10), np.int32)
    mask[1, 7:] = 0
    types = np.zeros((2, 10), np.int32)
    encoder_params = params  # encoder_key="" roots the tree at the encoder
    ours = np.asarray(
        module.apply(
            {"params": {k: v for k, v in encoder_params.items() if k != "pooler"}},
            jnp.asarray(tokens),
            attention_mask=jnp.asarray(mask),
            token_type_ids=jnp.asarray(types),
        )
    )
    with torch.no_grad():
        out = hf_model(
            torch.tensor(tokens, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
            token_type_ids=torch.tensor(types, dtype=torch.long),
        )
        theirs = out.last_hidden_state.numpy()
        their_pooled = out.pooler_output.numpy()
    # padded positions attend nothing meaningful in either impl — compare
    # real positions only
    np.testing.assert_allclose(ours[0], theirs[0], atol=2e-3, rtol=1e-3)
    np.testing.assert_allclose(ours[1, :7], theirs[1, :7], atol=2e-3, rtol=1e-3)

    # pooler: tanh(cls @ W + b) with the loaded pooler weights
    pk = np.asarray(encoder_params["pooler"]["kernel"])
    pb = np.asarray(encoder_params["pooler"]["bias"])
    our_pooled = np.tanh(ours[:, 0] @ pk + pb)
    np.testing.assert_allclose(our_pooled, their_pooled, atol=2e-3, rtol=1e-3)


def test_mixtral_logits_match_torch_reference(tmp_path):
    """Mixtral block-sparse MoE checkpoints load through the grouped
    expert mapping and reproduce transformers' torch logits — router
    transpose, per-expert w1/w3/w2 stacking, and the renormalized top-k
    routing all verified against the independent implementation."""
    cfg = transformers.MixtralConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=10_000.0,
        tie_word_embeddings=False,
    )
    torch.manual_seed(3)
    hf_model = transformers.MixtralForCausalLM(cfg).eval().to(torch.float32)
    hf_model.save_pretrained(tmp_path, safe_serialization=True)
    params, loaded = load_llama_checkpoint(str(tmp_path), dtype=jnp.float32)
    assert loaded.num_experts == 4 and loaded.num_selected == 2
    module = Llama(dataclasses.replace(loaded, dtype="float32"))
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, 512, size=(2, 12), dtype=np.int32)
    ours = np.asarray(module.apply({"params": params}, jnp.asarray(tokens)))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(ours.argmax(-1), theirs.argmax(-1))


def test_vit_logits_match_torch_reference(tmp_path):
    """HF ViT checkpoints (pre-LN, qkv biases, erf GELU, cls+pos
    embeddings, OIHW patch conv) load through the ViT mapping and
    reproduce transformers' torch classification logits."""
    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128, hidden_act="gelu",
        num_labels=10,
    )
    torch.manual_seed(4)
    hf_model = (
        transformers.ViTForImageClassification(hf_cfg).eval().to(torch.float32)
    )
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    from unionml_tpu.models import ViT
    from unionml_tpu.models.convert import load_vit_checkpoint

    params, cfg = load_vit_checkpoint(str(tmp_path))
    assert cfg.qkv_bias and cfg.gelu_exact and cfg.num_classes == 10
    module = ViT(dataclasses.replace(cfg, dtype="float32"))
    rng = np.random.default_rng(7)
    images = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    ours = np.asarray(module.apply({"params": params}, jnp.asarray(images)))
    with torch.no_grad():
        theirs = hf_model(
            torch.tensor(images.transpose(0, 3, 1, 2))  # NHWC -> NCHW
        ).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(ours.argmax(-1), theirs.argmax(-1))
