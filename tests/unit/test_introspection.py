"""XLA program introspection & continuous profiling
(docs/observability.md): cost-analysis tracking with recompile
detection, MFU/roofline gauges with the peak-table override path,
on-demand profiler capture and device-memory breakdown over both HTTP
transports, and the request flight recorder — including the
injected-fault recovery snapshot naming the poisoned requests. All
CPU-only (``cost_analysis`` works on CPU jit)."""

import json
import os

import httpx
import numpy as np
import pytest

from unionml_tpu import introspection
from unionml_tpu.introspection import (
    ProfileInProgress,
    ProgramTracker,
    capture_profile,
    device_memory_breakdown,
    resolve_device_peaks,
)
from unionml_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    TraceRecorder,
)

# measured sub-minute module: part of the `-m quick` tier
pytestmark = pytest.mark.quick


# ------------------------------------------------------------- tracker


def test_tracker_records_cost_and_compiles_per_signature():
    import jax
    import jax.numpy as jnp

    reg = MetricsRegistry()
    tracker = ProgramTracker(registry=reg, component="t0")
    fn = tracker.wrap(
        "t.matmul",
        jax.jit(lambda x: (x @ x).sum()),
        sig_fn=lambda x: x.shape,
    )
    fn(jnp.ones((16, 16)))            # compile #1
    fn(jnp.ones((16, 16)))            # cached dispatch
    fn(jnp.ones((32, 32)))            # compile #2 (new shape)
    stats = tracker.stats()["t.matmul"]
    assert stats["calls"] == 3
    assert stats["compiles"] == 2
    assert stats["flops_per_call"] > 0
    assert stats["bytes_per_call"] > 0
    # cumulative flops mix the two signatures' costs, so the total
    # exceeds 3x the smaller shape's cost
    assert stats["flops_total"] > 3 * 0
    assert stats["compile_ms"]["n"] == 2
    text = reg.exposition()
    for name in (
        "unionml_program_calls_total",
        "unionml_program_compiles_total",
        "unionml_program_flops_total",
        "unionml_program_bytes_total",
        "unionml_program_compile_ms_bucket",
        "unionml_program_mfu_ratio",
        "unionml_program_hbm_ratio",
    ):
        assert name in text, name
    row = next(
        line for line in text.splitlines()
        if line.startswith("unionml_program_compiles_total{")
        and 'program="t.matmul"' in line
    )
    assert row.rsplit(" ", 1)[1] == "2"


def test_tracker_detects_recompiles_and_survives_donation():
    """A shape revisited after jit cache behavior is stable stays
    cached (no phantom recompiles), and cost analysis works for donated
    (deleted-buffer) arguments via the abstract-aval lowering."""
    import jax
    import jax.numpy as jnp

    tracker = ProgramTracker(registry=MetricsRegistry(), component="t1")
    jitted = jax.jit(
        lambda s, x: {"a": s["a"] + x.sum()}, donate_argnums=(0,)
    )
    fn = tracker.wrap("t.donated", jitted)
    state = {"a": jnp.ones((8, 8))}
    for _ in range(3):
        state = fn(state, jnp.ones((8, 8)))
    stats = tracker.stats()["t.donated"]
    assert stats["calls"] == 3 and stats["compiles"] == 1
    assert stats["bytes_per_call"] > 0  # cost analysis on donated args


def test_tracker_opaque_fallback_for_plain_callables():
    """A non-jitted callable is tracked opaquely: calls count, no cost
    analysis, no crash."""
    tracker = ProgramTracker(registry=MetricsRegistry(), component="t2")
    fn = tracker.wrap("t.plain", lambda x: x + 1)
    assert fn(1) == 2 and fn(2) == 3
    stats = tracker.stats()["t.plain"]
    assert stats["calls"] == 2 and stats["compiles"] == 0
    assert stats["flops_total"] == 0


def test_tracker_reset_keeps_learned_costs():
    import jax
    import jax.numpy as jnp

    tracker = ProgramTracker(registry=MetricsRegistry(), component="t3")
    fn = tracker.wrap("t.fn", jax.jit(lambda x: x * 2.0))
    fn(jnp.ones(64))
    tracker.reset()
    stats = tracker.stats()["t.fn"]
    assert stats["calls"] == 0 and stats["flops_total"] == 0
    fn(jnp.ones(64))  # cached dispatch after reset still knows its cost
    assert tracker.stats()["t.fn"]["bytes_total"] > 0


# ------------------------------------------------------- peaks and MFU


def test_peak_table_resolution_on_cpu():
    peaks = resolve_device_peaks()
    assert peaks["platform"] == "cpu"
    assert peaks["source"] == "table"
    assert peaks["peak_flops"] and peaks["peak_bytes_per_s"]


def test_peak_env_override(monkeypatch):
    """The escape hatch for unknown chips: env peaks win over the
    table, and the MFU gauges divide by them."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv(introspection.PEAK_FLOPS_ENV, "1e6")
    monkeypatch.setenv(introspection.PEAK_HBM_ENV, "0.000001")  # 1e3 B/s
    peaks = resolve_device_peaks()
    assert peaks["source"] == "env"
    assert peaks["peak_flops"] == 1e6
    assert peaks["peak_bytes_per_s"] == pytest.approx(1e3)

    reg = MetricsRegistry()
    tracker = ProgramTracker(registry=reg, component="t4")
    fn = tracker.wrap("t.fn", jax.jit(lambda x: (x @ x).sum()))
    for _ in range(4):
        fn(jnp.ones((64, 64)))
    stats = tracker.stats()
    assert stats["device"]["source"] == "env"
    # tiny fake peaks make the achieved/peak ratios visibly nonzero
    assert stats["t.fn"]["mfu"] > 0
    assert stats["t.fn"]["hbm_utilization"] > 0
    text = reg.exposition()
    mfu_row = next(
        line for line in text.splitlines()
        if line.startswith("unionml_program_mfu_ratio{")
    )
    assert float(mfu_row.rsplit(" ", 1)[1]) > 0


def test_malformed_peak_override_falls_back(monkeypatch):
    monkeypatch.setenv(introspection.PEAK_FLOPS_ENV, "not-a-number")
    peaks = resolve_device_peaks()
    assert peaks["source"] == "table"  # malformed override ignored


# -------------------------------------------------------------- engine


@pytest.fixture(scope="module")
def tiny_llama():
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _engine(module, **kwargs):
    from unionml_tpu.serving.engine import DecodeEngine

    defaults = dict(
        slots=2, max_new_tokens=6, prompt_buckets=(8,), chunk_steps=2,
        registry=MetricsRegistry(), tracer=TraceRecorder(),
    )
    defaults.update(kwargs)
    return DecodeEngine(module, **defaults)


def test_engine_stats_programs_and_metrics(tiny_llama):
    """stats()["programs"] reports flops/bytes/compiles/MFU for the
    engine's compiled programs, and the same numbers land in /metrics
    — the acceptance surface for engine decode."""
    module, params = tiny_llama
    engine = _engine(module, flight=FlightRecorder())
    try:
        engine.generate(params, [[1, 2, 3], [4, 5, 6]])
        programs = engine.stats()["programs"]
        assert programs["device"]["platform"] == "cpu"
        decode = programs["engine.decode"]
        assert decode["calls"] >= 1 and decode["compiles"] >= 1
        assert decode["flops_per_call"] > 0
        assert decode["bytes_per_call"] > 0
        assert decode["compile_ms"]["n"] >= 1
        assert 0 <= decode["mfu"] < 10  # finite ratio, nonsense-free
        prefill = programs["engine.prefill"]
        assert prefill["calls"] == 2 and prefill["flops_total"] > 0
        text = engine._registry.exposition()
        row = next(
            line for line in text.splitlines()
            if line.startswith("unionml_program_flops_total{")
            and 'program="engine.decode"' in line
            and f'component="{engine.instance}"' in line
        )
        assert float(row.rsplit(" ", 1)[1]) > 0
    finally:
        engine.close()


def test_engine_introspection_parity_and_off_switch(tiny_llama):
    """introspect=False produces bit-identical tokens with no programs
    section and no flight events — the instrumentation-off leg the
    serve_introspection bench measures."""
    module, params = tiny_llama
    flight = FlightRecorder()
    on = _engine(module, flight=flight)
    off = _engine(module, introspect=False)
    try:
        prompts = [[1, 2, 3], [4, 5, 6, 7]]
        out_on = on.generate(params, prompts)
        out_off = off.generate(params, prompts)
        assert out_on == out_off
        assert "programs" in on.stats()
        assert "programs" not in off.stats()
        assert off._flight is None and off._programs is None
        assert flight.total_recorded > 0
    finally:
        on.close()
        off.close()


def test_engine_flight_records_request_lifecycle(tiny_llama):
    module, params = tiny_llama
    flight = FlightRecorder()
    engine = _engine(module, flight=flight)
    try:
        engine.generate(params, [[1, 2, 3]])
        events = flight.dump()
        kinds = [e["kind"] for e in events]
        for kind in ("submit", "prefill", "decode", "finish"):
            assert kind in kinds, (kind, kinds)
        finish = flight.dump(kind="finish")[-1]
        assert finish["tokens"] == 6 and finish["rid"]
        # every event for that request carries the same rid
        per_req = flight.dump(rid=finish["rid"])
        assert {e["kind"] for e in per_req} >= {"submit", "prefill", "finish"}
        # prefill event names the admission shape and cache hit length
        prefill = flight.dump(kind="prefill")[-1]
        assert prefill["bucket"] == 8 and prefill["cached_tokens"] == 0
    finally:
        engine.close()


def test_recovery_leaves_flight_snapshot_naming_poisoned(tiny_llama):
    """Acceptance: an injected-fault recovery (FaultInjector) leaves a
    flight-recorder snapshot naming the poisoned requests, and the
    recovery trace span carries the snapshot."""
    from unionml_tpu.serving.faults import FaultInjector, xla_oom_error

    module, params = tiny_llama
    fi, flight, tracer = FaultInjector(), FlightRecorder(), TraceRecorder()
    engine = _engine(
        module, flight=flight, tracer=tracer, fault_injector=fi
    )
    try:
        engine.generate(params, [[1, 2, 3]])  # warm + prove healthy
        fi.arm("engine.dispatch", exc=xla_oom_error())
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            engine.generate(params, [[4, 5, 6]])
        # generate() raises as soon as the waiter is released; the
        # recovery event/span land moments later — poll briefly
        import time as _time

        deadline = _time.monotonic() + 10
        recoveries = flight.dump(kind="recovery")
        while not recoveries and _time.monotonic() < deadline:
            _time.sleep(0.01)
            recoveries = flight.dump(kind="recovery")
        assert recoveries, "no recovery event recorded"
        rids = recoveries[-1]["rids"]
        assert rids, "recovery event names no poisoned requests"
        # the poisoned request's own lifecycle is retrievable by rid
        trail = flight.snapshot(rids)
        assert any(e["kind"] == "submit" for e in trail)
        # and the recovery trace span carries rids + the flight trail
        # (the span lands after the poisoned waiters are released, so
        # poll briefly: generate() raises before _recover returns)
        import time as _time

        deadline = _time.monotonic() + 10
        span = None
        while span is None and _time.monotonic() < deadline:
            chrome = tracer.export_chrome()
            span = next(
                (e for e in chrome["traceEvents"]
                 if e.get("name") == "recover"),
                None,
            )
            if span is None:
                _time.sleep(0.01)
        assert span is not None, "recovery span never recorded"
        assert span["args"]["poisoned"] == rids
        assert span["args"]["flight"], "span carries no flight snapshot"
        json.dumps(span["args"]["flight"])  # JSON-safe for export
    finally:
        engine.close()


def test_deadline_shed_lands_in_flight(tiny_llama):
    """A request shed at dequeue leaves a drop event naming the cause —
    the 504 postmortem path."""
    module, params = tiny_llama
    flight = FlightRecorder()
    engine = _engine(module, flight=flight)
    try:
        from unionml_tpu.serving.faults import DeadlineExceeded

        with pytest.raises(DeadlineExceeded):
            engine.generate(params, [[1, 2, 3]], deadline_ms=0.001)
        drops = flight.dump(kind="drop")
        assert drops and drops[-1]["cause"] == "deadline_shed"
    finally:
        engine.close()


# ------------------------------------------------------------- batcher


def test_batcher_programs_and_flight():
    import jax

    from unionml_tpu.serving.batcher import MicroBatcher

    reg, flight = MetricsRegistry(), FlightRecorder()
    batcher = MicroBatcher(
        jax.jit(lambda f: f.sum(axis=1)),
        max_batch_size=8, max_wait_ms=5.0, registry=reg, flight=flight,
    )
    try:
        batcher.submit(np.ones((2, 3), np.float32))
        stats = batcher.stats()
        prog = stats["programs"]["batcher.predict"]
        assert prog["calls"] >= 1 and prog["compiles"] >= 1
        assert prog["flops_per_call"] > 0
        kinds = {e["kind"] for e in flight.dump()}
        assert {"submit", "batch"} <= kinds
    finally:
        batcher.close()


def test_batcher_introspect_off():
    from unionml_tpu.serving.batcher import MicroBatcher

    batcher = MicroBatcher(
        lambda f: f.sum(axis=1), max_batch_size=4, max_wait_ms=2.0,
        registry=MetricsRegistry(), introspect=False,
    )
    try:
        out = batcher.submit(np.ones((1, 3), np.float32))
        np.testing.assert_allclose(out, [3.0])
        assert "programs" not in batcher.stats()
    finally:
        batcher.close()


# ------------------------------------------------------------- trainer


def test_trainer_step_program_in_metrics():
    """Acceptance: the trainer step's flops/MFU land in /metrics on
    CPU (component="trainer", program="trainer.step")."""
    import jax.numpy as jnp

    from unionml_tpu.execution import run_step_trainer

    reg = MetricsRegistry()

    def step(state, batch):
        x, y = batch
        return state, {"loss": jnp.mean((x.sum(axis=1) - y) ** 2)}

    rng = np.random.default_rng(0)
    run_step_trainer(
        step_fn=step, state={"w": jnp.zeros(4)},
        features=rng.normal(size=(32, 4)).astype(np.float32),
        targets=rng.normal(size=(32,)).astype(np.float32),
        num_epochs=1, batch_size=8, donate_state=False, registry=reg,
    )
    text = reg.exposition()
    row = next(
        line for line in text.splitlines()
        if line.startswith("unionml_program_flops_total{")
        and 'component="trainer"' in line
        and 'program="trainer.step"' in line
    )
    assert float(row.rsplit(" ", 1)[1]) > 0
    assert "unionml_program_mfu_ratio" in text


# -------------------------------------------- capture + memory (direct)


def test_capture_profile_returns_artifact_dir(tmp_path):
    out = capture_profile(0.05, log_dir=str(tmp_path / "prof"))
    assert out["trace_dir"] == str(tmp_path / "prof")
    assert os.path.isdir(out["trace_dir"])
    assert out["seconds"] >= 0.05
    # CPU jax writes trace artifacts; unsupported backends degrade to 0
    assert out["file_count"] >= 0


def test_capture_profile_validates_and_guards():
    with pytest.raises(ValueError):
        capture_profile(0)
    # hold the capture lock like a running capture would
    assert introspection._capture_lock.acquire(blocking=False)
    try:
        with pytest.raises(ProfileInProgress):
            capture_profile(0.01)
    finally:
        introspection._capture_lock.release()


def test_device_memory_breakdown_shape():
    import jax.numpy as jnp

    keep = jnp.ones((32, 32), jnp.float32)  # one known live buffer
    out = device_memory_breakdown()
    assert out["devices"] and out["devices"][0]["platform"] == "cpu"
    live = out["live_arrays"]
    assert live["count"] >= 1 and live["bytes"] >= keep.nbytes
    assert "float32" in live["by_dtype"]
    assert live["top"] and live["top"][0]["bytes"] >= live["top"][-1]["bytes"]
    del keep


# ----------------------------------------------------- HTTP transports


def _stub_app(**kwargs):
    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    dataset = Dataset(name="introspect_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    stub = Model(name="introspect_stub", init=lambda: {"w": 1}, dataset=dataset)

    @stub.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @stub.predictor
    def predictor(p: dict, feats: list) -> list:
        return [float(np.asarray(f).sum()) for f in feats]

    stub.artifact = ModelArtifact({"w": 1}, {}, {})
    return ServingApp(stub, registry=MetricsRegistry(), **kwargs)


def test_debug_endpoints_stdlib_transport():
    flight = FlightRecorder()
    flight.record("probe", rid="r1")
    app = _stub_app(flight=flight)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(f"{base}/debug/profile?seconds=0.05", timeout=60)
        assert r.status_code == 200
        body = r.json()
        assert os.path.isdir(body["trace_dir"]) and body["seconds"] >= 0.05
        r = httpx.get(f"{base}/debug/memory", timeout=60)
        assert r.status_code == 200
        assert r.json()["devices"][0]["platform"] == "cpu"
        r = httpx.get(f"{base}/debug/flight?n=5", timeout=30)
        assert r.status_code == 200
        events = r.json()["events"]
        assert events and events[-1]["kind"] == "probe"
        # filters
        r = httpx.get(f"{base}/debug/flight?rid=r1&kind=probe", timeout=30)
        assert len(r.json()["events"]) == 1
        # validation: bad seconds -> 422, bad n -> 422
        assert httpx.post(
            f"{base}/debug/profile?seconds=-1", timeout=30
        ).status_code == 422
        assert httpx.post(
            f"{base}/debug/profile?seconds=zzz", timeout=30
        ).status_code == 422
        assert httpx.get(
            f"{base}/debug/flight?n=zzz", timeout=30
        ).status_code == 422
        # JSON-body form of the capture duration
        r = httpx.post(
            f"{base}/debug/profile", json={"seconds": 0.02}, timeout=60
        )
        assert r.status_code == 200
        # the debug routes land in the known-path metric series
        text = httpx.get(f"{base}/metrics", timeout=30).text
        assert 'path="/debug/profile"' in text
        assert 'path="/debug/flight"' in text
        assert 'path="<other>"' not in text
    finally:
        app.shutdown()


def test_debug_profile_409_while_capture_running():
    app = _stub_app()
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        assert introspection._capture_lock.acquire(blocking=False)
        try:
            r = httpx.post(f"{base}/debug/profile?seconds=0.01", timeout=30)
            assert r.status_code == 409
        finally:
            introspection._capture_lock.release()
    finally:
        app.shutdown()


def test_debug_endpoints_fastapi_transport():
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    from unionml_tpu.serving.fastapi import serving_app

    flight = FlightRecorder()
    flight.record("probe", rid="r9")
    core = _stub_app(flight=flight)
    app = fastapi.FastAPI()
    # mount the same core through the adapter seam the tests for /stats
    # use: build via serving_app on the underlying model, then swap in
    # our pre-built core's flight recorder by mounting core directly
    serving_app(core.model, app, flight=flight)
    with TestClient(app) as client:
        r = client.post("/debug/profile?seconds=0.05")
        assert r.status_code == 200 and os.path.isdir(r.json()["trace_dir"])
        r = client.get("/debug/memory")
        assert r.status_code == 200
        assert r.json()["devices"][0]["platform"] == "cpu"
        r = client.get("/debug/flight", params={"n": 5})
        assert r.status_code == 200
        assert r.json()["events"][-1]["kind"] == "probe"
        assert client.post("/debug/profile?seconds=-1").status_code == 422
        assert introspection._capture_lock.acquire(blocking=False)
        try:
            assert client.post("/debug/profile?seconds=0.01").status_code == 409
        finally:
            introspection._capture_lock.release()


def test_flight_endpoint_covers_engine_traffic(tiny_llama):
    """End to end: engine traffic recorded into an app-served flight
    recorder is dumpable over HTTP with request rids intact."""
    module, params = tiny_llama
    flight = FlightRecorder()
    engine = _engine(module, flight=flight)
    app = _stub_app(flight=flight)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        engine.generate(params, [[1, 2, 3]])
        events = httpx.get(f"{base}/debug/flight", timeout=30).json()["events"]
        kinds = {e["kind"] for e in events}
        assert {"submit", "prefill", "finish"} <= kinds
        rid = next(e["rid"] for e in events if e["kind"] == "finish")
        scoped = httpx.get(
            f"{base}/debug/flight?rid={rid}", timeout=30
        ).json()["events"]
        assert scoped and all(
            e.get("rid") == rid or rid in e.get("rids", ()) for e in scoped
        )
    finally:
        app.shutdown()
        engine.close()


# ---------------------------------------------------- flight recorder


def test_flight_recorder_bounded_ring_and_filters():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("tick", rid=f"r{i}", i=i)
    events = fr.dump()
    assert len(events) == 4
    assert [e["i"] for e in events] == [6, 7, 8, 9]  # newest kept, ordered
    stats = fr.stats()
    assert stats["total_recorded"] == 10 and stats["dropped"] == 6
    assert fr.dump(n=2)[0]["i"] == 8
    assert fr.dump(n=0) == [] and fr.dump(n=-3) == []  # not "everything"
    assert fr.dump(rid="r9")[0]["i"] == 9
    assert fr.dump(kind="nope") == []
    assert fr.snapshot(["r9"], limit=0) == []
    fr.record("group", rids=["r8", "r9"])
    assert fr.snapshot(["r9"])[-1]["kind"] == "group"
    fr.reset()
    assert fr.dump() == [] and fr.stats()["total_recorded"] == 0
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)
