"""LoRA / QLoRA fine-tuning (models/lora.py).

No reference counterpart (the reference trains whatever the user's
sklearn/torch/keras trainer does — reference: unionml/model.py:425-440);
LoRA is the TPU-native fine-tuning path for the serving flagship (int8
frozen base + adapters = single-chip 8B fine-tune, BASELINE.md round 3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.models import (
    LLAMA_LORA_PARTITION_RULES,
    LLAMA_QUANT_PATTERNS,
    Llama,
    LlamaConfig,
    create_lora_train_state,
    lm_step,
    make_lm_predictor,
    merge_lora,
    merge_param_trees,
    quantize_params,
    split_lora_params,
)
from unionml_tpu.parallel.sharding import ShardingConfig, compile_step

TOKENS = jnp.zeros((2, 16), jnp.int32)


def _batch(seed=0, batch=2, seq=17, vocab=500):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(1, vocab, (batch, seq)), jnp.int32)


def _base_params(cfg=None):
    cfg = cfg or LlamaConfig.tiny()
    return Llama(cfg).init(jax.random.PRNGKey(0), TOKENS)["params"]


def test_lora_init_is_identity():
    """lora_b starts at zero: step-0 forward == the base model exactly."""
    base_params = _base_params()
    model = Llama(LlamaConfig.tiny(lora_rank=4))
    state = create_lora_train_state(model, TOKENS, base_params=base_params)
    out_lora = model.apply({"params": state.full_params()}, TOKENS)
    out_base = Llama(LlamaConfig.tiny()).apply({"params": base_params}, TOKENS)
    np.testing.assert_array_equal(np.asarray(out_lora), np.asarray(out_base))


def test_lora_step_trains_adapters_only():
    model = Llama(LlamaConfig.tiny(lora_rank=4))
    state = create_lora_train_state(
        model, TOKENS, base_params=_base_params(), learning_rate=1e-2
    )
    # optimizer state is adapter-sized: the frozen base carries no m/v
    adapter_count = sum(x.size for x in jax.tree_util.tree_leaves(state.params))
    opt_count = sum(
        x.size for x in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(x, "size")
    )
    base_count = sum(
        x.size for x in jax.tree_util.tree_leaves(state.frozen_params)
    )
    assert opt_count <= 2 * adapter_count + 2  # adam m+v (+ counters)
    assert adapter_count < base_count / 10

    frozen_before = jax.tree_util.tree_map(np.asarray, state.frozen_params)
    adapters_before = jax.tree_util.tree_map(np.asarray, state.params)
    step = jax.jit(lm_step(model))
    batch = _batch()
    losses = []
    for _ in range(5):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]  # adapters learn
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        frozen_before, state.frozen_params,
    )  # base frozen bit-exact
    changed = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(
            lambda a, b: float(np.abs(a - np.asarray(b)).max()),
            adapters_before, state.params,
        )
    )
    assert max(changed) > 0  # adapters actually moved


def test_lora_merge_matches_unmerged_forward():
    cfg = LlamaConfig.tiny(lora_rank=4)
    model = Llama(cfg)
    state = create_lora_train_state(
        model, TOKENS, base_params=_base_params(), learning_rate=1e-2
    )
    step = jax.jit(lm_step(model))
    for _ in range(3):
        state, _ = step(state, _batch())
    merged = merge_lora(state.full_params(), alpha=cfg.lora_alpha)
    # merged tree is lora-free and loads the rank-0 architecture
    lora_leaves, _ = split_lora_params(merged)
    assert lora_leaves == {}
    out_merged = Llama(LlamaConfig.tiny()).apply({"params": merged}, TOKENS)
    out_lora = model.apply({"params": state.full_params()}, TOKENS)
    # the lora branch computes (x@A)@B in bf16 while the merged kernel
    # folds the delta in fp32 — equal up to bf16 rounding of the logits
    err = float(jnp.max(jnp.abs(out_merged - out_lora)))
    scale = float(jnp.max(jnp.abs(out_lora))) + 1e-9
    assert err / scale < 0.02


def test_qlora_int8_base_trains_and_serves():
    """The QLoRA loop: quantize → adapter train → merge → bucketed serve."""
    qparams = quantize_params(_base_params(), LLAMA_QUANT_PATTERNS)
    cfg = LlamaConfig.tiny(quantized=True, lora_rank=4)
    model = Llama(cfg)
    state = create_lora_train_state(
        model, TOKENS, base_params=qparams, learning_rate=1e-2
    )
    step = jax.jit(lm_step(model))
    losses = []
    for _ in range(5):
        state, metrics = step(state, _batch())
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    # int8 kernels stay bit-frozen (no grads leak into the base)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        qparams, state.frozen_params,
    )

    merged = merge_lora(state.full_params(), alpha=cfg.lora_alpha)
    serve_model = Llama(LlamaConfig.tiny(quantized=True))
    out_merged = serve_model.apply({"params": merged}, TOKENS)
    out_lora = model.apply({"params": state.full_params()}, TOKENS)
    # requantization error: bounded by the int8 grid on top of bf16 noise
    err = float(jnp.max(jnp.abs(out_merged - out_lora)))
    scale = float(jnp.max(jnp.abs(out_lora))) + 1e-9
    assert err / scale < 0.05

    predictor = make_lm_predictor(serve_model, max_new_tokens=4, bucket_lens=(16,))
    outs = predictor(merged, [[5, 6, 7, 8]])
    assert len(outs) == 1 and len(outs[0]) == 4


def test_lora_sharded_step_matches_serial():
    """dp2 x tp2 QLoRA-layout rules: compiled-mesh adapters == serial."""
    import optax

    cfg = LlamaConfig.tiny(lora_rank=4)
    model = Llama(cfg)
    # SGD for the equality check: adam's m/sqrt(v) normalization turns
    # near-zero-gradient elements into +-lr sign coin-flips, amplifying
    # bf16 reduction-order noise into O(lr) param diffs that say nothing
    # about the sharding's correctness
    state = create_lora_train_state(
        model, TOKENS, base_params=_base_params(), optimizer=optax.sgd(0.5)
    )
    step = lm_step(model)
    batch = _batch(batch=4)

    serial_state = state
    serial_step = jax.jit(step)
    for _ in range(3):
        serial_state, serial_metrics = serial_step(serial_state, batch)

    sharding = ShardingConfig(data=-1, tensor=2, rules=LLAMA_LORA_PARTITION_RULES)
    compiled, placed = compile_step(step, state, sharding=sharding)
    sharded_state = placed
    sharded_batch = jax.device_put(batch, sharding.batch_sharding())
    for _ in range(3):
        sharded_state, sharded_metrics = compiled(sharded_state, sharded_batch)

    # bf16 activations (2^-8 ~ 4e-3 relative rounding) + cross-device
    # psum reorder the reductions; that per-step few-e-3 activation
    # drift feeds grads that 3 compounding SGD steps at lr=0.5 amplify
    # to ~1e-2 absolute on O(1) params — so 2e-2 is the bf16 compounding
    # floor with 2x margin (was 5e-3 = barely one bf16 ulp, seen flaking
    # at clean HEAD), while a sharding bug (missing/doubled psum) moves
    # params at O(1). The fp32 SP/EP tests keep the tight bounds.
    np.testing.assert_allclose(
        float(sharded_metrics["loss"]), float(serial_metrics["loss"]),
        rtol=1e-2,
    )
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-2
        ),
        serial_state.params, jax.device_get(sharded_state.params),
    )


def test_lora_state_through_serving_surface():
    """A LoRATrainState passed straight to the bucketed predictor resolves
    to its FULL params (frozen base + adapters), matching the merged
    weights — no manual merge needed for the state-or-params contract."""
    cfg = LlamaConfig.tiny(lora_rank=4)
    model = Llama(cfg)
    state = create_lora_train_state(model, TOKENS, base_params=_base_params())
    predictor = make_lm_predictor(model, max_new_tokens=4, bucket_lens=(16,))
    out_state = predictor(state, [[5, 6, 7, 8]])
    out_params = predictor(state.full_params(), [[5, 6, 7, 8]])
    assert out_state == out_params


def test_create_lora_state_validations():
    with pytest.raises(ValueError, match="no lora_a/lora_b"):
        create_lora_train_state(Llama(LlamaConfig.tiny()), TOKENS)

    model = Llama(LlamaConfig.tiny(lora_rank=4))
    good = create_lora_train_state(model, TOKENS, base_params=_base_params())
    with pytest.raises(ValueError, match="already contain lora"):
        create_lora_train_state(model, TOKENS, base_params=good.full_params())
    wrong = _base_params(LlamaConfig.tiny(num_layers=1))
    with pytest.raises(ValueError, match="structure does not match"):
        create_lora_train_state(model, TOKENS, base_params=wrong)


def test_split_merge_roundtrip():
    model = Llama(LlamaConfig.tiny(lora_rank=2))
    full = model.init(jax.random.PRNGKey(1), TOKENS)["params"]
    lora, base = split_lora_params(full)
    assert lora and base
    rebuilt = merge_param_trees(base, lora)
    assert jax.tree_util.tree_structure(rebuilt) == jax.tree_util.tree_structure(full)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        full, rebuilt,
    )


def test_tree_walkers_accept_frozendict():
    """Checkpoint-loaded trees often arrive as flax FrozenDicts — the
    walkers must traverse any Mapping, not just dict (a FrozenDict leaf
    would yield zero adapters / silently drop base keys)."""
    from flax.core import freeze

    from unionml_tpu.models.lora import merge_param_trees, split_lora_params

    tree = freeze({
        "block": {
            "q": {"kernel": np.zeros((4, 4)), "lora_a": np.ones((4, 2)),
                  "lora_b": np.zeros((2, 4))},
            "norm": {"scale": np.ones(4)},
        }
    })
    adapters, base = split_lora_params(tree)
    assert set(adapters["block"]["q"]) == {"lora_a", "lora_b"}
    assert set(base["block"]) == {"q", "norm"}
    merged = merge_param_trees(freeze(base), adapters)
    assert set(merged["block"]["q"]) == {"kernel", "lora_a", "lora_b"}
