"""Native C++ host batch loader vs the numpy fallback: both must produce
the identical deterministic batch stream (the loader's resume contract
depends on it), and the trainer integration must still converge."""

import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.data.native import BatchLoader, epoch_permutation, get_library


def make_data(n=257, feat=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, feat)).astype(np.float32)
    y = rng.integers(0, 3, size=(n,)).astype(np.int32)
    return x, y


def collect(loader, epoch=0, start_batch=0):
    return [tuple(np.array(a) for a in b) for b in loader.epoch(epoch, start_batch)]


def test_native_library_builds():
    assert get_library() is not None, "g++ toolchain present — native build must work"


def test_native_matches_numpy_fallback():
    x, y = make_data()
    nat = BatchLoader([x, y], batch_size=32, seed=7, use_native=True)
    py = BatchLoader([x, y], batch_size=32, seed=7, use_native=False)
    assert nat._handle is not None and py._handle is None
    for epoch in (0, 1, 5):
        bn = collect(nat, epoch)
        bp = collect(py, epoch)
        assert len(bn) == len(bp) == 9  # ceil(257/32)
        for (xa, ya), (xb, yb) in zip(bn, bp):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
    nat.close()


def test_permutation_covers_all_rows_and_differs_by_epoch():
    p0 = epoch_permutation(1000, seed=3, epoch=0)
    p1 = epoch_permutation(1000, seed=3, epoch=1)
    assert sorted(p0.tolist()) == list(range(1000))
    assert p0.tolist() != p1.tolist()
    # same (seed, epoch) is stable
    np.testing.assert_array_equal(p0, epoch_permutation(1000, seed=3, epoch=0))


def test_batches_cover_every_row_exactly_once():
    x, y = make_data(n=96)
    loader = BatchLoader([x, y], batch_size=16, seed=1)
    seen = np.concatenate([b[1] for b in collect(loader)])
    assert seen.shape == (96,)
    # multiset equality through the label array round-trip
    xs = np.concatenate([b[0] for b in collect(loader)])
    np.testing.assert_array_equal(np.sort(xs[:, 0]), np.sort(x[:, 0]))
    loader.close()


def test_mid_epoch_resume_matches_full_stream():
    x, y = make_data(n=128)
    loader = BatchLoader([x, y], batch_size=16, seed=5)
    full = collect(loader, epoch=2)
    resumed = collect(loader, epoch=2, start_batch=3)
    assert len(resumed) == len(full) - 3
    for (xa, ya), (xb, yb) in zip(full[3:], resumed):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    loader.close()


def test_epochs_iterator_resume_coordinates():
    x, y = make_data(n=64)
    loader = BatchLoader([x, y], batch_size=16, seed=5)
    all_steps = list(loader.epochs(2))
    assert [(e, i) for e, i, _ in all_steps] == [
        (0, 0), (0, 1), (0, 2), (0, 3), (1, 0), (1, 1), (1, 2), (1, 3)
    ]
    resumed = list(loader.epochs(2, start_epoch=1, start_batch=2))
    assert [(e, i) for e, i, _ in resumed] == [(1, 2), (1, 3)]
    np.testing.assert_array_equal(resumed[0][2][0], all_steps[6][2][0])
    loader.close()


def test_zero_copy_mode_valid_until_advance():
    x, y = make_data(n=64)
    loader = BatchLoader([x, y], batch_size=16, seed=2, copy=False, use_native=True)
    ref = BatchLoader([x, y], batch_size=16, seed=2, use_native=False)
    it, rit = loader.epoch(0), ref.epoch(0)
    for _ in range(4):
        b, rb = next(it), next(rit)
        # compare while the lent buffer is live
        np.testing.assert_array_equal(np.asarray(b[0]), rb[0])
        np.testing.assert_array_equal(np.asarray(b[1]), rb[1])
    loader.close()


def test_concurrent_epoch_iterators_rejected():
    x, y = make_data(n=64)
    loader = BatchLoader([x, y], batch_size=16, seed=0)
    it1 = loader.epoch(0)
    next(it1)
    it2 = loader.epoch(1)
    next(it2)  # starting a second stream invalidates the first
    with pytest.raises(RuntimeError, match="concurrent epoch"):
        next(it1)
    # the new stream keeps working and sequential use stays fine
    next(it2)
    it2.close()
    full = collect(loader, epoch=0)
    assert len(full) == 4
    loader.close()


def test_drop_remainder_and_short_batches():
    x, y = make_data(n=50)
    keep = BatchLoader([x, y], batch_size=16, seed=0)
    drop = BatchLoader([x, y], batch_size=16, seed=0, drop_remainder=True)
    kb, db = collect(keep), collect(drop)
    assert [b[0].shape[0] for b in kb] == [16, 16, 16, 2]
    assert [b[0].shape[0] for b in db] == [16, 16, 16]
    keep.close()
    drop.close()


def test_step_trainer_uses_loader_and_converges():
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    from unionml_tpu.execution import run_step_trainer
    from unionml_tpu.models import create_train_state

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    module = Tiny()
    state = create_train_state(module, jnp.zeros((1, 4)), optimizer=optax.adam(0.05))

    def step(state, batch):
        xb, yb = batch

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, xb)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

        import jax

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), {"loss": loss}

    state = run_step_trainer(
        step_fn=step, state=state, features=x, targets=y,
        num_epochs=5, batch_size=32, seed=0,
    )
    logits = module.apply({"params": state.params}, x)
    acc = float((np.argmax(np.asarray(logits), -1) == y).mean())
    assert acc > 0.9
