"""Serving goodput plane tests (unionml_tpu/serving/perf.py).

The contract under test: per-token ITL attribution never double-counts
across a preemption-resume boundary, dispatcher passes classify into
the closed PASS_KINDS taxonomy on a synthetic trace, a tail exemplar's
rid resolves end-to-end into the stitched trace over the stdlib
transport, the regression watchdog fires/holds/clears on synthetic
values, and a plane-off engine records nothing.
"""

import json
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.perf import (
    PASS_KINDS,
    PERF_REGRESSION_REASONS,
    ServingPerfPlane,
    ServingRegressionWatchdog,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _gauge_value(registry, name, engine):
    for family in registry.collect():
        if family.name == name:
            for values, child in family.children():
                if values == (engine,):
                    return child.value
    return None


def _wait_for(cond, what, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# --------------------------------------- pass classification (pure math)


def test_pass_classification_on_synthetic_trace():
    t = [0.0]
    registry = telemetry.MetricsRegistry()
    plane = ServingPerfPlane(
        registry=registry, engine="e0", slots=4, chunk_steps=2,
        clock=lambda: t[0],
    )
    plane.note_pass(4)                      # full_batch
    plane.note_pass(2)                      # padded_slots
    plane.note_pass(3, prefill_mix=True)    # prefill_mix wins the tag
    plane.note_idle()
    plane.note_tokens(9)
    t[0] = 3.0
    report = plane.report()
    assert set(report["passes"]) == set(PASS_KINDS)
    assert report["passes"] == {
        "full_batch": 1, "padded_slots": 1, "prefill_mix": 1, "idle": 1,
    }
    # slots=4 × chunk_steps=2 = 8 slot-steps per pass
    assert report["slot_steps"] == {
        "full_batch": 8, "padded_slots": 8, "prefill_mix": 8, "idle": 8,
    }
    assert report["occupied_slot_steps"] == (4 + 2 + 3) * 2
    assert report["goodput_ratio"] == pytest.approx(18 / 32)
    assert report["occupancy_ratio"] == pytest.approx(18 / 24)
    assert report["tokens"] == 9
    assert report["tokens_per_s"] == pytest.approx(3.0)
    # gauges published into the registry under the engine label
    assert _gauge_value(
        registry, "unionml_serving_goodput_ratio", "e0"
    ) == pytest.approx(18 / 32)
    assert _gauge_value(
        registry, "unionml_serving_occupancy_ratio", "e0"
    ) == pytest.approx(18 / 24)


def test_kv_pressure_ring_bound_and_reset():
    registry = telemetry.MetricsRegistry()
    plane = ServingPerfPlane(
        registry=registry, engine="e1", slots=2, chunk_steps=1, ring=16,
        clock=lambda: 0.0,
    )
    for _ in range(100):
        plane.note_pass(2, kv_in_use=6, kv_capacity=8)
    report = plane.report()
    assert report["ring_passes"] == 16      # bounded window
    assert report["total_passes"] == 100
    assert report["kv_pressure_ratio"] == pytest.approx(0.75)
    assert _gauge_value(
        registry, "unionml_serving_kv_pressure_ratio", "e1"
    ) == pytest.approx(0.75)
    plane.reset()
    report = plane.report()
    assert report["ring_passes"] == 0 and report["total_passes"] == 0
    assert report["goodput_ratio"] == 0.0
    assert _gauge_value(
        registry, "unionml_serving_goodput_ratio", "e1"
    ) == 0.0


# ------------------------------------------------- regression watchdog


def test_watchdog_fires_and_clears_on_synthetic_values():
    flight = telemetry.FlightRecorder()
    wd = ServingRegressionWatchdog(flight=flight, engine="e0")
    for _ in range(20):
        wd.observe_ttft(10.0)
    assert wd.advisory()["regressed"] is False
    assert flight.dump(kind="perf_regression") == []
    # a 3× jump sustained past the consecutive debounce enters
    for _ in range(6):
        wd.observe_ttft(30.0)
    advisory = wd.advisory()
    assert advisory["regressed"] is True
    assert advisory["reasons"] == ["ttft_regression"]
    entered = [
        e for e in flight.dump(kind="perf_regression")
        if e["state"] == "entered"
    ]
    assert len(entered) == 1
    assert entered[0]["reason"] == "ttft_regression"
    assert entered[0]["engine"] == "e0"
    assert entered[0]["reason"] in PERF_REGRESSION_REASONS
    # recovery clears (bounded: the detector clears below 1.2×)
    for _ in range(60):
        wd.observe_ttft(10.0)
        if not wd.advisory()["regressed"]:
            break
    assert wd.advisory()["regressed"] is False
    cleared = [
        e for e in flight.dump(kind="perf_regression")
        if e["state"] == "cleared"
    ]
    assert len(cleared) == 1 and cleared[0]["reason"] == "ttft_regression"


def test_watchdog_holds_inside_the_band():
    """A 1.3× drift sits inside the 1.5× enter threshold: no event."""
    flight = telemetry.FlightRecorder()
    wd = ServingRegressionWatchdog(flight=flight, engine="e0")
    for _ in range(20):
        wd.observe_itl(10.0)
    for _ in range(20):
        wd.observe_itl(13.0)
    assert wd.advisory()["regressed"] is False
    assert flight.dump(kind="perf_regression") == []


def test_watchdog_goodput_collapse_reads_ratio_drop():
    """Goodput feeds inverted — a ratio collapse (down) must read as a
    regression (up) and the flight event must carry the RAW ratio."""
    flight = telemetry.FlightRecorder()
    wd = ServingRegressionWatchdog(flight=flight, engine="e0")
    for _ in range(20):
        wd.observe_goodput(0.9)
    for _ in range(6):
        wd.observe_goodput(0.3)
    advisory = wd.advisory()
    assert advisory["reasons"] == ["goodput_collapse"]
    entered = [
        e for e in flight.dump(kind="perf_regression")
        if e["state"] == "entered"
    ]
    assert entered and entered[0]["reason"] == "goodput_collapse"
    assert entered[0]["value"] == pytest.approx(0.3)


# ------------------------------------- ITL anchoring (no double-count)


def test_itl_no_double_count_across_preemption_resume(tiny_llama):
    """The decode-lump fix's core invariant: the evict→resume queueing
    gap must never land in the ITL histogram — the anchor clears at
    preemption (engine._preempt_victim) and re-arms at the resume
    harvest, so only intra-segment chunk spacing is cadence."""
    module, _ = tiny_llama
    registry = telemetry.MetricsRegistry()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(8,),
        registry=registry, flight=telemetry.FlightRecorder(),
        introspect=False, perf=True,
    )
    try:
        req = SimpleNamespace(
            priority="normal", _itl_anchor=0.0, _itl_sum_ms=0.0,
            _itl_n=0, rid="r-itl",
        )
        engine._observe_itl(req, 1.000, 1)   # arms the anchor, no gap yet
        engine._observe_itl(req, 1.010, 2)   # 10 ms gap / 2 tokens
        req._itl_anchor = 0.0                # preemption clears the anchor
        engine._observe_itl(req, 5.000, 2)   # resume: 4 s queue gap SKIPPED
        engine._observe_itl(req, 5.020, 2)   # 20 ms gap / 2 tokens
        samples = engine._itl_summary()
        assert samples["n"] == 2             # one observation per chunk
        # per-token values: 10/2 = 5 ms and 20/2 = 10 ms
        assert samples["mean"] == pytest.approx(7.5, abs=0.01)
        assert req._itl_n == 4
        assert req._itl_sum_ms == pytest.approx(30.0, abs=0.01)
        # every call counted its tokens toward achieved throughput
        assert engine._perf.report()["tokens"] == 1 + 2 + 2 + 2
    finally:
        engine.close()


def test_engine_itl_and_ledger_under_chunked_prefill(tiny_llama):
    """A real chunked-prefill generate: stats() reports the merged ITL
    percentiles, the finish flight event carries the full segment
    ledger, and the ITL token count covers every token after the
    first (chunk spacing / chunk size, no admission noise)."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    n_new = 12
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=n_new, prompt_buckets=(8, 64),
        prefill_chunk=16, chunk_steps=4, registry=registry,
        flight=flight, perf=True,
    )
    try:
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 61, size=n).tolist() for n in (5, 33)]
        outs = engine.generate(params, prompts)
        assert all(len(out) == n_new for out in outs)
        stats = engine.stats()
        assert stats["itl_mean_ms"] > 0.0
        assert stats["itl_p99_ms"] >= stats["itl_mean_ms"]
        assert stats["itl_ms"]["n"] > 0
        assert "goodput" in stats
        assert stats["goodput"]["passes"]["full_batch"] + \
            stats["goodput"]["passes"]["padded_slots"] + \
            stats["goodput"]["passes"]["prefill_mix"] > 0
        finishes = flight.dump(kind="finish")
        assert len(finishes) == 2
        for event in finishes:
            for key in (
                "queue_ms", "admission_ms", "prefill_ms", "ttft_ms",
                "decode_ms", "itl_mean_ms", "itl_tokens",
            ):
                assert key in event, key
            assert event["itl_tokens"] == n_new - 1
            assert event["itl_mean_ms"] > 0.0
    finally:
        engine.close()


def test_plane_off_records_nothing(tiny_llama):
    """DecodeEngine(perf=False): no goodput gauges registered, no ITL
    samples, no exemplars on the latency histograms, no goodput block
    in stats(), and goodput_report() raises (→ 422 at the transport)."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        registry=registry, flight=telemetry.FlightRecorder(),
        introspect=False, perf=False,
    )
    try:
        engine.generate(params, [[3, 1, 4, 1, 5]])
        stats = engine.stats()
        assert "goodput" not in stats
        assert "itl_mean_ms" not in stats
        family_names = {f.name for f in registry.collect()}
        assert "unionml_serving_goodput_ratio" not in family_names
        for family in registry.collect():
            if family.kind == "histogram":
                for _values, child in family.children():
                    assert child.exemplars() == []
        with pytest.raises(ValueError):
            engine.goodput_report()
    finally:
        engine.close()


# ------------------- tail exemplar → stitched trace (stdlib transport)


def _engine_app(module, params, n_new=10):
    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    tracer = telemetry.TraceRecorder()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=n_new, prompt_buckets=(8,),
        chunk_steps=4, registry=registry, flight=flight, tracer=tracer,
        perf=True,
    )
    dataset = Dataset(name="perf_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    lm = Model(name="perf_lm", init=lambda: params, dataset=dataset)

    @lm.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @lm.predictor
    def predictor(p: dict, prompts: list) -> list:
        return engine.generate(p, prompts)

    lm.artifact = ModelArtifact(params, {}, {})
    app = ServingApp(
        lm, stats=engine.stats, health=engine.health, drain=engine.drain,
        registry=registry, flight=flight, tracer=tracer,
        goodput=engine.goodput_report,
        stream=lambda p, prompts: engine.generate_stream(p, prompts[0]),
    )
    return app, engine


def _get_json(base, path):
    with urllib.request.urlopen(f"{base}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read().decode())


def test_tail_exemplar_resolves_in_stitched_trace(tiny_llama):
    """THE acceptance: stream a request over the stdlib transport, ask
    `/debug/tail` for the slowest recent requests, and resolve a tail
    row's rid straight into `/debug/trace?rid=` — histogram bucket →
    stitched timeline with no log-grepping. `/debug/goodput` serves
    the plane's report over the same transport."""
    module, params = tiny_llama
    app, engine = _engine_app(module, params)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        req = urllib.request.Request(
            f"{base}/predict/stream",
            data=json.dumps({"features": [3, 1, 4, 1, 5]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        tokens = []
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            for raw in resp:
                line = raw.decode()
                if line.startswith("data: "):
                    event = json.loads(line[len("data: "):])
                    if not event.get("done"):
                        tokens.extend(event["tokens"])
        assert len(tokens) == 10

        # the finish path lands the exemplar shortly after the stream
        _wait_for(
            lambda: _get_json(
                base, "/debug/tail?metric=unionml_engine_decode_ms&n=3"
            )[1]["requests"],
            what="a decode tail exemplar",
        )
        status, tail = _get_json(
            base, "/debug/tail?metric=unionml_engine_decode_ms&n=3"
        )
        assert status == 200
        assert tail["metric"] == "unionml_engine_decode_ms"
        row = tail["requests"][0]
        assert row["value_ms"] > 0.0
        # the phase split rode in from the finish flight event
        assert row["segments"]["itl_tokens"] == 9
        assert row["segments"]["decode_ms"] >= 0.0
        assert row["trace"] == f"/debug/trace?rid={row['rid']}"

        # ... and the rid resolves into ONE stitched timeline
        status, doc = _get_json(base, f"/debug/trace?rid={row['rid']}")
        assert status == 200
        assert doc["trace_id"] and doc["spans"]
        assert any(s["name"].startswith("prefill") for s in doc["spans"])

        # goodput over the same transport
        status, goodput = _get_json(base, "/debug/goodput")
        assert status == 200
        assert goodput["engine"] == engine.instance
        assert 0.0 < goodput["goodput_ratio"] <= 1.0
        assert goodput["tokens"] >= 10
        assert goodput["watchdog"]["regressed"] is False

        # the SLO percentile rows read from the same histograms
        rows = app._serving_percentiles()
        assert rows["ttft_ms"]["n"] >= 1
        assert rows["itl_ms"]["n"] >= 1
        assert 0.0 < rows["goodput_ratio"][engine.instance] <= 1.0

        # unknown / non-histogram metrics answer 422
        for bad in (
            "/debug/tail?metric=nope",
            "/debug/tail?metric=unionml_serving_goodput_ratio",
        ):
            try:
                urllib.request.urlopen(f"{base}{bad}", timeout=30)
                raise AssertionError("expected 422")
            except urllib.error.HTTPError as exc:
                assert exc.code == 422
    finally:
        app.shutdown()
        engine.close()
