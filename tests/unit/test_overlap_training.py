"""Collective/compute overlap (docs/performance.md "Overlapped
training"): the deferred-consumption accumulation scan and the
shard_map bucketed-psum step must trace the bit-identical loss
trajectory of the serial accumulate — overlap is a SCHEDULING change,
never a numerics change — and bucketed_psum itself must be bitwise
equal to a plain psum under shard_map."""

import numpy as np
import pytest

pytestmark = pytest.mark.quick

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from unionml_tpu.execution import resolve_grad_overlap, run_step_trainer
from unionml_tpu.models.train import (
    GradOverlap,
    accumulated_value_and_grad,
    classification_step,
    create_train_state,
    grad_overlap_scope,
)
from unionml_tpu.parallel import ShardingConfig, bucketed_psum, compile_step


class _Mlp(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.relu(nn.Dense(32)(x)))


def _data(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, 4, size=(n,)).astype(np.int32)
    return x, y


def _loss_fn(module):
    def loss_fn(params, mb):
        feats, labels = mb
        logits = module.apply({"params": params}, feats)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()
        return loss, {"acc": jnp.float32(0.0)}

    return loss_fn


def _leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(p), np.asarray(q))
        for p, q in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b))
    )


# ------------------------------------------------------- trajectory parity


def _loss_trajectory(module, x, y, cfg, overlap):
    """Per-step losses + final params of a 6-step accumulated run,
    compiled under `overlap` (None = serial)."""
    loss_fn = _loss_fn(module)

    def step(state, batch):
        (loss, _aux), grads = accumulated_value_and_grad(
            loss_fn, state.params, batch, overlap=overlap
        )
        return state.apply_gradients(grads=grads), {"loss": loss}

    state = create_train_state(module, x[:4], learning_rate=1e-2, seed=1)
    mcfg = cfg.microbatched()
    compiled, state = compile_step(step, state, sharding=mcfg)
    bspec = mcfg.batch_sharding()
    losses = []
    for i in range(6):
        xb = x[i * 32:(i + 1) * 32].reshape(4, 8, -1)
        yb = y[i * 32:(i + 1) * 32].reshape(4, 8)
        state, m = compiled(
            state, (jax.device_put(xb, bspec), jax.device_put(yb, bspec))
        )
        losses.append(np.asarray(m["loss"]).item())
    return losses, state


def test_defer_mode_bit_identical_on_2x2_mesh():
    """The GSPMD deferred-consumption scan on the dp2×fsdp2 mesh: the
    loss trajectory (not just the final state) is BITWISE equal to the
    serial accumulate — same adds in the same order plus an exact +0."""
    module = _Mlp()
    x, y = _data()
    cfg = ShardingConfig(data=2, fsdp=2, devices=jax.devices()[:4])
    serial, s_final = _loss_trajectory(module, x, y, cfg, None)
    defer, d_final = _loss_trajectory(
        module, x, y, cfg, GradOverlap(mode="defer")
    )
    assert serial == defer  # bitwise: float == float
    assert _leaves_equal(s_final.params, d_final.params)


def test_shard_map_mode_bit_identical_on_dp_mesh():
    """The explicit shard_map + deferred bucketed-psum step on a pure-DP
    mesh traces the bitwise-identical trajectory (power-of-two rows and
    device count: every scale factor is exact)."""
    module = _Mlp()
    x, y = _data()
    cfg = ShardingConfig(data=4, devices=jax.devices()[:4])
    serial, s_final = _loss_trajectory(module, x, y, cfg, None)
    overlap = GradOverlap(mode="shard_map", mesh=cfg.mesh(), axes=("data",))
    sm, m_final = _loss_trajectory(module, x, y, cfg, overlap)
    assert serial == sm
    assert _leaves_equal(s_final.params, m_final.params)


def test_trainer_overlap_grads_end_to_end():
    """run_step_trainer(overlap_grads=True) on the mixed mesh reaches
    the bitwise final state of the serial run — the ambient
    grad_overlap_scope reaches the zoo factory's scan at trace time."""
    module = _Mlp()
    x, y = _data(seed=5)

    def run(overlap_grads):
        return run_step_trainer(
            step_fn=classification_step(module, accumulate_steps=4),
            state=create_train_state(module, x[:4], learning_rate=1e-2, seed=4),
            features=x, targets=y, batch_size=8, accumulate_steps=4,
            num_epochs=2, seed=9,
            sharding=ShardingConfig(data=2, fsdp=2, devices=jax.devices()[:4]),
            overlap_grads=overlap_grads,
        )

    assert _leaves_equal(run(False).params, run(True).params)


# ----------------------------------------------------- strategy selection


def test_resolve_grad_overlap_selection():
    dp = ShardingConfig(data=4, devices=jax.devices()[:4])
    mixed = ShardingConfig(data=2, fsdp=2, tensor=2)
    assert resolve_grad_overlap(dp, 4).mode == "shard_map"
    assert resolve_grad_overlap(dp, 4).axes == ("data",)
    assert resolve_grad_overlap(mixed, 4).mode == "defer"
    assert resolve_grad_overlap(None, 4).mode == "defer"
    # nothing to overlap without a microbatch pipeline
    assert resolve_grad_overlap(dp, 1) is None


def test_grad_overlap_scope_is_ambient():
    with grad_overlap_scope(GradOverlap(mode="defer")):
        from unionml_tpu.models.train import current_grad_overlap

        assert current_grad_overlap().mode == "defer"
    from unionml_tpu.models.train import current_grad_overlap

    assert current_grad_overlap() is None


def test_unknown_overlap_mode_rejected():
    module = _Mlp()
    x, y = _data(n=32)
    state = create_train_state(module, x[:4])
    micro = (x.reshape(4, 8, -1), y.reshape(4, 8))
    with pytest.raises(ValueError, match="GradOverlap mode"):
        accumulated_value_and_grad(
            _loss_fn(module), state.params, micro,
            overlap=GradOverlap(mode="wat"),
        )
    with pytest.raises(ValueError, match="mesh"):
        accumulated_value_and_grad(
            _loss_fn(module), state.params, micro,
            overlap=GradOverlap(mode="shard_map"),
        )


# ------------------------------------------------------------ bucketed psum


def test_bucketed_psum_matches_plain_psum():
    """Bucketing changes how many collectives XLA sees, never the
    values: bitwise equal to leaf-wise psum under shard_map, for bucket
    sizes that split the tree anywhere from one-bucket to one-per-leaf."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    cfg = ShardingConfig(data=8)
    mesh = cfg.mesh()
    rng = np.random.default_rng(0)
    tree = {
        "a": rng.normal(size=(8, 128)).astype(np.float32),   # 4 KB/shard
        "b": rng.normal(size=(8, 4)).astype(np.float32),
        "c": {"d": rng.normal(size=(8, 513)).astype(np.float32)},
    }

    def reduce_with(bucket_bytes):
        fn = shard_map(
            lambda t: bucketed_psum(t, "data", bucket_bytes=bucket_bytes),
            mesh, in_specs=(P("data"),), out_specs=P(), check_rep=False,
        )
        return fn(tree)

    plain = shard_map(
        lambda t: jax.lax.psum(t, "data"),
        mesh, in_specs=(P("data"),), out_specs=P(), check_rep=False,
    )(tree)
    for bucket_bytes in (1, 600, 1 << 20):
        out = reduce_with(bucket_bytes)
        for a, b in zip(
            jax.tree_util.tree_leaves(plain), jax.tree_util.tree_leaves(out)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    with pytest.raises(ValueError, match="bucket_bytes"):
        bucketed_psum(tree, "data", bucket_bytes=0)


def test_bucketed_psum_grouping():
    """The byte-bounded grouping itself: greedy fill, oversized leaves
    get their own bucket, order preserved."""
    calls = []

    class _FakeLax:
        @staticmethod
        def psum(leaves, axis):
            calls.append(len(leaves))
            return leaves

    import unionml_tpu.parallel.collectives as c

    real_lax = c.lax
    c.lax = _FakeLax
    try:
        tree = [
            np.zeros(100, np.float32),   # 400 B
            np.zeros(100, np.float32),   # 400 B  -> bucket 1 (800 <= 1000)
            np.zeros(100, np.float32),   # 400 B  -> bucket 2
            np.zeros(1000, np.float32),  # 4000 B -> its own bucket 3
            np.zeros(10, np.float32),    # 40 B   -> bucket 4
        ]
        out = bucketed_psum(tree, "data", bucket_bytes=1000)
        assert calls == [2, 1, 1, 1]
        assert [o.shape for o in out] == [t.shape for t in tree]
    finally:
        c.lax = real_lax
