"""Mesh/sharding tests on the CPU-simulated 8-device mesh (SURVEY.md §4.3)."""

import jax
import jax.numpy as jnp
import dataclasses

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from unionml_tpu.parallel import (
    PartitionRule,
    ShardingConfig,
    compile_step,
    make_mesh,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_inferred_axis():
    mesh = make_mesh({"data": -1})
    assert mesh.shape == {"data": 8}
    mesh2 = make_mesh({"data": -1, "tensor": 2})
    assert mesh2.shape["data"] == 4 and mesh2.shape["tensor"] == 2


def test_make_mesh_bad_sizes():
    with pytest.raises(ValueError):
        make_mesh({"data": 3})
    with pytest.raises(ValueError):
        make_mesh({"data": -1, "tensor": -1})


def test_make_mesh_hybrid_dcn_axes():
    """Multi-slice layout: the `data` axis spans 2 slices over DCN while
    `tensor` stays inside a slice on ICI — a psum over `data` still
    reduces correctly across the whole hybrid mesh."""
    mesh = make_mesh({"data": 4, "tensor": 2}, dcn_axes={"data": 2})
    assert mesh.shape == {"data": 4, "tensor": 2}

    from unionml_tpu.parallel.compat import shard_map

    x = jnp.arange(8.0)

    def body(x):
        return jax.lax.psum(x, "data")

    out = shard_map(
        body, mesh=mesh, in_specs=P(("data", "tensor")), out_specs=P(("data", "tensor"))
    )(x)
    # device (d, t) holds element d*2+t; psum over `data` gives, for fixed
    # t, sum_d x[d*2+t] = 12 + 4t — wrong reduction groups would differ
    np.testing.assert_allclose(
        np.asarray(out), np.array([12.0, 16.0] * 4)
    )


def test_make_mesh_dcn_axes_validated():
    with pytest.raises(ValueError, match="not a mesh axis"):
        make_mesh({"data": 4, "tensor": 2}, dcn_axes={"dat": 2})
    with pytest.raises(ValueError, match="must divide"):
        make_mesh({"data": 4, "tensor": 2}, dcn_axes={"data": 3})
    with pytest.raises(ValueError, match="must divide"):
        make_mesh({"data": 4, "tensor": 2}, dcn_axes={"data": 0})


def test_bert_attn_impl_validated():
    from unionml_tpu.models import BertClassifier, BertConfig

    model = BertClassifier(
        dataclasses.replace(BertConfig.tiny(), attn_impl="nope")
    )
    tokens = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="unknown attention impl"):
        model.init(jax.random.PRNGKey(0), tokens)
    # the padded-batch (bias) path must validate too, not silently fall
    # back to the reference kernel
    with pytest.raises(ValueError, match="unknown attention impl"):
        model.init(
            jax.random.PRNGKey(0), tokens,
            attention_mask=jnp.ones((1, 8), jnp.int32),
        )


def test_serve_gradio_gated_without_dependency():
    from unionml_tpu import Dataset, Model

    ds = Dataset(name="g_ds")
    m = Model(name="g", dataset=ds)
    with pytest.raises((ImportError, ValueError), match="gradio|artifact"):
        m.serve_gradio()


def test_sharding_config_dp():
    cfg = ShardingConfig(data=-1)
    assert cfg.mesh().shape == {"data": 8}
    assert cfg.batch_pspec() == P("data")


def test_sharding_config_dp_fsdp_batch_axes():
    cfg = ShardingConfig(data=2, fsdp=4)
    assert cfg.batch_pspec() == P(("data", "fsdp"))
    # fsdp fallback shards the largest divisible dim
    leaf = jnp.zeros((16, 3))
    assert cfg.param_pspec("dense/kernel", leaf) == P("fsdp", None)
    scalar = jnp.zeros(())
    assert cfg.param_pspec("step", scalar) == P()


def test_partition_rules_tensor_parallel():
    cfg = ShardingConfig(
        data=-1,
        tensor=2,
        rules=[
            PartitionRule(r"attn/.*kernel", (None, "tensor")),
            PartitionRule(r"mlp/out/kernel", ("tensor", None)),
        ],
    )
    leaf = jnp.zeros((8, 8))
    assert cfg.param_pspec("layer0/attn/q/kernel", leaf) == P(None, "tensor")
    assert cfg.param_pspec("layer0/mlp/out/kernel", leaf) == P("tensor", None)
    assert cfg.param_pspec("layer0/norm/scale", leaf) == P()


def test_compile_step_dp_training():
    """A linear-regression step compiled over the 8-device data axis: the
    gradient psum over ICI is inserted by GSPMD from the shardings."""
    cfg = ShardingConfig(data=-1)

    def step(state, batch):
        x, y = batch
        w, b = state["w"], state["b"]

        def loss_fn(w, b):
            pred = x @ w + b
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(w, b)
        return {"w": w - 0.1 * grads[0], "b": b - 0.1 * grads[1]}, {"loss": loss}

    state = {"w": jnp.zeros((4,)), "b": jnp.zeros(())}
    compiled, placed = compile_step(step, state, sharding=cfg, donate_state=False)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    true_w = np.array([1.0, -2.0, 0.5, 3.0], dtype=np.float32)
    y = x @ true_w + 0.25

    batch = jax.device_put((x, y), cfg.batch_sharding())
    state = placed
    for _ in range(200):
        state, metrics = compiled(state, batch)
    np.testing.assert_allclose(np.asarray(state["w"]), true_w, atol=0.05)
    np.testing.assert_allclose(np.asarray(state["b"]), 0.25, atol=0.05)
    assert float(metrics["loss"]) < 1e-3


def test_compile_step_fsdp_state_sharded():
    cfg = ShardingConfig(data=2, fsdp=4)

    def step(state, batch):
        return jax.tree_util.tree_map(lambda p: p + jnp.mean(batch), state), {}

    state = {"w": jnp.ones((8, 4))}
    compiled, placed = compile_step(step, state, sharding=cfg, donate_state=False)
    # the parameter is physically sharded over the fsdp axis
    sh = placed["w"].sharding
    assert sh.spec == P("fsdp", None)
    out, _ = compiled(placed, jnp.ones((8, 1)))
    assert out["w"].sharding.spec == P("fsdp", None)
