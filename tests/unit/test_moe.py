"""MoE dispatch + expert-parallel tests on the CPU-simulated mesh.

Strategy (SURVEY.md §4.3): the explicit all_to_all shard_map path is
checked numerically (values AND gradients) against a dense per-token
reference; the GSPMD path is checked by compiling a full MoE-Llama train
step with the `expert` mesh axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.models import (
    LLAMA_MOE_PARTITION_RULES,
    Llama,
    LlamaConfig,
    create_train_state,
    lm_step,
    make_generator,
)
from unionml_tpu.ops.moe import (
    MoEMlp,
    expert_parallel_moe,
    make_dispatch,
    top_k_routing,
)
from unionml_tpu.parallel import ShardingConfig, compile_step, make_mesh


def _dense_moe_reference(x, router_kernel, w_gate, w_up, w_down, num_selected):
    """Per-token loop-free dense reference: every routed token processed."""
    gate_logits = (x @ router_kernel).astype(jnp.float32)
    weights, indices, aux = top_k_routing(gate_logits, num_selected)
    num_experts = w_gate.shape[0]
    onehot = jax.nn.one_hot(indices, num_experts, dtype=x.dtype)  # [T,k,E]
    combine = jnp.einsum("tke,tk->te", onehot, weights.astype(x.dtype))
    gated = jax.nn.silu(jnp.einsum("td,edh->eth", x, w_gate))
    up = jnp.einsum("td,edh->eth", x, w_up)
    expert_out = jnp.einsum("eth,ehd->etd", gated * up, w_down)
    return jnp.einsum("etd,te->td", expert_out, combine), aux


def _moe_weights(tokens=32, d=16, hidden=32, experts=8, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (tokens, d))
    router = jax.random.normal(ks[1], (d, experts)) * 0.5
    w_gate = jax.random.normal(ks[2], (experts, d, hidden)) * (d**-0.5)
    w_up = jax.random.normal(ks[3], (experts, d, hidden)) * (d**-0.5)
    w_down = jax.random.normal(ks[4], (experts, hidden, d)) * (hidden**-0.5)
    return x, router, w_gate, w_up, w_down


def test_make_dispatch_respects_capacity():
    gate_logits = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
    dispatch, combine, _ = make_dispatch(gate_logits, num_selected=2, capacity=5)
    # each expert bucket holds at most `capacity` tokens, one per slot
    assert float(dispatch.sum(axis=(0, 2)).max()) <= 5
    assert float(dispatch.max()) <= 1.0
    # every slot holds at most one token
    assert float(dispatch.sum(axis=0).max()) <= 1.0
    # combine weight lives exactly where dispatch does
    assert float(jnp.abs(combine * (1 - dispatch)).max()) == 0.0


def test_make_dispatch_first_choices_win_slots():
    # 3 tokens all routing expert 0 first; capacity 2 drops the last token's
    # first choice but keeps all second choices on expert 1
    gate_logits = jnp.array(
        [[5.0, 1.0, -5.0], [5.0, 1.0, -5.0], [5.0, 1.0, -5.0]], jnp.float32
    )
    dispatch, _, _ = make_dispatch(gate_logits, num_selected=2, capacity=2)
    per_expert = np.asarray(dispatch.sum(axis=2))  # [T, E]
    # tokens 0 and 1 won expert 0's two slots; token 2's 1st choice dropped
    np.testing.assert_array_equal(per_expert[:, 0], [1, 1, 0])
    # 2nd choices (expert 1) bucket after all 1st choices: tokens 0, 1 fit
    np.testing.assert_array_equal(per_expert[:, 1], [1, 1, 0])


@pytest.mark.parametrize("ep", [2, 4])
def test_expert_parallel_matches_dense(ep):
    x, router, w_gate, w_up, w_down = _moe_weights()
    mesh = make_mesh({"expert": ep}, devices=jax.devices()[:ep])
    ref, aux_ref = _dense_moe_reference(x, router, w_gate, w_up, w_down, 2)
    # capacity = local token count: nothing can overflow, outputs must match
    out, aux = expert_parallel_moe(
        x, router, w_gate, w_up, w_down, mesh,
        num_selected=2, capacity=x.shape[0] // ep,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # aux loss: per-shard mean of per-shard top-1 fractions != global aux in
    # general, but both are O(1) balance stats — just require finiteness
    assert np.isfinite(float(aux))


def test_expert_parallel_gradients_match_dense():
    x, router, w_gate, w_up, w_down = _moe_weights(tokens=16, experts=4)
    mesh = make_mesh({"expert": 2}, devices=jax.devices()[:2])

    def loss_ep(x, w_gate, w_down):
        out, _ = expert_parallel_moe(
            x, router, w_gate, w_up, w_down, mesh,
            num_selected=2, capacity=x.shape[0] // 2,
        )
        return jnp.sum(out**2)

    def loss_ref(x, w_gate, w_down):
        out, _ = _dense_moe_reference(x, router, w_gate, w_up, w_down, 2)
        return jnp.sum(out**2)

    g_ep = jax.grad(loss_ep, argnums=(0, 1, 2))(x, w_gate, w_down)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w_gate, w_down)
    for a, b in zip(g_ep, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_expert_parallel_capacity_drops_tokens():
    # capacity 1 per expert: overflow tokens lose (part of) their MLP
    # contribution, so the output must differ from the uncapped reference
    x, router, w_gate, w_up, w_down = _moe_weights(tokens=32, experts=2)
    mesh = make_mesh({"expert": 2}, devices=jax.devices()[:2])
    ref, _ = _dense_moe_reference(x, router, w_gate, w_up, w_down, 1)
    out, _ = expert_parallel_moe(
        x, router, w_gate, w_up, w_down, mesh, num_selected=1, capacity=1
    )
    assert not np.allclose(np.asarray(out), np.asarray(ref))


def test_moe_mlp_module_dense_path():
    module = MoEMlp(
        num_experts=4, num_selected=2, hidden_dim=32, model_dim=16,
        dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = module.init(jax.random.PRNGKey(1), x)["params"]
    out, aux = module.apply({"params": params}, x)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))
    # routed MLP must actually transform the input
    assert not np.allclose(np.asarray(out), np.asarray(x))


def test_moe_llama_train_step_loss_decreases():
    cfg = LlamaConfig.tiny(vocab_size=64, num_experts=4, num_selected=2)
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64)
    state = create_train_state(module, tokens[:1], learning_rate=1e-2)
    step = jax.jit(lm_step(module))
    _, first = step(state, tokens)
    for _ in range(10):
        state, metrics = step(state, tokens)
    assert float(metrics["loss"]) < float(first["loss"])
    assert np.isfinite(float(metrics["aux_loss"])) and float(metrics["aux_loss"]) > 0


def test_dense_llama_aux_loss_metric_is_zero():
    cfg = LlamaConfig.tiny(vocab_size=64)
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 64)
    state = create_train_state(module, tokens[:1])
    _, metrics = jax.jit(lm_step(module))(state, tokens)
    assert float(metrics["aux_loss"]) == 0.0


def test_moe_llama_expert_parallel_gspmd_step():
    # full train step over a data x expert x tensor mesh: expert weights
    # shard over `expert` per LLAMA_MOE_PARTITION_RULES, GSPMD inserts the
    # dispatch collectives
    cfg = LlamaConfig.tiny(vocab_size=64, num_experts=4, num_selected=2)
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    state = create_train_state(module, tokens[:1], learning_rate=1e-2)
    sharding = ShardingConfig(
        data=-1, expert=2, tensor=2, rules=LLAMA_MOE_PARTITION_RULES
    )
    step, state = compile_step(lm_step(module), state, sharding=sharding)
    # expert dim actually sharded on the mesh
    moe_shard = state.params["block_0"]["moe"]["w_gate"].sharding
    assert "expert" in moe_shard.spec
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


def test_moe_config_validation():
    with pytest.raises(ValueError, match="num_selected"):
        LlamaConfig.tiny(num_experts=1)  # default num_selected=2 > experts
    with pytest.raises(ValueError, match="num_selected"):
        LlamaConfig.tiny(num_experts=4, num_selected=0)


def test_quantized_moe_matches_fp_module():
    from unionml_tpu.models import LLAMA_QUANT_PATTERNS, quantize_params

    fp = MoEMlp(num_experts=4, num_selected=2, hidden_dim=32, model_dim=16,
                dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = fp.init(jax.random.PRNGKey(1), x)["params"]
    ref, aux_ref = fp.apply({"params": params}, x)

    qparams = quantize_params({"moe": params}, LLAMA_QUANT_PATTERNS)["moe"]
    assert qparams["w_gate_q"].dtype == jnp.int8
    assert qparams["w_gate_scale"].shape == (4, 32)
    qmod = MoEMlp(num_experts=4, num_selected=2, hidden_dim=32, model_dim=16,
                  dtype=jnp.float32, quantized=True)
    out, aux = qmod.apply({"params": qparams}, x)
    # int8 weight-only: a ~1% relative error bound on the block output
    rel = float(jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref))
    assert rel < 0.02, rel
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-5)


def test_quantized_moe_bf16_error_bounded():
    # production dtype: fp32 accumulate + fp32 scale before the single
    # bf16 cast must keep the int8 error near the fp32-path bound
    from unionml_tpu.models import LLAMA_QUANT_PATTERNS, quantize_params

    fp = MoEMlp(num_experts=4, num_selected=2, hidden_dim=64, model_dim=32,
                dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32))
    params = fp.init(jax.random.PRNGKey(1), x)["params"]
    ref, _ = fp.apply({"params": params}, x)
    qparams = quantize_params({"moe": params}, LLAMA_QUANT_PATTERNS)["moe"]
    qmod = MoEMlp(num_experts=4, num_selected=2, hidden_dim=64, model_dim=32,
                  dtype=jnp.bfloat16, quantized=True)
    out, _ = qmod.apply({"params": qparams}, x.astype(jnp.bfloat16))
    rel = float(
        jnp.linalg.norm(out.astype(jnp.float32) - ref) / jnp.linalg.norm(ref)
    )
    assert rel < 0.05, rel


def test_quantized_moe_llama_generation():
    from unionml_tpu.models import LLAMA_QUANT_PATTERNS, quantize_params

    cfg = LlamaConfig.tiny(vocab_size=64, num_experts=4, num_selected=2)
    module = Llama(cfg)
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    qcfg = LlamaConfig.tiny(vocab_size=64, num_experts=4, num_selected=2,
                            quantized=True)
    qparams = quantize_params(params, LLAMA_QUANT_PATTERNS)
    generate = make_generator(Llama(qcfg), max_new_tokens=4)
    out = generate(qparams, jnp.asarray([[1, 2, 3, 4]], jnp.int32))
    assert out.shape == (1, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_moe_llama_generation():
    cfg = LlamaConfig.tiny(vocab_size=64, num_experts=4, num_selected=2)
    module = Llama(cfg)
    tokens = jnp.zeros((1, 4), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    generate = make_generator(module, max_new_tokens=4)
    out = generate(params, jnp.asarray([[1, 2, 3, 4]], jnp.int32))
    assert out.shape == (1, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_migrate_moe_router_params_old_layout_restores():
    """Old Dense-submodule router checkpoints rename to router_kernel.

    PARITY.md documents the layout break; the helper must produce a tree
    MoEMlp.apply accepts, keep the router fp32, and drop the old bias.
    """
    from unionml_tpu.ops import migrate_moe_router_params

    module = MoEMlp(num_experts=4, num_selected=2, hidden_dim=8, model_dim=8)
    x = jnp.ones((1, 3, 8), jnp.bfloat16)
    params = module.init(jax.random.PRNGKey(0), x)["params"]

    # reconstruct the pre-round-1 layout: router as a Dense submodule
    old = {k: v for k, v in params.items() if k != "router_kernel"}
    old["router"] = {
        "kernel": params["router_kernel"].astype(jnp.bfloat16),
        "bias": jnp.zeros((4,), jnp.bfloat16),
    }
    nested_old = {"block_0": {"moe": old}, "head": {"kernel": jnp.ones((8, 2))}}

    # old flax artifacts are often FrozenDicts — the helper must recurse
    # through any Mapping, not just plain dicts
    import flax.core

    migrated = migrate_moe_router_params(flax.core.freeze(nested_old))
    new_moe = migrated["block_0"]["moe"]
    assert "router" not in new_moe
    assert new_moe["router_kernel"].dtype == jnp.float32
    # untouched siblings survive
    np.testing.assert_array_equal(
        np.asarray(migrated["head"]["kernel"]), np.ones((8, 2))
    )
    out, aux = module.apply({"params": new_moe}, x)
    assert out.shape == x.shape and np.isfinite(float(aux))
