"""Serving-mode auto-selection (serving/auto.py): the measured
engine-vs-batcher crossover rule, decided from evidence instead of
operator guesswork."""

import jax
import jax.numpy as jnp
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.serving.auto import (
    choose_serving_mode,
    decide_mode,
    measure_decode_chunk_ms,
    measure_rtt_ms,
)


def test_decide_mode_both_ways():
    # tunneled-backend regime: RTT >> chunk compute → batcher
    assert decide_mode(rtt_ms=119.0, decode_chunk_ms=26.0) == "batcher"
    # directly-attached or big-model regime: chunk >= RTT → engine
    assert decide_mode(rtt_ms=0.5, decode_chunk_ms=26.0) == "engine"
    assert decide_mode(rtt_ms=88.0, decode_chunk_ms=88.0) == "engine"  # tie
    with pytest.raises(ValueError, match="non-negative"):
        decide_mode(rtt_ms=-1.0, decode_chunk_ms=1.0)


def test_choose_serving_mode_injected_timings():
    out = choose_serving_mode(rtt_ms=119.0, decode_chunk_ms=26.7)
    assert out["mode"] == "batcher"
    assert out["rtt_ms"] == 119.0 and out["decode_chunk_ms"] == 26.7
    assert "rule" in out
    out = choose_serving_mode(rtt_ms=10.0, decode_chunk_ms=88.0)
    assert out["mode"] == "engine"


def test_choose_serving_mode_requires_a_measurement_source():
    with pytest.raises(ValueError, match="decode_chunk_ms"):
        choose_serving_mode(rtt_ms=1.0)


def test_measurements_run_and_are_positive():
    rtt = measure_rtt_ms(reps=3)
    assert rtt >= 0.0
    cfg = LlamaConfig.tiny(vocab_size=64)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    chunk = measure_decode_chunk_ms(
        module, params, chunk_steps=4, prompt_len=8, reps=1
    )
    assert chunk >= 0.0
    decision = choose_serving_mode(module, params, chunk_steps=4)
    assert decision["mode"] in ("engine", "batcher")
