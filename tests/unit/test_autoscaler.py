"""Autoscaler choreography tests (docs/robustness.md "Autoscaling &
self-healing"): the closed loop must scale OUT on sustained multiwindow
SLO burn or headroom exhaustion (never on a blip), warm-join new
capacity from the fleet's hottest prefix blocks before it takes
traffic, scale IN only through the hysteresis band and never while
failure recovery is in flight, survive a broken provisioner with
backoff instead of wedging, repair the fleet under repeated kills, and
— THE acceptance — replace a replica killed mid-flood with zero
caller-visible failures and token parity, then shrink back to baseline
when the load drops."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.autoscaler import (
    AutoscalerPolicy,
    EngineReplicaProvisioner,
    FleetAutoscaler,
    HttpReplicaProvisioner,
    ReplicaProvisioner,
)
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    EngineUnavailable,
    FaultInjector,
    xla_oom_error,
)
from unionml_tpu.serving.prefix_cache import RadixPrefixCache
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    ReplicaHandle,
    RouterPolicy,
)
from unionml_tpu.serving.usage import UsageLedger

pytestmark = pytest.mark.chaos


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakeSlo:
    """Settable stand-in for SloWatchdog's burn read."""

    def __init__(self):
        self.fast = 0.0
        self.slow = 0.0
        self.evals = 0

    def evaluate(self, now=None):
        self.evals += 1
        return {}

    def burn_scores(self):
        return {"fast": self.fast, "slow": self.slow}


class FakeReplica(ReplicaHandle):
    """Scriptable replica with an optional REAL prefix cache (warm-join
    export/import rides the genuine block machinery)."""

    def __init__(self, name, tokens=(1, 2, 3, 4), *, chunk=2,
                 queue_depth=0, burn=0.0, status="ok", cache=None,
                 breaker_open=False):
        self.name = name
        self.tokens = list(tokens)
        self.chunk = chunk
        self.queue_depth = queue_depth
        self.burn = burn
        self.status = status
        self.cache = cache
        self.breaker_open = breaker_open
        self.dead = False
        self.dispatches = 0
        self.drained = False

    def generate_stream(self, prompt, *, max_new_tokens=None):
        if self.dead:
            raise EngineUnavailable(
                f"{self.name} is dead", reason="unreachable",
            )
        self.dispatches += 1
        for i in range(0, len(self.tokens), self.chunk):
            yield self.tokens[i:i + self.chunk]

    def health(self):
        if self.dead:
            raise ConnectionError(f"{self.name} is dead")
        return {
            "status": self.status,
            "queue_depth": self.queue_depth,
            "burn": self.burn,
            "breaker_open": self.breaker_open,
        }

    def cached_prefix_len(self, prompt):
        return 0 if self.cache is None else self.cache.peek(prompt)

    def cache_blocks(self):
        return 0 if self.cache is None else self.cache.entries

    def export_hot_blocks(self, max_blocks=64):
        if self.cache is None:
            return []
        return self.cache.export_hot(max_blocks=max_blocks)

    def import_cache_blocks(self, entries):
        return 0 if self.cache is None else self.cache.import_blocks(entries)

    def drain(self, timeout=None):
        self.drained = True
        return True


class FakeProvisioner(ReplicaProvisioner):
    def __init__(self, *, fail_times=0, with_cache=False, tokens=(9, 9)):
        self.fail_times = fail_times
        self.with_cache = with_cache
        self.tokens = tokens
        self.attempts = 0
        self.provisioned = []
        self.released = []

    def provision(self, name):
        self.attempts += 1
        if self.attempts <= self.fail_times:
            raise RuntimeError(f"provision boom #{self.attempts}")
        cache = (
            RadixPrefixCache(
                block_size=4, registry=telemetry.MetricsRegistry(),
            )
            if self.with_cache else None
        )
        replica = FakeReplica(name, tokens=self.tokens, cache=cache)
        self.provisioned.append(replica)
        return replica

    def release(self, handle):
        self.released.append(handle.name)


def _fleet(replicas, clock, **router_kw):
    router_kw.setdefault("health_ttl_s", 0.0)
    router_kw.setdefault("jitter_s", 0.0)
    router_kw.setdefault("backoff_base_s", 0.0)
    return FleetRouter(
        replicas,
        policy=RouterPolicy(**router_kw),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        clock=clock,
        sleep=lambda s: None,
    )


def _autoscaler(router, provisioner, clock, *, slo=None, usage=None,
                flight=None, **policy_kw):
    policy_kw.setdefault("cooldown_out_s", 10.0)
    policy_kw.setdefault("cooldown_in_s", 10.0)
    return FleetAutoscaler(
        router, provisioner,
        policy=AutoscalerPolicy(**policy_kw),
        slo=slo, usage=usage,
        registry=telemetry.MetricsRegistry(),
        flight=flight if flight is not None else router._flight,
        clock=clock,
    )


def _seed_cache(cache, n_blocks, base=100):
    tokens = list(range(base, base + 4 * n_blocks))
    rows = [
        ((np.full((1, 4, 2), i, np.float32),),) for i in range(n_blocks)
    ]
    cache.insert(tokens, 0, rows)
    return tokens


# ---------------------------------------------------------------- policy


def test_policy_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalerPolicy(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalerPolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="sustain_evals"):
        AutoscalerPolicy(sustain_evals=0)
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscalerPolicy(headroom_out=0.5, headroom_in=0.4)
    with pytest.raises(ValueError, match="warm_blocks"):
        AutoscalerPolicy(warm_blocks=-1)
    with pytest.raises(ValueError, match="reap_unhealthy_evals"):
        AutoscalerPolicy(reap_unhealthy_evals=0)


# ------------------------------------------------------------- scale out


def test_scale_out_on_sustained_burn_not_blip():
    """Both windows must burn past threshold for sustain_evals
    consecutive evaluations — a one-evaluation blip buys nothing, the
    sustained burn buys a replica."""
    clock = _Clock()
    slo = FakeSlo()
    router = _fleet([FakeReplica("r0")], clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, slo=slo, sustain_evals=2, max_replicas=3,
    )

    # a blip: hot once, then clear — no scale
    slo.fast, slo.slow = 20.0, 5.0
    assert auto.evaluate(now=clock())["decision"] == "scale_hold"
    slo.fast, slo.slow = 0.0, 0.0
    clock.advance(1)
    assert auto.evaluate(now=clock())["decision"] == "scale_hold"
    assert prov.attempts == 0

    # sustained: hot for two consecutive evaluations — scale out
    slo.fast, slo.slow = 20.0, 5.0
    clock.advance(1)
    auto.evaluate(now=clock())
    clock.advance(1)
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_out"
    assert decision["reason"] == "slo_burn"
    assert "auto-0" in router.health()["replicas"]
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "scale_out" in kinds and "join" in kinds

    # fast window alone must NOT trigger (multiwindow discipline)
    slo.fast, slo.slow = 20.0, 0.0
    for _ in range(4):
        clock.advance(20)
        assert auto.evaluate(now=clock())["decision"] == "scale_hold"
    assert prov.attempts == 1


def test_scale_out_on_headroom_exhaustion_and_max_cap():
    """Recent-window headroom under headroom_out scales out; the
    max_replicas cap holds further growth (decision explainable as
    at_max)."""
    clock = _Clock()
    ledger = UsageLedger(registry=telemetry.MetricsRegistry())
    router = _fleet([FakeReplica("r0")], clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, usage=ledger,
        headroom_out=0.3, headroom_in=0.6, max_replicas=2,
        cooldown_out_s=1.0,
    )
    auto.evaluate(now=clock())  # baseline sample (captures totals)

    ledger.attribute({"t": 95}, device_s=1.0, slot_steps=100.0)
    clock.advance(5)
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_out"
    assert decision["reason"] == "headroom"
    assert decision["headroom"] == pytest.approx(0.05)

    # still exhausted, but the fleet is at max_replicas now
    ledger.attribute({"t": 95}, device_s=1.0, slot_steps=100.0)
    clock.advance(5)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "at_max",
    )
    holds = router._flight.dump(kind="scale_hold")
    assert holds and holds[-1]["reason"] == "at_max"


def test_scale_out_cooldown_hysteresis_on_synthetic_clock():
    """Per-direction cooldown: a second trigger inside cooldown_out_s
    holds (explainably), after the window it scales — deterministic on
    the synthetic clock."""
    clock = _Clock()
    slo = FakeSlo()
    slo.fast, slo.slow = 20.0, 5.0
    router = _fleet([FakeReplica("r0")], clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, slo=slo, sustain_evals=1,
        cooldown_out_s=30.0, max_replicas=4,
    )
    assert auto.evaluate(now=clock())["decision"] == "scale_out"
    clock.advance(5)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "cooldown_out",
    )
    clock.advance(26)  # past the cooldown
    assert auto.evaluate(now=clock())["decision"] == "scale_out"
    assert prov.attempts == 2


# ------------------------------------------------------------ warm joins


def test_warm_join_imports_hot_blocks_from_warmest_donor():
    """The joiner is fleet-warmed BEFORE it becomes routable: hottest
    blocks from the donor with the most resident cache blocks."""
    clock = _Clock()
    cold = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    warm = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    _seed_cache(cold, 1)
    tokens = _seed_cache(warm, 3)
    router = _fleet(
        [FakeReplica("r0", cache=cold), FakeReplica("r1", cache=warm)],
        clock,
    )
    prov = FakeProvisioner(with_cache=True)
    auto = _autoscaler(
        router, prov, clock, min_replicas=3, max_replicas=4, warm_blocks=8,
    )
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_out", "below_min",
    )
    assert decision["donor"] == "r1"           # warmest, not r0
    assert decision["warmed_blocks"] == 3
    joiner = prov.provisioned[0]
    assert joiner.cache.entries == 3
    assert joiner.cache.peek(tokens) == 12     # warm prefixes ready
    event = router._flight.dump(kind="scale_out")[-1]
    assert event["donor"] == "r1" and event["warmed_blocks"] == 3
    assert int(auto._m_warmed.value) == 3


def test_warm_join_with_zero_exportable_blocks():
    """An empty fleet cache must not break the join: the replica joins
    cold, explainably (warmed_blocks=0, no donor)."""
    clock = _Clock()
    router = _fleet(
        [FakeReplica("r0", cache=RadixPrefixCache(
            block_size=4, registry=telemetry.MetricsRegistry(),
        ))],
        clock,
    )
    prov = FakeProvisioner(with_cache=True)
    auto = _autoscaler(router, prov, clock, min_replicas=2, max_replicas=3)
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_out"
    assert decision["donor"] is None and decision["warmed_blocks"] == 0
    assert prov.provisioned[0].cache.entries == 0
    assert "auto-0" in router.health()["replicas"]


# ------------------------------------------------------------- scale in


def test_scale_in_drains_coldest_lowest_load_with_hysteresis():
    """Scale-in picks the coldest-cache, lowest-load victim, and only
    fires when the PROJECTED post-removal headroom clears the band —
    mid-band utilization holds even though no trigger is hot."""
    clock = _Clock()
    ledger = UsageLedger(registry=telemetry.MetricsRegistry())
    warm = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    _seed_cache(warm, 3)
    replicas = [
        FakeReplica("r0", cache=warm, queue_depth=1),
        FakeReplica("r1", queue_depth=3),   # cold cache, deeper queue
        FakeReplica("r2", queue_depth=0),   # cold cache, idle -> victim
    ]
    router = _fleet(replicas, clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, usage=ledger,
        headroom_out=0.1, headroom_in=0.5, cooldown_in_s=5.0,
    )
    auto.evaluate(now=clock())  # baseline totals

    # mid-band: headroom 0.4 -> projected 1 - 0.6*3/2 = 0.1 < 0.5: HOLD
    ledger.attribute({"t": 60}, slot_steps=100.0)
    clock.advance(6)
    assert auto.evaluate(now=clock())["decision"] == "scale_hold"
    assert len(router.health()["replicas"]) == 3

    # light traffic: headroom 0.9 -> projected 0.85 > 0.5: scale in
    ledger.attribute({"t": 10}, slot_steps=100.0)
    clock.advance(6)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_in", "surplus",
    )
    assert decision["replica"] == "r2"     # coldest cache, lowest load
    assert replicas[2].drained
    assert "r2" not in router.health()["replicas"]
    event = router._flight.dump(kind="scale_in")[-1]
    assert event["replica"] == "r2"

    # cooldown_in: an immediately-following idle eval holds
    for r in replicas:
        r.queue_depth = 0   # idle consolidation also needs empty queues
    clock.advance(1)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "cooldown_in",
    )
    # past the cooldown, the idle fleet keeps consolidating
    clock.advance(6)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_in", "idle",
    )
    # and never below min_replicas: one replica left -> steady forever
    clock.advance(6)
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_hold"
    assert len(router.health()["replicas"]) == 1


def test_scale_in_holds_during_ejection_breaker_and_drain():
    """Scale decisions must not fight failure recovery: an ejected
    replica, an open breaker, or an in-flight drain each hold
    scale-in — explainably."""
    clock = _Clock()
    replicas = [FakeReplica("r0"), FakeReplica("r1"), FakeReplica("r2")]
    router = _fleet(replicas, clock)
    prov = FakeProvisioner()
    auto = _autoscaler(router, prov, clock, cooldown_in_s=0.0)

    # racing an ejection: r0 mid-recovery
    router._replicas["r0"].state = "ejected"
    router._replicas["r0"].rejoin_at = clock() + 100.0
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "recovery_in_flight",
    )
    assert len(router.health()["replicas"]) == 3
    router._replicas["r0"].state = "live"

    # an open circuit breaker anywhere holds
    replicas[1].breaker_open = True
    clock.advance(1)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "breaker_open",
    )
    replicas[1].breaker_open = False

    # a drain in flight holds
    router.drain_replica("r2")
    clock.advance(1)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "drain_in_flight",
    )
    router.rejoin_replica("r2")

    # recovery over: the idle fleet may consolidate again
    clock.advance(1)
    assert auto.evaluate(now=clock())["decision"] == "scale_in"


def test_scale_in_respects_router_min_live_floor():
    """The router's own min_live floor outranks the autoscaler's
    appetite: live-1 < min_live holds even when min_replicas allows."""
    clock = _Clock()
    router = _fleet(
        [FakeReplica("r0"), FakeReplica("r1")], clock, min_live=2,
    )
    auto = _autoscaler(
        router, FakeProvisioner(), clock,
        min_replicas=1, cooldown_in_s=0.0,
    )
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "min_live",
    )


# ----------------------------------------------- provisioner resilience


def test_provisioner_failure_retries_with_backoff_not_wedge():
    """A broken provisioner schedules exponential-backoff retries; the
    loop keeps evaluating and succeeds once the provisioner heals."""
    clock = _Clock()
    slo = FakeSlo()
    slo.fast, slo.slow = 20.0, 5.0
    router = _fleet([FakeReplica("r0")], clock)
    prov = FakeProvisioner(fail_times=2)
    auto = _autoscaler(
        router, prov, clock, slo=slo, sustain_evals=1,
        provision_backoff_s=1.0, provision_backoff_max_s=8.0,
        cooldown_out_s=0.0, max_replicas=3,
    )
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "provision_failed",
    )
    # inside the backoff: held WITHOUT another provision attempt
    clock.advance(0.5)
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "provision_backoff",
    )
    assert prov.attempts == 1
    # past the backoff: retry fires (and fails; backoff doubles to 2 s)
    clock.advance(0.6)
    decision = auto.evaluate(now=clock())
    assert decision["reason"] == "provision_failed"
    assert prov.attempts == 2
    clock.advance(1.0)   # inside the DOUBLED backoff
    assert auto.evaluate(now=clock())["reason"] == "provision_backoff"
    clock.advance(1.1)   # past it: healed provisioner succeeds
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_out"
    assert prov.attempts == 3
    assert int(auto._m_provision_failures.value) == 2
    fails = [
        e for e in router._flight.dump(kind="scale_hold")
        if e["reason"] == "provision_failed"
    ]
    assert len(fails) == 2 and "retry_in_s" in fails[0]


def test_min_replicas_floor_under_repeated_kills():
    """Self-healing: every kill is reaped and replaced back to
    min_replicas, cooldown exempt (repair must not wait out a scale
    cooldown)."""
    clock = _Clock()
    replicas = [FakeReplica("r0"), FakeReplica("r1")]
    router = _fleet(replicas, clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, min_replicas=2, max_replicas=2,
        cooldown_out_s=1000.0, reap_unhealthy_evals=2,
    )
    victims = [replicas[0], replicas[1]]
    for round_, victim in enumerate(victims):
        victim.dead = True
        # eval 1: corpse seen (at_max until reaped -> hold)
        clock.advance(1)
        decision = auto.evaluate(now=clock())
        assert decision["decision"] == "scale_hold"
        # eval 2: corpse reaped AND replacement provisioned
        clock.advance(1)
        decision = auto.evaluate(now=clock())
        assert (decision["decision"], decision["reason"]) == (
            "scale_out", "below_min",
        ), f"round {round_}: {decision}"
        members = router.health()["replicas"]
        assert victim.name not in members
        assert len(members) == 2
        assert router.health()["live_replicas"] == 2
    assert int(auto._m_reaped.value) == 2
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "scale_reap" in kinds
    # kill a provisioned replica too: reaping releases it
    prov.provisioned[0].dead = True
    clock.advance(1)
    auto.evaluate(now=clock())
    clock.advance(1)
    auto.evaluate(now=clock())
    assert prov.provisioned[0].name in prov.released


def test_http_provisioner_spawn_and_teardown():
    spawned, torn = [], []

    def spawn(name):
        spawned.append(name)
        return f"http://127.0.0.1:1/{name}"

    prov = HttpReplicaProvisioner(
        spawn, teardown=lambda h: torn.append(h.name), timeout_s=3.0,
    )
    handle = prov.provision("auto-7")
    assert spawned == ["auto-7"]
    assert handle.name == "auto-7"
    assert handle.timeout_s == 3.0
    prov.release(handle)
    assert torn == ["auto-7"]


def test_stats_and_decision_counters_reconstruct_decisions():
    """Every evaluation lands in exactly one decisions_total child —
    the counter stream alone reconstructs out/in/hold history."""
    clock = _Clock()
    slo = FakeSlo()
    router = _fleet([FakeReplica("r0"), FakeReplica("r1")], clock)
    auto = _autoscaler(
        router, FakeProvisioner(), clock, slo=slo,
        sustain_evals=1, cooldown_in_s=0.0, max_replicas=3,
    )
    n_evals = 0
    for fast, slow in [(0, 0), (20, 5), (0, 0), (0, 0)]:
        slo.fast, slo.slow = float(fast), float(slow)
        clock.advance(20)
        auto.evaluate(now=clock())
        n_evals += 1
    total = sum(
        child.value for _, child in auto._m_decisions.children()
    )
    assert total == n_evals
    by_decision = {}
    for values, child in auto._m_decisions.children():
        by_decision[values[0]] = by_decision.get(values[0], 0) + child.value
    assert by_decision.get("scale_out") == 1    # the burn eval
    assert by_decision.get("scale_in", 0) >= 1  # idle consolidation
    stats = auto.stats()
    assert stats["last_decision"]["decision"] in (
        "scale_out", "scale_in", "scale_hold",
    )


# -------------------------------------------- engine-backed (THE test)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(
        gen(params, jnp.asarray([prompt], jnp.int32))
    )[0].tolist()


class KillableEngineReplica(EngineReplica):
    """An EngineReplica that can 'die' like a crashed process: armed
    fault kills the in-flight batch (retryable, PR 3 recovery), the
    kill flag makes every later dispatch/health read unreachable."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.killed = False

    def kill(self):
        self.killed = True

    def generate_stream(self, prompt, *, max_new_tokens=None):
        if self.killed:
            raise EngineUnavailable(
                f"{self.name} process died", reason="unreachable",
            )
        return super().generate_stream(
            prompt, max_new_tokens=max_new_tokens
        )

    def generate(self, prompt, *, max_new_tokens=None):
        if self.killed:
            raise EngineUnavailable(
                f"{self.name} process died", reason="unreachable",
            )
        return super().generate(prompt, max_new_tokens=max_new_tokens)

    def health(self):
        if self.killed:
            raise ConnectionError(f"{self.name} process died")
        return super().health()


def test_autoscaler_replaces_killed_replica_under_flood(tiny_llama):
    """THE acceptance: a sustained flood drives headroom under the
    scale-out floor, a replica is killed mid-flood, and the autoscaler
    (a) scales out, (b) reaps and replaces the corpse (warm-joined
    from a donor's hot prefix blocks), with ZERO caller-visible
    failures and exact token parity throughout; after the flood the
    fleet scales back in to baseline within the cooldown."""
    module, params = tiny_llama
    n_new = 16
    slots, bucket, chunk_steps = 2, 32, 4
    ledger = UsageLedger(registry=telemetry.MetricsRegistry())
    fis = [FaultInjector(), FaultInjector()]

    def make_engine(fi=None):
        return DecodeEngine(
            module, slots=slots, max_new_tokens=n_new,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            prefix_cache=True, usage=ledger, max_queue_depth=64,
            **({"fault_injector": fi} if fi is not None else {}),
        )

    engines = [make_engine(fis[0]), make_engine(fis[1])]
    replicas = [
        KillableEngineReplica(engines[i], params, name=f"r{i}")
        for i in range(2)
    ]
    flight = telemetry.FlightRecorder()
    router = FleetRouter(
        replicas,
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.0,
            max_attempts=4, retry_budget_burst=50.0,
            retry_budget_ratio=1.0, eject_consecutive=1,
            eject_cooldown_s=1000.0,  # a corpse stays ejected; reap ends it
        ),
        registry=telemetry.MetricsRegistry(),
        flight=flight,
    )
    aux_engines = []

    def factory():
        engine = make_engine()
        aux_engines.append(engine)
        return engine, params

    auto = FleetAutoscaler(
        router,
        EngineReplicaProvisioner(factory),
        policy=AutoscalerPolicy(
            min_replicas=2, max_replicas=3,
            headroom_out=0.7, headroom_in=0.95,
            cooldown_out_s=0.0, cooldown_in_s=0.0,
            warm_blocks=32, reap_unhealthy_evals=2,
            drain_timeout_s=30.0,
        ),
        usage=ledger,
        registry=telemetry.MetricsRegistry(),
        flight=flight,
    )
    rng = np.random.default_rng(0)
    distinct = [
        rng.integers(1, 97, bucket // 2).tolist() for _ in range(6)
    ]
    try:
        for e in engines:
            e.warmup(params)
        solo = {
            tuple(p): _solo(
                module, params, p, n_new, max_len=engines[0].cache_len,
            ) for p in distinct
        }
        results, failures, lock = [], [], threading.Lock()
        clients, n_req = 6, 60
        started = threading.Event()

        def client(idx):
            for j in range(n_req // clients):
                p = distinct[(idx + j) % len(distinct)]
                if idx == 0 and j == 1:
                    started.set()
                try:
                    out = router.generate(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:  # EVERY failure counts
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        started.wait(timeout=60)

        # the control loop, driven explicitly (the production ticker
        # is just this on a timer)
        killed = [False]
        deadline = time.monotonic() + 240.0
        while any(t.is_alive() for t in threads):
            if time.monotonic() > deadline:
                pytest.fail("flood did not complete")
            auto.evaluate()
            members = router.health()["replicas"]
            if not killed[0] and "auto-0" in members:
                # scale-out happened: NOW kill r0 mid-flood (fault
                # poisons the in-flight batch retryably, then the
                # replica reads as a dead process)
                fis[0].arm("engine.dispatch", exc=xla_oom_error())
                replicas[0].kill()
                killed[0] = True
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=60)

        assert killed[0], "the mid-flood kill never armed (no scale-out?)"
        assert not failures, (
            f"{len(failures)} caller-visible failures (want 0): "
            f"{sorted(set(failures))[:3]}"
        )
        bad = sum(1 for key, out in results if out != solo[key])
        assert bad == 0, f"{bad}/{len(results)} lost token parity"
        assert len(results) == n_req

        # the corpse was reaped and replaced: r0 gone, fleet healthy
        def settle(max_evals=20):
            for _ in range(max_evals):
                auto.evaluate()
                members = router.health()["replicas"]
                if "r0" not in members and all(
                    m["state"] == "live" for m in members.values()
                ):
                    return members
                time.sleep(0.05)
            return router.health()["replicas"]

        members = settle()
        assert "r0" not in members, f"corpse not reaped: {members}"
        assert int(auto._m_reaped.value) >= 1

        # scale-out was fleet-WARMED: the joiner imported hot blocks
        outs = flight.dump(kind="scale_out")
        assert outs, "no scale_out flight event"
        assert any(e.get("warmed_blocks", 0) > 0 for e in outs), outs
        assert int(auto._m_warmed.value) > 0
        # and the joiner served with parity (asserted above) from a
        # cache that actually holds fleet prefixes
        warmed = [e for e in outs if e.get("warmed_blocks", 0) > 0]
        assert warmed[0]["donor"] in ("r0", "r1", "auto-0")

        # flood over: no traffic -> the fleet consolidates to baseline
        for _ in range(30):
            auto.evaluate()
            if len(router.health()["replicas"]) <= 2:
                break
            time.sleep(0.02)
        members = router.health()["replicas"]
        assert len(members) == 2, f"did not scale back in: {members}"
        kinds = [e["kind"] for e in flight.dump()]
        assert "scale_in" in kinds

        # every decision is reconstructible: one counter per evaluation
        total = sum(
            child.value for _, child in auto._m_decisions.children()
        )
        assert total > 0
    finally:
        auto.close()
        for e in engines + aux_engines:
            e.close()


def test_scale_in_holds_while_work_is_queued():
    """Queued work anywhere contradicts idle/surplus regardless of
    ledger wiring: a fleet run with usage=None must not shrink itself
    under load just because no capacity signal exists."""
    clock = _Clock()
    replicas = [
        FakeReplica("r0", queue_depth=3), FakeReplica("r1", queue_depth=2),
    ]
    router = _fleet(replicas, clock)
    auto = _autoscaler(router, FakeProvisioner(), clock, cooldown_in_s=0.0)
    for _ in range(4):
        clock.advance(10)
        decision = auto.evaluate(now=clock())
        assert decision["decision"] == "scale_hold", decision
    assert len(router.health()["replicas"]) == 2
    # queues drain -> the idle fleet may consolidate
    for r in replicas:
        r.queue_depth = 0
    clock.advance(10)
    assert auto.evaluate(now=clock())["decision"] == "scale_in"


def test_join_name_collision_releases_handle_and_retries_fresh():
    """add_replica raising (e.g. an operator-registered replica
    already owns the name) must release the provisioned handle and
    surface as a decision — and the next attempt picks a fresh name."""
    clock = _Clock()
    router = _fleet([FakeReplica("r0"), FakeReplica("auto-0")], clock)
    prov = FakeProvisioner()
    auto = _autoscaler(
        router, prov, clock, min_replicas=3, max_replicas=4,
        cooldown_out_s=0.0, provision_backoff_s=0.0,
    )
    decision = auto.evaluate(now=clock())
    assert (decision["decision"], decision["reason"]) == (
        "scale_hold", "provision_failed",
    )
    assert prov.released == ["auto-0"]          # no leaked handle
    clock.advance(1)
    decision = auto.evaluate(now=clock())
    assert decision["decision"] == "scale_out"
    assert decision["replica"] == "auto-1"      # fresh name, no loop
    assert "auto-1" in router.health()["replicas"]
