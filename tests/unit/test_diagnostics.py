"""Diagnostics (profiling/NaN/sharding checks) and elastic resume tests —
the aux-subsystem obligations of SURVEY.md §5.1-5.4."""

import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

import jax
import jax.numpy as jnp

from unionml_tpu.diagnostics import (
    StepTimer,
    assert_finite,
    assert_sharding,
    describe_sharding,
    nan_guard,
)


def test_step_timer_windows_and_summary():
    t = StepTimer(window=5)
    for _ in range(12):
        t.tick(32)
    s = t.summary()
    assert s["steps"] == 12 and s["examples"] == 12 * 32
    assert len(t.rates) == 2  # two full windows
    assert s["samples_per_sec_median"] > 0


def test_assert_finite_names_the_leaf():
    good = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    assert_finite(good, name="state")  # no raise
    bad = {"a": jnp.ones((3,)), "b": {"c": jnp.array([1.0, np.nan])}}
    with pytest.raises(FloatingPointError, match=r"state.*\['b'\]\['c'\].*1 non-finite"):
        assert_finite(bad, name="state")
    ints = {"i": jnp.arange(3)}  # integer leaves are skipped
    assert_finite(ints)


def test_nan_guard_toggles_debug_nans():
    assert not jax.config.jax_debug_nans
    with nan_guard():
        assert jax.config.jax_debug_nans
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: 0.0 / x)(jnp.zeros(()))
    assert not jax.config.jax_debug_nans


def test_describe_and_assert_sharding_on_mesh():
    from jax.sharding import NamedSharding, PartitionSpec

    from unionml_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 8})
    x = jax.device_put(jnp.ones((16, 4)), NamedSharding(mesh, PartitionSpec("data", None)))
    tree = {"batch": x, "host": np.ones(3)}
    desc = describe_sharding(tree)
    assert "data" in desc["['batch']"]
    assert desc["['host']"] == "<host>"

    assert_sharding(tree, {"batch": PartitionSpec("data", None)})
    with pytest.raises(AssertionError, match="realized sharding"):
        assert_sharding(tree, {"batch": PartitionSpec(None, "data")})
    with pytest.raises(AssertionError, match="no leaves matched"):
        assert_sharding(tree, {"nonexistent": PartitionSpec()})


# ------------------------------------------------------------- elastic


def _make_problem():
    import optax
    from flax import linen as nn

    from unionml_tpu.models import create_train_state

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    module = Tiny()
    state = create_train_state(module, jnp.zeros((1, 4)), optimizer=optax.adam(0.01))

    def step(state, batch):
        xb, yb = batch

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, xb)
            return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), {"loss": loss}

    return step, state, x, y


def test_elastic_resume_reaches_identical_state(tmp_path):
    from unionml_tpu.elastic import Preemption, run_elastic_trainer

    step, state0, x, y = _make_problem()

    # uninterrupted run: 2 epochs x 4 batches = 8 steps
    ref_state, ref_steps = run_elastic_trainer(
        step_fn=step, state=state0, arrays=[x, y],
        checkpoint_dir=str(tmp_path / "ref"), num_epochs=2, batch_size=32,
        seed=3, checkpoint_every=3,
    )
    assert ref_steps == 8

    # faulted run: dies after step 5 (past the step-3 checkpoint)
    step2, state1, _, _ = _make_problem()

    def bomb(global_step):
        if global_step == 5:
            raise Preemption("simulated preemption")

    with pytest.raises(Preemption):
        run_elastic_trainer(
            step_fn=step2, state=state1, arrays=[x, y],
            checkpoint_dir=str(tmp_path / "run"), num_epochs=2, batch_size=32,
            seed=3, checkpoint_every=3, fault_hook=bomb,
        )

    # restart: resumes from step 3, replays 4..8
    step3, state2, _, _ = _make_problem()
    out_state, out_steps = run_elastic_trainer(
        step_fn=step3, state=state2, arrays=[x, y],
        checkpoint_dir=str(tmp_path / "run"), num_epochs=2, batch_size=32,
        seed=3, checkpoint_every=3,
    )
    assert out_steps == 8
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(out_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_manager_async_rotation_and_roundtrip(tmp_path):
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    states = {
        s: {"w": jnp.full((4,), float(s)), "step": jnp.int32(s)} for s in (1, 2, 3, 4)
    }
    with CheckpointManager(str(tmp_path / "ck"), max_to_keep=2) as mgr:
        for s, st in states.items():
            mgr.save(s, st)
        mgr.wait()
        assert mgr._steps() == [3, 4]  # rotation kept the newest two
        restored = mgr.restore(states[4])
        np.testing.assert_array_equal(np.asarray(restored["w"]), np.full((4,), 4.0))
        assert int(restored["step"]) == 4
        # pinned restore of the older surviving step
        older = mgr.restore(states[3], step=3)
        assert int(older["step"]) == 3


def test_checkpoint_manager_sync_mode(tmp_path):
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    with CheckpointManager(str(tmp_path / "ck"), async_save=False) as mgr:
        mgr.save(7, {"w": jnp.ones((2,))})
        # committed before save() returned: visible without wait()
        assert mgr._steps() == [7]


def test_checkpoint_manager_keep_all_and_validation(tmp_path):
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    # max_to_keep=0 means "disable rotation", not "delete everything"
    with CheckpointManager(
        str(tmp_path / "ck"), max_to_keep=0, async_save=False
    ) as mgr:
        for s in (1, 2, 3, 4):
            mgr.save(s, {"w": jnp.full((2,), float(s))})
        mgr.wait()
        assert mgr._steps() == [1, 2, 3, 4]
    with pytest.raises(ValueError, match="max_to_keep"):
        CheckpointManager(str(tmp_path / "bad"), max_to_keep=-1)


def test_elastic_fresh_run_no_checkpoint(tmp_path):
    from unionml_tpu.elastic import run_elastic_trainer

    step, state, x, y = _make_problem()
    out, steps = run_elastic_trainer(
        step_fn=step, state=state, arrays=[x, y],
        checkpoint_dir=str(tmp_path / "fresh"), num_epochs=1, batch_size=64,
        checkpoint_every=100,
    )
    assert steps == 2
    # final checkpoint written even though checkpoint_every wasn't hit
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    assert CheckpointManager(str(tmp_path / "fresh")).latest_step() == 2


def _stream_batches(start, stop):
    """Deterministic step-indexed batch stream (batch = f(step index))."""
    for i in range(start, stop):
        rng = np.random.default_rng(1000 + i)
        xb = rng.normal(size=(16, 4)).astype(np.float32)
        yb = (xb.sum(axis=1) > 0).astype(np.int32)
        yield (xb, yb)


def test_elastic_stream_seekable_resume_identical(tmp_path):
    """Streaming elastic resume, seekable form: stream(start_step) is
    called with the resume position; killed+resumed == uninterrupted."""
    from unionml_tpu.elastic import Preemption, run_elastic_trainer

    step, state0, *_ = _make_problem()

    ref_state, ref_steps = run_elastic_trainer(
        step_fn=step, state=state0, stream=lambda start: _stream_batches(start, 8),
        num_steps=8, checkpoint_dir=str(tmp_path / "ref"), checkpoint_every=3,
    )
    assert ref_steps == 8

    step2, state2, *_ = _make_problem()
    seek_calls = []

    def seekable(start):
        seek_calls.append(start)
        return _stream_batches(start, 8)

    with pytest.raises(Preemption):
        run_elastic_trainer(
            step_fn=step2, state=state2, stream=seekable, num_steps=8,
            checkpoint_dir=str(tmp_path / "pre"), checkpoint_every=3,
            fault_hook=lambda s: (_ for _ in ()).throw(Preemption())
            if s == 4 else None,
        )
    step3, state3, *_ = _make_problem()
    resumed, steps = run_elastic_trainer(
        step_fn=step3, state=state3, stream=seekable, num_steps=8,
        checkpoint_dir=str(tmp_path / "pre"), checkpoint_every=3,
    )
    assert steps == 8
    assert seek_calls == [0, 3]  # resumed from the step-3 checkpoint, sought
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_stream_replay_skip_resume_identical(tmp_path):
    """Zero-arg (replayable) streams resume by skipping consumed batches."""
    from unionml_tpu.elastic import Preemption, run_elastic_trainer

    step, state0, *_ = _make_problem()
    ref_state, _ = run_elastic_trainer(
        step_fn=step, state=state0, stream=lambda start: _stream_batches(start, 6),
        num_steps=6, checkpoint_dir=str(tmp_path / "ref"), checkpoint_every=2,
    )

    step2, state2, *_ = _make_problem()
    with pytest.raises(Preemption):
        run_elastic_trainer(
            step_fn=step2, state=state2, stream=lambda: _stream_batches(0, 6),
            num_steps=6, checkpoint_dir=str(tmp_path / "pre"), checkpoint_every=2,
            fault_hook=lambda s: (_ for _ in ()).throw(Preemption())
            if s == 3 else None,
        )
    step3, state3, *_ = _make_problem()
    resumed, steps = run_elastic_trainer(
        step_fn=step3, state=state3, stream=lambda: _stream_batches(0, 6),
        num_steps=6, checkpoint_dir=str(tmp_path / "pre"), checkpoint_every=2,
    )
    assert steps == 6
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(resumed.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_stream_exhaustion_checkpoints_terminal_step(tmp_path):
    from unionml_tpu.checkpoint.sharded import CheckpointManager
    from unionml_tpu.elastic import run_elastic_trainer

    step, state0, *_ = _make_problem()
    _, steps = run_elastic_trainer(
        step_fn=step, state=state0, stream=lambda start: _stream_batches(start, 5),
        checkpoint_dir=str(tmp_path / "ex"), checkpoint_every=100,
    )
    assert steps == 5
    assert CheckpointManager(str(tmp_path / "ex")).latest_step() == 5
    # a restart resumes at 5 and trains nothing further
    step2, state2, *_ = _make_problem()
    _, steps2 = run_elastic_trainer(
        step_fn=step2, state=state2, stream=lambda start: _stream_batches(start, 5),
        checkpoint_dir=str(tmp_path / "ex"), checkpoint_every=100,
    )
    assert steps2 == 5


def test_elastic_rejects_ambiguous_sources(tmp_path):
    from unionml_tpu.elastic import run_elastic_trainer

    step, state0, x, y = _make_problem()
    with pytest.raises(ValueError, match="exactly one"):
        run_elastic_trainer(
            step_fn=step, state=state0, arrays=[x, y],
            stream=lambda: iter(()), checkpoint_dir=str(tmp_path / "z"),
        )
    with pytest.raises(ValueError, match="exactly one"):
        run_elastic_trainer(
            step_fn=step, state=state0, checkpoint_dir=str(tmp_path / "z")
        )


def test_elastic_stream_guards_truncated_replay_and_bad_signature(tmp_path):
    from unionml_tpu.elastic import run_elastic_trainer

    step, state0, *_ = _make_problem()
    # run 4 steps, checkpoint at 2 and 4
    run_elastic_trainer(
        step_fn=step, state=state0, stream=lambda start: _stream_batches(start, 4),
        checkpoint_dir=str(tmp_path / "t"), checkpoint_every=2,
    )
    # replayable resume whose stream now yields fewer batches than consumed
    step2, state2, *_ = _make_problem()
    with pytest.raises(RuntimeError, match="before the resume position"):
        run_elastic_trainer(
            step_fn=step2, state=state2, stream=lambda: _stream_batches(0, 2),
            checkpoint_dir=str(tmp_path / "t"), checkpoint_every=2,
        )
    # required keyword-only param fits neither contract -> named error
    step3, state3, *_ = _make_problem()
    with pytest.raises(ValueError, match="positional"):
        run_elastic_trainer(
            step_fn=step3, state=state3,
            stream=lambda *, start: _stream_batches(start, 4),
            checkpoint_dir=str(tmp_path / "t2"),
        )


def test_elastic_resume_with_accumulation_identical(tmp_path):
    """Gradient accumulation inside the elastic loop: a hard-killed run
    resumes to the bit-identical state, with global steps counting
    optimizer updates (not microbatches)."""
    from unionml_tpu.elastic import Preemption, run_elastic_trainer
    from unionml_tpu.models.train import classification_step
    from unionml_tpu.models import create_train_state
    import optax
    from flax import linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(2)(x)

    rng = np.random.default_rng(1)
    x = rng.normal(size=(128, 4)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int32)
    module = Tiny()

    def fresh():
        return create_train_state(module, jnp.zeros((1, 4)), optimizer=optax.adam(0.01))

    step = classification_step(module, accumulate_steps=2)
    common = dict(
        step_fn=step, arrays=[x, y], num_epochs=2, batch_size=16,
        accumulate_steps=2, seed=7, checkpoint_every=2,
    )
    # 128 rows / (2*16) feed = 4 updates/epoch x 2 epochs = 8 global steps
    ref_state, ref_steps = run_elastic_trainer(
        state=fresh(), checkpoint_dir=str(tmp_path / "ref"), **common
    )
    assert ref_steps == 8

    def bomb(global_step):
        if global_step == 5:
            raise Preemption("simulated preemption")

    with pytest.raises(Preemption):
        run_elastic_trainer(
            state=fresh(), checkpoint_dir=str(tmp_path / "run"),
            fault_hook=bomb, **common
        )
    out_state, out_steps = run_elastic_trainer(
        state=fresh(), checkpoint_dir=str(tmp_path / "run"), **common
    )
    assert out_steps == 8
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(out_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
