"""Fleet-router chaos tests (docs/robustness.md "Fleet robustness"):
the cluster front door must make a replica loss, hang, or drain
invisible to callers — mid-stream replica death retries transparently
on a survivor with token parity, ejection walks the
eject→half-open→rejoin lifecycle with cooldown hysteresis, the
fleet-wide retry budget bounds amplification, hedging races a second
replica past the latency quantile, and drain/join choreography moves
traffic without dropping a request."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    FaultInjector,
    Overloaded,
    deadline_scope,
    xla_oom_error,
)
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
    RouterPolicy,
    make_router_app,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _resident(engine):
    with engine._lock:
        return sum(r is not None for r in engine._occupant)


def _wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    pytest.fail(f"timed out waiting for {what}")


class FakeReplica(ReplicaHandle):
    """Scriptable in-process replica: serves ``tokens`` in ``chunk``-
    sized chunks, optionally failing (``fail_with``) or stalling
    (``delay_s`` per chunk); counts dispatches."""

    def __init__(self, name, tokens=(1, 2, 3, 4), *, chunk=2,
                 fail_with=None, fail_times=0, delay_s=0.0, queue_depth=0,
                 cached=0, burn=0.0, status="ok"):
        self.name = name
        self.tokens = list(tokens)
        self.chunk = chunk
        self.fail_with = fail_with
        self.fail_times = fail_times  # 0 = fail every dispatch
        self.delay_s = delay_s
        self.queue_depth = queue_depth
        self.cached = cached
        self.burn = burn
        self.status = status
        self.dispatches = 0
        self.health_calls = 0
        self.drained = False
        self.resumed = False

    def generate_stream(self, prompt, *, max_new_tokens=None):
        self.dispatches += 1
        if self.fail_with is not None and (
            self.fail_times == 0 or self.dispatches <= self.fail_times
        ):
            raise self.fail_with
        for i in range(0, len(self.tokens), self.chunk):
            if self.delay_s:
                time.sleep(self.delay_s)
            self.chunks_yielded = getattr(self, "chunks_yielded", 0) + 1
            yield self.tokens[i:i + self.chunk]

    def health(self):
        self.health_calls += 1
        return {
            "status": self.status,
            "queue_depth": self.queue_depth,
            "burn": self.burn,
        }

    def cached_prefix_len(self, prompt):
        return self.cached

    def drain(self, timeout=None):
        self.drained = True
        return True

    def resume(self):
        self.resumed = True


def _router(replicas, **policy_kw):
    policy_kw.setdefault("health_ttl_s", 0.0)
    policy_kw.setdefault("jitter_s", 0.0)
    policy_kw.setdefault("backoff_base_s", 0.0)
    clock = policy_kw.pop("clock", time.monotonic)
    return FleetRouter(
        replicas,
        policy=RouterPolicy(**policy_kw),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        clock=clock,
        sleep=lambda s: None,
    )


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ----------------------------------------------------------------- policy


def test_policy_validation():
    with pytest.raises(ValueError, match="max_attempts"):
        RouterPolicy(max_attempts=0)
    with pytest.raises(ValueError, match="retry_budget_ratio"):
        RouterPolicy(retry_budget_ratio=1.5)
    with pytest.raises(ValueError, match="hedge_quantile"):
        RouterPolicy(hedge_quantile=1.0)
    with pytest.raises(ValueError, match="eject_consecutive"):
        RouterPolicy(eject_consecutive=0)
    with pytest.raises(ValueError, match="at least one replica"):
        FleetRouter([], registry=telemetry.MetricsRegistry())
    with pytest.raises(ValueError, match="unique"):
        FleetRouter(
            [FakeReplica("a"), FakeReplica("a")],
            registry=telemetry.MetricsRegistry(),
        )


# ----------------------------------------------------------------- picking


def test_pick_prefers_cache_locality():
    """The replica holding the longest cached prefix wins the pick
    (SGLang-style cache-aware routing)."""
    a = FakeReplica("a", cached=0)
    b = FakeReplica("b", cached=6)
    router = _router([a, b])
    router.generate([1, 2, 3, 4, 5, 6, 7, 8])
    assert b.dispatches == 1 and a.dispatches == 0


def test_pick_avoids_deep_queue_and_breaker():
    a = FakeReplica("a", queue_depth=5)
    b = FakeReplica("b", queue_depth=0)
    router = _router([a, b])
    router.generate([1, 2, 3])
    assert b.dispatches == 1 and a.dispatches == 0

    # breaker-open replica is scored far below a clean one
    c = FakeReplica("c")
    d = FakeReplica("d")
    c.health = lambda: {
        "status": "degraded", "queue_depth": 0, "breaker_open": True,
    }
    r2 = _router([c, d])
    r2.generate([1, 2, 3])
    assert d.dispatches == 1 and c.dispatches == 0


def test_pick_shifts_off_slo_burn():
    """A replica burning SLO budget loses the pick before it formally
    breaches — load shifts ahead of the page."""
    a = FakeReplica("a", burn=2.0)
    b = FakeReplica("b", burn=0.0)
    router = _router([a, b])
    router.generate([1, 2, 3])
    assert b.dispatches == 1 and a.dispatches == 0


def test_pick_skips_draining_replica_health():
    """A replica whose OWN health says draining (drained directly, not
    through the router) is not routed to."""
    a = FakeReplica("a", status="draining")
    b = FakeReplica("b")
    router = _router([a, b])
    for _ in range(4):
        router.generate([1, 2, 3])
    assert a.dispatches == 0 and b.dispatches == 4


# ---------------------------------------------------------------- failover


def test_retry_fails_over_to_survivor():
    boom = EngineUnavailable("replica down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom, cached=8)   # picked first
    b = FakeReplica("b", tokens=(9, 8, 7))
    router = _router([a, b])
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [9, 8, 7]
    assert a.dispatches == 1 and b.dispatches == 1
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "route" in kinds and "retry" in kinds


def test_non_retryable_errors_surface():
    """The caller's own deadline and validation errors must NOT burn
    retries — a second attempt is just as wrong."""
    a = FakeReplica("a", fail_with=DeadlineExceeded("too late"), cached=8)
    b = FakeReplica("b")
    router = _router([a, b])
    with pytest.raises(DeadlineExceeded):
        router.generate([1, 2, 3, 4, 5, 6, 7, 8])
    assert b.dispatches == 0

    c = FakeReplica("c", fail_with=ValueError("bad prompt"), cached=8)
    d = FakeReplica("d")
    r2 = _router([c, d])
    with pytest.raises(ValueError):
        r2.generate([1, 2, 3, 4, 5, 6, 7, 8])
    assert d.dispatches == 0


def test_retry_budget_bounds_amplification():
    """With every dispatch failing, total dispatches stay within
    requests + burst + ratio * requests — the retry-storm bound."""
    boom = EngineUnavailable("down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom)
    b = FakeReplica("b", fail_with=boom)
    n, ratio, burst = 20, 0.2, 2.0
    router = _router(
        [a, b], retry_budget_ratio=ratio, retry_budget_burst=burst,
        max_attempts=5,
    )
    failures = 0
    for _ in range(n):
        with pytest.raises(EngineUnavailable):
            router.generate([1, 2, 3])
        failures += 1
    dispatches = a.dispatches + b.dispatches
    retries = dispatches - n
    assert failures == n
    assert retries <= burst + ratio * n, (
        f"{retries} retries for {n} requests exceeds the "
        f"{burst} + {ratio} * {n} budget"
    )
    assert int(router._m_budget_exhausted.value) > 0


def test_retry_honors_retry_after_hint():
    slept = []
    a = FakeReplica(
        "a", fail_with=Overloaded("busy", retry_after_s=0.7), cached=8,
    )
    b = FakeReplica("b")
    router = FleetRouter(
        [a, b],
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.01,
        ),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        sleep=slept.append,
    )
    router.generate([1, 2, 3, 4, 5, 6, 7, 8])
    assert slept and slept[0] >= 0.7  # the hint outranks the backoff


# ---------------------------------------------------- ejection lifecycle


def test_eject_half_open_rejoin_lifecycle():
    """THE hysteresis walk: consecutive failures eject, the cooldown
    expires into half-open, one probe flows, success rejoins and
    resets the ladder; a failed probe re-ejects with doubled
    cooldown."""
    clock = _Clock()
    boom = EngineUnavailable("down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom, cached=8)  # preferred & failing
    b = FakeReplica("b", tokens=(5, 5))
    router = _router(
        [a, b], clock=clock, eject_consecutive=2, eject_cooldown_s=10.0,
        retry_budget_burst=100.0, retry_budget_ratio=1.0,
    )
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]

    # two failing requests (each retried onto b) eject a
    for _ in range(2):
        assert router.generate(prompt) == [5, 5]
    assert router.health()["replicas"]["a"]["state"] == "ejected"
    assert int(router._m_ejections.labels("a").value) == 1
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "eject" in kinds

    # while ejected, traffic all lands on b
    before = a.dispatches
    for _ in range(4):
        router.generate(prompt)
    assert a.dispatches == before

    # cooldown expiry → half-open → a probe flows (rr trickle), but a
    # still fails → re-eject with DOUBLED cooldown
    clock.advance(10.5)
    for _ in range(10):  # enough picks for the probe trickle to fire
        router.generate(prompt)
    assert router.health()["replicas"]["a"]["state"] == "ejected"
    assert int(router._m_ejections.labels("a").value) == 2
    state = router._replicas["a"]
    assert state.rejoin_at - clock() == pytest.approx(20.0, abs=0.6)
    eject_events = [
        e for e in router._flight.dump(kind="eject")
        if e.get("replica") == "a"
    ]
    assert eject_events[-1]["cause"] == "probe_failed"

    # heal the replica; second probe succeeds → rejoin, ladder reset
    a.fail_with = None
    a.tokens = [5, 5]
    clock.advance(20.5)
    for _ in range(10):
        router.generate(prompt)
    assert router.health()["replicas"]["a"]["state"] == "live"
    assert router.health()["replicas"]["a"]["eject_count"] == 0
    assert int(router._m_rejoins.labels("a").value) == 1
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "probe" in kinds and "rejoin" in kinds
    # healed replica takes traffic again (it holds the cached prefix)
    before = a.dispatches
    router.generate(prompt)
    assert a.dispatches == before + 1


def test_router_health_degrades_below_floor():
    """All replicas ejected: the router answers degraded health (the
    balancer above sheds) instead of blackholing."""
    clock = _Clock()
    boom = EngineUnavailable("down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom)
    router = _router(
        [a], clock=clock, eject_consecutive=1, max_attempts=1,
    )
    with pytest.raises(EngineUnavailable):
        router.generate([1, 2, 3])
    assert router.health()["status"] == "degraded"
    assert router.health()["live_replicas"] == 0
    with pytest.raises(EngineUnavailable, match="no live replicas"):
        router.generate([1, 2, 3])
    assert int(router._g_live.value) == 0


# ----------------------------------------------------------------- hedging


def test_hedge_second_dispatch_wins_tail():
    """A dispatch stuck past the hedge delay races a second replica;
    the fast answer wins and the loser is recorded."""
    a = FakeReplica("a", tokens=(1, 1, 1, 1), delay_s=0.4, cached=8)
    b = FakeReplica("b", tokens=(1, 1, 1, 1))
    router = _router(
        [a, b], hedge=True, hedge_min_s=0.05, hedge_warmup=10**9,
    )
    # warmup never reached → delay = max(hedge_min_s, 1.0) would be 1s;
    # shrink by seeding samples is the honest path, so drop the floor:
    router._hedge_delay_s = lambda: 0.05
    t0 = time.perf_counter()
    out = router.generate([1, 2, 3, 4, 5, 6, 7, 8])
    elapsed = time.perf_counter() - t0
    assert out == [1, 1, 1, 1]
    assert b.dispatches == 1, "hedge lane must have dispatched"
    assert elapsed < 0.8, f"hedge should beat the 1.6s slow lane ({elapsed:.2f}s)"
    wins = int(router._m_hedges.labels("b", "win").value)
    assert wins == 1
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "hedge" in kinds


def test_failed_hedge_lane_does_not_abort_primary():
    """A hedge lane that fails fast (its replica is down) sets the
    done event with NO winner — the healthy, still-streaming primary
    lane must keep going and win, not abandon itself."""
    a = FakeReplica("a", tokens=(7, 7, 7, 7), delay_s=0.15, cached=8)
    boom = EngineUnavailable("down", reason="unreachable")
    b = FakeReplica("b", fail_with=boom)
    router = _router(
        [a, b], hedge=True, hedge_min_s=0.02, hedge_warmup=10**9,
    )
    router._hedge_delay_s = lambda: 0.02
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [7, 7, 7, 7]
    assert b.dispatches == 1  # the hedge fired, failed, and was ignored


def test_hedge_falls_back_to_retry_envelope():
    """With hedge=True, a transient failure of the primary BEFORE the
    hedge delay must still be retried (the hedge cannot weaken the
    retry contract) — the fallback draws a budget token and succeeds
    on a survivor."""
    boom = EngineUnavailable("transient", reason="unreachable")
    a = FakeReplica("a", fail_with=boom, cached=8)  # picked first, dies
    b = FakeReplica("b", tokens=(6, 6))
    router = _router([a, b], hedge=True, hedge_min_s=5.0, hedge_warmup=0)
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [6, 6]
    # the fallback envelope may re-try a (still live below the eject
    # threshold, and it holds the cached prefix) before failing over
    assert a.dispatches >= 1 and b.dispatches >= 1


def test_probe_slot_freed_on_non_retryable_probe_exit():
    """A half-open probe that ends in a caller error (non-retryable)
    says nothing about replica health: the probe slot must be freed —
    not leaked — so a later probe can still rejoin the replica."""
    clock = _Clock()
    boom = EngineUnavailable("down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom, cached=8)
    b = FakeReplica("b", tokens=(5, 5))
    router = _router(
        [a, b], clock=clock, eject_consecutive=1, eject_cooldown_s=10.0,
        retry_budget_burst=100.0, retry_budget_ratio=1.0,
    )
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    router.generate(prompt)            # a fails once -> ejected
    assert router.health()["replicas"]["a"]["state"] == "ejected"
    clock.advance(10.5)
    # the probe dispatch hits a CALLER error (non-retryable)
    a.fail_with = ValueError("bad prompt for this replica")
    got_value_error = 0
    for i in range(16):                # rr trickle reaches the probe
        try:
            router.generate(prompt)
        except ValueError:
            got_value_error += 1
            break
    assert got_value_error == 1, "a probe must have flowed to a"
    assert router._replicas["a"].probe_inflight is False
    # heal a: the NEXT probe must still be possible (no leaked slot)
    a.fail_with = None
    a.tokens = [5, 5]
    for _ in range(16):
        router.generate(prompt)
    assert router.health()["replicas"]["a"]["state"] == "live"


def test_requests_total_outcomes_sum_to_dispatches():
    """Every dispatch lands in exactly ONE outcome bucket: a request
    that exhausts retries counts its last dispatch as error, the
    hidden ones as retried_away — never both."""
    boom = EngineUnavailable("down", reason="unreachable")
    a = FakeReplica("a", fail_with=boom)
    b = FakeReplica("b", fail_with=boom)
    router = _router(
        [a, b], max_attempts=2, retry_budget_burst=100.0,
        retry_budget_ratio=1.0, eject_consecutive=10**9,
    )
    for _ in range(5):
        with pytest.raises(EngineUnavailable):
            router.generate([1, 2, 3])
    outcomes = {
        values: child.value
        for values, child in router._m_routed.children()
    }
    assert sum(outcomes.values()) == a.dispatches + b.dispatches
    errors = sum(v for k, v in outcomes.items() if k[1] == "error")
    assert errors == 5  # one terminal failure per request


def test_hedge_loser_abandons_stream():
    """The losing lane must stop consuming once a winner exists — not
    decode to completion (that would double device work on exactly the
    degraded fleet hedging protects)."""
    a = FakeReplica("a", tokens=tuple(range(40)), chunk=2, delay_s=0.06,
                    cached=8)                       # slow loser: 20 chunks
    b = FakeReplica("b", tokens=tuple(range(40)), chunk=40)
    router = _router(
        [a, b], hedge=True, hedge_min_s=0.02, hedge_warmup=10**9,
    )
    router._hedge_delay_s = lambda: 0.02
    out = router.generate(list(range(1, 9)))
    assert out == list(range(40))
    time.sleep(0.5)  # give the loser lane time to notice and bail
    assert getattr(a, "chunks_yielded", 0) < 20, (
        "loser decoded to completion instead of abandoning"
    )
    # outcome disjointness holds for hedged requests too
    outcomes = {
        values: child.value for values, child in router._m_routed.children()
    }
    assert outcomes.get(("b", "ok")) == 1
    assert outcomes.get(("a", "hedge_lose")) == 1


def test_hedge_fallback_excludes_failed_lanes():
    """The hedge-failure fallback must not immediately re-pick the
    replica that just failed (cache affinity still scores it highest
    until it ejects)."""
    boom = EngineUnavailable("transient", reason="unreachable")
    a = FakeReplica("a", fail_with=boom, cached=8)   # fails fast, always
    b = FakeReplica("b", tokens=(6, 6))
    router = _router(
        [a, b], hedge=True, hedge_min_s=5.0, hedge_warmup=0,
        max_attempts=2,
    )
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [6, 6]
    # the fallback went straight to b: a saw ONLY the original lane
    # dispatch, not a doomed fallback re-pick
    assert a.dispatches == 1 and b.dispatches == 1


def test_hedge_not_fired_under_quantile():
    a = FakeReplica("a", tokens=(2, 2), cached=8)
    b = FakeReplica("b", tokens=(2, 2))
    router = _router([a, b], hedge=True, hedge_min_s=5.0, hedge_warmup=0)
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [2, 2]
    assert b.dispatches == 0  # fast first lane: no hedge spent


def test_hedge_spends_no_budget_without_second_replica():
    """On a 1-replica fleet, a slow request past the hedge delay must
    NOT burn a retry-budget token on a lane whose pick would fail —
    that would drain the bucket and starve genuine retries."""
    a = FakeReplica("a", tokens=(3, 3), delay_s=0.1)
    router = _router(
        [a], hedge=True, hedge_min_s=0.01, hedge_warmup=10**9,
        retry_budget_burst=2.0,
    )
    router._hedge_delay_s = lambda: 0.01
    assert router.generate([1, 2, 3]) == [3, 3]
    assert router._budget_tokens == 2.0  # nothing spent
    assert int(router._m_budget_exhausted.value) == 0
    assert not [e for e in router._flight.dump(kind="hedge")]


def test_http_replica_forwards_token_cap_in_payload():
    """max_new_tokens crosses the /predict hop as a payload field (the
    old loud refusal was a stopgap — disaggregated two-leg dispatch
    needs the cap to survive the hop for token parity): explicit
    argument first, else the ambient token_cap_scope, else absent."""
    from unionml_tpu.serving.scheduler import token_cap_scope

    replica = HttpReplica("http://example.invalid:1", name="remote")
    assert replica._payload([1, 2, 3], 8) == {
        "features": [[1, 2, 3]], "max_new_tokens": 8,
    }
    with token_cap_scope(5):
        assert replica._payload([1, 2, 3], None)["max_new_tokens"] == 5
        # explicit beats ambient
        assert replica._payload([1, 2, 3], 8)["max_new_tokens"] == 8
    assert "max_new_tokens" not in replica._payload([1, 2, 3], None)


def test_router_app_multi_prompt_concurrent():
    """A multi-prompt predict dispatches rows concurrently (so replica
    engines can continuous-batch them), preserves row order, and
    relays a row's failure."""
    a = FakeReplica("a", tokens=(9, 9), delay_s=0.05)
    router = _router([a])
    app = make_router_app(router, registry=telemetry.MetricsRegistry())
    t0 = time.perf_counter()
    out = app.predict({"features": [[1, 2], [3, 4], [5, 6], [7, 8]]})
    elapsed = time.perf_counter() - t0
    assert out == [[9, 9]] * 4
    # 4 rows x 2 chunks x 50ms each would be 400ms serialized; the
    # concurrent dispatch overlaps them (generous bound for slow CI)
    assert elapsed < 0.35, f"rows appear serialized ({elapsed:.2f}s)"


# ------------------------------------------------------ drain/join dance


def test_drain_join_choreography():
    a = FakeReplica("a", tokens=(1, 2), cached=8)
    b = FakeReplica("b", tokens=(3, 4))
    router = _router([a, b])
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    assert router.generate(prompt) == [1, 2]

    assert router.drain_replica("a") is True
    assert a.drained
    assert router.health()["replicas"]["a"]["state"] == "draining"
    for _ in range(3):  # all traffic shifts to b, no caller failures
        assert router.generate(prompt) == [3, 4]
    assert a.dispatches == 1

    router.rejoin_replica("a")
    assert a.resumed
    assert router.generate(prompt) == [1, 2]  # affinity restored
    kinds = [e["kind"] for e in router._flight.dump()]
    assert "drain" in kinds and "rejoin" in kinds

    # fleet-wide drain: router itself refuses, health says draining
    assert router.drain() is True
    assert router.health()["status"] == "draining"
    with pytest.raises(EngineUnavailable, match="draining"):
        router.generate(prompt)
    router.resume()
    assert router.health()["status"] == "ok"
    assert router.generate(prompt) == [1, 2]


def test_add_remove_replica_membership():
    a = FakeReplica("a", tokens=(1,))
    router = _router([a])
    b = FakeReplica("b", tokens=(2,), cached=8)
    router.add_replica(b)
    with pytest.raises(ValueError, match="already present"):
        router.add_replica(FakeReplica("b"))
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [2]
    assert router.remove_replica("b") is True
    assert b.drained
    assert "b" not in router.health()["replicas"]
    assert router.generate([1, 2, 3, 4, 5, 6, 7, 8]) == [1]


# ------------------------------------------- engine-backed chaos (THE test)


def test_replica_killed_midstream_invisible_to_caller(tiny_llama):
    """THE acceptance scenario: a replica dies mid-stream (OOM-shaped
    device fault via PR 3's FaultInjector) and the caller sees ZERO
    failures — the router transparently retries on a survivor, replays
    past the tokens already emitted, and the concatenated stream is
    token-identical to the solo run. The victim is NOT ejected for one
    failure (hysteresis threshold), and the flight recorder explains
    the failover."""
    module, params = tiny_llama
    n_new = 24
    fis = [FaultInjector(), FaultInjector()]
    engines = [
        DecodeEngine(
            module, slots=2, max_new_tokens=n_new, prompt_buckets=(8,),
            chunk_steps=2, fault_injector=fis[i],
        )
        for i in range(2)
    ]
    replicas = [
        EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)
    ]
    flight = telemetry.FlightRecorder()
    router = FleetRouter(
        replicas,
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.0,
        ),
        registry=telemetry.MetricsRegistry(),
        flight=flight,
    )
    prompt = [3, 1, 4, 1, 5]
    try:
        # two idle identical replicas tie on score: the deterministic
        # round-robin tie-break sends the first request to r0 — so the
        # victim is known a priori, and the fault is armed BEFORE the
        # stream starts (the 2nd decode-chunk dispatch dies), closing
        # the race where a fast CPU decode outruns a late arm()
        victim = 0
        fis[victim].arm("engine.dispatch", nth=2, exc=xla_oom_error())
        tokens = [t for c in router.generate_stream(prompt) for t in c]
        assert tokens == _solo(module, params, prompt, n_new, max_len=engines[0].cache_len)
        assert fis[victim].injected("engine.dispatch") == 1, (
            "the fault must actually have fired mid-stream"
        )
        # the failover is visible to operators, not to the caller
        kinds = [e["kind"] for e in flight.dump()]
        assert "retry" in kinds
        name = f"r{victim}"
        assert int(router._m_routed.labels(name, "retried_away").value) == 1
        assert int(engines[victim]._m_recoveries.value) == 1
        # one failure < eject_consecutive: the victim recovered and
        # stays live (PR 3's supervised recovery handles the process;
        # the router's job was only to hide the blast radius)
        assert router.health()["replicas"][name]["state"] == "live"
        # and the fleet keeps serving with solo parity across the pick
        # spread — the recovered victim included (doubles as the
        # round-robin correctness check, on the already-built engines)
        for p in (prompt, [1, 2, 3], [4, 5, 6], [2, 3, 4]):
            assert router.generate(p) == _solo(
                module, params, p, n_new, max_len=engines[0].cache_len
            )
    finally:
        for e in engines:
            e.close()


def test_cache_affinity_routes_to_warm_engine(tiny_llama):
    """After one request lands on a replica, its radix cache holds the
    prompt's prefix — the router's peek sends the follow-up with the
    same prefix back to the warm replica."""
    module, params = tiny_llama
    n_new = 8
    engines = [
        DecodeEngine(
            module, slots=2, max_new_tokens=n_new, prompt_buckets=(32,),
            chunk_steps=4, prefix_cache=True,
        )
        for _ in range(2)
    ]
    router = FleetRouter(
        [EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)],
        policy=RouterPolicy(health_ttl_s=0.0),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
    )
    try:
        # 16 tokens = one full radix block (default block_size): the
        # peek has something to see once the harvester inserts it
        shared = list(range(1, 17))
        router.generate(shared)
        # the insert happens on the harvester; wait for the peek to see it
        _wait_for(
            lambda: any(
                e.prefix_cache is not None and e.prefix_cache.peek(shared) > 0
                for e in engines
            ),
            what="prefix inserted into some replica's cache",
        )
        warm = next(
            i for i, e in enumerate(engines)
            if e.prefix_cache.peek(shared) > 0
        )
        routes = [
            e for e in router._flight.dump(kind="route")
        ]
        n_before = len(routes)
        router.generate(shared + [77, 78])
        last = router._flight.dump(kind="route")[-1]
        assert len(router._flight.dump(kind="route")) == n_before + 1
        assert last["replica"] == f"r{warm}"
    finally:
        for e in engines:
            e.close()


# ------------------------------------------------- context propagation


def test_scopes_propagate_through_engine_replica(tiny_llama):
    """X-Deadline-Ms semantics survive the router hop: an expired
    ambient deadline sheds at the replica's dequeue, surfacing the
    typed 504 error — NOT a retry (retrying a missed deadline is just
    as late)."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=32, prompt_buckets=(8,),
        chunk_steps=2,
    )
    router = FleetRouter(
        [EngineReplica(engine, params, name="r0")],
        policy=RouterPolicy(health_ttl_s=0.0),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
    )
    try:
        done = {}

        def occupy():
            done["a"] = router.generate([1, 2, 3])

        t = threading.Thread(target=occupy)
        t.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        with pytest.raises(DeadlineExceeded):
            with deadline_scope(1.0):
                router.generate([4, 5, 6])
        t.join(timeout=120)
        assert not isinstance(done.get("a"), BaseException)
    finally:
        engine.close()


def test_http_replica_emits_propagation_headers():
    """The outbound hop re-emits ambient deadline/tenant/trace scopes
    as headers, so the remote transport re-opens them and the trace
    tree + ledger span the fleet."""
    from unionml_tpu.serving.usage import tenant_scope

    replica = HttpReplica("http://example.invalid:1", name="remote")
    ctx = telemetry.TraceContext(
        trace_id="0af7651916cd43dd8448eb211c80319c",
        span_id="b7ad6b7169203331",
    )
    with deadline_scope(1500.0), tenant_scope("acme"), \
            telemetry.trace_scope(ctx):
        headers = replica._headers()
    assert headers["X-Deadline-Ms"] == "1500.0"
    assert headers["X-Tenant-ID"] == "acme"
    assert headers["traceparent"].startswith(
        "00-0af7651916cd43dd8448eb211c80319c-"
    )
    # and the unreachable host maps to the retryable typed error
    with pytest.raises(EngineUnavailable, match="unreachable"):
        list(replica.generate_stream([1, 2, 3]))


def test_http_replica_maps_typed_statuses():
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        code = 429

        def do_POST(self):
            self.send_response(self.code)
            self.send_header("Retry-After", "7")
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, fmt, *args):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        replica = HttpReplica(base, name="remote")
        with pytest.raises(Overloaded) as exc_info:
            list(replica.generate_stream([1, 2, 3]))
        assert exc_info.value.retry_after_s == 7.0
        Handler.code = 503
        with pytest.raises(EngineUnavailable):
            list(replica.generate_stream([1, 2, 3]))
        Handler.code = 504
        with pytest.raises(DeadlineExceeded):
            list(replica.generate_stream([1, 2, 3]))
        # a 4xx validation reject is DETERMINISTIC: it maps to the
        # non-retryable class so the router never burns budget on it
        Handler.code = 422
        with pytest.raises(ValueError):
            list(replica.generate_stream([1, 2, 3]))
        Handler.code = 500  # other 5xx stay retryable
        with pytest.raises(EngineUnavailable):
            replica.generate([1, 2, 3])
    finally:
        server.shutdown()
        server.server_close()


def test_fastapi_seam_accepts_prebuilt_core():
    """fastapi.serving_app(core=...) mounts a pre-built app (the
    router front door) instead of constructing one — with app=None it
    hands the core back unchanged (the dependency-free path; the
    FastAPI mount itself is gated on the optional import)."""
    from unionml_tpu.serving.fastapi import serving_app

    a = FakeReplica("a", tokens=(4, 2))
    router = _router([a])
    core = make_router_app(router, registry=telemetry.MetricsRegistry())
    assert serving_app(None, core=core) is core
    assert core.predict({"features": [1, 2, 3]}) == [[4, 2]]


# ------------------------------------------------- HTTP front door e2e


def test_router_app_full_stack(tiny_llama):
    """make_router_app over two engine replicas, served on the stdlib
    transport, consumed through HttpReplica — the same dialect top to
    bottom: predict parity with solo, SSE stream parity, health/stats/
    metrics surfaces, drain → 503 with Retry-After."""
    httpx = pytest.importorskip("httpx")
    module, params = tiny_llama
    n_new = 12
    engines = [
        DecodeEngine(
            module, slots=2, max_new_tokens=n_new, prompt_buckets=(8,),
            chunk_steps=4,
        )
        for _ in range(2)
    ]
    registry = telemetry.MetricsRegistry()
    router = FleetRouter(
        [EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)],
        policy=RouterPolicy(health_ttl_s=0.0),
        registry=registry,
        flight=telemetry.FlightRecorder(),
    )
    app = make_router_app(router, registry=registry)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    prompt = [1, 2, 3, 4]
    try:
        solo = _solo(module, params, prompt, n_new, max_len=engines[0].cache_len)
        resp = httpx.post(
            f"{base}/predict", json={"features": [prompt]}, timeout=120,
        )
        assert resp.status_code == 200
        assert resp.json() == [solo]
        assert "X-Request-ID" in resp.headers

        # SSE streaming through the front door
        with httpx.stream(
            "POST", f"{base}/predict/stream", json={"features": prompt},
            timeout=120,
        ) as sresp:
            assert sresp.status_code == 200
            events = []
            for line in sresp.iter_lines():
                if line.startswith("data: "):
                    import json as _json

                    events.append(_json.loads(line[len("data: "):]))
        assert events[-1]["done"] is True
        streamed = [t for e in events[:-1] for t in e["tokens"]]
        assert streamed == solo

        # the same endpoint consumed through HttpReplica (a router CAN
        # front another router — the interface is closed under HTTP)
        remote = HttpReplica(base, name="front")
        assert FleetRouter(
            [remote],
            policy=RouterPolicy(health_ttl_s=0.0),
            registry=telemetry.MetricsRegistry(),
            flight=telemetry.FlightRecorder(),
        ).generate(prompt) == solo

        health = httpx.get(f"{base}/health", timeout=30).json()
        assert health["status"] == "ok" and health["live_replicas"] == 2
        stats = httpx.get(f"{base}/stats", timeout=30).json()
        assert stats["engine"] == "router"
        metrics = httpx.get(f"{base}/metrics", timeout=30).text
        assert "unionml_router_requests_total" in metrics
        assert "unionml_router_live_replicas" in metrics

        # drain: predict answers 503 + Retry-After; health says draining
        app.drain(timeout=30)
        resp = httpx.post(
            f"{base}/predict", json={"features": [prompt]}, timeout=120,
        )
        assert resp.status_code == 503
        assert "retry-after" in {k.lower() for k in resp.headers}
        assert httpx.get(f"{base}/health", timeout=30).status_code == 503
        app.resume()
        assert httpx.get(f"{base}/health", timeout=30).json()["status"] == "ok"
        resp = httpx.post(
            f"{base}/predict", json={"features": [prompt]}, timeout=120,
        )
        assert resp.status_code == 200 and resp.json() == [solo]

        # validation errors stay 422 through the front door
        resp = httpx.post(f"{base}/predict", json={}, timeout=30)
        assert resp.status_code == 422
    finally:
        app.shutdown()
        for e in engines:
            e.close()


# -------------------------------------- weighted least-request picking


def test_latency_weight_sheds_slow_replica_without_ejection():
    """PR 10's named follow-up: with latency_weight on, a healthy-but-
    slow replica's rolling dispatch latency pushes its score down, so
    it sheds share smoothly — no failures, no ejection."""
    a = FakeReplica("a", tokens=(1, 1), chunk=2, delay_s=0.05)  # slow
    b = FakeReplica("b", tokens=(1, 1), chunk=2)                # fast
    router = _router([a, b], latency_weight=50.0)
    # warmup: with no samples the term is 0 and ties round-robin, so
    # both replicas take traffic and seed their windows
    for _ in range(4):
        router.generate([1, 2, 3])
    warmup_a = a.dispatches
    assert warmup_a >= 1, "round-robin warmup must reach the slow replica"
    # steady state: the slow replica's ~50ms mean costs it 2.5 score
    # points — it loses every subsequent pick
    for _ in range(12):
        router.generate([1, 2, 3])
    assert a.dispatches == warmup_a, (
        f"slow replica kept winning picks ({a.dispatches} vs warmup "
        f"{warmup_a})"
    )
    assert b.dispatches == 16 - warmup_a
    # shed share, NOT ejected: the replica never failed
    assert router.health()["replicas"]["a"]["state"] == "live"
    assert int(router._m_ejections.labels("a").value) == 0


def test_latency_weight_off_by_default():
    a = FakeReplica("a", tokens=(1, 1), delay_s=0.03)
    b = FakeReplica("b", tokens=(1, 1))
    router = _router([a, b])
    for _ in range(8):
        router.generate([1, 2, 3])
    # pure round-robin ties: the slow replica keeps its half
    assert a.dispatches == 4 and b.dispatches == 4


# ------------------------------------------------- remote cache peek


def test_fleet_cached_prefix_len_is_max_over_routable():
    a = FakeReplica("a", cached=4)
    b = FakeReplica("b", cached=12)
    c = FakeReplica("c", cached=99)
    router = _router([a, b, c])
    router.drain_replica("c")  # draining replicas don't count
    assert router.cached_prefix_len([1, 2, 3]) == 12


def test_remote_cache_peek_e2e_with_ttl(tmp_path):
    """Satellite: HttpReplica.cached_prefix_len probes the remote
    GET /debug/cache/peek (it hardcoded 0 before — cross-host
    cache-affinity routing was blind) and TTL-caches the probe like
    health, so it can never become a per-pick round trip."""
    a = FakeReplica("a", cached=8)
    router = _router([a])
    registry = telemetry.MetricsRegistry()
    app = make_router_app(router, registry=registry)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        remote = HttpReplica(base, name="front", peek_ttl_s=60.0)
        prompt = list(range(1, 17))

        def peek_requests(expect):
            # the stdlib handler lands its request series in a finally
            # AFTER the response flushes — bounded wait, like the
            # /metrics scrape smoke
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                n = sum(
                    child.value
                    for values, child in app._m_http_requests.children()
                    if values[1] == "/debug/cache/peek"
                )
                if n >= expect:
                    return n
                time.sleep(0.01)
            return n

        for _ in range(5):
            assert remote.cached_prefix_len(prompt) == 8
        assert peek_requests(1) == 1, (
            "TTL cache must collapse repeat probes"
        )
        # ttl=0 means always-fresh (same contract as health_ttl_s)
        fresh = HttpReplica(base, name="fresh", peek_ttl_s=0.0)
        for _ in range(3):
            assert fresh.cached_prefix_len(prompt) == 8
        assert peek_requests(4) == 4
        # a different prompt is a different cache key
        assert remote.cached_prefix_len([7, 7, 7]) == 8
        # the probe feeds the real pick: a second-tier router over the
        # HTTP replica scores cache affinity across the hop
        assert remote.cached_prefix_len(prompt) > 0
    finally:
        app.shutdown()


def test_remote_cache_peek_degrades_to_zero():
    """No endpoint / unreachable host / bad prompt — the probe answers
    0 and never raises: affinity is an optimization, not a routing
    prerequisite."""
    unreachable = HttpReplica("http://example.invalid:1", name="r")
    assert unreachable.cached_prefix_len([1, 2, 3]) == 0


def test_serving_app_cache_peek_route_contract():
    """ServingApp.debug_cache_peek: 422-shaped errors for a missing
    peek source or an unparseable prompt; the engine-backed wiring is
    one kwarg."""
    from unionml_tpu.serving.http import ServingApp

    class _Model:
        name = "m"
        artifact = object()

    app = ServingApp(_Model())
    with pytest.raises(ValueError, match="no cache peek"):
        app.debug_cache_peek("1,2,3")
    peeked = []
    app2 = ServingApp(
        _Model(), cache_peek=lambda toks: peeked.append(toks) or 16,
    )
    assert app2.debug_cache_peek("1,2,3") == {"cached_prefix_len": 16}
    assert peeked == [[1, 2, 3]]
    with pytest.raises(ValueError):
        app2.debug_cache_peek("")
    with pytest.raises(ValueError):
        app2.debug_cache_peek("1,x,3")


def test_remote_cache_peek_negative_caches_missing_endpoint():
    """A remote WITHOUT the peek route (HTTP 404, any transport's 404
    shape) is negative-cached permanently: one probe, then zero — an
    old replica must not cost a wasted RTT per novel prompt."""
    import http.server

    hits = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            hits.append(self.path)
            body = b'{"detail": "Not Found"}'  # FastAPI's 404 shape
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass

    server = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        replica = HttpReplica(
            f"http://127.0.0.1:{server.server_address[1]}", name="old",
            peek_ttl_s=0.0,   # always-fresh: only the negative cache saves us
        )
        assert replica.cached_prefix_len([1, 2, 3]) == 0
        assert replica.cached_prefix_len([9, 9, 9]) == 0
        assert replica.cached_prefix_len([5, 5, 5]) == 0
        assert len(hits) == 1, f"endpoint probed {len(hits)} times"
        assert replica._peek_supported is False
    finally:
        server.shutdown()
        server.server_close()


def test_remote_cache_peek_keys_on_prefix():
    """The probe cache keys (and queries) only the first
    peek_prompt_tokens tokens — unique-suffix traffic, the normal LLM
    workload, still hits the TTL cache."""
    a = FakeReplica("a", cached=8)
    router = _router([a])
    app = make_router_app(router, registry=telemetry.MetricsRegistry())
    host, port = app.serve(port=0, blocking=False)
    try:
        remote = HttpReplica(
            f"http://{host}:{port}", name="front",
            peek_ttl_s=60.0, peek_prompt_tokens=4,
        )
        prefix = [1, 2, 3, 4]
        for suffix in ([9], [8, 7], [6, 5, 4]):
            assert remote.cached_prefix_len(prefix + suffix) == 8
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            n = sum(
                child.value
                for values, child in app._m_http_requests.children()
                if values[1] == "/debug/cache/peek"
            )
            if n >= 1:
                break
            time.sleep(0.01)
        assert n == 1, f"prefix-keyed cache missed ({n} probes)"
    finally:
        app.shutdown()
