"""CLI tests (reference analog: cli exercised via tests/integration)."""

import json
import sys
from pathlib import Path

import pytest
from click.testing import CliRunner

from unionml_tpu.cli import app

APPS_DIR = Path(__file__).parent.parent / "apps"


@pytest.fixture
def runner():
    return CliRunner()


def test_init_scaffolds_template(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    # bytecode caches appear in templates/ whenever a template app is
    # imported; the scaffolder must skip them (regression: compileall
    # broke init with a UnicodeDecodeError). Work on a copy so the
    # installed package dir is never mutated.
    import shutil

    import unionml_tpu.cli as cli_mod

    templates_copy = tmp_path / "templates"
    shutil.copytree(cli_mod.TEMPLATES_DIR, templates_copy)
    pycache = templates_copy / "basic" / "__pycache__"
    pycache.mkdir()
    (pycache / "app.cpython-312.pyc").write_bytes(b"\xcb\r\r\n\x00binary")
    monkeypatch.setattr(cli_mod, "TEMPLATES_DIR", templates_copy)

    result = runner.invoke(app, ["init", "my_app"])
    assert result.exit_code == 0, result.output
    assert (tmp_path / "my_app" / "app.py").exists()
    assert not (tmp_path / "my_app" / "__pycache__").exists()
    content = (tmp_path / "my_app" / "app.py").read_text()
    assert "my_app" in content and "{{app_name}}" not in content
    # post-gen git init ran
    assert (tmp_path / "my_app" / ".git").exists()


def test_init_tpu_template(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(app, ["init", "tpu_app", "--template", "basic_tpu"])
    assert result.exit_code == 0, result.output
    assert "train_step" in (tmp_path / "tpu_app" / "app.py").read_text()


@pytest.mark.parametrize("template", ["serverless", "vision_tpu", "llm_serving"])
def test_init_new_templates_compile_and_register(runner, tmp_path, monkeypatch, template):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(app, ["init", "cv_app", "--template", template])
    assert result.exit_code == 0, result.output
    app_py = tmp_path / "cv_app" / "app.py"
    assert app_py.exists()
    assert "{{app_name}}" not in app_py.read_text()
    # the scaffold must import cleanly and register its spec (no training)
    import importlib.util

    spec = importlib.util.spec_from_file_location(f"cv_app_{template}", app_py)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        assert mod.model._predictor is not None
        assert mod.dataset._reader is not None
        if template == "serverless":
            assert callable(mod.handler) and callable(mod.on_upload)
    finally:
        sys.modules.pop(spec.name, None)


def test_init_rejects_bad_name(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(app, ["init", "bad-name!"])
    assert result.exit_code != 0
    assert "valid Python identifier" in result.output


def test_init_rejects_existing_dir(runner, tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "dup").mkdir()
    result = runner.invoke(app, ["init", "dup"])
    assert result.exit_code != 0 and "already exists" in result.output


def test_deploy_train_predict_roundtrip(runner, tmp_path, monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    monkeypatch.chdir(APPS_DIR)
    sys.path.insert(0, str(APPS_DIR))
    try:
        import sklearn_app

        sklearn_app.model._backend = None
        sklearn_app.model.remote(project="cli-project")

        result = runner.invoke(
            app, ["deploy", "sklearn_app:model", "--app-version", "vcli"]
        )
        assert result.exit_code == 0, result.output
        assert "deployed fixture_model version vcli" in result.output

        result = runner.invoke(
            app,
            ["train", "sklearn_app:model", "--app-version", "vcli",
             "--inputs", json.dumps({"hyperparameters": {"max_iter": 200}, "n": 200})],
        )
        assert result.exit_code == 0, result.output
        assert "metrics" in result.output

        result = runner.invoke(app, ["list-model-versions", "sklearn_app:model"])
        assert result.exit_code == 0 and "train-" in result.output

        out_path = tmp_path / "fetched.joblib"
        result = runner.invoke(
            app, ["fetch-model", "sklearn_app:model", "-o", str(out_path)]
        )
        assert result.exit_code == 0, result.output
        assert out_path.exists()
    finally:
        sys.path.remove(str(APPS_DIR))


ALL_TEMPLATES = ["basic", "basic_tpu", "llm_serving", "serverless", "vision_tpu"]
SCAFFOLD_FILES = [
    "app.py", "README.md", "requirements.txt", "Dockerfile", ".gitignore",
    "tests/test_app.py",
]


@pytest.mark.parametrize("template", ALL_TEMPLATES)
def test_init_emits_full_project_scaffold(runner, tmp_path, monkeypatch, template):
    """Every template scaffolds a DEPLOYABLE project, not just an app.py
    (reference parity: each cookiecutter template ships README,
    requirements, Dockerfile, .gitignore, and a unit test)."""
    monkeypatch.chdir(tmp_path)
    result = runner.invoke(app, ["init", "proj", "--template", template])
    assert result.exit_code == 0, result.output
    root = tmp_path / "proj"
    for rel in SCAFFOLD_FILES:
        assert (root / rel).exists(), f"{template} scaffold missing {rel}"
    readme = (root / "README.md").read_text()
    assert "proj" in readme and "{{app_name}}" not in readme
    dockerfile = (root / "Dockerfile").read_text()
    assert "requirements.txt" in dockerfile and "CMD" in dockerfile
    if template == "serverless":
        assert (root / "template.yaml").exists()
        assert (root / "events" / "gateway_predict.json").exists()


@pytest.mark.parametrize("template", ALL_TEMPLATES)
def test_scaffolded_project_tests_pass(runner, tmp_path, monkeypatch, template):
    """Every scaffold's own test suite passes as generated — `init`
    output is deployable, not just importable."""
    import os
    import subprocess

    monkeypatch.chdir(tmp_path)
    result = runner.invoke(app, ["init", "proj", "--template", template])
    assert result.exit_code == 0, result.output
    env = dict(os.environ)
    repo_root = str(Path(__file__).parent.parent.parent)
    env["PYTHONPATH"] = os.pathsep.join([repo_root, env.get("PYTHONPATH", "")])
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--no-header", "-p", "no:cacheprovider"],
        cwd=tmp_path / "proj", env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
