"""Gradient accumulation: accum=K at microbatch b must trace the same
loss trajectory as one-shot batch K*b (grad linearity + mean-style
losses), under plain jit, a DP mesh, and an SP mesh; and the Model
surface must plumb ``accumulate_steps`` end to end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.train import (
    accumulated_value_and_grad,
    classification_step,
    create_train_state,
    lm_step,
)
from unionml_tpu.parallel import ShardingConfig
from unionml_tpu.execution import run_step_trainer

from flax import linen as nn


class _Mlp(nn.Module):
    classes: int = 4

    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(32)(x))
        return nn.Dense(self.classes)(h)


def _data(n=64, d=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, classes, size=(n,)).astype(np.int32)
    return x, y


def test_accumulated_grads_match_big_batch():
    """Core math: mean grads over K microbatches == big-batch grads."""
    module = _Mlp()
    x, y = _data()
    state = create_train_state(module, x[:4], learning_rate=1e-2)

    def loss_fn(params, microbatch):
        feats, labels = microbatch
        logits = module.apply({"params": params}, feats)
        import optax

        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()
        return loss, {"acc": jnp.float32(0.0)}

    micro = (x[:32].reshape(4, 8, -1), y[:32].reshape(4, 8))
    (loss_a, _), grads_a = jax.jit(
        lambda p, b: accumulated_value_and_grad(loss_fn, p, b)
    )(state.params, micro)
    (loss_b, _), grads_b = jax.jit(
        jax.value_and_grad(loss_fn, has_aux=True)
    )(state.params, (x[:32], y[:32]))
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads_a), jax.tree_util.tree_leaves(grads_b)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def _train_losses(step, state, x, y, *, batch_size, accumulate_steps, steps=4):
    """Drive the raw step over deterministic contiguous batches."""
    losses = []
    feed = batch_size * accumulate_steps
    for i in range(steps):
        xb = x[i * feed : (i + 1) * feed]
        yb = y[i * feed : (i + 1) * feed]
        if accumulate_steps > 1:
            xb = xb.reshape((accumulate_steps, batch_size) + xb.shape[1:])
            yb = yb.reshape((accumulate_steps, batch_size))
        state, metrics = jax.jit(step)(state, (jnp.asarray(xb), jnp.asarray(yb)))
        losses.append(float(metrics["loss"]))
    return losses


def test_classification_accum_4x8_matches_batch_32():
    module = _Mlp()
    x, y = _data(n=128)
    s0 = create_train_state(module, x[:4], learning_rate=1e-2, seed=1)
    base = _train_losses(
        classification_step(module), s0, x, y, batch_size=32, accumulate_steps=1
    )
    s0 = create_train_state(module, x[:4], learning_rate=1e-2, seed=1)
    acc = _train_losses(
        classification_step(module, accumulate_steps=4),
        s0, x, y, batch_size=8, accumulate_steps=4,
    )
    np.testing.assert_allclose(base, acc, rtol=1e-4)


def test_lm_accum_matches_big_batch():
    """The scan accumulator equals an unrolled per-microbatch grad mean
    exactly (same microbatch forwards), and the big-batch loss to bf16
    tolerance. Post-optimizer params are NOT compared: adam normalizes by
    sqrt(v), so epsilon-scale bf16 grad noise flips near-zero updates."""
    cfg = LlamaConfig.tiny(vocab_size=64)
    module = Llama(cfg)
    rng = np.random.default_rng(3)
    toks = rng.integers(1, 64, size=(32, 16)).astype(np.int32)
    state = create_train_state(module, jnp.asarray(toks[:2]), learning_rate=1e-3, seed=2)

    base_step = lm_step(module)
    acc_step = lm_step(module, accumulate_steps=4)
    micro = jnp.asarray(toks.reshape(4, 8, 16))
    _, m_base = jax.jit(base_step)(state, jnp.asarray(toks))
    _, m_acc = jax.jit(acc_step)(state, micro)
    np.testing.assert_allclose(
        float(m_base["loss"]), float(m_acc["loss"]), rtol=2e-3
    )

    # mechanism-exact check: scan accumulation == unrolled mean
    def loss_fn(params, mb):
        inputs, targets = mb[:, :-1], mb[:, 1:]
        from unionml_tpu.models.train import masked_cross_entropy

        logits = module.apply({"params": params}, inputs)
        return masked_cross_entropy(logits, targets), {"z": jnp.float32(0.0)}

    (loss_a, _), grads_a = jax.jit(
        lambda p, b: accumulated_value_and_grad(loss_fn, p, b)
    )(state.params, micro)
    vg = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))
    per = [vg(state.params, micro[i]) for i in range(4)]
    loss_b = np.mean([float(l) for (l, _), _ in per])
    np.testing.assert_allclose(float(loss_a), loss_b, rtol=1e-5)
    mean_grads = jax.tree_util.tree_map(
        lambda *gs: sum(np.asarray(g, np.float32) for g in gs) / 4.0,
        *[g for _, g in per],
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(grads_a), jax.tree_util.tree_leaves(mean_grads)
    ):
        np.testing.assert_allclose(np.asarray(a), b, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize(
    "sharding_kwargs",
    [
        {"data": 8},                                   # DP mesh
        {"data": 2, "fsdp": 2, "tensor": 2},           # mixed mesh
    ],
    ids=["dp8", "dp2xfsdp2xtp2"],
)
def test_trainer_accumulation_under_mesh(sharding_kwargs):
    """run_step_trainer(accumulate_steps=4) on a sharded mesh reaches the
    same loss as batch-32 accumulation-free training (same data order)."""
    module = _Mlp()
    x, y = _data(n=256, seed=5)
    cfg = ShardingConfig(**sharding_kwargs)

    s0 = create_train_state(module, x[:4], learning_rate=1e-2, seed=4)
    out_base = run_step_trainer(
        step_fn=classification_step(module), state=s0, features=x, targets=y,
        batch_size=32, num_epochs=2, seed=9, sharding=cfg,
    )
    s0 = create_train_state(module, x[:4], learning_rate=1e-2, seed=4)
    out_acc = run_step_trainer(
        step_fn=classification_step(module, accumulate_steps=4),
        state=s0, features=x, targets=y,
        batch_size=8, accumulate_steps=4, num_epochs=2, seed=9,
        sharding=ShardingConfig(**sharding_kwargs),
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(out_base.params),
        jax.tree_util.tree_leaves(out_acc.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5)


def test_model_surface_accumulate_steps():
    """@model.train_step(accumulate_steps=4) trains through Model.train."""
    from unionml_tpu import Dataset, Model

    module = _Mlp()
    x, y = _data(n=128, seed=6)
    dataset = Dataset(name="accum_data")

    @dataset.reader
    def reader() -> dict:
        return {"features": x, "targets": y}

    @dataset.splitter
    def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
        k = int(len(data["features"]) * (1 - test_size))
        return (
            {"features": data["features"][:k], "targets": data["targets"][:k]},
            {"features": data["features"][k:], "targets": data["targets"][k:]},
        )

    @dataset.parser
    def parser(data: dict, features, targets):
        return (data["features"], data["targets"])

    model = Model(
        name="accum_model",
        init=lambda: create_train_state(module, x[:4], learning_rate=1e-2),
        dataset=dataset,
    )

    @model.train_step(accumulate_steps=4)
    def step(state, batch):
        return classification_step(module, accumulate_steps=4)(state, batch)

    @model.predictor
    def predictor(state, features: np.ndarray) -> list:
        logits = module.apply({"params": state.params}, jnp.asarray(features))
        return np.argmax(np.asarray(logits), -1).tolist()

    obj, metrics = model.train(batch_size=8, num_epochs=3)
    preds = model.predict(features=x[:8])
    assert len(preds) == 8 and all(0 <= p < 4 for p in preds)


def test_accumulation_input_validation():
    module = _Mlp()
    x, y = _data(n=16)
    state = create_train_state(module, x[:4])
    with pytest.raises(ValueError, match="accumulate_steps"):
        run_step_trainer(
            step_fn=classification_step(module), state=state,
            features=x, targets=y, batch_size=8, accumulate_steps=0,
        )
    with pytest.raises(ValueError, match="at least"):
        run_step_trainer(
            step_fn=classification_step(module, accumulate_steps=4), state=state,
            features=x, targets=y, batch_size=8, accumulate_steps=4,
        )
