"""Remote backend tests: deploy → subprocess execute → registry
(reference analog: tests/integration/test_flyte_remote.py, with the
LocalBackend subprocess sandbox standing in for the Flyte sandbox)."""

import sys
from pathlib import Path

import numpy as np

import pytest

APPS_DIR = Path(__file__).parent.parent / "apps"


@pytest.fixture
def fixture_model(monkeypatch, tmp_path):
    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    sys.path.insert(0, str(APPS_DIR))
    try:
        import sklearn_app

        sklearn_app.model._backend = None  # reset cached backend per test
        sklearn_app.model.remote(project="fixture-project")
        yield sklearn_app.model
    finally:
        sys.path.remove(str(APPS_DIR))


def test_deploy_and_remote_train(fixture_model):
    version = fixture_model.remote_deploy(app_version="v1")
    assert version == "v1"
    dep_dir = fixture_model._remote.deployment_dir("v1")
    assert (dep_dir / "sklearn_app.py").exists()
    assert (dep_dir / ".unionml_manifest.json").exists()

    artifact = fixture_model.remote_train(app_version="v1", hyperparameters={"max_iter": 200}, n=200)
    assert artifact.model_object is not None
    assert artifact.metrics["test"] > 0.8


def test_remote_predict_and_registry(fixture_model):
    fixture_model.remote_deploy(app_version="v1")
    fixture_model.remote_train(app_version="v1", hyperparameters={"max_iter": 200}, n=200)

    versions = fixture_model.remote_list_model_versions()
    assert len(versions) == 1 and versions[0].startswith("train-")

    preds = fixture_model.remote_predict(model_version="latest", n=50)
    assert isinstance(preds, list) and len(preds) == 50

    # predict from raw features
    preds2 = fixture_model.remote_predict(
        features=[{"x1": 5.0, "x2": 5.0}, {"x1": -5.0, "x2": -5.0}]
    )
    assert preds2 == [1.0, 0.0]


def test_patch_deploy(fixture_model):
    """Patch redeploy overlays source (reference: test_flyte_remote.py:131-146)."""
    fixture_model.remote_deploy(app_version="v1")
    version = fixture_model.remote_deploy(app_version="v1", patch=True)
    assert version.startswith("v1-patch")
    assert fixture_model._remote.deployment_dir(version).exists()


def test_failed_execution_surfaces_log(fixture_model):
    fixture_model.remote_deploy(app_version="v1")
    with pytest.raises(RuntimeError, match="FAILED"):
        # bogus reader kwarg -> workflow TypeError inside the runner process
        fixture_model.remote_train(app_version="v1", bogus_kwarg=1)


def test_execute_requires_deployment(fixture_model):
    with pytest.raises(FileNotFoundError):
        fixture_model.remote_train(app_version="never-deployed")


def test_app_version_dirty_tree_guard(tmp_path, monkeypatch):
    import subprocess

    from unionml_tpu.remote import VersionFetchError, get_app_version

    repo = tmp_path / "repo"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.email", "t@t"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.name", "t"], cwd=repo, check=True)
    (repo / "f.txt").write_text("hello")
    subprocess.run(["git", "add", "."], cwd=repo, check=True)
    subprocess.run(["git", "commit", "-q", "-m", "init"], cwd=repo, check=True)

    version = get_app_version(cwd=str(repo))
    assert len(version) == 7

    (repo / "f.txt").write_text("dirty")
    with pytest.raises(VersionFetchError, match="uncommitted"):
        get_app_version(cwd=str(repo))
    assert get_app_version(allow_uncommitted=True, cwd=str(repo)).endswith("-dirty")


# ---------------------------------------------------------------------------
# TPUVMBackend with a faked SSH/scp transport (reference analog:
# tests/integration/test_flyte_remote.py:33-57 — a local stand-in instead
# of real cluster hosts). The transport primitives (_ssh/_run_ssh/_scp_*)
# are replaced with local bash/cp so env wiring, per-host logs, failure
# aggregation, and the no-shared-FS fetch path all run for real.
# ---------------------------------------------------------------------------

import os
import subprocess

REPO_ROOT = Path(__file__).parent.parent.parent


def _make_tpuvm_backend(tmp_path, hosts, **kwargs):
    from unionml_tpu.remote import TPUVMBackend

    kwargs.setdefault("provision", False)
    return TPUVMBackend(
        hosts=hosts,
        project="fixture-project",
        root=str(tmp_path / "backend"),
        workdir=str(tmp_path / "vm_work"),
        **kwargs,
    )


def _fake_transport(monkeypatch, backend, fail_hosts=(), capture=None, stub=False):
    """Local-subprocess stand-ins for the SSH/scp primitives.

    ``stub=True`` records remote commands without executing them (for
    wiring/provisioning assertions); otherwise commands run locally via
    bash, so the real runner executes in the pushed workdir.
    """

    def fake_run_ssh(host, command):
        if capture is not None:
            capture.append(("run_ssh", host, command))
        if stub and "pip install" in command:
            return subprocess.CompletedProcess([], 0, "", "")
        if "docker pull" in command:
            # remote docker isn't available in the fake environment in
            # either mode; the capture records the pull for assertions
            return subprocess.CompletedProcess([], 0, "", "")
        return subprocess.run(["bash", "-c", command], capture_output=True, text=True)

    def fake_scp_to(host, src, dst):
        if capture is not None:
            capture.append(("scp_to", host, src, dst))
        # the fake "remote" shares this FS, so a registry stage can target
        # the very dir it comes from — a no-op copy, not an error
        if Path(src.rstrip("/.")).resolve() == Path(dst).resolve():
            return
        subprocess.run(["bash", "-c", f"mkdir -p {dst} && cp -r {src} {dst}"], check=True)

    def fake_scp_from(host, src, dst):
        if capture is not None:
            capture.append(("scp_from", host, src, dst))
        subprocess.run(["bash", "-c", f"mkdir -p {dst} && cp -r {src} {dst}"], check=True)

    def fake_ssh(host, command, **popen_kwargs):
        if capture is not None:
            capture.append(("ssh", host, command))
        if stub:
            return subprocess.Popen(["true"], **popen_kwargs)
        if host in fail_hosts:
            return subprocess.Popen(
                ["bash", "-c", "echo 'fake host crash' >&2; exit 3"], **popen_kwargs
            )
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [str(REPO_ROOT), str(APPS_DIR), env.get("PYTHONPATH", "")]
        )
        return subprocess.Popen(["bash", "-c", command], env=env, **popen_kwargs)

    monkeypatch.setattr(backend, "_run_ssh", fake_run_ssh)
    monkeypatch.setattr(backend, "_scp_to", fake_scp_to)
    monkeypatch.setattr(backend, "_scp_from", fake_scp_from)
    monkeypatch.setattr(backend, "_ssh", fake_ssh)
    return backend


@pytest.fixture
def tpuvm_model(monkeypatch, tmp_path):
    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    sys.path.insert(0, str(APPS_DIR))
    try:
        import sklearn_app

        sklearn_app.model._backend = None
        sklearn_app.model.remote(project="fixture-project")
        yield sklearn_app.model, tmp_path
    finally:
        sys.path.remove(str(APPS_DIR))


def test_tpuvm_multihost_env_wiring(tpuvm_model, monkeypatch):
    """Every host gets the jax.distributed coordinator env (host 0 is the
    coordinator) and its own runner log; processes are tracked for wait()."""
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA", "hostB"])
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture, stub=True)
    model._backend = backend

    backend.deploy(model, app_version="v1")
    record = backend.execute(model, workflow="train", app_version="v1",
                             inputs={}, wait=False)
    launched = backend._procs[record.execution_id]
    try:
        cmds = {e[1]: e[2] for e in capture if e[0] == "ssh"}
        assert "JAX_COORDINATOR_ADDRESS=hostA:8476" in cmds["hostA"]
        assert "JAX_NUM_PROCESSES=2" in cmds["hostA"]
        assert "JAX_PROCESS_ID=0" in cmds["hostA"]
        assert "JAX_PROCESS_ID=1" in cmds["hostB"]
        assert len(launched["procs"]) == 2
        for i in range(2):
            assert (Path(record.exec_dir) / f"runner.host{i}.log").exists()
    finally:
        for _, proc, log in launched["procs"]:
            proc.wait(timeout=30)
            log.close()
        backend._procs.pop(record.execution_id, None)


def test_tpuvm_per_host_failure_propagates(tpuvm_model, monkeypatch):
    """A crashed host fails the execution with that host's rc + log tail
    (round-1 gap: _launch fired SSH processes and never looked back)."""
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA", "hostB"])
    _fake_transport(monkeypatch, backend, fail_hosts={"hostB"})
    model._backend = backend

    backend.deploy(model, app_version="v1")
    with pytest.raises(RuntimeError, match=r"host 1 \(hostB\): rc=3"):
        backend.execute(model, workflow="train", app_version="v1",
                        inputs={}, wait=True)
    # the record was marked FAILED for later inspectors — and the host
    # died WITHOUT reporting (simulated crash rc=3), so the failure is
    # classified as a preemption: eligible for execute(max_restarts=)
    from unionml_tpu.remote import ExecutionRecord

    execs = list((Path(str(tmp_path / "backend")) / "executions" /
                  "fixture-project").iterdir())
    assert len(execs) == 1
    rec = ExecutionRecord.load(execs[0])
    assert rec.status == "FAILED"
    assert rec.failure_kind == "preempted"


def test_tpuvm_single_host_end_to_end_without_shared_fs(tpuvm_model, monkeypatch):
    """Full lifecycle over the faked transport with shared_fs=False: deploy
    push -> runner executes in the per-version workdir -> inputs staged out,
    host-0 outputs fetched back -> artifact loads. Single host launches
    without any jax.distributed env."""
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA"], shared_fs=False)
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture)
    model._backend = backend

    model.remote_deploy(app_version="v1")
    artifact = model.remote_train(app_version="v1",
                                  hyperparameters={"max_iter": 200}, n=200)
    assert artifact.model_object is not None
    assert artifact.metrics["test"] > 0.8
    (cmd,) = [e[2] for e in capture if e[0] == "ssh"]
    assert "JAX_COORDINATOR_ADDRESS" not in cmd  # single host: no dist init
    assert "_exec" in cmd  # runner pointed at the staged exec dir
    assert any(e[0] == "scp_from" for e in capture)  # outputs fetched back

    # predict resolves the trained model on the host: without a shared FS
    # the backend must stage the train execution into the host's registry
    preds = model.remote_predict(
        app_version="v1",
        features=[{"x1": 5.0, "x2": 5.0}, {"x1": -5.0, "x2": -5.0}],
    )
    assert preds == [1.0, 0.0]


def test_tpuvm_provisioning_installs_on_every_host(tpuvm_model, monkeypatch):
    """Full deploys push the environment bundle and pip-install it per host;
    patch deploys skip provisioning (fast-registration parity)."""
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA", "hostB"], provision=True)
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture, stub=True)
    model._backend = backend

    def fake_bundle(dest):
        env_dir = Path(dest) / "_env"
        env_dir.mkdir(parents=True, exist_ok=True)
        (env_dir / "unionml_tpu-0.1.0-py3-none-any.whl").write_bytes(b"wheel")
        (env_dir / "requirements.lock").write_text("jax==0.0.test\n")
        return env_dir

    import unionml_tpu.remote.packaging as packaging

    monkeypatch.setattr(packaging, "build_environment_bundle", fake_bundle)

    backend.deploy(model, app_version="v1")
    pip_cmds = [(e[1], e[2]) for e in capture
                if e[0] == "run_ssh" and "pip install" in e[2]]
    assert {h for h, _ in pip_cmds} == {"hostA", "hostB"}
    assert all("requirements.lock" in c and ".whl" in c for _, c in pip_cmds)

    capture.clear()
    backend.deploy(model, app_version="v1-patch123", patch=True)
    assert not [e for e in capture
                if e[0] == "run_ssh" and "pip install" in e[2]]


def test_environment_bundle_builds_offline(tmp_path):
    """The real wheel build + pinned lock (the docker_build_push analog)."""
    from unionml_tpu.remote import build_environment_bundle

    env_dir = build_environment_bundle(tmp_path / "dep")
    wheels = list(env_dir.glob("unionml_tpu-*.whl"))
    assert len(wheels) == 1
    lock = (env_dir / "requirements.lock").read_text()
    assert "jax==" in lock and "flax==" in lock and "optax==" in lock


def test_tpuvm_registry_staging_rewrites_exec_dir(tpuvm_model, monkeypatch):
    """The record staged to a no-shared-FS host must carry the HOST-side
    exec_dir, not the deployer-local one — the runner's fetch_outputs
    follows record.exec_dir, which doesn't exist on a separate FS."""
    import json as _json

    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA"], shared_fs=False)
    _fake_transport(monkeypatch, backend)
    model._backend = backend

    model.remote_deploy(app_version="v1")
    model.remote_train(app_version="v1", hyperparameters={"max_iter": 200}, n=200)

    staged = {}
    orig_scp = backend._scp_to

    def spy_scp(host, src, dst):
        if "/executions/" in dst:
            rec = _json.loads(
                (Path(src.rstrip(".").rstrip("/")) / "record.json").read_text()
            )
            staged["exec_dir"] = rec["exec_dir"]
            staged["dst"] = dst
        orig_scp(host, src, dst)

    monkeypatch.setattr(backend, "_scp_to", spy_scp)
    preds = model.remote_predict(
        app_version="v1",
        features=[{"x1": 5.0, "x2": 5.0}, {"x1": -5.0, "x2": -5.0}],
    )
    assert preds == [1.0, 0.0]
    assert staged, "registry staging never happened"
    assert staged["exec_dir"] == staged["dst"]


def test_remote_train_with_jax_train_state_artifact(monkeypatch, tmp_path):
    """TrainState model objects cross the execution boundary: they are not
    picklable (optax closures), so the runner encodes them as the app's
    saver bytes and remote_load/_load_model_artifact decode them back
    (remote/artifacts.py). Covers remote_train AND remote_predict."""
    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    sys.path.insert(0, str(APPS_DIR))
    try:
        import flax_app

        flax_app.model._backend = None
        flax_app.model.remote(project="flax-fixture")
        flax_app.model.remote_deploy(app_version="v1")
        artifact = flax_app.model.remote_train(
            app_version="v1", hyperparameters={"learning_rate": 1e-2}, n=64
        )
        import jax

        assert jax.tree_util.tree_leaves(artifact.model_object.params)
        assert artifact.metrics["test"] >= 0.8

        preds = flax_app.model.remote_predict(
            features=np.ones((4, 8), dtype=np.float32)
        )
        assert preds == [1, 1, 1, 1]
    finally:
        sys.path.remove(str(APPS_DIR))


def test_tpuvm_wait_without_launch_rejected_when_no_shared_fs(tpuvm_model):
    """wait() from a process that did not launch the execution only sees the
    record turn terminal when the launcher's scp lands it (shared_fs=False);
    a timeout must name that cause, not raise a bare TimeoutError."""
    from unionml_tpu.remote.backend import ExecutionRecord

    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA"], shared_fs=False)
    exec_dir = tmp_path / "orphan-exec"
    exec_dir.mkdir()
    record = ExecutionRecord(
        execution_id="orphan", project="fixture-project",
        workflow="train", app_version="v1", exec_dir=str(exec_dir),
    )
    record.save()
    with pytest.raises(TimeoutError, match="shared_fs"):
        backend.wait(record, timeout=1.0)


def test_dump_outputs_names_non_model_offender(fixture_model):
    """An unpicklable key other than model_object must be named in the
    error (chained from the original) instead of failing the saver-encoded
    retry with a second traceback masking the cause."""
    import io

    from unionml_tpu.remote.artifacts import dump_outputs

    outputs = {
        "model_object": {"w": 1.0},
        "hyperparameters": {},
        "metrics": {"callback": lambda x: x},  # unpicklable, not the model
    }
    with pytest.raises(RuntimeError, match="metrics") as err:
        dump_outputs(fixture_model, outputs, io.BytesIO())
    assert err.value.__cause__ is not None  # original pickling error chained


def _fake_docker(monkeypatch, backend, capture, *, fail_on=None):
    """Local docker stand-in: records build/push/pull; `docker run ...`
    launched over SSH is rewritten to execute the inner runner command
    directly, so the containerized launch path runs for real."""

    def fake_run_docker(args):
        capture.append(("docker",) + tuple(args[:2]))
        if fail_on and args[0] == fail_on:
            return subprocess.CompletedProcess([], 1, "", f"fake {fail_on} failure")
        return subprocess.CompletedProcess([], 0, "", "")

    monkeypatch.setattr(backend, "_run_docker", fake_run_docker)
    return backend


def test_tpuvm_image_deploy_builds_pushes_and_pulls(tpuvm_model, monkeypatch):
    """Image mode: full deploy = docker build + push + per-host pull, NO
    pip provisioning; patch deploy skips all image work."""
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(
        tmp_path, ["hostA", "hostB"], provision=True, image="reg.example/app"
    )
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture, stub=True)
    _fake_docker(monkeypatch, backend, capture)

    backend.deploy(model, app_version="v1")
    assert ("docker", "build", "-t") in capture
    assert ("docker", "push", "reg.example/app:v1") in capture
    pulls = [(e[1], e[2]) for e in capture if e[0] == "run_ssh" and "docker pull" in e[2]]
    assert {h for h, _ in pulls} == {"hostA", "hostB"}
    assert all("reg.example/app:v1" in c for _, c in pulls)
    # image supersedes pip provisioning
    assert not [e for e in capture if e[0] == "run_ssh" and "pip install" in e[2]]

    capture.clear()
    backend.deploy(model, app_version="v1-patch123", patch=True)
    assert not [e for e in capture if e[0] == "docker"]
    assert not [e for e in capture if e[0] == "run_ssh" and "docker pull" in e[2]]


def test_tpuvm_image_deploy_failure_surfaces(tpuvm_model, monkeypatch):
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA"], image="reg.example/app")
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture, stub=True)
    _fake_docker(monkeypatch, backend, capture, fail_on="push")
    with pytest.raises(RuntimeError, match="docker push failed"):
        backend.deploy(model, app_version="v1")


def test_tpuvm_image_execution_runs_in_container(tpuvm_model, monkeypatch):
    """The launch command wraps the runner in `docker run` with the
    workdir/registry mounts and env flags; executing it (with the docker
    prefix stripped by the fake transport) completes the full train
    lifecycle — proving the in-container command is the real runner
    invocation."""
    import re

    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(
        tmp_path, ["hostA"], shared_fs=False, image="reg.example/app",
        image_push=False,
    )
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture)
    _fake_docker(monkeypatch, backend, capture)

    real_ssh = backend._ssh

    def docker_exec_ssh(host, command, **popen_kwargs):
        if command.startswith("docker run"):
            m = re.search(r"reg\.example/app:\S+ (python -m unionml_tpu\.remote\.runner .*)$", command)
            assert m, command
            assert f"-v {backend.root}:{backend.root}" in command
            assert "-e UNIONML_TPU_HOME=" in command and "--network host" in command
            # single host: no jax.distributed env
            assert "JAX_COORDINATOR_ADDRESS" not in command
            envs = dict(
                kv.split("=", 1)
                for kv in re.findall(r"-e ([A-Z_]+=\S+)", command)
            )
            inner = m.group(1)
            env = dict(os.environ)
            env.update(envs)
            env["PYTHONPATH"] = os.pathsep.join(
                [str(REPO_ROOT), str(APPS_DIR), env.get("PYTHONPATH", "")]
            )
            wd = re.search(r"-w (\S+)", command).group(1)
            return subprocess.Popen(["bash", "-c", inner], cwd=wd, env=env, **popen_kwargs)
        return real_ssh(host, command, **popen_kwargs)

    monkeypatch.setattr(backend, "_ssh", docker_exec_ssh)
    model._backend = backend
    model.remote_deploy(app_version="v1")
    artifact = model.remote_train(app_version="v1",
                                  hyperparameters={"max_iter": 200}, n=200)
    assert artifact.metrics["test"] > 0.8
    assert any(e[0] == "docker" and e[1] == "build" for e in capture)


# ---------------------------------------------------------------------------
# Stage.resources are consumed at launch (reference: unionml/defaults.py:5
# sizes the task container; here the launcher derives the runner env)


def test_resources_env_derivation():
    from unionml_tpu.defaults import Resources, cpu_count, resources_env

    host_only = Resources(cpu="2", mem="1Gi", chips=0)
    env = resources_env(host_only)
    assert env["JAX_PLATFORMS"] == "cpu"  # never grab the accelerator
    assert env["OMP_NUM_THREADS"] == "2"
    device = Resources(cpu="500m", mem="8Gi", chips=1)
    env = resources_env(device)
    assert "JAX_PLATFORMS" not in env     # the accelerator stays visible
    assert env["OMP_NUM_THREADS"] == "1"  # fractional cpu rounds up to 1
    assert cpu_count(Resources(cpu="nonsense")) == 1


def test_workflow_resources_take_stage_maxima():
    from unionml_tpu.defaults import Resources
    from unionml_tpu.remote.backend import _mem_bytes, _workflow_resources
    from unionml_tpu.stage import Workflow, stage_from_fn

    wf = Workflow("wf")
    reader = stage_from_fn(
        lambda: [], name="reader", owner=None,
        resources=Resources(cpu="1", mem="512Mi", chips=0),
    )
    trainer = stage_from_fn(
        lambda: None, name="trainer", owner=None,
        resources=Resources(cpu="4", mem="8Gi", chips=1, accelerator="tpu-v5e"),
    )
    wf.add_node(reader, {})
    wf.add_node(trainer, {})
    env = _workflow_resources(wf)
    assert env.cpu == "4" and env.chips == 1 and env.mem == "8Gi"
    assert env.accelerator == "tpu-v5e"
    assert _mem_bytes("512Mi") < _mem_bytes("1Gi") < _mem_bytes("2G")


def test_manifest_env_backcompat_and_chips0():
    from unionml_tpu.remote.backend import _manifest_env

    # pre-round-4 manifests carry no resources: no overrides
    assert _manifest_env({"app": "x:y"}, "train") == {}
    manifest = {
        "resources": {
            "prep": {"cpu": "2", "mem": "1Gi", "chips": 0, "accelerator": None},
            "train": {"cpu": "4", "mem": "8Gi", "chips": 1, "accelerator": "tpu-v5e"},
        }
    }
    assert _manifest_env(manifest, "prep")["JAX_PLATFORMS"] == "cpu"
    assert "JAX_PLATFORMS" not in _manifest_env(manifest, "train")
    assert _manifest_env(manifest, "unknown") == {}


def test_local_backend_applies_resources_env(fixture_model, monkeypatch):
    """The launched runner's environment carries the derived resource env.
    The sklearn fixture is a HOST-ONLY model family, so its stages default
    to chips=0 (Resources docstring promise): the runner env pins
    JAX_PLATFORMS=cpu and caps threadpools at the host default."""
    import subprocess as sp

    import unionml_tpu.remote.backend as backend_mod

    model = fixture_model
    backend = model._remote
    backend.deploy(model, app_version="rv1")
    manifest_path = backend.deployment_dir("rv1") / ".unionml_manifest.json"
    assert "resources" in manifest_path.read_text()

    captured = {}
    real_popen = sp.Popen

    def capture_popen(cmd, **kwargs):
        captured["env"] = kwargs.get("env", {})
        return real_popen(["true"], stdout=kwargs.get("stdout"),
                          stderr=kwargs.get("stderr"))

    monkeypatch.setattr(backend_mod.subprocess, "Popen", capture_popen)
    record = backend.execute(
        model, workflow=model.train_workflow_name, app_version="rv1",
        inputs={}, wait=False,
    )
    assert record is not None
    assert captured["env"]["OMP_NUM_THREADS"] == "1"
    # host-only workflow (chips=0): the launcher pins JAX_PLATFORMS=cpu so
    # a data-prep/sklearn run never grabs the accelerator a co-tenant
    # serving process is using
    assert captured["env"].get("JAX_PLATFORMS") == "cpu"

    # device workflow (chips=1): redeploy with explicit device resources —
    # the launcher must apply the thread caps but NOT pin the platform
    # (whatever JAX_PLATFORMS the ambient env carries passes through).
    # monkeypatch-scoped: the sklearn_app module is SHARED across tests,
    # so unrestored mutations leak into later fixtures (caught by the
    # tpuvm resources test failing only in full-suite order)
    from unionml_tpu.defaults import DEFAULT_DEVICE_RESOURCES

    monkeypatch.setitem(
        model._train_task_kwargs, "resources", DEFAULT_DEVICE_RESOURCES
    )
    monkeypatch.setattr(model, "_train_task", None)  # regenerate stage
    backend.deploy(model, app_version="rv2")
    captured.clear()
    record = backend.execute(
        model, workflow=model.train_workflow_name, app_version="rv2",
        inputs={}, wait=False,
    )
    assert record is not None
    assert captured["env"]["OMP_NUM_THREADS"] == "4"
    import os as _os

    assert captured["env"].get("JAX_PLATFORMS") == _os.environ.get(
        "JAX_PLATFORMS"
    )


def test_tpuvm_resources_env_in_ssh_command(tpuvm_model, monkeypatch):
    model, tmp_path = tpuvm_model
    backend = _make_tpuvm_backend(tmp_path, ["hostA"])
    capture = []
    _fake_transport(monkeypatch, backend, capture=capture, stub=True)
    model._backend = backend
    backend.deploy(model, app_version="v1")
    record = backend.execute(model, workflow="train", app_version="v1",
                             inputs={}, wait=False)
    launched = backend._procs[record.execution_id]
    try:
        cmds = {e[1]: e[2] for e in capture if e[0] == "ssh"}
        # sklearn app = host-only family: chips=0 defaults flow into the
        # SSH launch line (thread cap + platform pin)
        assert "OMP_NUM_THREADS=1" in cmds["hostA"]
        assert "JAX_PLATFORMS=cpu" in cmds["hostA"]
    finally:
        for _, proc, log in launched["procs"]:
            proc.wait(timeout=30)
            log.close()
        backend._procs.pop(record.execution_id, None)


def test_elastic_train_step_survives_preemption(monkeypatch, tmp_path):
    """SURVEY §5.3 e2e: a train_step registered with checkpoint_dir is
    preemption-safe through the remote lifecycle. The runner is
    HARD-KILLED (os._exit — no cleanup, no terminal status) mid-run;
    LocalBackend.wait detects the dead pid, execute(max_restarts=1)
    relaunches the same execution, the elastic trainer resumes from the
    newest checkpoint, and the final state is BIT-IDENTICAL to an
    uninterrupted run."""
    import numpy as np

    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    sys.path.insert(0, str(APPS_DIR))
    try:
        import elastic_app

        model = elastic_app.model
        model._backend = None
        model.remote(project="elastic-project")
        backend = model._remote

        # 48 train rows / batch 8 = 6 steps/epoch x 4 epochs = 24 steps;
        # checkpoints at 2,4,...; the bomb kills the runner at step 5
        monkeypatch.setenv("UNIONML_TEST_DIE_AT", "5")
        trainer_kwargs = {"num_epochs": 4, "batch_size": 8, "seed": 0}
        backend.deploy(model, app_version="e1")
        record = backend.execute(
            model, workflow="train", app_version="e1",
            inputs={"trainer_kwargs": trainer_kwargs},
            wait=True, max_restarts=1,
        )
        assert record.status == "SUCCEEDED"
        log = (Path(record.exec_dir) / "runner.log").read_text()
        assert "died without reporting" in log   # the kill really happened
        assert "resuming from step" in log       # ...and the relaunch RESUMED
        interrupted = backend.fetch_outputs(record)["model_object"]

        # control: fresh deployment (fresh relative checkpoint dir), no bomb
        monkeypatch.delenv("UNIONML_TEST_DIE_AT")
        backend.deploy(model, app_version="e2")
        record2 = backend.execute(
            model, workflow="train", app_version="e2",
            inputs={"trainer_kwargs": trainer_kwargs}, wait=True,
        )
        control = backend.fetch_outputs(record2)["model_object"]
        np.testing.assert_array_equal(
            np.asarray(interrupted["w"]), np.asarray(control["w"])
        )
        np.testing.assert_array_equal(
            np.asarray(interrupted["b"]), np.asarray(control["b"])
        )
    finally:
        sys.path.remove(str(APPS_DIR))


def test_max_restarts_skips_deterministic_failures(fixture_model, monkeypatch):
    """An app-REPORTED failure (reproducible crash) must surface
    immediately — max_restarts only retries preemptions (runner died
    without reporting), or every buggy run would retrain N times."""
    model = fixture_model
    backend = model._remote
    backend.deploy(model, app_version="df1")
    launches = []
    real_launch = backend._launch

    def counting_launch(*a, **k):
        launches.append(1)
        return real_launch(*a, **k)

    monkeypatch.setattr(backend, "_launch", counting_launch)
    with pytest.raises(RuntimeError, match="FAILED"):
        backend.execute(
            model, workflow="train", app_version="df1",
            inputs={"bogus_kwarg": 1},   # deterministic TypeError in-app
            wait=True, max_restarts=3,
        )
    assert len(launches) == 1, "deterministic failure was relaunched"


def test_pinned_requirements_toml_fallback_parser():
    """The Python-3.10 textual fallback must survive extras brackets
    inside specs and comments — a ']' only terminates the array
    OUTSIDE quotes (silently dropping deps would ship a broken env)."""
    from unionml_tpu.remote.packaging import _parse_dependencies_toml

    tricky = "\n".join([
        "[build-system]",
        'requires = ["setuptools"]',
        "[project]",
        'name = "x"',
        "dependencies = [",
        '    "jax[tpu]>=0.4.30",  # extras bracket inside the spec',
        "    'flax>=0.8',",
        '    "numpy>=1.24",',
        "]",
        "[project.optional-dependencies]",
        'dev = ["pytest"]',
    ])
    assert _parse_dependencies_toml(tricky) == [
        "jax[tpu]>=0.4.30", "flax>=0.8", "numpy>=1.24",
    ]
    assert _parse_dependencies_toml(
        '[project]\ndependencies = ["a[x]>=1", "b>=2"]\n'
    ) == ["a[x]>=1", "b>=2"]
    with pytest.raises(KeyError):
        _parse_dependencies_toml("[project]\nname='x'\n")
