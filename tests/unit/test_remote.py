"""Remote backend tests: deploy → subprocess execute → registry
(reference analog: tests/integration/test_flyte_remote.py, with the
LocalBackend subprocess sandbox standing in for the Flyte sandbox)."""

import sys
from pathlib import Path

import pytest

APPS_DIR = Path(__file__).parent.parent / "apps"


@pytest.fixture
def fixture_model(monkeypatch, tmp_path):
    monkeypatch.setenv("UNIONML_TPU_HOME", str(tmp_path / "backend"))
    sys.path.insert(0, str(APPS_DIR))
    try:
        import sklearn_app

        sklearn_app.model._backend = None  # reset cached backend per test
        sklearn_app.model.remote(project="fixture-project")
        yield sklearn_app.model
    finally:
        sys.path.remove(str(APPS_DIR))


def test_deploy_and_remote_train(fixture_model):
    version = fixture_model.remote_deploy(app_version="v1")
    assert version == "v1"
    dep_dir = fixture_model._remote.deployment_dir("v1")
    assert (dep_dir / "sklearn_app.py").exists()
    assert (dep_dir / ".unionml_manifest.json").exists()

    artifact = fixture_model.remote_train(app_version="v1", hyperparameters={"max_iter": 200}, n=200)
    assert artifact.model_object is not None
    assert artifact.metrics["test"] > 0.8


def test_remote_predict_and_registry(fixture_model):
    fixture_model.remote_deploy(app_version="v1")
    fixture_model.remote_train(app_version="v1", hyperparameters={"max_iter": 200}, n=200)

    versions = fixture_model.remote_list_model_versions()
    assert len(versions) == 1 and versions[0].startswith("train-")

    preds = fixture_model.remote_predict(model_version="latest", n=50)
    assert isinstance(preds, list) and len(preds) == 50

    # predict from raw features
    preds2 = fixture_model.remote_predict(
        features=[{"x1": 5.0, "x2": 5.0}, {"x1": -5.0, "x2": -5.0}]
    )
    assert preds2 == [1.0, 0.0]


def test_patch_deploy(fixture_model):
    """Patch redeploy overlays source (reference: test_flyte_remote.py:131-146)."""
    fixture_model.remote_deploy(app_version="v1")
    version = fixture_model.remote_deploy(app_version="v1", patch=True)
    assert version.startswith("v1-patch")
    assert fixture_model._remote.deployment_dir(version).exists()


def test_failed_execution_surfaces_log(fixture_model):
    fixture_model.remote_deploy(app_version="v1")
    with pytest.raises(RuntimeError, match="FAILED"):
        # bogus reader kwarg -> workflow TypeError inside the runner process
        fixture_model.remote_train(app_version="v1", bogus_kwarg=1)


def test_execute_requires_deployment(fixture_model):
    with pytest.raises(FileNotFoundError):
        fixture_model.remote_train(app_version="never-deployed")


def test_app_version_dirty_tree_guard(tmp_path, monkeypatch):
    import subprocess

    from unionml_tpu.remote import VersionFetchError, get_app_version

    repo = tmp_path / "repo"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.email", "t@t"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.name", "t"], cwd=repo, check=True)
    (repo / "f.txt").write_text("hello")
    subprocess.run(["git", "add", "."], cwd=repo, check=True)
    subprocess.run(["git", "commit", "-q", "-m", "init"], cwd=repo, check=True)

    version = get_app_version(cwd=str(repo))
    assert len(version) == 7

    (repo / "f.txt").write_text("dirty")
    with pytest.raises(VersionFetchError, match="uncommitted"):
        get_app_version(cwd=str(repo))
    assert get_app_version(allow_uncommitted=True, cwd=str(repo)).endswith("-dirty")
