"""Generated API/CLI references must match the committed files
(reference analog: Sphinx builds docs in CI, build.yml)."""

import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).parent.parent.parent


def test_generated_references_are_current():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "gen_api_reference.py"), "--check"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"docs/api_reference.md or docs/cli_reference.md is stale — "
        f"regenerate with `python scripts/gen_api_reference.py`\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_docs_site_builds_and_links_resolve():
    """The static docs site (reference analog: the Sphinx site) must
    build: every nav entry exists and internal .md links resolve."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "build_docs_site.py"), "--check"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_basics_clean():
    """The dependency-free correctness lint (unused imports, bare except,
    mutable defaults, ==None, placeholder-free f-strings) stays clean."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint_basics.py")],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
