"""Speculative decoding inside the continuous-batching engine.

The contract: a DecodeEngine built with ``draft_module`` emits tokens
IDENTICAL to plain greedy decoding of the target — for any draft —
while slots advance by variable per-round acceptance. (The
make_speculative_generator acceptance rule, restructured for the
resident slot batch; round-4 VERDICT item 3.)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine


@pytest.fixture(scope="module")
def pair():
    t_cfg = LlamaConfig.tiny(vocab_size=97)
    d_cfg = LlamaConfig.tiny(vocab_size=97, num_layers=1, hidden_dim=32,
                             num_heads=2, num_kv_heads=1, mlp_dim=64)
    target, draft = Llama(t_cfg), Llama(d_cfg)
    tp = target.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    dp = draft.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return target, draft, {"target": tp, "draft": dp}


def _solo(module, t_params, prompt, n_new, eos_id=None, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(
        module, max_new_tokens=n_new, max_len=max_len, eos_id=eos_id
    )
    return np.asarray(gen(t_params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def test_spec_engine_matches_plain_greedy(pair):
    target, draft, params = pair
    engine = DecodeEngine(
        target, draft_module=draft, speculate_k=3, slots=3,
        max_new_tokens=10, prompt_buckets=(8, 16), chunk_steps=2,
    )
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 13)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(target, params["target"], prompt, 10, max_len=engine.cache_len)
        stats = engine.stats()
        assert stats["speculative"]["rounds"] > 0
        assert 0.0 <= stats["speculative"]["acceptance_rate"] <= 1.0
    finally:
        engine.close()


def test_spec_engine_flash_prefill_matches_plain_greedy(pair):
    """prefill_impl="flash" on the TARGET (the spec engine's monolithic
    admissions are full prefills): tokens must still equal plain greedy
    decoding of the flash-config target. The draft keeps the cached
    prefill — the two models honor their own configs independently."""
    import dataclasses

    target, draft, params = pair
    ftarget = Llama(dataclasses.replace(target.config, prefill_impl="flash"))
    engine = DecodeEngine(
        ftarget, draft_module=draft, speculate_k=3, slots=3,
        max_new_tokens=10, prompt_buckets=(8, 16), chunk_steps=2,
    )
    try:
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 13)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(ftarget, params["target"], prompt, 10, max_len=engine.cache_len)
    finally:
        engine.close()


def test_spec_engine_self_speculation_full_acceptance(pair):
    """Draft == target: every proposal is accepted (the acceptance-rule
    sanity check — a bookkeeping bug shows up as rate < 1)."""
    target, _, params = pair
    engine = DecodeEngine(
        target, draft_module=target, speculate_k=3, slots=2,
        max_new_tokens=9, prompt_buckets=(8,), chunk_steps=2,
    )
    try:
        both = {"target": params["target"], "draft": params["target"]}
        out = engine.generate(both, [[7, 3, 9, 2]])[0]
        assert out == _solo(target, params["target"], [7, 3, 9, 2], 9, max_len=engine.cache_len)
        assert engine.stats()["speculative"]["acceptance_rate"] == 1.0
    finally:
        engine.close()


def test_spec_engine_mid_decode_join(pair):
    """A request joining while another slot is mid-speculation must not
    perturb either sequence (per-slot fills advance independently)."""
    import threading
    import time

    target, draft, params = pair
    engine = DecodeEngine(
        target, draft_module=draft, speculate_k=2, slots=2,
        max_new_tokens=20, prompt_buckets=(8,), chunk_steps=2,
        pipeline_depth=2,
    )
    try:
        engine.warmup(params)
        rng = np.random.default_rng(4)
        p1 = rng.integers(1, 97, 8).tolist()
        p2 = rng.integers(1, 97, 5).tolist()
        res = {}
        t = threading.Thread(
            target=lambda: res.update(a=engine.generate(params, [p1])[0])
        )
        t.start()
        time.sleep(0.15)
        res["b"] = engine.generate(params, [p2], max_new_tokens=8)[0]
        t.join(timeout=60)
        assert res["a"] == _solo(target, params["target"], p1, 20, max_len=engine.cache_len)
        assert res["b"] == _solo(target, params["target"], p2, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_spec_engine_eos_and_budget(pair):
    """eos inside a round truncates emission exactly like plain greedy
    (device n_emit truncation + host _req_done walk agree)."""
    target, draft, params = pair
    plain = _solo(target, params["target"], [5, 3, 9, 2], 12)
    eos = plain[3]   # force an eos hit mid-generation
    engine = DecodeEngine(
        target, draft_module=draft, speculate_k=3, slots=2,
        max_new_tokens=12, prompt_buckets=(8,), chunk_steps=2, eos_id=eos,
    )
    try:
        out = engine.generate(params, [[5, 3, 9, 2]])[0]
        # the engine truncates AT eos (the _req_done contract); the solo
        # generator's static shapes pad AFTER it — compare the prefix
        assert out == plain[: plain.index(eos) + 1]
        assert out[-1] == eos and eos not in out[:-1]
    finally:
        engine.close()


def test_spec_engine_chunked_prefill(pair):
    """Speculation composes with chunked admission: both caches fill
    chunk-by-chunk, then rounds run over the spliced slot."""
    target, draft, params = pair
    engine = DecodeEngine(
        target, draft_module=draft, speculate_k=2, slots=2,
        max_new_tokens=8, prompt_buckets=(8, 32), prefill_chunk=8,
        chunk_steps=2,
    )
    try:
        rng = np.random.default_rng(6)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (6, 20, 32)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(target, params["target"], prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_spec_engine_streaming(pair):
    target, draft, params = pair
    engine = DecodeEngine(
        target, draft_module=draft, speculate_k=2, slots=2,
        max_new_tokens=10, prompt_buckets=(8,), chunk_steps=2,
    )
    try:
        chunks = list(engine.generate_stream(params, [7, 3, 9, 2]))
        flat = [t for c in chunks for t in c]
        assert flat == _solo(target, params["target"], [7, 3, 9, 2], 10, max_len=engine.cache_len)
        assert len(chunks[0]) == 1   # prefill token = the TTFT event
    finally:
        engine.close()


def test_spec_engine_validation(pair):
    target, draft, params = pair
    with pytest.raises(ValueError, match="greedy-only"):
        DecodeEngine(target, draft_module=draft, temperature=0.7)
    with pytest.raises(ValueError, match="prefix KV-cache"):
        DecodeEngine(target, draft_module=draft, prefix_cache=True)
    with pytest.raises(ValueError, match="vocabularies differ"):
        DecodeEngine(
            target,
            draft_module=Llama(LlamaConfig.tiny(vocab_size=50)),
        )
    with pytest.raises(ValueError, match="speculate_k"):
        DecodeEngine(target, draft_module=draft, speculate_k=0)
    with pytest.raises(ValueError, match='"target"'):
        eng = DecodeEngine(target, draft_module=draft, prompt_buckets=(8,),
                           max_new_tokens=8, chunk_steps=2, pipeline_depth=1)
        try:
            eng.generate(params["target"], [[1, 2, 3]])
        finally:
            eng.close()
