"""Encoder-decoder family: cached scan decode must equal cache-free
full-prefix decoding, source padding must be invisible, the seq2seq step
must train, and TP sharding must hold generation bit-identical."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import (
    ENCDEC_PARTITION_RULES,
    EncDecConfig,
    EncoderDecoder,
    make_seq2seq_generator,
    seq2seq_step,
)


@pytest.fixture(scope="module")
def tiny_encdec():
    cfg = EncDecConfig.tiny(vocab_size=97)
    module = EncoderDecoder(cfg)
    src = jnp.zeros((1, 8), jnp.int32)
    tgt = jnp.zeros((1, 4), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), src, tgt)["params"]
    return module, params


def _full_prefix_greedy(module, params, src, n_new, bos=1):
    """Gold standard: re-run the cache-free decoder on the growing
    prefix each step."""
    mask = np.asarray(src) != 0
    toks = np.full((src.shape[0], 1), bos, np.int32)
    out = []
    for _ in range(n_new):
        logits = module.apply(
            {"params": params}, jnp.asarray(src), jnp.asarray(toks),
            src_mask=jnp.asarray(mask),
        )
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1).astype(jnp.int32))
        out.append(nxt)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_cached_scan_decode_matches_full_prefix(tiny_encdec):
    module, params = tiny_encdec
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(1, 97, size=(2, 10)), jnp.int32)
    gen = make_seq2seq_generator(module, max_new_tokens=6)
    got = np.asarray(gen(params, src, None, src != 0))
    want = _full_prefix_greedy(module, params, src, 6)
    np.testing.assert_array_equal(got, want)


def test_source_padding_is_invisible(tiny_encdec):
    """Right-padding the source (ids 0, masked) must not change the
    generated tokens."""
    module, params = tiny_encdec
    rng = np.random.default_rng(1)
    src = rng.integers(1, 97, size=(1, 7)).astype(np.int32)
    padded = np.zeros((1, 12), np.int32)
    padded[:, :7] = src
    gen = make_seq2seq_generator(module, max_new_tokens=5)
    out_a = np.asarray(gen(params, jnp.asarray(src), None, jnp.asarray(src != 0)))
    out_b = np.asarray(gen(params, jnp.asarray(padded), None, jnp.asarray(padded != 0)))
    np.testing.assert_array_equal(out_a, out_b)


def test_seq2seq_step_trains(tiny_encdec):
    """Teacher-forced training reduces the masked CE on a learnable
    copy-ish task (target = shifted source)."""
    cfg = EncDecConfig.tiny(vocab_size=64)
    module = EncoderDecoder(cfg)
    rng = np.random.default_rng(2)
    src = rng.integers(1, 64, size=(32, 10)).astype(np.int32)
    tgt = np.concatenate([np.full((32, 1), 1, np.int32), src[:, :6]], axis=1)
    params = module.init(
        jax.random.PRNGKey(3), jnp.asarray(src[:1]), jnp.asarray(tgt[:1])
    )["params"]
    from unionml_tpu.models.train import TrainState, adamw

    state = TrainState.create(apply_fn=module.apply, params=params, tx=adamw(5e-3))
    step = jax.jit(seq2seq_step(module), donate_argnums=0)
    batch = (jnp.asarray(src), jnp.asarray(tgt))
    state, first = step(state, batch)
    for _ in range(20):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])


def test_generation_under_tensor_parallel_sharding(tiny_encdec):
    """TP-sharded params generate bit-identically (GSPMD collectives)."""
    from unionml_tpu.parallel import ShardingConfig, shard_pytree

    module, params = tiny_encdec
    rng = np.random.default_rng(4)
    src = jnp.asarray(rng.integers(1, 97, size=(2, 8)), jnp.int32)
    gen = make_seq2seq_generator(module, max_new_tokens=4)
    ref = np.asarray(gen(params, src, None, src != 0))
    sharding = ShardingConfig(data=-1, tensor=2, rules=ENCDEC_PARTITION_RULES)
    tp = shard_pytree(params, sharding)
    specs = [str(tuple(l.sharding.spec)) for l in jax.tree_util.tree_leaves(tp)]
    assert any("tensor" in s for s in specs), specs
    got = np.asarray(gen(tp, src, None, src != 0))
    np.testing.assert_array_equal(got, ref)


def test_eos_freezes_and_cross_attention_guard(tiny_encdec):
    module, params = tiny_encdec
    src = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    first = int(_full_prefix_greedy(module, params, np.asarray(src), 1)[0, 0])
    gen = make_seq2seq_generator(module, max_new_tokens=5, eos_id=first, pad_id=0)
    out = np.asarray(gen(params, src, None, src != 0))[0]
    assert out[0] == first and (out[1:] == 0).all()

    from unionml_tpu.models.layers import Attention

    attn = Attention(num_heads=2, causal=True)
    x = jnp.zeros((1, 4, 16))
    with pytest.raises(ValueError, match="cross attention"):
        attn.init(jax.random.PRNGKey(0), x, kv=x)


def test_seq2seq_step_accumulation_and_pad_id():
    """accumulate_steps matches single-batch grads-wise (loss equality)
    and a custom pad_id controls source masking."""
    cfg = EncDecConfig.tiny(vocab_size=64)
    module = EncoderDecoder(cfg)
    rng = np.random.default_rng(5)
    src = rng.integers(2, 64, size=(16, 8)).astype(np.int32)
    tgt = np.concatenate([np.full((16, 1), 1, np.int32), src[:, :4]], axis=1)
    params = module.init(
        jax.random.PRNGKey(0), jnp.asarray(src[:1]), jnp.asarray(tgt[:1])
    )["params"]
    from unionml_tpu.models.train import TrainState, adamw

    def fresh():
        return TrainState.create(apply_fn=module.apply, params=params, tx=adamw(1e-3))

    _, m_base = jax.jit(seq2seq_step(module))(fresh(), (jnp.asarray(src), jnp.asarray(tgt)))
    micro = (jnp.asarray(src.reshape(2, 8, 8)), jnp.asarray(tgt.reshape(2, 8, 5)))
    _, m_acc = jax.jit(seq2seq_step(module, accumulate_steps=2))(fresh(), micro)
    np.testing.assert_allclose(
        float(m_base["loss"]), float(m_acc["loss"]), rtol=2e-3
    )

    # pad_id=63: ids equal to 63 become invisible; generation under the
    # matching mask is unchanged when those positions are appended
    gen = make_seq2seq_generator(module, max_new_tokens=4)
    src1 = jnp.asarray(rng.integers(2, 62, size=(1, 6)), jnp.int32)
    padded = jnp.concatenate([src1, jnp.full((1, 4), 63, jnp.int32)], axis=1)
    out_a = np.asarray(gen(params, src1, None, src1 != 63))
    out_b = np.asarray(gen(params, padded, None, padded != 63))
    np.testing.assert_array_equal(out_a, out_b)


def test_seq2seq_predictor_ragged_buckets_and_warmup(tiny_encdec):
    """Ragged sources bucket/pad transparently: per-row outputs equal
    unpadded single-source generation; warmup counts executables; eos
    trimming applies."""
    from unionml_tpu.models import make_seq2seq_predictor

    module, params = tiny_encdec

    class S:
        pass

    s = S()
    s.params = params
    pred = make_seq2seq_predictor(
        module, max_new_tokens=5, src_buckets=(8, 16)
    )
    sources = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10]]
    out = pred(s, sources)
    assert len(out) == 2 and all(len(r) == 5 for r in out)
    for src_row, got in zip(sources, out):
        want = _full_prefix_greedy(
            module, params, np.asarray([src_row], np.int32), 5
        )[0].tolist()
        assert got == want, (got, want)

    n = pred.warmup(s, max_batch=4)
    assert n == 2 * 3  # buckets {8,16} x batches {1,2,4}
    with pytest.raises(ValueError, match="not configured"):
        pred.warmup(s, max_batch=1, buckets=(64,))
    with pytest.raises(ValueError, match="empty bucket tuple"):
        pred.warmup(s, max_batch=1, buckets=())

    # eos trimming
    first = out[0][0]
    pred_eos = make_seq2seq_predictor(
        module, max_new_tokens=5, src_buckets=(8,), eos_id=first
    )
    trimmed = pred_eos(s, [sources[0]])[0]
    assert trimmed == [first]


def test_seq2seq_predictor_rejects_oversized_source(tiny_encdec):
    from unionml_tpu.models import make_seq2seq_predictor

    module, params = tiny_encdec

    class S:
        pass

    s = S()
    s.params = params
    pred = make_seq2seq_predictor(module, max_new_tokens=3, src_buckets=(8,))
    with pytest.raises(ValueError, match="exceeds the largest"):
        pred(s, [list(range(1, 12))])
