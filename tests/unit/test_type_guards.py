"""Table-driven signature-contract tests
(reference: tests/unit/test_type_guards.py, 407 LoC valid/invalid matrices)."""

from typing import Dict, List, Optional, Tuple, Union

import pandas as pd
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu import type_guards
from unionml_tpu.type_guards import SignatureError


class FakeModel:
    ...


# ---------------------------------------------------------------- reader

def test_guard_reader_valid():
    def reader() -> pd.DataFrame:
        ...

    type_guards.guard_reader(reader)


def test_guard_reader_invalid():
    def reader():
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_reader(reader)


# ---------------------------------------------------------------- loader

@pytest.mark.parametrize(
    "annotation, ok",
    [
        (pd.DataFrame, True),
        (str, False),
        (Union[pd.DataFrame, str], True),
    ],
)
def test_guard_loader(annotation, ok):
    def loader(data: annotation) -> pd.DataFrame:  # type: ignore[valid-type]
        ...

    loader.__annotations__["data"] = annotation
    if ok:
        type_guards.guard_loader(loader, pd.DataFrame)
    else:
        with pytest.raises(SignatureError):
            type_guards.guard_loader(loader, pd.DataFrame)


# ---------------------------------------------------------------- splitter

def test_guard_splitter_valid():
    def splitter(
        data: pd.DataFrame, test_size: float, shuffle: bool, random_state: int
    ) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    type_guards.guard_splitter(splitter, pd.DataFrame, "reader")


def test_guard_splitter_kwargs_via_var_keyword():
    def splitter(data: pd.DataFrame, **kwargs) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    type_guards.guard_splitter(splitter, pd.DataFrame, "reader")


def test_guard_splitter_missing_kwargs():
    def splitter(data: pd.DataFrame, test_size: float) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_splitter(splitter, pd.DataFrame, "reader")


def test_guard_splitter_wrong_data_type():
    def splitter(data: int, test_size: float, shuffle: bool, random_state: int):
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_splitter(splitter, pd.DataFrame, "reader")


# ---------------------------------------------------------------- parser

def test_guard_parser_valid():
    def parser(
        data: pd.DataFrame, features: Optional[List[str]], targets: List[str]
    ) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    type_guards.guard_parser(parser, pd.DataFrame, "reader")


def test_guard_parser_missing_kwargs():
    def parser(data: pd.DataFrame, features: Optional[List[str]]):
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_parser(parser, pd.DataFrame, "reader")


# ---------------------------------------------------------------- trainer

def test_guard_trainer_valid():
    def trainer(model: FakeModel, features: pd.DataFrame, target: pd.DataFrame) -> FakeModel:
        ...

    type_guards.guard_trainer(trainer, FakeModel, (pd.DataFrame, pd.DataFrame))


def test_guard_trainer_wrong_model_type():
    def trainer(model: int, features: pd.DataFrame) -> FakeModel:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_trainer(trainer, FakeModel, (pd.DataFrame,))


def test_guard_trainer_wrong_return():
    def trainer(model: FakeModel, features: pd.DataFrame) -> int:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_trainer(trainer, FakeModel, (pd.DataFrame,))


def test_guard_trainer_too_many_data_args():
    def trainer(model: FakeModel, a: pd.DataFrame, b: pd.DataFrame, c: pd.DataFrame) -> FakeModel:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_trainer(trainer, FakeModel, (pd.DataFrame, pd.DataFrame))


def test_guard_trainer_keyword_only_args_allowed():
    def trainer(
        model: FakeModel, features: pd.DataFrame, *, num_epochs: int = 3
    ) -> FakeModel:
        ...

    type_guards.guard_trainer(trainer, FakeModel, (pd.DataFrame,))


# ---------------------------------------------------------------- evaluator

def test_guard_evaluator_valid():
    def evaluator(model: FakeModel, features: pd.DataFrame, target: pd.DataFrame) -> float:
        ...

    type_guards.guard_evaluator(evaluator, FakeModel, (pd.DataFrame, pd.DataFrame))


def test_guard_evaluator_wrong_model():
    def evaluator(model: str, features: pd.DataFrame) -> float:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_evaluator(evaluator, FakeModel, (pd.DataFrame,))


# ---------------------------------------------------------------- predictor

def test_guard_predictor_valid():
    def predictor(model: FakeModel, features: pd.DataFrame) -> List[float]:
        ...

    type_guards.guard_predictor(predictor, FakeModel, pd.DataFrame)


def test_guard_predictor_with_unions():
    """Union-type acceptance (reference: test_type_guards.py:322)."""

    def predictor(model: FakeModel, features: Union[pd.DataFrame, List[Dict]]) -> List[float]:
        ...

    type_guards.guard_predictor(predictor, FakeModel, pd.DataFrame)


def test_guard_predictor_extra_args():
    def predictor(model: FakeModel, features: pd.DataFrame, other: int) -> List[float]:
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_predictor(predictor, FakeModel, pd.DataFrame)


def test_guard_predictor_no_return_annotation():
    def predictor(model: FakeModel, features: pd.DataFrame):
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_predictor(predictor, FakeModel, pd.DataFrame)


# ------------------------------------------------- feature loader/transformer

def test_guard_feature_loader():
    def feature_loader(raw) -> pd.DataFrame:
        ...

    type_guards.guard_feature_loader(feature_loader)

    def bad_loader(a, b):
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_feature_loader(bad_loader)


def test_guard_feature_transformer():
    def feature_transformer(features: pd.DataFrame) -> pd.DataFrame:
        ...

    type_guards.guard_feature_transformer(feature_transformer)

    def bad(a, b):
        ...

    with pytest.raises(SignatureError):
        type_guards.guard_feature_transformer(bad)


# --------------------------------------------------------------------- #
# train_step guard (TPU-native tier)
# --------------------------------------------------------------------- #

def test_guard_train_step_accepts_valid_signatures():
    from unionml_tpu.type_guards import guard_train_step

    guard_train_step(lambda state, batch: (state, {}))

    def with_defaults(state, batch, lr=0.1):
        return state, {}

    guard_train_step(with_defaults)

    def passthrough(*args):
        return args

    guard_train_step(passthrough)


def test_guard_train_step_rejects_bad_signatures():
    import pytest

    from unionml_tpu.type_guards import SignatureError, guard_train_step

    with pytest.raises(SignatureError, match="train_step"):
        guard_train_step(lambda state: (state, {}))
    with pytest.raises(SignatureError, match="train_step"):
        guard_train_step(lambda a, b, c: (a, {}))

    def kw_only(state, batch, *, lr):
        return state, {}

    # a required keyword-only arg would crash at the first trainer call
    with pytest.raises(SignatureError, match="train_step"):
        guard_train_step(kw_only)


def test_model_train_step_registration_guard():
    import pytest

    from unionml_tpu import Dataset, Model
    from unionml_tpu.type_guards import SignatureError

    dataset = Dataset(name="g")

    @dataset.reader
    def reader() -> dict:
        return {}

    model = Model(name="g", init=dict, dataset=dataset)
    with pytest.raises(SignatureError, match="train_step"):
        model.train_step(lambda onlystate: (onlystate, {}))
