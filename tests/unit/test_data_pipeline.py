"""Host→HBM data path (data/pipeline.py): sharded placement, prefetch
ordering, and the multi-host row-slicing contract. The true 2-process
assembly runs in tests/integration/test_multihost.py; here the
single-process semantics (process 0 owns every row) are pinned."""

import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu.data import (
    DeviceFeed,
    local_batches,
    prefetch_to_device,
    process_batch_slice,
)
from unionml_tpu.parallel import ShardingConfig


def test_prefetch_preserves_order_and_sharding():
    cfg = ShardingConfig(data=2, fsdp=4)
    batches = [
        (np.full((8, 4), i, np.float32), np.full((8,), i, np.float32))
        for i in range(5)
    ]
    out = list(prefetch_to_device(iter(batches), sharding=cfg))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert float(x[0, 0]) == i and float(y[0]) == i
        assert x.sharding.is_equivalent_to(cfg.batch_sharding(), x.ndim)


def test_device_feed_default_placement():
    feed = DeviceFeed()
    arr = feed.put(np.ones((4, 2), np.float32))
    assert isinstance(arr, jax.Array)


def test_process_batch_slice_single_process_owns_all():
    cfg = ShardingConfig(data=2, fsdp=4)
    assert process_batch_slice(cfg.batch_sharding(), 16) == slice(0, 16)


def test_process_batch_slice_rejects_row_starved_process():
    from jax.sharding import NamedSharding, PartitionSpec

    cfg = ShardingConfig(data=2, fsdp=4)
    # a replicated batch spec gives this process rows — fine; but a batch
    # smaller than the shard count starves nobody single-process. The
    # ownerless case needs multi-process, so assert the replicated case
    # degrades to the full range instead.
    sharding = NamedSharding(cfg.mesh(), PartitionSpec())
    assert process_batch_slice(sharding, 8) == slice(0, 8)


def test_local_batches_slices_global_batches():
    cfg = ShardingConfig(data=2, fsdp=4)
    batches = [
        (np.arange(16, dtype=np.float32), np.arange(16, dtype=np.float32) * 2)
        for _ in range(3)
    ]
    got = list(local_batches(iter(batches), cfg, 16))
    assert len(got) == 3
    # single process: the local slice IS the global batch
    np.testing.assert_array_equal(got[0][0], batches[0][0])
    np.testing.assert_array_equal(got[0][1], batches[0][1])


def test_local_batches_feed_roundtrip_matches_direct_put():
    """local_batches → DeviceFeed.put lands the same global values as a
    straight sharded device_put of the global batch."""
    cfg = ShardingConfig(data=2, fsdp=4)
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    direct = jax.device_put(x, cfg.batch_sharding())
    feed = DeviceFeed(sharding=cfg)
    via_local = feed.put(next(local_batches(iter([x]), cfg, 16)))
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(via_local))


def test_prefetch_keeps_buffer_in_flight():
    pulled = []

    def source():
        for i in range(4):
            pulled.append(i)
            yield np.full((4,), i, np.float32)

    it = prefetch_to_device(source(), buffer_size=2)
    first = next(it)
    # buffer_size batches were eagerly pulled before the first yield —
    # batch 1's device transfer was already in flight while the consumer
    # processes batch 0 (the refill lands at the next pull)
    assert pulled == [0, 1]
    assert float(first[0]) == 0
    second = next(it)
    assert pulled == [0, 1, 2]
    assert [int(b[0]) for b in [second] + list(it)] == [1, 2, 3]


def test_batch_pytree_placement():
    cfg = ShardingConfig(data=-1)
    feed = DeviceFeed(sharding=cfg)
    batch = {"x": np.ones((8, 3), np.float32), "y": np.zeros((8,), np.int32)}
    placed = feed.put(batch)
    assert set(placed) == {"x", "y"}
    assert placed["x"].sharding.is_equivalent_to(cfg.batch_sharding(), 2)
    assert jnp.issubdtype(placed["y"].dtype, jnp.integer)
