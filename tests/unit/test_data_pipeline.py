"""Host→HBM data path (data/pipeline.py): sharded placement, prefetch
ordering, and the multi-host row-slicing contract. The true 2-process
assembly runs in tests/integration/test_multihost.py; here the
single-process semantics (process 0 owns every row) are pinned."""

import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

import time

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu.data import (
    DeviceFeed,
    local_batches,
    prefetch_to_device,
    process_batch_slice,
)
from unionml_tpu.parallel import ShardingConfig


def test_prefetch_preserves_order_and_sharding():
    cfg = ShardingConfig(data=2, fsdp=4)
    batches = [
        (np.full((8, 4), i, np.float32), np.full((8,), i, np.float32))
        for i in range(5)
    ]
    out = list(prefetch_to_device(iter(batches), sharding=cfg))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert float(x[0, 0]) == i and float(y[0]) == i
        assert x.sharding.is_equivalent_to(cfg.batch_sharding(), x.ndim)


def test_device_feed_default_placement():
    feed = DeviceFeed()
    arr = feed.put(np.ones((4, 2), np.float32))
    assert isinstance(arr, jax.Array)


def test_process_batch_slice_single_process_owns_all():
    cfg = ShardingConfig(data=2, fsdp=4)
    assert process_batch_slice(cfg.batch_sharding(), 16) == slice(0, 16)


def test_process_batch_slice_rejects_row_starved_process():
    from jax.sharding import NamedSharding, PartitionSpec

    cfg = ShardingConfig(data=2, fsdp=4)
    # a replicated batch spec gives this process rows — fine; but a batch
    # smaller than the shard count starves nobody single-process. The
    # ownerless case needs multi-process, so assert the replicated case
    # degrades to the full range instead.
    sharding = NamedSharding(cfg.mesh(), PartitionSpec())
    assert process_batch_slice(sharding, 8) == slice(0, 8)


def test_local_batches_slices_global_batches():
    cfg = ShardingConfig(data=2, fsdp=4)
    batches = [
        (np.arange(16, dtype=np.float32), np.arange(16, dtype=np.float32) * 2)
        for _ in range(3)
    ]
    got = list(local_batches(iter(batches), cfg, 16))
    assert len(got) == 3
    # single process: the local slice IS the global batch
    np.testing.assert_array_equal(got[0][0], batches[0][0])
    np.testing.assert_array_equal(got[0][1], batches[0][1])


def test_local_batches_feed_roundtrip_matches_direct_put():
    """local_batches → DeviceFeed.put lands the same global values as a
    straight sharded device_put of the global batch."""
    cfg = ShardingConfig(data=2, fsdp=4)
    x = np.arange(32, dtype=np.float32).reshape(16, 2)
    direct = jax.device_put(x, cfg.batch_sharding())
    feed = DeviceFeed(sharding=cfg)
    via_local = feed.put(next(local_batches(iter([x]), cfg, 16)))
    np.testing.assert_array_equal(np.asarray(direct), np.asarray(via_local))


def test_prefetch_keeps_buffer_in_flight():
    pulled = []

    def source():
        for i in range(4):
            pulled.append(i)
            yield np.full((4,), i, np.float32)

    it = prefetch_to_device(source(), buffer_size=2)
    first = next(it)
    # buffer_size batches were eagerly pulled before the first yield —
    # batch 1's device transfer was already in flight while the consumer
    # processes batch 0 (the refill lands at the next pull)
    assert pulled == [0, 1]
    assert float(first[0]) == 0
    second = next(it)
    assert pulled == [0, 1, 2]
    assert [int(b[0]) for b in [second] + list(it)] == [1, 2, 3]


def test_double_buffer_parity_and_order():
    """Threaded feed yields the same batches in the same order as the
    inline mode, with identical sharded placement (docs/performance.md
    "Overlapped training")."""
    cfg = ShardingConfig(data=2, fsdp=4)
    batches = [
        (np.full((8, 4), i, np.float32), np.full((8,), i, np.float32))
        for i in range(7)
    ]
    out = list(
        prefetch_to_device(iter(batches), sharding=cfg, double_buffer=True)
    )
    assert len(out) == 7
    for i, (x, y) in enumerate(out):
        assert float(x[0, 0]) == i and float(y[0]) == i
        assert x.sharding.is_equivalent_to(cfg.batch_sharding(), x.ndim)


def test_double_buffer_source_error_propagates():
    def bad_source():
        yield np.ones((4,), np.float32)
        raise RuntimeError("loader died")

    it = prefetch_to_device(bad_source(), double_buffer=True)
    assert float(next(it)[0]) == 1.0
    with pytest.raises(RuntimeError, match="loader died"):
        list(it)


def test_double_buffer_abandoned_consumer_stops_feeder():
    """Closing the generator mid-stream must unblock and stop the
    feeder thread — an abandoned feed cannot pin device buffers (or a
    blocked thread) until process exit."""
    import threading

    # compare thread OBJECTS, not names: a leaked feeder from an earlier
    # test would otherwise make the assertion vacuously pass
    before = set(threading.enumerate())

    def source():
        for i in range(100):
            yield np.full((4,), i, np.float32)

    it = prefetch_to_device(source(), buffer_size=2, double_buffer=True)
    assert float(next(it)[0]) == 0.0
    it.close()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        alive = {
            t for t in set(threading.enumerate()) - before
            if t.name == "prefetch-feed" and t.is_alive()
        }
        if not alive:
            break
        time.sleep(0.01)
    assert not alive, "prefetch feeder thread still alive after close()"


def test_double_buffer_goodput_drains_dispatch_bucket():
    """In threaded mode the device-put dispatch leaves the critical
    path: host_to_device records nothing, and data_wait sees only true
    starvation (the consumer actually waiting on the feeder)."""

    class _Phases:
        def __init__(self):
            self.names = []

        def phase(self, name):
            import contextlib

            self.names.append(name)
            return contextlib.nullcontext()

    tracker = _Phases()
    batches = [np.full((4,), i, np.float32) for i in range(5)]
    out = list(
        prefetch_to_device(iter(batches), goodput=tracker, double_buffer=True)
    )
    assert len(out) == 5
    assert set(tracker.names) == {"data_wait"}  # no host_to_device phases


def test_double_buffer_trainer_donation_parity():
    """run_step_trainer(double_buffer=True) — which donates the fed
    batch buffers to the step — reaches the bitwise final state of the
    plain run: every donated buffer was fresh, none reused stale."""
    import jax as _jax
    from flax import linen as nn

    from unionml_tpu.execution import run_step_trainer
    from unionml_tpu.models.train import classification_step, create_train_state

    class _Mlp(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

    module = _Mlp()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(128,)).astype(np.int32)

    def run(**kw):
        return run_step_trainer(
            step_fn=classification_step(module),
            state=create_train_state(module, x[:4], learning_rate=1e-2, seed=1),
            features=x, targets=y, batch_size=32, num_epochs=2, seed=9, **kw
        )

    base = run()
    dbuf = run(double_buffer=True)  # donate_batch defaults on
    for a, b in zip(
        _jax.tree_util.tree_leaves(base.params),
        _jax.tree_util.tree_leaves(dbuf.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_batch_pytree_placement():
    cfg = ShardingConfig(data=-1)
    feed = DeviceFeed(sharding=cfg)
    batch = {"x": np.ones((8, 3), np.float32), "y": np.zeros((8,), np.int32)}
    placed = feed.put(batch)
    assert set(placed) == {"x", "y"}
    assert placed["x"].sharding.is_equivalent_to(cfg.batch_sharding(), 2)
    assert jnp.issubdtype(placed["y"].dtype, jnp.integer)
