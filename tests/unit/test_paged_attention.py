"""Paged-attention op tests: reference parity + Pallas kernel numerics.

The reference path must be BIT-identical to the contiguous cached
attention on the same rows (that is the engine's paged-vs-contiguous
parity anchor); the Pallas kernel matches the reference within float
reduction order (the flash-kernel numerics contract), in interpreter
mode on CPU.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from unionml_tpu.ops.attention import cached_attention, quantized_cache_attention
from unionml_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
)

B, H, KVH, D, BS, W, N = 3, 4, 2, 16, 8, 4, 12


def _setup(dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, H, D)), dtype)
    k = jnp.asarray(rng.standard_normal((N, BS, KVH, D)), dtype)
    v = jnp.asarray(rng.standard_normal((N, BS, KVH, D)), dtype)
    table = jnp.asarray(rng.integers(1, N, (B, W)), jnp.int32)
    lengths = jnp.asarray([1, 13, W * BS], jnp.int32)
    return q, k, v, table, lengths


def _contiguous(pool, table):
    return jnp.take(pool, table.reshape(-1), axis=0).reshape(
        (B, W * BS) + pool.shape[2:]
    )


def _bias(lengths):
    kv_pos = jnp.arange(W * BS)[None, :]
    visible = kv_pos[None] <= (lengths - 1)[:, None, None]
    return jnp.where(visible, 0.0, -1e30)[:, None]


def test_reference_bit_identical_to_contiguous():
    q, k, v, table, lengths = _setup()
    ref = paged_attention_reference(q, k, v, table, lengths)
    contig = cached_attention(
        q[:, None], _contiguous(k, table), _contiguous(v, table),
        bias=_bias(lengths),
    )[:, 0]
    assert bool(jnp.all(ref == contig))


def test_reference_bit_identical_int8():
    rng = np.random.default_rng(1)
    q, _, _, table, lengths = _setup(seed=1)
    kq = jnp.asarray(rng.integers(-127, 128, (N, BS, KVH, D)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (N, BS, KVH, D)), jnp.int8)
    ks = jnp.asarray(rng.random((N, BS, KVH)) * 0.02 + 1e-3, jnp.float32)
    vs = jnp.asarray(rng.random((N, BS, KVH)) * 0.02 + 1e-3, jnp.float32)
    ref = paged_attention_reference(
        q, kq, vq, table, lengths, k_scale=ks, v_scale=vs
    )
    contig = quantized_cache_attention(
        q[:, None], _contiguous(kq, table), _contiguous(vq, table),
        _contiguous(ks, table), _contiguous(vs, table), bias=_bias(lengths),
    )[:, 0]
    assert bool(jnp.all(ref == contig))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_pallas_matches_reference(dtype):
    q, k, v, table, lengths = _setup(dtype=dtype)
    ref = paged_attention(q, k, v, table, lengths, impl="reference")
    pal = paged_attention(q, k, v, table, lengths, impl="pallas")
    tol = 1e-6 if dtype == jnp.float32 else 2e-2
    assert float(
        jnp.max(jnp.abs(pal.astype(jnp.float32) - ref.astype(jnp.float32)))
    ) < tol


def test_pallas_matches_reference_int8():
    rng = np.random.default_rng(2)
    q, _, _, table, lengths = _setup(seed=2)
    kq = jnp.asarray(rng.integers(-127, 128, (N, BS, KVH, D)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (N, BS, KVH, D)), jnp.int8)
    ks = jnp.asarray(rng.random((N, BS, KVH)) * 0.02 + 1e-3, jnp.float32)
    vs = jnp.asarray(rng.random((N, BS, KVH)) * 0.02 + 1e-3, jnp.float32)
    ref = paged_attention(
        q, kq, vq, table, lengths, k_scale=ks, v_scale=vs, impl="reference"
    )
    pal = paged_attention(
        q, kq, vq, table, lengths, k_scale=ks, v_scale=vs, impl="pallas"
    )
    assert float(jnp.max(jnp.abs(pal - ref))) < 1e-5


def test_zero_length_rows_are_finite():
    """Dead slots decode with length 0 (everything masked): the output
    is garbage by contract but must be FINITE — NaN would poison the
    residual stream of live slots through layer norms."""
    q, k, v, table, _ = _setup()
    lengths = jnp.zeros((B,), jnp.int32)
    for impl in ("reference", "pallas"):
        out = paged_attention(q, k, v, table, lengths, impl=impl)
        assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


def test_gqa_groups_share_kv_head():
    """A pool whose two kv heads hold identical rows must produce
    identical outputs across the full q-head width (group mapping)."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(
        np.tile(rng.standard_normal((B, 1, D)), (1, H, 1)), jnp.float32
    )
    one = rng.standard_normal((N, BS, 1, D))
    k = jnp.asarray(np.tile(one, (1, 1, KVH, 1)), jnp.float32)
    v = jnp.asarray(np.tile(one, (1, 1, KVH, 1)), jnp.float32)
    table = jnp.asarray(rng.integers(1, N, (B, W)), jnp.int32)
    lengths = jnp.asarray([5, 17, 30], jnp.int32)
    for impl in ("reference", "pallas"):
        out = paged_attention(q, k, v, table, lengths, impl=impl)
        spread = jnp.max(jnp.abs(out - out[:, :1]))
        assert float(spread) < 1e-5


def test_shape_validation():
    q, k, v, table, lengths = _setup()
    with pytest.raises(ValueError):
        paged_attention(q[0], k, v, table, lengths)  # q rank
    with pytest.raises(ValueError):
        paged_attention(q, k, v, table[:1], lengths)  # batch mismatch
    with pytest.raises(ValueError):
        paged_attention(q, k, v, table, lengths[:1])  # lengths shape
    with pytest.raises(ValueError):
        paged_attention(
            q, k, v, table, lengths, k_scale=jnp.ones((N, BS, KVH))
        )  # k_scale without v_scale
    with pytest.raises(ValueError):
        paged_attention(q, k, v, table, lengths, impl="nope")
