"""Automatic prefix KV-cache tests.

Two layers under test:

- :class:`unionml_tpu.serving.prefix_cache.RadixPrefixCache` alone — a pure
  host structure (radix tree + byte-budgeted LRU store), exercised with
  fabricated KV trees: match/insert, eviction order, pinned and leased
  survival, and concurrent lookup/insert safety.
- the :class:`~unionml_tpu.serving.engine.DecodeEngine` integration —
  the contract that matters: cold, warm-hit, and partial-hit
  generations are TOKEN-IDENTICAL to the cache-off engine / solo
  generator, a warm admission skips the shared prefix's prefill
  programs (asserted via the ``prefill_tokens_saved`` counter and the
  trace's prefill-span shape), and ``system_prefix`` rides the cache as
  a pinned entry.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.prefix_cache import RadixPrefixCache, tree_nbytes


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _block_tree(block=4, fill=0.0):
    """A fabricated one-layer KV tree shaped like the engine's
    ``[1, block, heads, dim]`` cache rows."""
    k = np.full((1, block, 2, 4), fill, np.float32)
    return ((k, k + 1.0),)


_BLOCK_BYTES = tree_nbytes(_block_tree())


# --------------------------------------------------------------------- #
# host-level store semantics
# --------------------------------------------------------------------- #


@pytest.mark.quick
def test_prefix_cache_match_insert_roundtrip():
    cache = RadixPrefixCache(block_size=4, max_bytes=1 << 20,
                        registry=telemetry.MetricsRegistry())
    toks = np.arange(1, 13, dtype=np.int32)          # 3 full blocks
    miss = cache.match(toks)
    assert miss.n_blocks == 0 and miss.rows == []
    miss.release()
    cache.insert(toks, 0, [_block_tree(fill=float(i)) for i in range(3)])
    assert cache.entries == 3
    assert cache.bytes == 3 * _BLOCK_BYTES

    hit = cache.match(toks)
    assert hit.n_blocks == 3 and hit.n_tokens == 12
    # rows come back in prompt order, by identity of content
    assert hit.rows[1][0][0][0, 0, 0, 0] == 1.0
    hit.release()

    # a diverging prompt shares only the leading blocks
    part = cache.match(np.concatenate([toks[:8], [90, 91, 92, 93]]))
    assert part.n_blocks == 2
    part.release()
    # sub-block tails never match (keys are whole blocks)
    short = cache.match(toks[:7])
    assert short.n_blocks == 1
    short.release()

    s = cache.stats()
    # miss, full hit, diverging partial, and the 7-token lookup (its one
    # cacheable block matched → a full hit at block granularity)
    assert s["hits"] == 2 and s["misses"] == 1
    assert s["partial_hits"] == 1 and s["hit_rate"] == pytest.approx(0.75)


@pytest.mark.quick
def test_prefix_cache_peek_is_readonly():
    """peek() (the fleet router's affinity probe) reports the cached
    prefix length in tokens WITHOUT leasing, LRU-bumping, or counting
    a hit/miss — N router probes per request must not distort the
    cache telemetry or pin paths."""
    cache = RadixPrefixCache(block_size=4, max_bytes=1 << 20,
                        registry=telemetry.MetricsRegistry())
    toks = np.arange(1, 13, dtype=np.int32)          # 3 full blocks
    assert cache.peek(toks) == 0
    cache.insert(toks, 0, [_block_tree(fill=float(i)) for i in range(3)])
    assert cache.peek(toks) == 12
    assert cache.peek(toks[:7]) == 4                  # block granularity
    assert cache.peek(np.concatenate([toks[:8], [90, 91, 92, 93]])) == 8
    assert cache.peek([50, 51]) == 0                  # sub-block prompt
    s = cache.stats()
    # no peek landed in the hit/miss counters, and nothing is leased:
    # full-pressure eviction can still reclaim every block
    assert s["hits"] == 0 and s["misses"] == 0 and s["partial_hits"] == 0
    for node in list(cache._root.children.values()):
        assert node.refcount == 0


@pytest.mark.quick
def test_prefix_cache_insert_requires_ancestors():
    """Blocks whose prefix path is missing are dropped — a child's rows
    are meaningless without the blocks above them."""
    cache = RadixPrefixCache(block_size=4, max_bytes=1 << 20,
                        registry=telemetry.MetricsRegistry())
    toks = np.arange(1, 13, dtype=np.int32)
    attached = cache.insert(toks, 2, [_block_tree()])  # parents absent
    assert attached == 0 and cache.entries == 0
    cache.insert(toks, 0, [_block_tree(), _block_tree()])
    assert cache.insert(toks, 2, [_block_tree()]) == 1
    assert cache.entries == 3


@pytest.mark.quick
def test_prefix_cache_lru_eviction_under_byte_budget():
    """Over-budget inserts evict least-recently-used LEAF blocks first;
    the store never exceeds max_bytes."""
    cache = RadixPrefixCache(block_size=4, max_bytes=3 * _BLOCK_BYTES,
                        registry=telemetry.MetricsRegistry())
    a = np.arange(1, 9, dtype=np.int32)       # 2 blocks
    b = np.arange(50, 58, dtype=np.int32)     # 2 blocks, distinct subtree
    cache.insert(a, 0, [_block_tree(), _block_tree()])
    cache.match(a).release()                  # refresh a's recency
    cache.insert(b, 0, [_block_tree(), _block_tree()])
    assert cache.bytes <= 3 * _BLOCK_BYTES
    assert cache.entries == 3
    # a's LEAF (block 2) was the LRU victim; its root block survives
    assert cache.match(a).n_blocks >= 1
    got_b = cache.match(b)
    assert got_b.n_blocks == 2               # the fresh insert is intact
    got_b.release()
    assert cache.stats()["evictions"] == 1


@pytest.mark.quick
def test_prefix_cache_insert_never_evicts_own_chain():
    """Regression: a mid-insert eviction pass must not pick a block of
    the chain being inserted as its LRU victim — that detached the
    chain while its bytes stayed charged (a permanent budget leak).
    The in-progress path is refcount-protected, so an over-budget tail
    is REJECTED instead."""
    cache = RadixPrefixCache(block_size=4, max_bytes=2 * _BLOCK_BYTES + 1,
                             registry=telemetry.MetricsRegistry())
    toks = np.arange(1, 13, dtype=np.int32)           # a 3-block chain
    attached = cache.insert(toks, 0, [_block_tree(fill=float(i))
                                      for i in range(3)])
    assert attached == 2                              # tail rejected, not
    assert cache.entries == 2                         # a sibling evicted
    assert cache.bytes == 2 * _BLOCK_BYTES
    lease = cache.match(toks)                         # chain reachable and
    assert lease.n_blocks == 2                        # consistent
    assert lease.rows[1][0][0][0, 0, 0, 0] == 1.0
    lease.release()
    s = cache.stats()
    assert s["evictions"] == 0
    # and the budget still works once the insert is over: new unrelated
    # inserts evict the (now unprotected) LRU chain normally
    other = np.arange(50, 58, dtype=np.int32)
    cache.insert(other, 0, [_block_tree(), _block_tree()])
    assert cache.bytes <= 2 * _BLOCK_BYTES + 1


@pytest.mark.quick
def test_prefix_cache_pinned_blocks_survive_pressure():
    """pin() marks a token path never-evictable — present and future
    blocks — while unpinned neighbours churn."""
    cache = RadixPrefixCache(block_size=4, max_bytes=2 * _BLOCK_BYTES,
                        registry=telemetry.MetricsRegistry())
    pinned = np.arange(1, 9, dtype=np.int32)
    cache.pin(pinned)
    cache.insert(pinned, 0, [_block_tree(), _block_tree()])  # pinned at attach
    for i in range(5):
        other = np.arange(100 + 10 * i, 104 + 10 * i, dtype=np.int32)
        cache.insert(other, 0, [_block_tree()])
    surv = cache.match(pinned)
    assert surv.n_blocks == 2, "pinned blocks were evicted"
    surv.release()
    assert cache.bytes <= 2 * _BLOCK_BYTES + _BLOCK_BYTES  # churn bounded


@pytest.mark.quick
def test_prefix_cache_lease_blocks_eviction():
    """An un-released lease (an in-flight admission) pins its matched
    path against eviction; release makes it reclaimable again."""
    cache = RadixPrefixCache(block_size=4, max_bytes=1 * _BLOCK_BYTES,
                        registry=telemetry.MetricsRegistry())
    a = np.arange(1, 5, dtype=np.int32)
    cache.insert(a, 0, [_block_tree()])
    lease = cache.match(a)
    assert lease.n_blocks == 1
    b = np.arange(50, 54, dtype=np.int32)
    cache.insert(b, 0, [_block_tree()])   # no room: a is leased
    assert cache.match(b).n_blocks == 0   # rejected, not forced in
    assert cache.stats()["insert_rejected_blocks"] == 1
    still = cache.match(a)
    assert still.n_blocks == 1
    still.release()
    lease.release()
    lease.release()                        # idempotent
    cache.insert(b, 0, [_block_tree()])   # now a is evictable
    got = cache.match(b)
    assert got.n_blocks == 1
    got.release()


@pytest.mark.quick
def test_prefix_cache_concurrent_lookup_insert():
    """Hammer match/insert/release from many threads: no exceptions, no
    budget violation, and the tree stays internally consistent."""
    cache = RadixPrefixCache(block_size=4, max_bytes=20 * _BLOCK_BYTES,
                        registry=telemetry.MetricsRegistry())
    rng = np.random.default_rng(0)
    seqs = [
        np.concatenate([np.arange(1, 9), rng.integers(10, 90, 8)]).astype(np.int32)
        for _ in range(8)
    ]
    errors = []

    def worker(seed):
        r = np.random.default_rng(seed)
        try:
            for _ in range(60):
                toks = seqs[r.integers(len(seqs))]
                lease = cache.match(toks)
                nb = len(toks) // 4
                if lease.n_blocks < nb:
                    cache.insert(
                        toks, lease.n_blocks,
                        [_block_tree(fill=float(j))
                         for j in range(lease.n_blocks, nb)],
                    )
                lease.release()
        except BaseException as exc:  # noqa: BLE001 - surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert cache.bytes <= 20 * _BLOCK_BYTES
    lease = cache.match(seqs[0])
    assert 0 <= lease.n_blocks <= 4
    lease.release()


@pytest.mark.quick
def test_prefix_cache_clear_keeps_pins():
    cache = RadixPrefixCache(block_size=4, max_bytes=1 << 20,
                        registry=telemetry.MetricsRegistry())
    toks = np.arange(1, 9, dtype=np.int32)
    cache.pin(toks)
    cache.insert(toks, 0, [_block_tree(), _block_tree()])
    cache.clear()
    assert cache.entries == 0 and cache.bytes == 0
    cache.insert(toks, 0, [_block_tree(), _block_tree()])
    # re-inserted blocks re-pin: pressure cannot evict them
    cache.max_bytes = 2 * _BLOCK_BYTES
    cache.insert(np.arange(60, 64, dtype=np.int32), 0, [_block_tree()])
    lease = cache.match(toks)
    assert lease.n_blocks == 2
    lease.release()


# --------------------------------------------------------------------- #
# engine integration: token identity + reuse accounting
# --------------------------------------------------------------------- #


@pytest.mark.quick
def test_engine_prefix_cache_token_parity_and_savings(tiny_llama):
    """THE acceptance contract: cold, full-hit, and partial-hit prompts
    produce tokens bit-identical to the cache-off engine, while the
    warm admissions skip the shared prefix's prefill work (tokens-saved
    counter; the warm request's trace prefills only the suffix)."""
    module, params = tiny_llama
    rng = np.random.default_rng(7)
    shared = rng.integers(1, 97, 24).tolist()
    cold = shared + rng.integers(1, 97, 4).tolist()
    partial = shared + rng.integers(1, 97, 7).tolist()

    plain = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(32,), chunk_steps=3,
        registry=telemetry.MetricsRegistry(),
    )
    try:
        want_cold = plain.generate(params, [cold])[0]
        want_partial = plain.generate(params, [partial])[0]
    finally:
        plain.close()
    assert want_cold == _solo(module, params, cold, 6, max_len=plain.cache_len)

    registry = telemetry.MetricsRegistry()
    tracer = telemetry.TraceRecorder()
    cache = RadixPrefixCache(block_size=8, max_bytes=32 << 20, registry=registry)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(32,), chunk_steps=3,
        prefix_cache=cache, registry=registry, tracer=tracer,
    )
    try:
        assert engine.generate(params, [cold])[0] == want_cold       # miss
        assert engine.generate(params, [cold])[0] == want_cold       # full hit
        assert engine.generate(params, [partial])[0] == want_partial  # partial
        s = engine.stats()["prefix_cache"]
        assert s["misses"] == 1
        assert s["hits"] + s["partial_hits"] == 2
        # warm hit reuses 24 shared tokens (3 blocks); the partial hit
        # at least the same 3 blocks again
        assert s["prefill_tokens_saved"] >= 48
        saved = registry.counter(
            "unionml_prefix_cache_prefill_tokens_saved_total", "", ("cache",)
        ).labels(cache=cache.instance).value
        assert saved == s["prefill_tokens_saved"]
        # trace shape: the warm requests spliced cached blocks instead
        # of running prefill programs over them, and each request still
        # has exactly ONE terminal prefill span (the sampled token 0)
        spans = [
            line for line in tracer.export_jsonl().splitlines() if line
        ]
        import json

        names = [json.loads(line)["name"] for line in spans]
        assert names.count("prefill") == 3
        assert any(n.startswith("prefix-splice[") for n in names)
        prefill_tokens = [
            json.loads(line)["tokens"] for line in spans
            if json.loads(line)["name"] == "prefill"
        ]
        # cold admission prefilled all 28 tokens; warm ones only their
        # uncovered suffixes (4 and 7+24-24 tokens past the 3 blocks)
        assert max(prefill_tokens) == len(cold)
        assert sorted(prefill_tokens)[:2] == [len(cold) - 24, len(partial) - 24]
    finally:
        engine.close()


@pytest.mark.quick
def test_engine_prefix_cache_composes_with_chunked_prefill(tiny_llama):
    """A long-bucket admission with a cache hit still interleaves: the
    suffix runs block-granularity chunks through the same machinery,
    and outputs stay solo-identical."""
    module, params = tiny_llama
    cache = RadixPrefixCache(block_size=8, max_bytes=32 << 20,
                        registry=telemetry.MetricsRegistry())
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(16, 64),
        prefill_chunk=16, chunk_steps=3, prefix_cache=cache,
        registry=telemetry.MetricsRegistry(),
    )
    try:
        rng = np.random.default_rng(11)
        shared = rng.integers(1, 97, 32).tolist()
        prompts = [
            shared + rng.integers(1, 97, n).tolist() for n in (5, 17, 30)
        ]
        for p in prompts:
            assert engine.generate(params, [p])[0] == _solo(
                module, params, p, 6, max_len=engine.cache_len
            )
        # the 2nd and 3rd shared the 32-token (4-block) prefix
        assert engine.stats()["prefix_cache"]["prefill_tokens_saved"] >= 64
    finally:
        engine.close()


@pytest.mark.quick
def test_engine_prefix_cache_with_kv_quant(tiny_llama):
    """Cached blocks carry the int8 KV layout (quantized rows + scale
    planes) through extract → host store → splice unchanged."""
    import dataclasses

    module, params = tiny_llama
    qmodule = Llama(dataclasses.replace(module.config, kv_quant=True))
    cache = RadixPrefixCache(block_size=8, max_bytes=32 << 20,
                        registry=telemetry.MetricsRegistry())
    engine = DecodeEngine(
        qmodule, slots=2, max_new_tokens=6, prompt_buckets=(32,),
        chunk_steps=3, prefix_cache=cache,
        registry=telemetry.MetricsRegistry(),
    )
    try:
        rng = np.random.default_rng(13)
        shared = rng.integers(1, 97, 16).tolist()
        p1 = shared + rng.integers(1, 97, 5).tolist()
        p2 = shared + rng.integers(1, 97, 9).tolist()
        for p in (p1, p1, p2):
            assert engine.generate(params, [p])[0] == _solo(
                qmodule, params, p, 6, max_len=engine.cache_len
            )
        assert engine.stats()["prefix_cache"]["prefill_tokens_saved"] > 0
    finally:
        engine.close()


@pytest.mark.quick
def test_engine_system_prefix_rides_cache_pinned(tiny_llama):
    """The back-compat shim: system_prefix tokens are prepended and
    their blocks pinned — the second admission on reuses them instead
    of re-prefilling, and outputs equal the prefixed solo run."""
    module, params = tiny_llama
    rng = np.random.default_rng(17)
    prefix = rng.integers(1, 97, 16).tolist()  # block-aligned (16 = default)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8, 16),
        chunk_steps=3, system_prefix=prefix,
        registry=telemetry.MetricsRegistry(),
    )
    try:
        assert engine.prefix_cache is not None  # the shim auto-enables it
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 9)]
        for p in prompts:
            assert engine.generate(params, [p])[0] == _solo(
                module, params, prefix + p, 6, max_len=engine.cache_len
            )
        s = engine.stats()["prefix_cache"]
        # request 2 reused the pinned 16-token prefix block
        assert s["prefill_tokens_saved"] >= 16
        # pinned entries survive a pressure cap far below the store size
        engine.prefix_cache.max_bytes = 1
        engine.prefix_cache.insert(
            np.arange(200, 216, dtype=np.int32) % 97, 0, [_block_tree(16)]
        )
        lease = engine.prefix_cache.match(np.asarray(prefix, np.int32))
        assert lease.n_blocks == 1, "pinned system_prefix block evicted"
        lease.release()
    finally:
        engine.close()


@pytest.mark.quick
def test_spec_engine_accepts_system_prefix(tiny_llama):
    """Satellite: the old hard ValueError is lifted — a speculative
    engine with system_prefix prepends it through both prefills and
    stays token-identical to the target's greedy prefixed solo run."""
    module, params = tiny_llama
    draft = module  # same module as its own draft: acceptance = 100%
    engine = DecodeEngine(
        module, draft_module=draft, speculate_k=2, slots=2,
        max_new_tokens=6, prompt_buckets=(16,), chunk_steps=2,
        system_prefix=[5, 9, 13],
        registry=telemetry.MetricsRegistry(),
    )
    try:
        prompt = [1, 2, 3, 4, 5]
        out = engine.generate(
            {"target": params, "draft": params}, [prompt]
        )[0]
        assert out == _solo(module, params, [5, 9, 13] + prompt, 6, max_len=engine.cache_len)
    finally:
        engine.close()


@pytest.mark.slow
def test_engine_prefix_cache_eviction_stress(tiny_llama):
    """Eviction under a byte budget far smaller than the working set:
    many distinct prompts churn the store; every output stays
    solo-identical, the budget is never exceeded, and leased blocks are
    never yanked from under an in-flight admission."""
    module, params = tiny_llama
    # start unbounded; the budget is tightened to ~4 real blocks once a
    # real block's byte size is known
    cache = RadixPrefixCache(block_size=8, max_bytes=1 << 30,
                        registry=telemetry.MetricsRegistry())
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(32,),
        chunk_steps=2, prefix_cache=cache,
        registry=telemetry.MetricsRegistry(),
    )
    try:
        rng = np.random.default_rng(23)
        # size the budget from a real block's bytes: insert once, read back
        engine.generate(params, [rng.integers(1, 97, 16).tolist()])
        real_block_bytes = cache.bytes // max(1, cache.entries)
        cache.max_bytes = 4 * real_block_bytes
        prompts = [rng.integers(1, 97, size=rng.integers(9, 33)).tolist()
                   for _ in range(24)]
        for p in prompts:
            assert engine.generate(params, [p])[0] == _solo(
                module, params, p, 4, max_len=engine.cache_len
            )
            assert cache.bytes <= cache.max_bytes
        s = engine.stats()["prefix_cache"]
        assert s["evictions"] > 0
        assert s["entries"] <= 4
    finally:
        engine.close()


# --------------------------------------------------------------------- #
# fleet warming: export_hot / import_blocks
# --------------------------------------------------------------------- #


@pytest.mark.quick
def test_export_hot_selects_hottest_with_ancestor_closure():
    """MRU paths export first; a budget too small for a deep path's
    ancestor closure falls back to a shallower hot node instead of
    shipping an orphaned child."""
    cache = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    deep = list(range(100, 112))           # 3 blocks
    shallow = list(range(200, 204))        # 1 block
    cache.insert(deep, 0, [_block_tree(fill=float(i)) for i in range(3)])
    cache.insert(shallow, 0, [_block_tree(fill=9.0)])
    cache.match(shallow).release()         # shallow is now the hottest

    entries = cache.export_hot(max_blocks=1)
    assert len(entries) == 1
    assert entries[0]["tokens"].tolist() == shallow
    assert entries[0]["first_block"] == 0

    # budget 2: the hottest DEEP node needs 3 blocks (closure) — it is
    # skipped whole; shallow + the deep path's first block fit
    cache.match(deep).release()            # deep path hottest again
    entries = cache.export_hot(max_blocks=2)
    assert len(entries) == 2
    exported = sorted(
        (e["tokens"].tolist()[:4], e["first_block"]) for e in entries
    )
    assert (deep[:4], 0) in exported or (shallow, 0) in exported
    # parent-before-child order within the export
    firsts = [e["first_block"] for e in entries]
    assert firsts == sorted(firsts)


@pytest.mark.quick
def test_export_import_roundtrip_warms_peer_and_keeps_counters_clean():
    """A donor export imported into a cold peer makes the peer's peek
    warm — and neither side's hit/miss telemetry moves (warming is
    bookkeeping, not serving traffic)."""
    donor = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    joiner = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    tokens = list(range(1, 13))
    donor.insert(tokens, 0, [_block_tree(fill=float(i)) for i in range(3)])
    hits_before = donor.stats()["hits"], donor.stats()["misses"]

    entries = donor.export_hot(max_blocks=8)
    attached = joiner.import_blocks(entries)
    assert attached == 3
    assert joiner.peek(tokens) == 12
    assert (donor.stats()["hits"], donor.stats()["misses"]) == hits_before
    assert joiner.stats()["hits"] == 0 and joiner.stats()["misses"] == 0
    # the imported bytes are the donor's bytes (block store unit shared)
    assert joiner.bytes == donor.bytes
    # leases released: every exported node is evictable again
    donor.clear()      # would deadlock/leak if refcounts were held
    assert donor.entries == 0

    # empty donors and empty budgets export nothing, import attaches 0
    assert donor.export_hot() == []
    assert joiner.export_hot(max_blocks=0) == []
    assert joiner.import_blocks([]) == 0


@pytest.mark.quick
def test_import_respects_byte_budget_of_importer():
    """An importer at its byte budget keeps its own LRU discipline:
    blocks that do not fit are rejected, never force-attached."""
    donor = RadixPrefixCache(block_size=4, registry=telemetry.MetricsRegistry())
    tokens = list(range(1, 17))
    donor.insert(tokens, 0, [_block_tree(fill=float(i)) for i in range(4)])
    tiny = RadixPrefixCache(
        block_size=4, max_bytes=2 * _BLOCK_BYTES,
        registry=telemetry.MetricsRegistry(),
    )
    attached = tiny.import_blocks(donor.export_hot(max_blocks=8))
    assert attached == 2                   # budget, not the export size
    assert tiny.bytes <= 2 * _BLOCK_BYTES
    assert tiny.peek(tokens) == 8
