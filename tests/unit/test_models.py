"""Model zoo tests: forward shapes, train-step convergence, KV-cache decode
equivalence, and sharded (8-device CPU mesh) training — the framework-matrix
role of the reference's sklearn/pytorch/keras parametrization
(reference: tests/integration/ app dirs; SURVEY.md §4.3(c))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.models import (
    BertClassifier,
    BertConfig,
    Llama,
    LlamaConfig,
    LLAMA_PARTITION_RULES,
    Mlp,
    MlpConfig,
    ViT,
    ViTConfig,
    VIT_PARTITION_RULES,
    classification_step,
    create_train_state,
    init_cache,
    lm_step,
    make_evaluator,
    make_predictor,
)
from unionml_tpu.parallel import ShardingConfig


def test_mlp_forward_and_training_converges():
    cfg = MlpConfig(num_classes=2, hidden_dims=(32,))
    module = Mlp(cfg)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    state = create_train_state(module, x[:2], learning_rate=1e-2)
    step = jax.jit(classification_step(module))
    for _ in range(100):
        state, metrics = step(state, (x, y))
    assert float(metrics["accuracy"]) > 0.9
    evaluator = make_evaluator(module)
    assert evaluator(state, x, y) > 0.9
    preds = make_predictor(module)(state, x)
    assert preds.shape == (64,)


def test_vit_tiny_forward_shape():
    cfg = ViTConfig.tiny(image_size=16, num_classes=3)
    module = ViT(cfg)
    x = jnp.zeros((2, 16, 16, 3))
    params = module.init(jax.random.PRNGKey(0), x)["params"]
    logits = module.apply({"params": params}, x)
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32


def test_vit_base16_config_matches_paper():
    cfg = ViTConfig.base16()
    assert (cfg.hidden_dim, cfg.num_layers, cfg.num_heads, cfg.mlp_dim) == (
        768, 12, 12, 3072,
    )


def test_bert_tiny_classifier_forward_with_mask():
    cfg = BertConfig.tiny(vocab_size=100, num_classes=4)
    module = BertClassifier(cfg)
    ids = jnp.ones((2, 10), jnp.int32)
    mask = jnp.array([[1] * 10, [1] * 5 + [0] * 5])
    params = module.init(jax.random.PRNGKey(0), ids, attention_mask=mask)["params"]
    logits = module.apply({"params": params}, ids, attention_mask=mask)
    assert logits.shape == (2, 4)
    # padding must not influence the [CLS] logits: same ids, padded vs not
    short = module.apply({"params": params}, ids[:, :5], attention_mask=mask[:, :5])
    np.testing.assert_allclose(logits[1], short[1], rtol=2e-2, atol=2e-2)


def test_llama_tiny_lm_step_reduces_loss():
    cfg = LlamaConfig.tiny(vocab_size=64)
    module = Llama(cfg)
    rng = np.random.default_rng(0)
    tokens = np.asarray(rng.integers(0, 64, size=(8, 16)), np.int32)
    state = create_train_state(module, jnp.asarray(tokens[:1]), learning_rate=1e-2)
    step = jax.jit(lm_step(module))
    losses = []
    for _ in range(30):
        state, metrics = step(state, jnp.asarray(tokens))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8


def test_llama_kv_cache_decode_matches_full_forward():
    """Cached token-by-token decode must equal the full-sequence forward."""
    cfg = LlamaConfig.tiny(vocab_size=32)
    module = Llama(cfg)
    tokens = jnp.asarray([[3, 7, 11, 2, 9, 17, 4, 1]], jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    full = module.apply({"params": params}, tokens)

    cache = init_cache(cfg, batch=1, max_len=16, dtype=jnp.float32)

    @jax.jit
    def decode(params, cache, tok, idx):
        return module.apply(
            {"params": params}, tok, cache=cache, cache_index=idx
        )

    outs = []
    for i in range(tokens.shape[1]):
        logits, cache = decode(params, cache, tokens[:, i : i + 1], jnp.int32(i))
        outs.append(logits[:, 0])
    stepwise = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(stepwise), rtol=2e-2, atol=2e-2)


def test_llama_prefill_with_cache_matches_full_forward():
    cfg = LlamaConfig.tiny(vocab_size=32)
    module = Llama(cfg)
    tokens = jnp.asarray([[5, 2, 9, 13]], jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    full = module.apply({"params": params}, tokens)
    cache = init_cache(cfg, batch=1, max_len=8, dtype=jnp.float32)
    prefill, cache = jax.jit(
        lambda p, c, t: module.apply({"params": p}, t, cache=c, cache_index=jnp.int32(0))
    )(params, cache, tokens)
    np.testing.assert_allclose(np.asarray(full), np.asarray(prefill), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize(
    "sharding",
    [
        ShardingConfig(data=-1),
        ShardingConfig(data=2, fsdp=2, tensor=2, rules=VIT_PARTITION_RULES),
    ],
    ids=["dp8", "dp2_fsdp2_tp2"],
)
def test_vit_sharded_train_step(sharding):
    """ViT train step under DP and 3D (dp×fsdp×tp) meshes on 8 CPU devices."""
    from unionml_tpu.parallel import compile_step

    cfg = ViTConfig.tiny(image_size=16, num_classes=4)
    module = ViT(cfg)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 16, 16, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, size=(16,)), jnp.int32)
    state = create_train_state(module, x[:2], learning_rate=1e-3)
    step, state = compile_step(classification_step(module), state, sharding=sharding)
    for _ in range(3):
        state, metrics = step(state, (x, y))
    assert np.isfinite(float(metrics["loss"]))


def test_llama_tp_sharded_lm_step():
    """Llama LM step with tensor-parallel param rules over tensor=4."""
    from unionml_tpu.parallel import compile_step

    cfg = LlamaConfig.tiny(vocab_size=64)
    module = Llama(cfg)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, size=(8, 16)), jnp.int32)
    sharding = ShardingConfig(data=-1, tensor=2, rules=LLAMA_PARTITION_RULES)
    state = create_train_state(module, tokens[:1], learning_rate=1e-3)
    step, state = compile_step(lm_step(module), state, sharding=sharding)
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))
    # params actually sharded over the tensor axis
    k = state.params["block_0"]["attn"]["q"]["kernel"]
    assert len(k.sharding.device_set) >= 2


def test_mlm_masking_rule_and_pretraining_step():
    """The BERT masking rule (15% selected; 80/10/10 mask/random/keep,
    specials untouched) produces lm_step-compatible batches, and an MLM
    pretraining step over BertMlm reduces the masked-CE loss."""
    from unionml_tpu.models import BertConfig, BertMlm, make_mlm_batch
    from unionml_tpu.models.train import create_train_state, lm_step

    rng = np.random.default_rng(0)
    vocab, mask_id = 1024, 103
    tokens = rng.integers(4, vocab, size=(64, 32))
    tokens[:, 0] = 0  # special position (e.g. [CLS]=0 here) never masked
    inputs, labels = make_mlm_batch(
        tokens, mask_id=mask_id, vocab_size=vocab, rng=rng, special_ids=(0,)
    )
    selected = labels != -100
    frac = selected.mean()
    assert 0.10 < frac < 0.20, frac
    assert not selected[:, 0].any()                      # specials untouched
    assert (labels[selected] == tokens[selected]).all()  # labels = originals
    masked_frac = (inputs[selected] == mask_id).mean()
    assert 0.65 < masked_frac < 0.92, masked_frac        # ~80% become [MASK]
    kept = inputs[~selected] == tokens[~selected]
    assert kept.all()                                    # unselected unchanged

    cfg = BertConfig.tiny(vocab_size=vocab)
    module = BertMlm(cfg)
    state = create_train_state(
        module, jnp.asarray(inputs[:1]), learning_rate=5e-3, seed=1
    )
    step = jax.jit(lm_step(module), donate_argnums=0)
    batch = (jnp.asarray(inputs), jnp.asarray(labels))
    state, first = step(state, batch)
    for _ in range(15):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"]), (
        float(first["loss"]), float(metrics["loss"]),
    )


def test_mlm_masking_handles_unsigned_token_dtypes():
    """uint corpora must not wrap ignore_id to an in-range positive."""
    from unionml_tpu.models import make_mlm_batch

    rng = np.random.default_rng(2)
    tokens = rng.integers(4, 1000, size=(8, 16)).astype(np.uint16)
    inputs, labels = make_mlm_batch(
        tokens, mask_id=103, vocab_size=1024, rng=rng
    )
    assert labels.dtype.kind == "i"
    assert (labels == -100).any()
    selected = labels != -100
    assert (labels[selected] == tokens.astype(np.int64)[selected]).all()


def test_mlm_step_masks_padding_and_trains():
    """mlm_step threads the attention mask (pads invisible) and reduces
    masked CE; lm_step composition stays valid for unpadded batches."""
    from unionml_tpu.models import BertConfig, BertMlm, make_mlm_batch, mlm_step
    from unionml_tpu.models.train import create_train_state

    rng = np.random.default_rng(3)
    vocab = 512
    cfg = BertConfig.tiny(vocab_size=vocab)
    module = BertMlm(cfg)
    tokens = rng.integers(4, vocab, size=(32, 24))
    tokens[:, 20:] = 0  # right padding
    inputs, labels = make_mlm_batch(
        tokens, mask_id=103, vocab_size=vocab, rng=rng, special_ids=(0,)
    )
    mask = (tokens != 0).astype(np.int32)
    state = create_train_state(module, jnp.asarray(inputs[:1]), learning_rate=5e-3)
    step = jax.jit(mlm_step(module), donate_argnums=0)
    batch = (jnp.asarray(inputs), jnp.asarray(labels), jnp.asarray(mask))
    state, first = step(state, batch)
    for _ in range(10):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])
