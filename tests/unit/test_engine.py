"""Continuous-batching decode engine tests.

The contract under test: a request decoded in a shared slot batch —
including one that JOINS mid-flight while other slots are deep into
their decode — produces exactly the tokens its solo
``make_generator`` run would (greedy). Plus retirement (eos / budget),
slot reuse under overload, and the stats surface.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def test_engine_matches_solo_generation(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(8, 16), chunk_steps=4
    )
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 11, 16)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_flash_prefill_matches_solo(tiny_llama):
    """``prefill_impl="flash"``: the engine's no-prefix monolithic
    admissions run through the flash kernel (right-padded buckets —
    causal masking alone hides the trailing garbage) and must still
    produce each prompt's solo-generator tokens."""
    module, params = tiny_llama
    import dataclasses

    fmod = Llama(dataclasses.replace(module.config, prefill_impl="flash"))
    engine = DecodeEngine(
        fmod, slots=4, max_new_tokens=8, prompt_buckets=(8, 16), chunk_steps=4
    )
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 11, 16)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(fmod, params, prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_mid_decode_join_is_token_identical(tiny_llama):
    """A request submitted while another is mid-decode joins at a chunk
    boundary and must not perturb either sequence."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=24, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        engine.warmup(params)  # keep compile time out of the join timing
        rng = np.random.default_rng(1)
        p1 = rng.integers(1, 97, size=8).tolist()
        p2 = rng.integers(1, 97, size=6).tolist()
        results = {}

        def run(name, prompt, delay):
            time.sleep(delay)
            results[name] = engine.generate(params, [prompt])[0]

        t1 = threading.Thread(target=run, args=("a", p1, 0.0))
        t2 = threading.Thread(target=run, args=("b", p2, 0.05))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert results["a"] == _solo(module, params, p1, 24, max_len=engine.cache_len)
        assert results["b"] == _solo(module, params, p2, 24, max_len=engine.cache_len)
    finally:
        engine.close()


def test_more_requests_than_slots_queue_and_reuse(tiny_llama):
    """Overload: requests beyond the slot count wait, then reuse retired
    slots; every result still matches its solo run."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,), chunk_steps=3
    )
    try:
        rng = np.random.default_rng(2)
        prompts = [rng.integers(1, 97, size=7).tolist() for _ in range(6)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 6, max_len=engine.cache_len)
        stats = engine.stats()
        assert stats["completed_requests"] == 6
        assert stats["decode_steps"] > 0
        assert 0 < stats["slot_occupancy"] <= 1
        assert stats["queue_wait_ms"]["p50"] >= 0
        assert stats["prefill_ms"]["p50"] > 0
    finally:
        engine.close()


def test_eos_retires_slot_early(tiny_llama):
    """Force an eos hit: the engine must stop at (and include) eos, like
    make_generator, and the freed slot is immediately reusable."""
    module, params = tiny_llama
    prompt = list(range(1, 9))
    # find what greedy emits first so we can use it as the "eos"
    first = _solo(module, params, prompt, 1)[0]
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=8, prompt_buckets=(8,), chunk_steps=4,
        eos_id=first,
    )
    try:
        out = engine.generate(params, [prompt])[0]
        assert out == [first]  # eos on the very first token
        # slot freed: a second request still runs
        other = [9, 10, 11, 12]
        out2 = engine.generate(params, [other])[0]
        solo = _solo(module, params, other, 8, max_len=engine.cache_len)
        stop = solo.index(first) + 1 if first in solo else 8
        assert out2 == solo[:stop]
    finally:
        engine.close()


def test_per_request_token_budget(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=16, prompt_buckets=(8,), chunk_steps=8
    )
    try:
        prompt = list(range(1, 7))
        out = engine.generate(params, [prompt], max_new_tokens=3)[0]
        assert out == _solo(module, params, prompt, 3, max_len=engine.cache_len)
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.generate(params, [prompt], max_new_tokens=99)
    finally:
        engine.close()


def test_engine_rejects_bad_config(tiny_llama):
    module, _ = tiny_llama
    with pytest.raises(ValueError, match="max_len"):
        DecodeEngine(module, max_new_tokens=300, prompt_buckets=(64,))
    with pytest.raises(ValueError, match="bucket"):
        DecodeEngine(module, prompt_buckets=())
    with pytest.raises(ValueError, match="slot"):
        DecodeEngine(module, slots=0)


def test_temperature_sampling_varies_and_respects_budget(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(8,), chunk_steps=4,
        temperature=0.8, seed=3,
    )
    try:
        prompt = list(range(1, 9))
        outs = engine.generate(params, [prompt, prompt])
        assert all(len(o) == 8 for o in outs)
        vocab_ok = all(0 <= t < 97 for o in outs for t in o)
        assert vocab_ok
    finally:
        engine.close()


def test_bind_refuses_hot_swap_while_busy(tiny_llama):
    """Swapping weights mid-flight would mix trees within one decode —
    the engine must refuse until drained (and allow the swap when idle)."""
    module, params = tiny_llama
    import jax

    other = jax.tree_util.tree_map(lambda x: x + 0, params)  # distinct object
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=16, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        engine.warmup(params)
        done = threading.Event()

        def run():
            engine.generate(params, [list(range(1, 9))])
            done.set()

        t = threading.Thread(target=run)
        t.start()
        raised = False
        while not done.is_set():
            try:
                engine.bind(other)
            except RuntimeError:
                raised = True
                break
            time.sleep(0.001)
        t.join()
        assert raised or done.is_set()  # busy window may be tiny on CPU
        engine.bind(other)  # idle: swap allowed
        out = engine.generate(other, [list(range(1, 9))])
        assert len(out[0]) == 16
    finally:
        engine.close()


def test_stats_archive_is_lightweight(tiny_llama):
    """The stats source holds bounded float windows in the telemetry
    registry, not request payloads."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        engine.generate(params, [[1, 2, 3], [4, 5, 6]])
        for h in (engine._h_queue, engine._h_prefill, engine._h_decode,
                  engine._h_ttft):
            # floats only (no prompt/token payloads), hard-capped window
            assert all(isinstance(v, float) for v in h._window)
            assert len(h._window) <= h.WINDOW_CAP
        s = engine.stats()
        assert s["completed_requests"] == 2
        assert s["queue_wait_ms"]["p95"] >= s["queue_wait_ms"]["p50"] >= 0
    finally:
        engine.close()


def test_engine_with_moe_llama():
    """Continuous batching over a MoE decoder: per-slot decode routes
    tokens through the experts; outputs match solo generation."""
    cfg = LlamaConfig.tiny(vocab_size=97, num_experts=4, num_selected=2)
    module = Llama(cfg)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,), chunk_steps=3
    )
    try:
        prompts = [[1, 2, 3, 4], [5, 6, 7, 8, 9, 10]]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 6, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_under_tensor_parallel_sharding(tiny_llama):
    """Continuous batching with TP-sharded weights: GSPMD propagates the
    `tensor`-axis sharding through prefill and decode chunks, and slot
    outputs stay token-identical to the solo run **under the same
    sharding**. (Comparing against the UNSHARDED solo run is wrong:
    sharded matmuls reduce partial sums in a different order, and on a
    randomly-initialized tiny model the resulting ulp-level logit
    differences flip near-tie argmaxes — the sharded solo generator
    diverges from the unsharded one identically, so that comparison
    tested numerics, not the engine.)

    pipeline_depth=1 on the CPU mesh: deeper async pipelines of
    multi-device programs starve XLA's rendezvous on few-core hosts
    (same reason compile_step syncs per step there)."""
    from unionml_tpu.models import LLAMA_PARTITION_RULES
    from unionml_tpu.parallel import ShardingConfig, shard_pytree

    module, params = tiny_llama
    sharding = ShardingConfig(data=-1, tensor=2, rules=LLAMA_PARTITION_RULES)
    tp_params = shard_pytree(params, sharding)
    # guard against a silent replication fallback: the test must exercise
    # REAL tensor sharding or it proves nothing
    specs = [
        str(tuple(leaf.sharding.spec))
        for leaf in jax.tree_util.tree_leaves(tp_params)
    ]
    assert any("tensor" in s for s in specs), specs
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        chunk_steps=3, pipeline_depth=1,
    )
    try:
        prompts = [[1, 2, 3, 4, 5], [6, 7, 8]]
        outs = engine.generate(tp_params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, tp_params, prompt, 6, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_with_kv_quant_cache(tiny_llama):
    """The engine on the int8 KV cache (kv_quant=True): joins splice int8
    rows + scale planes, and every request still matches ITS solo run on
    the same quantized-cache path."""
    import dataclasses

    module, params = tiny_llama
    qmodule = Llama(dataclasses.replace(module.config, kv_quant=True))
    engine = DecodeEngine(
        qmodule, slots=4, max_new_tokens=8, prompt_buckets=(8, 16), chunk_steps=4
    )
    try:
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 11, 16)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(qmodule, params, prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_system_prefix_matches_prefixed_solo(tiny_llama):
    """Engine with system_prefix: every request's tokens equal the solo
    generation of (prefix + prompt) — the prefix KV is seeded once and
    shared by all slots."""
    module, params = tiny_llama
    rng = np.random.default_rng(9)
    prefix = rng.integers(1, 97, 7).tolist()
    engine = DecodeEngine(
        module, slots=3, max_new_tokens=6, prompt_buckets=(8, 16),
        chunk_steps=3, system_prefix=prefix,
    )
    try:
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 12)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prefix + prompt, 6, max_len=engine.cache_len)
        # second round reuses the seeded prefix rows (slot reuse path)
        outs2 = engine.generate(params, prompts[:2])
        for prompt, out in zip(prompts[:2], outs2):
            assert out == _solo(module, params, prefix + prompt, 6, max_len=engine.cache_len)
    finally:
        engine.close()


def test_generate_stream_token_identity_and_chunking(tiny_llama):
    """Streamed chunks concatenate to exactly the blocking generate()
    output; the first chunk is the single prefill token (the TTFT event)
    and later chunks respect the chunk_steps granularity."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=12, prompt_buckets=(8,), chunk_steps=4
    )
    try:
        prompt = list(range(1, 8))
        want = engine.generate(params, [prompt])[0]
        chunks = list(engine.generate_stream(params, prompt))
        assert [t for c in chunks for t in c] == want
        assert len(chunks[0]) == 1  # prefill token arrives alone
        assert all(len(c) <= engine.chunk_steps for c in chunks[1:])
        assert len(chunks) >= 3  # actually incremental, not one blob
    finally:
        engine.close()


def test_generate_stream_concurrent_with_blocking_calls(tiny_llama):
    """A stream interleaved with blocking generate() calls on other
    threads keeps token identity for everyone (chunk-boundary joins)."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, 97, size=6).tolist() for _ in range(3)]
        results = {}

        def blocking(i):
            results[i] = engine.generate(params, [prompts[i]])[0]

        threads = [
            threading.Thread(target=blocking, args=(i,)) for i in (1, 2)
        ]
        for t in threads:
            t.start()
        streamed = [t for c in engine.generate_stream(params, prompts[0]) for t in c]
        for t in threads:
            t.join()
        assert streamed == _solo(module, params, prompts[0], 8, max_len=engine.cache_len)
        for i in (1, 2):
            assert results[i] == _solo(module, params, prompts[i], 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_generate_stream_validation_and_eos(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(8,), chunk_steps=4,
        eos_id=3,
    )
    try:
        with pytest.raises(ValueError, match="max_new_tokens"):
            list(engine.generate_stream(params, [1, 2], max_new_tokens=99))
        with pytest.raises(ValueError, match="empty"):
            list(engine.generate_stream(params, []))
        prompt = list(range(1, 8))
        want = engine.generate(params, [prompt])[0]
        got = [t for c in engine.generate_stream(params, prompt) for t in c]
        assert got == want  # eos truncation identical across surfaces
    finally:
        engine.close()


def test_stats_include_ttft(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        engine.generate(params, [[1, 2, 3]])
        stats = engine.stats()
        assert "ttft_ms" in stats
        # TTFT covers queue+prefill only — it must not exceed the full
        # request latency (prefill + decode)
        assert stats["ttft_ms"]["p50"] <= (
            stats["queue_wait_ms"]["p50"] + stats["prefill_ms"]["p50"]
            + stats["decode_ms"]["p50"] + 1e-6
        )
    finally:
        engine.close()


def test_stream_consumer_disconnect_frees_slot(tiny_llama):
    """Closing a stream early (the SSE client-disconnect lifecycle) must
    abandon the request so its slot stops decoding dead work."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=64, prompt_buckets=(8,), chunk_steps=2
    )
    try:
        stream = engine.generate_stream(params, [1, 2, 3])
        next(stream)       # first (prefill) chunk arrives
        stream.close()     # GeneratorExit → abandoned
        deadline = time.perf_counter() + 10.0
        while time.perf_counter() < deadline:
            with engine._lock:
                if engine._occupant[0] is None:
                    break
            time.sleep(0.02)
        else:
            raise AssertionError("abandoned stream's slot was never freed")
        # the lone slot is reusable for a live request
        out = engine.generate(params, [[4, 5, 6]], max_new_tokens=4)
        assert len(out[0]) == 4
    finally:
        engine.close()


def test_chunked_prefill_token_identity(tiny_llama):
    """Buckets above prefill_chunk admit via lead-chunk programs + a
    final splice; every request (short prompt in a long bucket, exact
    multiples, ragged tails) matches its solo generation."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(8, 64),
        prefill_chunk=16, chunk_steps=4,
    )
    try:
        rng = np.random.default_rng(11)
        # 5/8 → monolithic bucket 8; 9 → 1 (final-only) chunk in bucket
        # 64; 16/33 → ragged; 64 → full 4-chunk cover
        prompts = [
            rng.integers(1, 97, size=n).tolist() for n in (5, 8, 9, 16, 33, 64)
        ]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_chunked_prefill_with_system_prefix(tiny_llama):
    """Chunked admission composes with the shared system prefix: the
    fresh cache seeds the prefix rows before the lead chunks run."""
    module, params = tiny_llama
    rng = np.random.default_rng(13)
    prefix = rng.integers(1, 97, 7).tolist()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(32,),
        prefill_chunk=8, chunk_steps=3, system_prefix=prefix,
    )
    try:
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (9, 20, 32)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prefix + prompt, 6, max_len=engine.cache_len)
    finally:
        engine.close()


def test_chunked_prefill_with_kv_quant(tiny_llama):
    """Long-bucket chunked admission over the int8 KV cache: lead chunks
    carry the quantized (k_q, v_q, scales) layout through the fresh
    cache and the final splice."""
    import dataclasses

    module, params = tiny_llama
    qmodule = Llama(dataclasses.replace(module.config, kv_quant=True))
    engine = DecodeEngine(
        qmodule, slots=2, max_new_tokens=8, prompt_buckets=(48,),
        prefill_chunk=16, chunk_steps=4,
    )
    try:
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (10, 48)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(qmodule, params, prompt, 8, max_len=engine.cache_len)
    finally:
        engine.close()


def test_decode_interleaves_with_chunked_admission(tiny_llama):
    """While a long prompt admits chunk-by-chunk, resident slots keep
    decoding: at least one decode chunk is dispatched strictly between
    the first and last prefill-chunk dispatches of the admission."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=180, prompt_buckets=(8, 64),
        prefill_chunk=8, chunk_steps=2, pipeline_depth=2,
    )
    try:
        engine.warmup(params)
        events = []
        lock = threading.Lock()
        real_step, real_decode = engine._prefill_step, engine._decode_chunk

        def rec_decode(*a, **k):
            with lock:
                events.append("decode")
            return real_decode(*a, **k)

        def slow_step(*a, **k):
            # stretch each lead-chunk dispatch across several dispatcher
            # passes so the admission window deterministically overlaps
            # live decode dispatch regardless of host load (the raw
            # timing race flaked under full-suite CPU contention)
            time.sleep(0.01)
            with lock:
                events.append("prefill_step")
            return real_step(*a, **k)

        engine._prefill_step = slow_step
        engine._decode_chunk = rec_decode

        # occupy a slot with a LONG decode, then admit a 64-token prompt
        # (8 lead chunks): its admission must not stall the decode. Up to
        # two retries tolerate pathological scheduler stalls.
        rng = np.random.default_rng(19)
        interleaved = False
        for _attempt in range(3):
            events.clear()
            # pre-draw both prompts: np.random.Generator is not
            # thread-safe, and drawing from the bg thread would race the
            # main thread's draw under CPU contention
            bg_prompt = rng.integers(1, 97, 8).tolist()
            main_prompt = rng.integers(1, 97, 64).tolist()
            bg = threading.Thread(
                target=lambda: engine.generate(params, [bg_prompt])
            )
            bg.start()
            time.sleep(0.05)  # let the background request admit + decode
            out = engine.generate(params, [main_prompt], max_new_tokens=4)
            bg.join(timeout=60)
            # a hung background generate must fail LOUDLY here — retrying
            # over a still-occupied slot would corrupt events/slot state
            # and could even pass spuriously
            assert not bg.is_alive(), "background generate hung"
            assert len(out[0]) == 4
            snapshot = list(events)
            if "prefill_step" in snapshot:
                first = snapshot.index("prefill_step")
                last = (
                    len(snapshot) - 1 - snapshot[::-1].index("prefill_step")
                )
                if "decode" in snapshot[first:last]:
                    interleaved = True
                    break
                # decode events AFTER the admission window mean the bg
                # request was live through it yet never interleaved —
                # the head-of-line-blocking regression this test exists
                # to catch. Fail now: retrying could mask an engine that
                # only intermittently stalls decode behind admission.
                assert "decode" not in snapshot[first:], snapshot
                # otherwise the bg request finished before admission
                # began (OS scheduler stall): uninformative — retry
        assert interleaved, snapshot
    finally:
        engine.close()
