"""Chaos-harness tier-1 tests (docs/robustness.md): every serving
failure mode reproduced deterministically on CPU via
:class:`~unionml_tpu.serving.faults.FaultInjector` — device-program
crash mid-stream with supervised recovery, overload shedding at both
the engine and HTTP layers, deadline expiry at dequeue, circuit
breaker, graceful drain, and the abandoned-request / prefix-cache-lease
races recovery must not leak through."""

import threading
import time

import httpx
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.batcher import MicroBatcher
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    DeadlineExceeded,
    EngineUnavailable,
    FaultInjector,
    Overloaded,
    deadline_scope,
    xla_oom_error,
)
from unionml_tpu.serving.prefix_cache import RadixPrefixCache

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.005)
    pytest.fail(f"timed out waiting for {what}")


def _resident(engine):
    with engine._lock:
        return sum(r is not None for r in engine._occupant)


# ---------------------------------------------------------------- injector


def test_injector_deterministic_nth_count():
    fi = FaultInjector()
    boom = RuntimeError("boom")
    # three unarmed fires count but do nothing
    for _ in range(3):
        fi.fire("engine.dispatch")
    assert fi.fired("engine.dispatch") == 3 and fi.injected("engine.dispatch") == 0
    # nth counts from ARMING time, not process start: nth=2 skips one
    # more firing, then injects twice (count=2), then self-disarms
    fi.arm("engine.dispatch", nth=2, count=2, exc=boom)
    fi.fire("engine.dispatch")                      # nth=1: clean
    for _ in range(2):
        with pytest.raises(RuntimeError, match="boom"):
            fi.fire("engine.dispatch")
    fi.fire("engine.dispatch")                      # plan exhausted: clean
    assert fi.injected("engine.dispatch") == 2
    # a second identical arming replays identically (determinism)
    fi.arm("engine.dispatch", nth=2, count=2, exc=boom)
    fi.fire("engine.dispatch")
    with pytest.raises(RuntimeError):
        fi.fire("engine.dispatch")
    fi.disarm()
    fi.fire("engine.dispatch")


def test_injector_validation_and_delay():
    fi = FaultInjector()
    with pytest.raises(ValueError, match="unknown injection point"):
        fi.arm("engine.typo", exc=RuntimeError())
    with pytest.raises(ValueError, match="exc and/or"):
        fi.arm("engine.dispatch")
    fi.arm("engine.harvest", delay_s=0.05)
    t0 = time.perf_counter()
    fi.fire("engine.harvest")                       # stall, no raise
    assert time.perf_counter() - t0 >= 0.05
    assert "RESOURCE_EXHAUSTED" in str(xla_oom_error())


def test_deadline_scope_nesting():
    from unionml_tpu.serving.faults import current_deadline_ms

    assert current_deadline_ms() is None
    with deadline_scope(100.0):
        assert current_deadline_ms() == 100.0
        with deadline_scope(5.0):
            assert current_deadline_ms() == 5.0
        assert current_deadline_ms() == 100.0
    assert current_deadline_ms() is None
    with pytest.raises(ValueError):
        with deadline_scope(0.0):
            pass


# ------------------------------------------------------------------ engine


def test_engine_recovers_from_midstream_device_fault(tiny_llama):
    """THE acceptance scenario: an OOM-shaped device-program fault
    injected mid-stream fails ONLY the poisoned batch (the two resident
    requests — one of them a live SSE-style stream), the queued
    requests admit after the rebuild and complete token-identical to
    their solo runs, and ``unionml_engine_recoveries_total``
    increments."""
    module, params = tiny_llama
    fi = FaultInjector()
    n_new = 48
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=n_new, prompt_buckets=(8,),
        chunk_steps=2, fault_injector=fi,
    )
    try:
        results = {}

        def run(name, prompt):
            try:
                results[name] = engine.generate(params, [prompt])[0]
            except BaseException as exc:
                results[name] = exc

        chunks, stream_err = [], [None]

        def run_stream(prompt):
            try:
                for ch in engine.generate_stream(params, prompt):
                    chunks.append(ch)
            except BaseException as exc:
                stream_err[0] = exc

        pa, pb, pc, pd = [1, 2, 3], [4, 5, 6], [2, 3, 4], [5, 6, 7]
        threads = [
            threading.Thread(target=run_stream, args=(pa,)),
            threading.Thread(target=run, args=("b", pb)),
        ]
        for t in threads:
            t.start()
        _wait_for(lambda: _resident(engine) == 2, what="both requests resident")
        _wait_for(lambda: len(chunks) > 0, what="stream mid-flight")
        # the NEXT decode-chunk dispatch hits an OOM-shaped XLA error
        fi.arm("engine.dispatch", exc=xla_oom_error())
        threads += [
            threading.Thread(target=run, args=("c", pc)),
            threading.Thread(target=run, args=("d", pd)),
        ]
        for t in threads[2:]:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        # poisoned batch: the stream and the resident generate both fail
        # with the injected error
        assert isinstance(stream_err[0], RuntimeError), stream_err[0]
        assert "RESOURCE_EXHAUSTED" in str(stream_err[0])
        assert isinstance(results["b"], RuntimeError), results["b"]
        # queued survivors re-admitted onto the rebuilt state and match
        # their solo generations exactly
        assert results["c"] == _solo(module, params, pc, n_new, max_len=engine.cache_len)
        assert results["d"] == _solo(module, params, pd, n_new, max_len=engine.cache_len)
        assert int(engine._m_recoveries.value) == 1
        assert engine.stats()["robustness"]["recoveries"] == 1
        # the engine keeps serving afterwards (breaker never opened:
        # one recovery < breaker_threshold)
        assert engine.health()["status"] == "ok"
        assert engine.generate(params, [pa])[0] == _solo(
            module, params, pa, n_new, max_len=engine.cache_len
        )
    finally:
        engine.close()


def test_engine_queue_full_sheds_with_typed_overload(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=48, prompt_buckets=(8,),
        chunk_steps=2, max_queue_depth=1,
    )
    try:
        results = {}

        def run(name, prompt):
            results[name] = engine.generate(params, [prompt])[0]

        t1 = threading.Thread(target=run, args=("a", [1, 2, 3]))
        t1.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        t2 = threading.Thread(target=run, args=("b", [4, 5, 6]))
        t2.start()
        _wait_for(lambda: engine._room.qsize() == 1, what="one queued")
        with pytest.raises(Overloaded, match="queue is full"):
            engine.generate(params, [[7, 8, 9]])
        assert engine.stats()["robustness"]["rejected"]["queue_full"] == 1
        # a multi-prompt call is all-or-nothing: nothing was enqueued
        assert engine._room.qsize() == 1
        t1.join(timeout=120)
        t2.join(timeout=120)
        # the admitted requests were untouched by the shed
        assert results["a"] == _solo(module, params, [1, 2, 3], 48, max_len=engine.cache_len)
        assert results["b"] == _solo(module, params, [4, 5, 6], 48, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_deadline_shed_at_dequeue(tiny_llama):
    """A queued request whose deadline expires is shed when the
    dispatcher dequeues it — before it consumes any prefill — via the
    ambient deadline_scope (the X-Deadline-Ms propagation path)."""
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=64, prompt_buckets=(8,),
        chunk_steps=2,
    )
    try:
        done = {}

        def run_a():
            done["a"] = engine.generate(params, [[1, 2, 3]])[0]
        t1 = threading.Thread(target=run_a)
        t1.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        err = [None]

        def run_b():
            try:
                with deadline_scope(1.0):  # expires long before the
                    engine.generate(params, [[4, 5, 6]])  # slot frees
            except BaseException as exc:
                err[0] = exc
        t2 = threading.Thread(target=run_b)
        t2.start()
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert isinstance(err[0], DeadlineExceeded), err[0]
        assert int(engine._m_deadline_shed.value) == 1
        # the shed is not an engine error, and the running request
        # finished untouched
        assert int(engine._m_errors.value) == 0
        assert done["a"] == _solo(module, params, [1, 2, 3], 64, max_len=engine.cache_len)
    finally:
        engine.close()


def test_engine_breaker_opens_after_consecutive_recoveries(tiny_llama):
    module, params = tiny_llama
    fi = FaultInjector()
    engine = DecodeEngine(
        module, slots=1, max_new_tokens=4, prompt_buckets=(8,),
        chunk_steps=2, fault_injector=fi,
        breaker_threshold=2, breaker_window_s=30.0, breaker_cooldown_s=0.5,
    )
    try:
        for i in range(2):
            fi.arm("engine.dispatch", exc=xla_oom_error())
            with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
                engine.generate(params, [[1, 2, 3]])
            # the waiter wakes from inside _recover's lock block; the
            # counters land before the lock releases, moments later
            _wait_for(
                lambda: int(engine._m_recoveries.value) == i + 1,
                what=f"recovery {i + 1} recorded",
            )
        # threshold hit: submissions now fail FAST with a typed error
        _wait_for(lambda: engine.breaker_open, what="breaker open")
        assert engine.health() == {
            "status": "degraded", "queue_depth": 0, "breaker_open": True,
        }
        with pytest.raises(EngineUnavailable) as exc_info:
            engine.generate(params, [[1, 2, 3]])
        assert exc_info.value.reason == "breaker_open"
        assert exc_info.value.retry_after_s > 0
        assert engine.stats()["robustness"]["rejected"]["breaker_open"] == 1
        # cooldown elapses -> half-open -> a healthy request closes it
        time.sleep(0.6)
        assert not engine.breaker_open
        out = engine.generate(params, [[1, 2, 3]])[0]
        assert out == _solo(module, params, [1, 2, 3], 4, max_len=engine.cache_len)
        assert engine.health()["status"] == "ok"
    finally:
        engine.close()


def test_engine_drain_finishes_inflight_then_rejects(tiny_llama):
    module, params = tiny_llama
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=48, prompt_buckets=(8,),
        chunk_steps=2,
    )
    try:
        done = {}

        def run_a():
            done["a"] = engine.generate(params, [[1, 2, 3]])[0]
        t1 = threading.Thread(target=run_a)
        t1.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        assert engine.drain(timeout=120) is True
        # the in-flight request FINISHED (drain never kills work) ...
        t1.join(timeout=10)
        assert done["a"] == _solo(module, params, [1, 2, 3], 48, max_len=engine.cache_len)
        # ... and admissions are now rejected with the draining reason
        assert engine.health()["status"] == "draining"
        with pytest.raises(EngineUnavailable) as exc_info:
            engine.generate(params, [[4, 5]])
        assert exc_info.value.reason == "draining"
        assert engine.stats()["robustness"]["draining"] is True
        # drain duration landed in its histogram
        assert engine._h_drain.summary()["n"] == 1
        engine.resume()
        assert engine.health()["status"] == "ok"
        assert engine.generate(params, [[4, 5]])[0] == _solo(
            module, params, [4, 5], 48, max_len=engine.cache_len
        )
    finally:
        engine.close()


def test_engine_tolerates_slow_harvest(tiny_llama):
    """A stalled readback (slow-harvest injection) delays but never
    corrupts: tokens stay identical to the solo run."""
    module, params = tiny_llama
    fi = FaultInjector()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(8,),
        chunk_steps=2, fault_injector=fi,
    )
    try:
        fi.arm("engine.harvest", delay_s=0.05, count=3)
        out = engine.generate(params, [[1, 2, 3, 4]])[0]
        assert out == _solo(module, params, [1, 2, 3, 4], 8, max_len=engine.cache_len)
        assert fi.injected("engine.harvest") == 3
    finally:
        engine.close()


def test_recovery_and_abandon_release_prefix_cache_leases(tiny_llama):
    """Satellite: the abandoned-request races. A poisoned batch whose
    requests hold prefix-cache leases (one of them a concurrently
    abandoned stream) must release every lease at recovery — a leaked
    refcount would pin blocks against eviction forever."""
    module, params = tiny_llama
    fi = FaultInjector()
    cache = RadixPrefixCache(block_size=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=32, prompt_buckets=(16,),
        chunk_steps=2, fault_injector=fi, prefix_cache=cache,
    )

    def live_refcounts():
        with cache._lock:
            total, stack = 0, list(cache._root.children.values())
            while stack:
                n = stack.pop()
                total += n.refcount
                stack.extend(n.children.values())
            return total

    try:
        shared = list(range(1, 13))  # 3 full blocks -> cacheable prefix
        # seed the cache, then verify steady state holds no refcounts
        engine.generate(params, [shared + [20]])
        _wait_for(lambda: live_refcounts() == 0, what="seed leases released")
        assert cache.entries > 0
        # two cache-hitting requests resident: a stream (abandoned
        # mid-recovery) and a generate (failed by the poisoned batch)
        stream = engine.generate_stream(params, shared + [21])
        next(iter(stream))          # consume TTFT: admission completed
        res = {}

        def run_b():
            try:
                res["b"] = engine.generate(params, [shared + [22]])[0]
            except BaseException as exc:
                res["b"] = exc
        t = threading.Thread(target=run_b)
        t.start()
        _wait_for(lambda: _resident(engine) == 2, what="both resident")
        fi.arm("engine.dispatch", exc=xla_oom_error())
        stream.close()              # abandon the stream during the fault
        t.join(timeout=120)
        assert not t.is_alive()
        _wait_for(
            lambda: int(engine._m_recoveries.value) == 1,
            what="recovery",
        )
        # no leaked leases anywhere — poisoned batch, abandoned stream,
        # and in-flight insert entries all released theirs
        _wait_for(lambda: live_refcounts() == 0, what="all leases released")
        # and the cache still SERVES: a fresh shared-prefix request
        # completes and matches its solo run (cache parity contract)
        out = engine.generate(params, [shared + [23]])[0]
        assert out == _solo(module, params, shared + [23], 32, max_len=engine.cache_len)
        _wait_for(lambda: live_refcounts() == 0, what="post-check release")
    finally:
        engine.close()


# ----------------------------------------------------------------- batcher


def test_batcher_queue_full_sheds(tiny_llama):
    picked_up = threading.Event()
    release = threading.Event()

    def predict(feats):
        picked_up.set()
        release.wait(30)
        return feats.sum(axis=1)

    batcher = MicroBatcher(
        predict, max_batch_size=2, max_wait_ms=1.0, max_queue_depth=1,
    )
    results = {}
    try:
        t1 = threading.Thread(
            target=lambda: results.update(a=batcher.submit(np.ones((1, 2))))
        )
        t1.start()
        assert picked_up.wait(30)   # worker is blocked inside the batch
        t2 = threading.Thread(
            target=lambda: results.update(b=batcher.submit(np.ones((1, 2))))
        )
        t2.start()
        _wait_for(lambda: batcher._queue.qsize() == 1, what="one queued")
        with pytest.raises(Overloaded, match="queue is full"):
            batcher.submit(np.ones((1, 2)))
        assert batcher.stats()["robustness"]["rejected"]["queue_full"] == 1
        release.set()
        t1.join(timeout=30)
        t2.join(timeout=30)
        np.testing.assert_allclose(results["a"], [2.0])
        np.testing.assert_allclose(results["b"], [2.0])
    finally:
        release.set()
        batcher.close()


def test_batcher_deadline_shed_and_drain():
    picked_up = threading.Event()
    release = threading.Event()

    def predict(feats):
        picked_up.set()
        release.wait(30)
        return feats.sum(axis=1)

    batcher = MicroBatcher(predict, max_batch_size=2, max_wait_ms=1.0)
    err = [None]
    try:
        t1 = threading.Thread(target=lambda: batcher.submit(np.ones((1, 2))))
        t1.start()
        assert picked_up.wait(30)

        def run_b():
            try:
                batcher.submit(np.ones((1, 2)), deadline_ms=20.0)
            except BaseException as exc:
                err[0] = exc
        t2 = threading.Thread(target=run_b)
        t2.start()
        time.sleep(0.05)            # the queued entry's deadline expires
        release.set()               # worker drains -> sheds it typed
        t1.join(timeout=30)
        t2.join(timeout=30)
        assert isinstance(err[0], DeadlineExceeded), err[0]
        assert int(batcher._m_deadline_shed.value) == 1
        # drain: admissions rejected, health flips, resume reopens
        assert batcher.drain(timeout=30) is True
        assert batcher.health()["status"] == "draining"
        with pytest.raises(EngineUnavailable):
            batcher.submit(np.ones((1, 2)))
        batcher.resume()
        assert batcher.health()["status"] == "ok"
    finally:
        release.set()
        batcher.close()


def test_batcher_predict_injection_surfaces_to_waiters():
    fi = FaultInjector()
    batcher = MicroBatcher(
        lambda feats: feats.sum(axis=1), max_batch_size=4, max_wait_ms=1.0,
        fault_injector=fi,
    )
    try:
        fi.arm("batcher.predict", exc=xla_oom_error())
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            batcher.submit(np.ones((1, 2)))
        # the injected batch failed; the next one is healthy
        np.testing.assert_allclose(batcher.submit(np.ones((1, 2))), [2.0])
    finally:
        batcher.close()


# ----------------------------------------------------- HTTP acceptance


def _engine_serving_app(**engine_kwargs):
    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = DecodeEngine(
        module, prompt_buckets=(8,), chunk_steps=2, **engine_kwargs
    )
    dataset = Dataset(name="faults_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    lm = Model(name="faults_lm", init=lambda: params, dataset=dataset)

    @lm.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @lm.predictor
    def predictor(p: dict, prompts: list) -> list:
        return engine.generate(p, prompts)

    lm.artifact = ModelArtifact(params, {}, {})
    app = ServingApp(
        lm, stats=engine.stats, health=engine.health, drain=engine.drain,
    )
    return app, engine


def test_http_overload_answers_429_with_retry_after():
    """THE transport acceptance scenario: drive the engine queue past
    ``max_queue_depth`` and observe 429 + ``Retry-After`` at the HTTP
    layer, then 503 (+ ``Retry-After``) once the app drains."""
    app, engine = _engine_serving_app(
        slots=1, max_new_tokens=48, max_queue_depth=1,
    )
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    results = {}

    def post(name, prompt):
        results[name] = httpx.post(
            f"{url}/predict", json={"features": [prompt]}, timeout=120
        )

    try:
        t1 = threading.Thread(target=post, args=("a", [1, 2, 3]))
        t1.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        t2 = threading.Thread(target=post, args=("b", [4, 5, 6]))
        t2.start()
        _wait_for(lambda: engine._room.qsize() == 1, what="one queued")
        # /health reports the backlog the balancer would act on
        assert httpx.get(f"{url}/health").json()["queue_depth"] == 1
        r = httpx.post(
            f"{url}/predict", json={"features": [[7, 8, 9]]}, timeout=30
        )
        assert r.status_code == 429
        assert "queue is full" in r.json()["error"]
        assert int(r.headers["retry-after"]) >= 1
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert results["a"].status_code == 200
        assert results["b"].status_code == 200
        # graceful drain: already-admitted work finished above; now the
        # app sheds with 503 + Retry-After and /health serves 503
        assert app.drain(timeout=120) is True
        r = httpx.post(
            f"{url}/predict", json={"features": [[1, 2]]}, timeout=30
        )
        assert r.status_code == 503 and r.json()["reason"] == "draining"
        assert int(r.headers["retry-after"]) >= 1
        h = httpx.get(f"{url}/health")
        assert h.status_code == 503 and h.json()["status"] == "draining"
    finally:
        app.shutdown()
        engine.close()


def test_http_deadline_header_maps_to_504():
    """X-Deadline-Ms propagates through the transport into the engine
    and an expired queued request surfaces as 504."""
    app, engine = _engine_serving_app(slots=1, max_new_tokens=64)
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    results = {}

    def post_a():
        results["a"] = httpx.post(
            f"{url}/predict", json={"features": [[1, 2, 3]]}, timeout=120
        )

    try:
        t1 = threading.Thread(target=post_a)
        t1.start()
        _wait_for(lambda: _resident(engine) == 1, what="slot occupied")
        r = httpx.post(
            f"{url}/predict", json={"features": [[4, 5, 6]]},
            headers={"X-Deadline-Ms": "1"}, timeout=120,
        )
        assert r.status_code == 504
        assert "deadline expired" in r.json()["error"]
        # malformed header is a 422, not a silent no-deadline
        r = httpx.post(
            f"{url}/predict", json={"features": [[4, 5, 6]]},
            headers={"X-Deadline-Ms": "soon"}, timeout=30,
        )
        assert r.status_code == 422
        t1.join(timeout=120)
        assert results["a"].status_code == 200
        assert int(engine._m_deadline_shed.value) == 1
    finally:
        app.shutdown()
        engine.close()
