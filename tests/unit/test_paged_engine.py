"""Block-paged decode engine tests (docs/performance.md "Paged KV
attention").

The contract: with the reference paged-attention path, a paged engine's
tokens are IDENTICAL to the contiguous engine's (and to each prompt's
solo generator run) across cold/warm/partial prefix-cache hits, chunked
prefill, and kv-quant/int4 composition — the layout changed, the math
did not. On top of parity: pool exhaustion surfaces as a clean typed
reject or a parked admission (never a mid-decode failure), retirement/
abandonment/recovery leak no blocks (``unionml_kv_pool_*`` returns to
baseline), block tables grow across the ``max_new_tokens`` boundary,
and block geometry is unified with the prefix cache.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import FaultInjector, Overloaded, xla_oom_error
from unionml_tpu.serving.prefix_cache import RadixPrefixCache


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=256):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _paged_engine(module, **kw):
    kw.setdefault("registry", telemetry.MetricsRegistry())
    kw.setdefault("paged", True)
    return DecodeEngine(module, **kw)


def _assert_pool_drained(engine, timeout=30.0):
    """The acceptance gauge: unionml_kv_pool_* back to baseline (the
    harvester's deferred frees may land a beat after the waiter wakes)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = engine.stats()["kv_pool"]
        if st["blocks_in_use"] == 0 and st["blocks_reserved"] == 0:
            return st
        time.sleep(0.02)
    raise AssertionError(f"kv pool leaked blocks: {engine.stats()['kv_pool']}")


def test_paged_engine_matches_solo(tiny_llama):
    module, params = tiny_llama
    engine = _paged_engine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(8, 16),
        chunk_steps=4,
    )
    try:
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, 97, size=n).tolist() for n in (5, 8, 11, 16)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 8, max_len=engine.cache_len)
        st = _assert_pool_drained(engine)
        assert st["allocated_blocks"] > 0
        assert st["allocated_blocks"] == st["freed_blocks"]
    finally:
        engine.close()


def test_paged_matches_contiguous_stream(tiny_llama):
    """The acceptance parity bar: one request stream, contiguous vs
    paged engine, bit-identical tokens on the reference kernel."""
    module, params = tiny_llama
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 97, size=n).tolist() for n in (3, 7, 12, 16, 9)]
    outs = {}
    for paged in (False, True):
        engine = DecodeEngine(
            module, slots=2, max_new_tokens=6, prompt_buckets=(16,),
            chunk_steps=3, paged=paged,
            registry=telemetry.MetricsRegistry(),
        )
        try:
            outs[paged] = engine.generate(params, prompts)
        finally:
            engine.close()
    assert outs[True] == outs[False]


def test_paged_prefix_cache_cold_warm_partial(tiny_llama):
    """Paged pool + radix prefix cache share one block unit: cold
    admission inserts, warm splices every block, partial splices the
    shared prefix and prefills the suffix — all token-identical to the
    cache-off contiguous baseline."""
    module, params = tiny_llama
    rng = np.random.default_rng(2)
    shared = rng.integers(1, 97, 32).tolist()
    p_cold = shared + rng.integers(1, 97, 8).tolist()
    p_part = shared + rng.integers(1, 97, 12).tolist()
    engine = _paged_engine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(48,),
        chunk_steps=3,
        prefix_cache=RadixPrefixCache(
            block_size=16, registry=telemetry.MetricsRegistry()
        ),
    )
    try:
        cold = engine.generate(params, [p_cold])[0]
        warm = engine.generate(params, [p_cold])[0]
        part = engine.generate(params, [p_part])[0]
        assert cold == _solo(module, params, p_cold, 6, max_len=engine.cache_len)
        assert warm == cold
        assert part == _solo(module, params, p_part, 6, max_len=engine.cache_len)
        pc = engine.stats()["prefix_cache"]
        assert pc["hits"] + pc["partial_hits"] >= 2
        assert pc["prefill_tokens_saved"] > 0
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_paged_chunked_prefill_token_identity(tiny_llama):
    module, params = tiny_llama
    rng = np.random.default_rng(3)
    engine = _paged_engine(
        module, slots=2, max_new_tokens=5, prompt_buckets=(64,),
        prefill_chunk=16, chunk_steps=2,
    )
    try:
        prompt = rng.integers(1, 97, 50).tolist()
        out = engine.generate(params, [prompt])[0]
        assert out == _solo(module, params, prompt, 5, max_len=engine.cache_len)
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_paged_kv_quant_parity():
    """int8 KV pools (quantized k/v blocks + per-row scale planes ride
    the rank-generic scatter/gather) decode identically to the int8
    contiguous cache."""
    cfg = LlamaConfig.tiny(vocab_size=97, kv_quant=True)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, 97, size=n).tolist() for n in (7, 12, 16)]
    outs = {}
    for paged in (False, True):
        engine = DecodeEngine(
            module, slots=2, max_new_tokens=6, prompt_buckets=(16,),
            chunk_steps=3, paged=paged,
            registry=telemetry.MetricsRegistry(),
        )
        try:
            outs[paged] = engine.generate(params, prompts)
        finally:
            engine.close()
    assert outs[True] == outs[False]


def test_paged_int4_weights_with_kv_quant():
    """The full serving quantization stack — int4 weights + int8 KV —
    composed with the paged pool: parity against the contiguous engine
    under the same quantized tree."""
    from unionml_tpu.models.quantization import (
        LLAMA_QUANT_PATTERNS,
        quantize_params,
    )

    base = LlamaConfig(
        vocab_size=97, hidden_dim=64, num_layers=2, num_heads=4,
        num_kv_heads=2, mlp_dim=128, max_len=256, rope_theta=10_000.0,
    )
    fp_params = Llama(base).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    q4 = quantize_params(fp_params, LLAMA_QUANT_PATTERNS, bits=4)
    cfg = LlamaConfig(**{
        **base.__dict__, "quantized": True, "weight_bits": 4,
        "kv_quant": True,
    })
    module = Llama(cfg)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 97, size=n).tolist() for n in (6, 11)]
    outs = {}
    for paged in (False, True):
        engine = DecodeEngine(
            module, slots=2, max_new_tokens=5, prompt_buckets=(16,),
            chunk_steps=2, paged=paged,
            registry=telemetry.MetricsRegistry(),
        )
        try:
            outs[paged] = engine.generate(q4, prompts)
        finally:
            engine.close()
    assert outs[True] == outs[False]


def test_block_geometry_unified(tiny_llama):
    """Satellite: bucket rounding no longer depends on whether a prefix
    cache is attached — a paged engine with and without one lands on
    the same bucket set, and a block-size mismatch raises."""
    module, _ = tiny_llama
    plain = _paged_engine(
        module, slots=1, max_new_tokens=4, prompt_buckets=(10, 40),
        prefill_chunk=8, kv_block_size=16,
    )
    with_cache = _paged_engine(
        module, slots=1, max_new_tokens=4, prompt_buckets=(10, 40),
        prefill_chunk=8,
        prefix_cache=RadixPrefixCache(
            block_size=16, registry=telemetry.MetricsRegistry()
        ),
    )
    try:
        assert plain.buckets == with_cache.buckets
        assert plain.cache_len == with_cache.cache_len
        assert plain._kv_block_size == with_cache._kv_block_size == 16
    finally:
        plain.close()
        with_cache.close()
    with pytest.raises(ValueError, match="block"):
        DecodeEngine(
            module, slots=1, max_new_tokens=4, prompt_buckets=(16,),
            paged=True, kv_block_size=8,
            prefix_cache=RadixPrefixCache(
                block_size=16, registry=telemetry.MetricsRegistry()
            ),
            registry=telemetry.MetricsRegistry(),
        )


def test_oversize_request_rejected_at_submit(tiny_llama):
    """A request whose worst case exceeds the whole pool can never be
    admitted: clean Overloaded at submit, nothing queued, no device
    work burned."""
    module, params = tiny_llama
    engine = _paged_engine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, kv_pool_blocks=2,  # capacity 1 block
    )
    try:
        with pytest.raises(Overloaded, match="never fit"):
            engine.generate(params, [list(range(1, 16))])
        st = engine.stats()
        assert st["robustness"]["rejected"]["pool_full"] == 1
        assert st["kv_pool"]["blocks_in_use"] == 0
    finally:
        engine.close()


def test_transient_exhaustion_parks_not_fails(tiny_llama):
    """A pool that only fits ONE resident request (capacity 2 blocks,
    2 blocks per request) serves a 6-deep stream by parking admissions
    until retirements free blocks — every request completes with
    solo-identical tokens and the pressure is visible in the flight
    recorder + alloc-failure counter. (One-resident sizing makes the
    park deterministic: any queued request overlaps the resident.)"""
    module, params = tiny_llama
    flight = telemetry.FlightRecorder()
    engine = _paged_engine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, kv_pool_blocks=3, flight=flight,
    )
    try:
        rng = np.random.default_rng(6)
        prompts = [rng.integers(1, 97, size=9).tolist() for _ in range(6)]
        outs = engine.generate(params, prompts)
        for prompt, out in zip(prompts, outs):
            assert out == _solo(module, params, prompt, 8, max_len=engine.cache_len)
        st = engine.stats()["kv_pool"]
        assert st["alloc_failures"] > 0
        pressure = [
            e for e in flight.dump() if e["kind"] == "pool_pressure"
        ]
        assert pressure and pressure[0]["reason"] == "alloc_fail"
        # every event carries the preempt-candidate field; it names the
        # oldest resident when one exists (None only in the narrow race
        # where the last resident retired with its blocks still
        # fence-deferred)
        assert all("preempt_candidate" in e for e in pressure)
        named = [e for e in pressure if e["preempt_candidate"]]
        for e in named:
            assert isinstance(e["preempt_candidate"], str)
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_pool_full_backlog_sheds_through_queue_bound(tiny_llama):
    """Under pool pressure the backlog behind a parked admission hits
    max_queue_depth and sheds with Overloaded (429) — the accepted
    requests still complete; flight analysis can tell pool-full
    (pool_pressure events) from queue-full (reject reason)."""
    module, params = tiny_llama
    engine = _paged_engine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, kv_pool_blocks=4, max_queue_depth=2,
    )
    try:
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 97, size=9).tolist() for _ in range(12)]
        shed, done = [], []
        lock = threading.Lock()

        def client(p):
            try:
                out = engine.generate(params, [p])[0]
                with lock:
                    done.append((p, out))
            except Overloaded:
                with lock:
                    shed.append(p)

        threads = [
            threading.Thread(target=client, args=(p,)) for p in prompts
        ]
        for t in threads:
            t.start()
            time.sleep(0.002)
        for t in threads:
            t.join(timeout=120)
        assert shed, "expected queue-full shedding under pool pressure"
        assert done, "expected accepted requests to complete"
        for p, out in done:
            assert out == _solo(module, params, p, 8, max_len=engine.cache_len)
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_table_growth_across_max_new_boundary(tiny_llama):
    """Decode crosses several block boundaries (small blocks, long
    generation): the table grows one block at a time from the
    admission-time reservation and the tokens stay solo-identical."""
    module, params = tiny_llama
    engine = _paged_engine(
        module, slots=2, max_new_tokens=24, prompt_buckets=(8,),
        chunk_steps=2, kv_block_size=8,
    )
    try:
        rng = np.random.default_rng(8)
        prompt = rng.integers(1, 97, size=6).tolist()
        out = engine.generate(params, [prompt])[0]
        assert out == _solo(module, params, prompt, 24, max_len=engine.cache_len)
        st = _assert_pool_drained(engine)
        # 6-token prompt + 24 new = 30 rows -> at least 4 blocks of 8
        assert st["allocated_blocks"] >= 4
    finally:
        engine.close()


def test_no_leaked_blocks_after_abandoned_stream(tiny_llama):
    module, params = tiny_llama
    engine = _paged_engine(
        module, slots=2, max_new_tokens=32, prompt_buckets=(16,),
        chunk_steps=2,
    )
    try:
        rng = np.random.default_rng(9)
        gen = engine.generate_stream(params, rng.integers(1, 97, 8).tolist())
        next(gen)
        gen.close()  # client disconnect mid-decode
        _assert_pool_drained(engine)
        # the engine still serves correctly afterwards
        prompt = rng.integers(1, 97, size=10).tolist()
        assert engine.generate(params, [prompt])[0] == _solo(
            module, params, prompt, 32, max_len=engine.cache_len
        )
        _assert_pool_drained(engine)
    finally:
        engine.close()


@pytest.mark.chaos
def test_no_leaked_blocks_after_recovery(tiny_llama):
    """PR 3's chaos harness against the paged pool: an injected OOM
    fails the poisoned batch, the pool resets with the rebuilt state,
    survivors and follow-ups decode correctly, occupancy returns to
    baseline."""
    module, params = tiny_llama
    fi = FaultInjector()
    engine = _paged_engine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, fault_injector=fi,
    )
    try:
        engine.warmup(params)
        rng = np.random.default_rng(10)
        fi.arm("engine.dispatch", exc=xla_oom_error())
        results = []
        lock = threading.Lock()

        def run(p):
            try:
                out = engine.generate(params, [p])[0]
                with lock:
                    results.append((p, out))
            except Exception:
                pass  # the poisoned batch

        threads = [
            threading.Thread(
                target=run, args=(rng.integers(1, 97, 9).tolist(),)
            )
            for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert engine.stats()["robustness"]["recoveries"] >= 1
        for p, out in results:
            assert out == _solo(module, params, p, 8, max_len=engine.cache_len)
        prompt = rng.integers(1, 97, size=10).tolist()
        assert engine.generate(params, [prompt])[0] == _solo(
            module, params, prompt, 8, max_len=engine.cache_len
        )
        st = _assert_pool_drained(engine)
        # the registry exposition carries the pool series at zero
        text = engine._registry.exposition()
        assert "unionml_kv_pool_blocks_in_use" in text
        assert st["blocks_in_use"] == 0
    finally:
        engine.close()


def test_lease_pinned_prefix_blocks_survive_pool_pressure(tiny_llama):
    """While a paged admission's lease pins host prefix blocks, budget
    pressure evicts around them — the leased path's rows stay live and
    the warm run stays token-identical."""
    module, params = tiny_llama
    cache = RadixPrefixCache(
        block_size=16, max_bytes=64 << 10,
        registry=telemetry.MetricsRegistry(),
    )
    engine = _paged_engine(
        module, slots=2, max_new_tokens=5, prompt_buckets=(48,),
        chunk_steps=3, prefix_cache=cache,
    )
    try:
        rng = np.random.default_rng(11)
        shared = rng.integers(1, 97, 32).tolist()
        prompt = shared + rng.integers(1, 97, 8).tolist()
        cold = engine.generate(params, [prompt])[0]
        # hold a lease (an in-flight admission's pin), then pressure the
        # budget with distinct prompts until evictions happen
        lease = cache.match(prompt)
        assert lease.n_blocks >= 2
        for _ in range(12):
            engine.generate(
                params, [rng.integers(1, 97, 40).tolist()]
            )
        assert cache.stats()["evictions"] > 0
        for node_rows in lease.rows:
            assert node_rows is not None  # never reclaimed under lease
        lease.release()
        warm = engine.generate(params, [prompt])[0]
        assert warm == cold
        _assert_pool_drained(engine)
    finally:
        engine.close()


def test_paged_refuses_speculative(tiny_llama):
    module, _ = tiny_llama
    draft = Llama(LlamaConfig.tiny(vocab_size=97))
    with pytest.raises(ValueError, match="paged"):
        DecodeEngine(
            module, slots=1, max_new_tokens=4, prompt_buckets=(16,),
            draft_module=draft, paged=True,
            registry=telemetry.MetricsRegistry(),
        )


def test_paged_eos_retires_and_frees(tiny_llama):
    """eos retirement mid-chunk: the slot's blocks free behind the
    dispatch fence and the pool drains."""
    module, params = tiny_llama
    rng = np.random.default_rng(12)
    prompts = [rng.integers(1, 97, size=7).tolist() for _ in range(3)]
    outs = {}
    for paged in (False, True):
        engine = DecodeEngine(
            module, slots=2, max_new_tokens=16, prompt_buckets=(8,),
            chunk_steps=4, eos_id=11, paged=paged,
            registry=telemetry.MetricsRegistry(),
        )
        try:
            outs[paged] = engine.generate(params, prompts)
            if paged:
                _assert_pool_drained(engine)
        finally:
            engine.close()
    assert outs[True] == outs[False]
