"""Weight-only int8 serving quantization: the quantized model must load
converted fp weights and generate nearly the same tokens."""

import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.models.quantization import (
    LLAMA_QUANT_PATTERNS,
    QuantizedDenseGeneral,
    quantize_params,
)


def test_quantized_dense_matches_fp_geometry():
    # qkv geometry: axis=-1, tuple features
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 16))
    qd = QuantizedDenseGeneral(features=(4, 8), axis=-1, dtype=jnp.float32)
    params = qd.init(jax.random.PRNGKey(1), x)
    assert params["params"]["kernel_q"].shape == (16, 32)
    assert qd.apply(params, x).shape == (2, 5, 4, 8)
    # o geometry: contract (-2, -1)
    y = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 4, 8))
    od = QuantizedDenseGeneral(features=16, axis=(-2, -1), dtype=jnp.float32)
    oparams = od.init(jax.random.PRNGKey(3), y)
    assert oparams["params"]["kernel_q"].shape == (32, 16)
    assert od.apply(oparams, y).shape == (2, 5, 16)


def test_quantize_params_structure_matches_quantized_module():
    cfg = LlamaConfig.tiny(vocab_size=97)
    fp = Llama(cfg)
    qm = Llama(dataclasses.replace(cfg, quantized=True))
    tokens = jnp.zeros((1, 8), jnp.int32)
    fp_params = fp.init(jax.random.PRNGKey(0), tokens)["params"]
    q_template = qm.init(jax.random.PRNGKey(0), tokens)["params"]
    converted = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)
    a = jax.tree_util.tree_structure(q_template)
    b = jax.tree_util.tree_structure(converted)
    assert a == b, f"{a}\n!=\n{b}"
    # shapes and dtypes line up leaf-for-leaf
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_flatten_with_path(q_template)[0],
        jax.tree_util.tree_flatten_with_path(converted)[0],
    ):
        assert la.shape == lb.shape, (pa, la.shape, lb.shape)


def test_quantized_generation_close_to_fp():
    cfg = LlamaConfig.tiny(vocab_size=97)
    fp = Llama(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, 97, size=(2, 6)), jnp.int32
    )
    fp_params = fp.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    q_params = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)
    qm = Llama(dataclasses.replace(cfg, quantized=True))

    # logits agree closely (int8 per-channel weight-only error)
    lf = fp.apply({"params": fp_params}, tokens)
    lq = qm.apply({"params": q_params}, tokens)
    denom = float(jnp.max(jnp.abs(lf))) or 1.0
    rel = float(jnp.max(jnp.abs(lf - lq))) / denom
    assert rel < 0.06, f"relative logit error {rel}"
    # greedy top-1 agreement on most positions
    agree = float(jnp.mean(
        (jnp.argmax(lf, -1) == jnp.argmax(lq, -1)).astype(jnp.float32)
    ))
    assert agree >= 0.75, f"top-1 agreement {agree}"

    # generation runs end to end through the quantized path
    gen = make_generator(qm, max_new_tokens=4, max_len=32)
    out = np.asarray(gen(q_params, tokens))
    assert out.shape == (2, 4)


def test_quantized_generation_under_tensor_parallel():
    """The 8B serving config needs TP + int8 together: quantized params
    shard under LLAMA_QUANT_PARTITION_RULES and generation matches the
    unsharded quantized run."""
    from unionml_tpu.models import LLAMA_QUANT_PARTITION_RULES
    from unionml_tpu.parallel import ShardingConfig, shard_pytree

    cfg = LlamaConfig.tiny(vocab_size=97)
    fp = Llama(cfg)
    fp_params = fp.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    q_params = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)
    qm = Llama(dataclasses.replace(cfg, quantized=True))

    prompt = jnp.asarray([[7, 3, 9, 2]], jnp.int32)
    gen = make_generator(qm, max_new_tokens=4, max_len=32)
    ref = np.asarray(gen(q_params, prompt))

    scfg = ShardingConfig(data=-1, tensor=2, rules=LLAMA_QUANT_PARTITION_RULES)
    sharded = shard_pytree(q_params, scfg)
    specs = [
        (jax.tree_util.keystr(p), tuple(l.sharding.spec))
        for p, l in jax.tree_util.tree_flatten_with_path(sharded)[0]
    ]
    # kernels AND their scales carry the tensor axis
    assert any("kernel_q" in p and "tensor" in str(s) for p, s in specs)
    assert any("scale" in p and "tensor" in str(s) for p, s in specs)
    got = np.asarray(gen(sharded, prompt))
    np.testing.assert_array_equal(got, ref)


def test_quantized_params_checkpoint_roundtrip(tmp_path):
    """Serving restart path: int8 params survive save/load bit-exactly."""
    from unionml_tpu.checkpoint.pytree_io import load_pytree, save_pytree

    cfg = LlamaConfig.tiny(vocab_size=97)
    fp = Llama(cfg)
    fp_params = fp.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    q_params = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)

    path = tmp_path / "m.utpu"
    save_pytree(q_params, {"seed": 0}, path)

    def factory(hp):
        assert hp == {"seed": 0}
        qm = Llama(dataclasses.replace(cfg, quantized=True))
        return qm.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    restored = load_pytree(path, factory)
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_flatten_with_path(q_params)[0],
        jax.tree_util.tree_flatten_with_path(restored)[0],
    ):
        assert np.asarray(la).dtype == np.asarray(lb).dtype, pa
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_quantization_halves_param_bytes():
    cfg = LlamaConfig.tiny(vocab_size=97)
    fp = Llama(cfg)
    fp_params = fp.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    q_params = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)

    def nbytes(t):
        return sum(np.asarray(l).nbytes for l in jax.tree_util.tree_leaves(t))

    # matmul weights dominate: int8 + small scales ≈ 1/4 of fp32 storage
    assert nbytes(q_params) < 0.45 * nbytes(fp_params)
