"""Training goodput accounting (unionml_tpu.goodput).

Covers the docs/observability.md "Training goodput" contract: bucket
math on a synthetic clock (attribution sums to wall time, compile
debits), the regression detector's hysteresis, straggler/skew math,
trainer + elastic-trainer integration (the preemption badput bucket),
the checkpoint save/restore instrumentation, and the SLO-watchdog
coupling through ``unionml_train_goodput_ratio``.
"""

import io

import numpy as np
import pytest

from unionml_tpu.goodput import (
    BADPUT_CAUSES,
    GoodputTracker,
    StepSkewMonitor,
    StepTimeRegressionDetector,
)
from unionml_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    TraceRecorder,
)


class FakeClock:
    """Deterministic monotonic clock for bucket-math tests."""

    def __init__(self):
        self.now = 100.0

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def make_tracker(clock=None, **kwargs):
    reg = kwargs.pop("registry", None) or MetricsRegistry()
    tracker = GoodputTracker(
        registry=reg,
        tracer=kwargs.pop("tracer", None) or TraceRecorder(registry=reg),
        flight=kwargs.pop("flight", None) or FlightRecorder(),
        clock=clock if clock is not None else FakeClock(),
        **kwargs,
    )
    return tracker, reg


# ---------------------------------------------------------- bucket math


def test_bucket_taxonomy_is_closed():
    # report() keys mirror the documented taxonomy exactly — a bucket
    # outside it could silently leak out of the attribution sum
    tracker, _ = make_tracker()
    rep = tracker.report()
    assert set(rep["badput_s"]) == set(BADPUT_CAUSES)
    assert set(rep["buckets_s"]) == {"compute", *BADPUT_CAUSES}


def test_phase_buckets_on_synthetic_clock():
    clock = FakeClock()
    tracker, reg = make_tracker(clock)
    tracker.start()
    with tracker.phase("data_wait"):
        clock.advance(2.0)
    with tracker.phase("host_to_device"):
        clock.advance(0.5)
    with tracker.phase("compute"):
        clock.advance(7.0)
    clock.advance(0.5)  # unattributed loop bookkeeping
    tracker.finish()
    rep = tracker.report()
    assert rep["wall_s"] == pytest.approx(10.0)
    assert rep["badput_s"]["data_wait"] == pytest.approx(2.0)
    assert rep["badput_s"]["host_to_device"] == pytest.approx(0.5)
    assert rep["goodput_s"] == pytest.approx(7.0)
    assert rep["goodput_ratio"] == pytest.approx(0.7)
    assert rep["unattributed_s"] == pytest.approx(0.5)
    # attribution identity: buckets + unattributed == wall, exactly
    total = sum(rep["buckets_s"].values()) + rep["unattributed_s"]
    assert total == pytest.approx(rep["wall_s"])
    assert rep["attributed_fraction"] == pytest.approx(0.95)


def test_badput_series_published():
    clock = FakeClock()
    tracker, reg = make_tracker(clock)
    tracker.start()
    with tracker.phase("checkpoint"):
        clock.advance(1.5)
    with tracker.phase("compute"):
        clock.advance(1.5)
    tracker.step_complete(3.0)
    snap = reg.snapshot()
    assert snap["unionml_train_badput_seconds_total"]["cause=checkpoint"] == (
        pytest.approx(1.5)
    )
    assert snap["unionml_train_goodput_seconds_total"][""] == pytest.approx(1.5)
    assert snap["unionml_train_goodput_ratio"][""] == pytest.approx(0.5)
    hist = snap["unionml_train_phase_ms"]["phase=checkpoint"]
    assert hist["count"] == 1


def test_unknown_phase_rejected():
    tracker, _ = make_tracker()
    with pytest.raises(ValueError, match="unknown phase"):
        tracker.phase("coffee_break")


def test_compile_debit_reclassifies_compute():
    clock = FakeClock()
    tracker, _ = make_tracker(clock)
    tracker.start()
    with tracker.phase("compute"):
        # ProgramTracker fires on_compile mid-call: 3 of these 5 seconds
        # were XLA compiling, not useful work
        clock.advance(5.0)
        tracker.note_compile_ms("trainer.step", 3000.0)
    rep = tracker.report()
    assert rep["goodput_s"] == pytest.approx(2.0)
    assert rep["badput_s"]["compile"] == pytest.approx(3.0)


def test_compile_debit_capped_at_phase_and_carried():
    clock = FakeClock()
    tracker, _ = make_tracker(clock)
    tracker.start()
    tracker.note_compile_ms("trainer.step", 4000.0)
    with tracker.phase("compute"):
        clock.advance(1.0)
    rep = tracker.report()
    # the debit can never exceed the phase it lands in; the remainder
    # waits for the next compute phase
    assert rep["goodput_s"] == pytest.approx(0.0)
    assert rep["badput_s"]["compile"] == pytest.approx(1.0)
    with tracker.phase("compute"):
        clock.advance(5.0)
    rep = tracker.report()
    assert rep["badput_s"]["compile"] == pytest.approx(4.0)
    assert rep["goodput_s"] == pytest.approx(2.0)


def test_resume_after_finish_excludes_gap():
    clock = FakeClock()
    tracker, _ = make_tracker(clock)
    tracker.start()
    with tracker.phase("compute"):
        clock.advance(4.0)
    tracker.finish()
    clock.advance(1000.0)  # the paused gap must not count as wall time
    tracker.start()
    with tracker.phase("compute"):
        clock.advance(6.0)
    tracker.finish()
    rep = tracker.report()
    assert rep["wall_s"] == pytest.approx(10.0)
    assert rep["goodput_ratio"] == pytest.approx(1.0)


def test_phase_spans_recorded_on_trainer_timeline():
    clock = FakeClock()
    reg = MetricsRegistry()
    tracer = TraceRecorder(registry=reg)
    tracker, _ = make_tracker(clock, registry=reg, tracer=tracer)
    tracker.start()
    with tracker.phase("data_wait"):
        clock.advance(1.0)
    with tracker.phase("compute"):
        clock.advance(2.0)
    tracker.finish()
    lines = tracer.export_jsonl().strip().splitlines()
    names = [line for line in lines if '"kind": "trainer"' in line]
    assert len(names) == 2
    assert any('"name": "data_wait"' in line for line in names)
    assert any('"name": "compute"' in line for line in names)


def test_timeline_rotates_onto_fresh_requests():
    # long runs record 3-4 spans per step: without rotation a 100k-step
    # run would hit TraceRecorder's per-request span cap ~1k steps in
    # and silently truncate the exported timeline
    clock = FakeClock()
    reg = MetricsRegistry()
    tracer = TraceRecorder(registry=reg)
    tracker, _ = make_tracker(
        clock, registry=reg, tracer=tracer, timeline_rotate_steps=2,
    )
    tracker.start()
    for _ in range(5):
        with tracker.phase("compute"):
            clock.advance(1.0)
        tracker.step_complete(1.0)
    tracker.finish()
    requests = [
        (rid, meta, spans)
        for rid, meta, spans in tracer._all_requests()
        if meta.get("kind") == "trainer"
    ]
    # 5 steps at rotate-every-2 → rotations after steps 2 and 4: three
    # chained requests, every span retained across them
    assert len(requests) == 3
    assert sum(len(spans) for _, _, spans in requests) == 5
    assert all(meta.get("end_s") is not None for _, meta, _ in requests)
    # attribution is unaffected by rotation
    assert tracker.report()["goodput_s"] == pytest.approx(5.0)


# ------------------------------------------------- regression detection


def test_regression_detector_hysteresis():
    det = StepTimeRegressionDetector(
        window=20, threshold=1.5, clear_threshold=1.2, consecutive=3,
        min_steps=5,
    )
    for _ in range(10):  # warmup: baseline settles at 1.0
        verdict = det.update(1.0)
        assert not verdict["anomaly"]
    assert det.baseline() == pytest.approx(1.0)

    # two anomalous steps do NOT trip the regressed state ...
    for _ in range(2):
        verdict = det.update(2.0)
        assert verdict["anomaly"] and not verdict["regressed"]
    # ... the third consecutive one does
    verdict = det.update(2.0)
    assert verdict["regressed"] and verdict["entered"]

    # inside the hysteresis band (1.2x < r < 1.5x): not anomalous, but
    # not clean enough to clear either
    for _ in range(5):
        verdict = det.update(1.3)
        assert not verdict["anomaly"] and verdict["regressed"]

    # three consecutive clean steps clear it
    det.update(1.0)
    det.update(1.0)
    verdict = det.update(1.0)
    assert verdict["cleared"] and not verdict["regressed"]
    # anomalous samples never polluted the baseline
    assert det.baseline() == pytest.approx(1.0)


def test_regression_detector_anomaly_resets_clear_streak():
    det = StepTimeRegressionDetector(
        window=20, threshold=1.5, clear_threshold=1.2, consecutive=2,
        min_steps=2,
    )
    for _ in range(5):
        det.update(1.0)
    det.update(3.0)
    det.update(3.0)
    assert det.regressed
    det.update(1.0)          # one clean step ...
    verdict = det.update(3.0)  # ... interrupted: still regressed
    assert verdict["regressed"]


def test_regression_detector_validation():
    with pytest.raises(ValueError, match="hysteresis"):
        StepTimeRegressionDetector(threshold=1.2, clear_threshold=1.2)
    with pytest.raises(ValueError):
        StepTimeRegressionDetector(window=1)


def test_step_complete_publishes_and_records_flight_events():
    flight = FlightRecorder()
    tracker, reg = make_tracker(
        flight=flight,
        detector=StepTimeRegressionDetector(
            window=10, threshold=1.5, clear_threshold=1.2, consecutive=2,
            min_steps=2,
        ),
    )
    tracker.start()
    for _ in range(5):
        tracker.step_complete(0.1)
    for _ in range(2):
        tracker.step_complete(0.5)  # 5x baseline: anomalous, then regressed
    snap = reg.snapshot()
    assert snap["unionml_train_step_anomalies_total"][""] == 2.0
    assert snap["unionml_train_step_time_ratio"][""] == pytest.approx(5.0)
    kinds = [e["kind"] for e in flight.dump()]
    assert kinds.count("step_time_anomaly") == 2
    transitions = flight.dump(kind="step_time_regression")
    assert [e["state"] for e in transitions] == ["entered"]


def test_step_complete_detect_false_keeps_sample_out_of_detector():
    # the async-dispatch trainer's window-boundary steps drain a whole
    # window of device work into one sample — fed to the detector they
    # would read as anomalies against the dispatch-scale baseline
    flight = FlightRecorder()
    tracker, reg = make_tracker(
        flight=flight,
        detector=StepTimeRegressionDetector(
            window=10, threshold=1.5, clear_threshold=1.2, consecutive=2,
            min_steps=2,
        ),
    )
    tracker.start()
    for _ in range(5):
        tracker.step_complete(0.001)  # dispatch-scale baseline
    verdict = tracker.step_complete(1.0, detect=False)  # window boundary
    assert not verdict["anomaly"] and not verdict["regressed"]
    snap = reg.snapshot()
    assert snap["unionml_train_step_anomalies_total"][""] == 0.0
    # the excluded sample neither moved the ratio gauge nor the baseline
    assert snap["unionml_train_step_time_ratio"][""] == pytest.approx(1.0)
    assert tracker.detector.baseline() == pytest.approx(0.001)
    assert not flight.dump(kind="step_time_anomaly")
    # the step itself still counts
    assert tracker.report()["steps"] == 6


# ------------------------------------------------------- straggler skew


def test_skew_monitor_names_stragglers():
    monitor = StepSkewMonitor(straggler_factor=1.5, min_skew_ms=50.0)
    sample = monitor.observe(7, [1.0, 1.01, 2.0, 0.99])
    assert sample["stragglers"] == [2]
    assert sample["skew_ms"] == pytest.approx(1000.0, rel=0.02)
    assert sample["median_ms"] == pytest.approx(1000.0, rel=0.02)


def test_skew_monitor_two_host_slice_sees_the_straggler():
    # even host counts take the LOWER middle as the median: with the
    # upper middle a 2-process slice has median == slowest, so skew is
    # always 0 and no straggler can ever trip
    monitor = StepSkewMonitor(straggler_factor=1.5, min_skew_ms=50.0)
    sample = monitor.observe(3, [1.0, 3.0])
    assert sample["median_ms"] == pytest.approx(1000.0)
    assert sample["skew_ms"] == pytest.approx(2000.0)
    assert sample["stragglers"] == [1]


def test_skew_monitor_absolute_floor_filters_jitter():
    # 2x the median but only 10 ms absolute: phantom straggler filtered
    monitor = StepSkewMonitor(straggler_factor=1.5, min_skew_ms=50.0)
    sample = monitor.observe(0, [0.010, 0.011, 0.020])
    assert sample["stragglers"] == []


def test_record_step_skew_publishes_gauges_and_flight():
    flight = FlightRecorder()
    tracker, reg = make_tracker(flight=flight)
    tracker.start()
    sample = tracker.record_step_skew(50, [1.0, 1.0, 3.0, 1.0])
    assert sample["stragglers"] == [2]
    snap = reg.snapshot()
    assert snap["unionml_train_step_skew_ms"][""] == pytest.approx(
        2000.0, rel=0.02
    )
    assert snap["unionml_train_host_step_ms"]["process=2"] == pytest.approx(
        3000.0
    )
    assert snap["unionml_train_stragglers_total"][""] == 1.0
    events = flight.dump(kind="straggler")
    assert len(events) == 1
    assert events[0]["process"] == 2 and events[0]["step"] == 50


# -------------------------------------------------- trainer integration


def _blob_problem():
    import jax.numpy as jnp

    def step(state, batch):
        x, y = batch
        w = state["w"] - 0.01 * x.T @ (x @ state["w"] - y)
        return {"w": w}, {"loss": jnp.mean((x @ state["w"] - y) ** 2)}

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64,)).astype(np.float32)
    state = {"w": jnp.zeros(4)}
    return step, state, x, y


def test_run_step_trainer_goodput_integration():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _blob_problem()
    reg = MetricsRegistry()
    tracer = TraceRecorder(registry=reg)
    tracker = GoodputTracker(
        registry=reg, tracer=tracer, flight=FlightRecorder()
    )
    run_step_trainer(
        step_fn=step, state=state, features=x, targets=y, num_epochs=2,
        batch_size=16, donate_state=False, registry=reg, goodput=tracker,
    )
    rep = tracker.report()
    assert rep["steps"] == 8
    assert rep["goodput_s"] > 0
    # the loop's wall time is essentially fully classified
    assert rep["attributed_fraction"] >= 0.9
    # the compile of the jitted step was detected and attributed
    assert rep["badput_s"]["compile"] > 0
    snap = reg.snapshot()
    assert 0.0 < snap["unionml_train_goodput_ratio"][""] <= 1.0
    # per-phase spans export like any request timeline
    jsonl = tracer.export_jsonl()
    assert '"kind": "trainer"' in jsonl and '"name": "compute"' in jsonl


def test_run_step_trainer_goodput_true_uses_shared_registry():
    from unionml_tpu import telemetry
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _blob_problem()
    reg = MetricsRegistry()
    before = telemetry.get_tracer()._all_requests()
    run_step_trainer(
        step_fn=step, state=state, features=x, targets=y,
        batch_size=16, donate_state=False, registry=reg, goodput=True,
    )
    snap = reg.snapshot()
    # goodput=True builds a tracker over the trainer's registry
    assert snap["unionml_train_goodput_seconds_total"][""] > 0
    # ... and its timeline landed on the process-global tracer
    after = telemetry.get_tracer()._all_requests()
    assert len(after) == len(before) + 1


def test_trainer_finishes_tracker_on_raising_stream():
    from unionml_tpu.execution import run_step_trainer

    step, state, _, _ = _blob_problem()
    reg = MetricsRegistry()
    tracer = TraceRecorder(registry=reg)
    tracker = GoodputTracker(
        registry=reg, tracer=tracer, flight=FlightRecorder()
    )

    def broken_stream():
        rng = np.random.default_rng(0)
        for _ in range(2):
            x = rng.normal(size=(16, 4)).astype(np.float32)
            yield x, rng.normal(size=(16,)).astype(np.float32)
        raise RuntimeError("loader died")

    with pytest.raises(RuntimeError, match="loader died"):
        run_step_trainer(
            step_fn=step, state=state, features=broken_stream(),
            donate_state=False, registry=reg, goodput=tracker,
        )
    # the timeline was finished (exported, not stuck live) and the wall
    # span froze — a retry with the same tracker excludes the gap
    assert not tracer._live
    assert tracker._t_stop is not None


def test_measure_device_time_samples_every_step():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _blob_problem()
    reg = MetricsRegistry()
    run_step_trainer(
        step_fn=step, state=state, features=x, targets=y,
        batch_size=16, donate_state=False, registry=reg,
        measure_device_time=True,
    )
    hist = reg.snapshot()["unionml_trainer_step_ms"][""]
    assert hist["count"] == 4  # one synced sample per step


def test_prefetch_phases_preserve_stream():
    from unionml_tpu.data.pipeline import prefetch_to_device

    clock = FakeClock()
    tracker, _ = make_tracker(clock)
    tracker.start()
    batches = [np.full((2, 2), float(i)) for i in range(5)]

    def slow_source():
        for b in batches:
            clock.advance(0.25)  # host starvation per batch
            yield b

    out = list(
        prefetch_to_device(slow_source(), goodput=tracker)
    )
    assert len(out) == 5
    for got, want in zip(out, batches):
        np.testing.assert_array_equal(np.asarray(got), want)
    rep = tracker.report()
    assert rep["badput_s"]["data_wait"] == pytest.approx(1.25)


# ------------------------------------------------ checkpoint instrumentation


def test_pytree_io_publishes_checkpoint_metrics():
    from unionml_tpu import telemetry
    from unionml_tpu.checkpoint import load_pytree, save_pytree

    reg = telemetry.get_registry()
    before = reg.snapshot().get("unionml_checkpoint_save_bytes_total", {})
    before_bytes = before.get("kind=pytree", 0.0)
    tree = {"w": np.arange(16, dtype=np.float32)}
    buf = io.BytesIO()
    save_pytree(tree, {"lr": 0.1}, buf)
    buf.seek(0)
    out = load_pytree(buf, lambda hp: {"w": np.zeros(16, np.float32)})
    np.testing.assert_array_equal(out["w"], tree["w"])
    snap = reg.snapshot()
    assert snap["unionml_checkpoint_save_bytes_total"]["kind=pytree"] > (
        before_bytes
    )
    assert snap["unionml_checkpoint_save_ms"]["kind=pytree"]["count"] >= 1
    assert snap["unionml_checkpoint_restore_ms"]["kind=pytree"]["count"] >= 1
    assert snap["unionml_checkpoint_restore_bytes_total"]["kind=pytree"] > 0


def test_checkpoint_manager_publishes_metrics(tmp_path):
    import jax.numpy as jnp

    from unionml_tpu.checkpoint.sharded import CheckpointManager

    reg = MetricsRegistry()
    state = {"w": jnp.arange(8, dtype=jnp.float32)}
    with CheckpointManager(tmp_path, registry=reg) as manager:
        manager.save(1, state)
        manager.wait()
        restored = manager.restore(state)
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.arange(8))
    snap = reg.snapshot()
    assert snap["unionml_checkpoint_save_ms"]["kind=sharded"]["count"] == 1
    assert snap["unionml_checkpoint_save_bytes_total"]["kind=sharded"] == 32.0
    assert snap["unionml_checkpoint_restore_ms"]["kind=sharded"]["count"] == 1
    assert snap["unionml_checkpoint_restore_bytes_total"]["kind=sharded"] == (
        32.0
    )


# --------------------------------------------- elastic trainer preemption


def test_elastic_preemption_replay_lands_in_preemption_bucket(tmp_path):
    import jax.numpy as jnp

    from unionml_tpu.elastic import Preemption, run_elastic_trainer

    def step(state, batch):
        x, y = batch
        w = state["w"] - 0.01 * x.T @ (x @ state["w"] - y)
        return {"w": w}, {}

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(10):
        x = rng.normal(size=(8, 4)).astype(np.float32)
        batches.append((x, rng.normal(size=(8,)).astype(np.float32)))

    def replayable():
        yield from batches

    def bomb(global_step):
        if global_step == 5:
            raise Preemption("simulated")

    with pytest.raises(Preemption):
        run_elastic_trainer(
            step_fn=step, state={"w": jnp.zeros(4)}, stream=replayable,
            checkpoint_dir=str(tmp_path), checkpoint_every=3,
            fault_hook=bomb, goodput=True,
        )

    reg = MetricsRegistry()
    tracker = GoodputTracker(
        registry=reg, tracer=TraceRecorder(registry=reg),
        flight=FlightRecorder(),
    )
    _, steps = run_elastic_trainer(
        step_fn=step, state={"w": jnp.zeros(4)}, stream=replayable,
        checkpoint_dir=str(tmp_path), checkpoint_every=3, goodput=tracker,
    )
    assert steps == 10
    rep = tracker.report()
    # restore + replaying the 3 consumed batches is preemption badput
    assert rep["badput_s"]["preemption"] > 0
    # the periodic saves are checkpoint badput
    assert rep["badput_s"]["checkpoint"] > 0
    assert rep["goodput_s"] > 0
    assert rep["attributed_fraction"] >= 0.9


def test_elastic_array_path_goodput(tmp_path):
    import jax.numpy as jnp

    from unionml_tpu.elastic import run_elastic_trainer

    def step(state, batch):
        x, y = batch
        w = state["w"] - 0.01 * x.T @ (x @ state["w"] - y)
        return {"w": w}, {}

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = rng.normal(size=(64,)).astype(np.float32)
    reg = MetricsRegistry()
    tracker = GoodputTracker(
        registry=reg, tracer=TraceRecorder(registry=reg),
        flight=FlightRecorder(),
    )
    _, steps = run_elastic_trainer(
        step_fn=step, state={"w": jnp.zeros(4)}, arrays=[x, y],
        checkpoint_dir=str(tmp_path), batch_size=16, checkpoint_every=2,
        goodput=tracker,
    )
    assert steps == 4
    rep = tracker.report()
    assert rep["badput_s"]["checkpoint"] > 0
    assert rep["badput_s"]["preemption"] == 0.0
    assert rep["goodput_s"] > 0
    # the checkpoint I/O series the badput bucket is attributed from
    # land in the SAME registry as the goodput series, not the global
    # one — the manager is constructed with the tracker's registry.
    # The elastic trainer defaults to the async writer single-process:
    # save_ms{kind=async} is the caller stall, commit_ms the background
    # leg (docs/observability.md "Checkpoint I/O")
    snap = reg.snapshot()
    assert snap["unionml_checkpoint_save_ms"]["kind=async"]["count"] >= 2
    assert snap["unionml_checkpoint_commit_ms"]["kind=async"]["count"] >= 2


# -------------------------------------------------------- SLO coupling


def test_goodput_collapse_breaches_gauge_objective():
    from unionml_tpu.slo import GaugeObjective, SloWatchdog

    clock = FakeClock()
    tracker, reg = make_tracker(clock)
    watchdog = SloWatchdog(
        [GaugeObjective(
            "train_goodput", "unionml_train_goodput_ratio", min_value=0.5,
        )],
        registry=reg, fast_window_s=10.0, slow_window_s=10.0,
    )
    tracker.start()
    with tracker.phase("compute"):
        clock.advance(9.0)
    with tracker.phase("data_wait"):
        clock.advance(1.0)
    tracker.step_complete(1.0)  # publishes ratio = 0.9
    report = watchdog.evaluate(now=1000.0)
    assert not report["breached"]

    with tracker.phase("data_wait"):
        clock.advance(90.0)  # input starvation: goodput collapses to 0.09
    tracker.step_complete(90.0)
    # one fast window later the healthy sample has aged out
    report = watchdog.evaluate(now=1015.0)
    assert report["breached"] == ["train_goodput"]
    snap = reg.snapshot()
    assert snap["unionml_slo_breached"]["objective=train_goodput"] == 1.0
