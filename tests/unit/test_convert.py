"""Checkpoint ingestion (models/convert.py): export→load round trips,
streamed-int8 equivalence with quantize_params, the streaming memory
bound, and the pretrained-merge helper.

Fixtures are generated locally (export_*_safetensors writes the HF
layout) — the bench environment has no network, so cross-implementation
fidelity against HF transformers' torch models is covered separately in
``test_convert_hf_parity.py``.
"""

import json
import os
import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu.models import (
    BertClassifier,
    BertConfig,
    Llama,
    LlamaConfig,
)
from unionml_tpu.models.bert import BertEncoder
from unionml_tpu.models.convert import (
    export_bert_safetensors,
    export_llama_safetensors,
    llama_config_from_hf,
    load_bert_checkpoint,
    load_llama_checkpoint,
    merge_pretrained,
)
from unionml_tpu.models.quantization import LLAMA_QUANT_PATTERNS, quantize_params


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(dtype="float32")
    params = Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return cfg, params


def _assert_trees_equal(a, b, exact=True):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = dict(jax.tree_util.tree_leaves_with_path(b))
    assert len(fa) == len(fb)
    for path, leaf in fa:
        other = fb[path]
        assert leaf.dtype == other.dtype, path
        assert leaf.shape == other.shape, path
        if exact:
            np.testing.assert_array_equal(
                np.asarray(leaf), np.asarray(other), err_msg=str(path)
            )
        else:
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(other), rtol=1e-6, err_msg=str(path)
            )


def test_llama_roundtrip_bit_exact(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    export_llama_safetensors(params, cfg, str(tmp_path))
    assert (tmp_path / "model.safetensors").exists()
    loaded, loaded_cfg = load_llama_checkpoint(
        str(tmp_path), dtype=jnp.float32, strict=True
    )
    # geometry read back from the written config.json
    assert loaded_cfg.hidden_dim == cfg.hidden_dim
    assert loaded_cfg.num_kv_heads == cfg.num_kv_heads
    _assert_trees_equal(params, loaded)


def test_llama_roundtrip_multishard(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    written = export_llama_safetensors(
        params, cfg, str(tmp_path), max_shard_bytes=200_000
    )
    assert len(written) > 1
    index = json.loads((tmp_path / "model.safetensors.index.json").read_text())
    assert set(index["weight_map"].values()) == {
        os.path.basename(p) for p in written
    }
    loaded, _ = load_llama_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    _assert_trees_equal(params, loaded)


def test_llama_tied_lm_head_fallback(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    export_llama_safetensors(params, cfg, str(tmp_path), tie_lm_head=True)
    loaded, _ = load_llama_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(loaded["lm_head"]["kernel"]),
        np.asarray(params["embed"]["embedding"]).T,
    )


def test_llama_streamed_int8_matches_quantize_params(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    export_llama_safetensors(params, cfg, str(tmp_path))
    streamed, _ = load_llama_checkpoint(str(tmp_path), cfg, quantize=True)
    direct, _ = load_llama_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    reference = quantize_params(direct, LLAMA_QUANT_PATTERNS)
    # norm scales / embed stay float: the streamed path casts them to the
    # serving dtype, so compare them non-exactly and the int8 leaves exactly
    ref_flat = dict(jax.tree_util.tree_leaves_with_path(reference))
    for path, leaf in jax.tree_util.tree_leaves_with_path(streamed):
        ref = ref_flat[path]
        if leaf.dtype == jnp.int8 or str(path[-1]) in ("['scale']",):
            np.testing.assert_array_equal(
                np.asarray(leaf), np.asarray(ref), err_msg=str(path)
            )
        else:
            np.testing.assert_allclose(
                np.asarray(leaf, np.float32), np.asarray(ref, np.float32),
                rtol=1e-2, err_msg=str(path),
            )
    # and the quantized tree actually loads into the quantized module
    qcfg = LlamaConfig.tiny(quantized=True)
    logits = Llama(qcfg).apply(
        {"params": streamed}, jnp.zeros((1, 4), jnp.int32)
    )
    assert logits.shape == (1, 4, cfg.vocab_size)


def test_streaming_memory_bound(tmp_path):
    """Peak host staging memory stays ~one tensor, not the checkpoint."""
    cfg = LlamaConfig.tiny(
        vocab_size=2048, hidden_dim=256, num_layers=6, num_heads=8,
        num_kv_heads=4, mlp_dim=1024, dtype="float32",
    )
    params = Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    export_llama_safetensors(params, cfg, str(tmp_path))
    total = sum(
        leaf.size * 4 for leaf in jax.tree_util.tree_leaves(params)
    )
    largest = max(
        leaf.size * 4 for leaf in jax.tree_util.tree_leaves(params)
    )
    del params
    tracemalloc.start()
    load_llama_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # a transform makes up to ~3 transient copies of ONE tensor; holding
    # the whole checkpoint host-side would show ~total
    assert total > 4 * largest, "fixture too small to discriminate"
    assert peak < max(4 * largest, total // 2), (
        f"peak host staging {peak} vs checkpoint {total}"
    )


def test_missing_tensor_is_loud(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    export_llama_safetensors(params, cfg, str(tmp_path))
    bigger = LlamaConfig.tiny(num_layers=3)
    with pytest.raises(KeyError, match="missing"):
        load_llama_checkpoint(str(tmp_path), bigger)


def test_strict_rejects_unconsumed_tensors(tiny_llama, tmp_path):
    cfg, params = tiny_llama
    export_llama_safetensors(params, cfg, str(tmp_path))
    from safetensors.numpy import save_file

    save_file(
        {"model.rotary.inv_freq": np.zeros(4, np.float32)},
        str(tmp_path / "extra.safetensors"),
    )
    os.remove(tmp_path / "model.safetensors.index.json") if (
        tmp_path / "model.safetensors.index.json"
    ).exists() else None
    with pytest.raises(KeyError, match="does not consume"):
        load_llama_checkpoint(str(tmp_path), cfg, strict=True)
    loaded, _ = load_llama_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    _assert_trees_equal(params, loaded)


def test_llama_config_from_hf_mapping():
    cfg = llama_config_from_hf(
        {
            "vocab_size": 128256, "hidden_size": 4096,
            "num_hidden_layers": 32, "num_attention_heads": 32,
            "num_key_value_heads": 8, "intermediate_size": 14336,
            "rope_theta": 500000.0, "max_position_embeddings": 131072,
        },
        max_len=8192, quantized=True,
    )
    assert cfg.num_kv_heads == 8
    assert cfg.max_len == 8192  # override wins over the HF value
    assert cfg.quantized


def test_bert_roundtrip_and_merge(tmp_path):
    cfg = BertConfig.tiny()
    module = BertClassifier(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    types = jnp.zeros((1, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), toks, token_type_ids=types)[
        "params"
    ]
    export_bert_safetensors(params, cfg, str(tmp_path))
    loaded, loaded_cfg = load_bert_checkpoint(str(tmp_path))
    assert loaded_cfg.hidden_dim == cfg.hidden_dim
    merged = merge_pretrained(params, loaded)
    # encoder and pooler come from the checkpoint...
    _assert_trees_equal(merged["encoder"], params["encoder"])
    _assert_trees_equal(merged["pooler"], params["pooler"])
    # ...and the classification head keeps its fresh init
    np.testing.assert_array_equal(
        np.asarray(merged["head"]["kernel"]),
        np.asarray(params["head"]["kernel"]),
    )
    # the merged tree runs
    out = module.apply({"params": merged}, toks, token_type_ids=types)
    assert out.shape == (1, cfg.num_classes)


def test_bert_encoder_key_empty_roots_tree(tmp_path):
    cfg = BertConfig.tiny()
    enc = BertEncoder(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = enc.init(
        jax.random.PRNGKey(0), toks, token_type_ids=jnp.zeros((1, 8), jnp.int32)
    )["params"]
    export_bert_safetensors(params, cfg, str(tmp_path), encoder_key="")
    loaded, _ = load_bert_checkpoint(str(tmp_path), cfg, encoder_key="")
    _assert_trees_equal(params, loaded, exact=False)


def test_bert_prefixed_checkpoint_names(tmp_path):
    """Task-model checkpoints carry a ``bert.`` prefix — detected."""
    cfg = BertConfig.tiny()
    module = BertClassifier(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = module.init(
        jax.random.PRNGKey(0), toks, token_type_ids=jnp.zeros((1, 8), jnp.int32)
    )["params"]
    export_bert_safetensors(params, cfg, str(tmp_path))
    from safetensors.numpy import load_file, save_file

    tensors = load_file(str(tmp_path / "model.safetensors"))
    save_file(
        {f"bert.{k}": v for k, v in tensors.items()},
        str(tmp_path / "model.safetensors"),
    )
    loaded, _ = load_bert_checkpoint(str(tmp_path), cfg)
    _assert_trees_equal(loaded["encoder"], params["encoder"])


def test_merge_pretrained_rejects_unknown_and_mismatched(tmp_path):
    base = {"a": {"w": np.zeros((2, 2))}}
    with pytest.raises(KeyError, match="no counterpart"):
        merge_pretrained(base, {"b": {"w": np.zeros((2, 2))}})
    with pytest.raises(ValueError, match="shape"):
        merge_pretrained(base, {"a": {"w": np.zeros((3, 2))}})


# ---------------------------------------------------------------------------
# Mixtral (MoE) mapping


@pytest.fixture(scope="module")
def tiny_moe():
    cfg = LlamaConfig.tiny(num_experts=4, num_selected=2, dtype="float32")
    params = Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return cfg, params


def test_moe_roundtrip_bit_exact(tiny_moe, tmp_path):
    cfg, params = tiny_moe
    export_llama_safetensors(params, cfg, str(tmp_path))
    loaded, loaded_cfg = load_llama_checkpoint(
        str(tmp_path), dtype=jnp.float32, strict=True
    )
    assert loaded_cfg.num_experts == 4 and loaded_cfg.num_selected == 2
    _assert_trees_equal(params, loaded)
    # the router must stay fp32 even under a bf16 serving load
    bf16, _ = load_llama_checkpoint(str(tmp_path), cfg)
    assert bf16["block_0"]["moe"]["router_kernel"].dtype == jnp.float32
    assert bf16["block_0"]["moe"]["w_gate"].dtype == jnp.bfloat16


def test_moe_streamed_int8_matches_quantize_params(tiny_moe, tmp_path):
    cfg, params = tiny_moe
    export_llama_safetensors(params, cfg, str(tmp_path))
    streamed, _ = load_llama_checkpoint(str(tmp_path), cfg, quantize=True)
    reference = quantize_params(params, LLAMA_QUANT_PATTERNS)
    moe_s = streamed["block_0"]["moe"]
    moe_r = reference["block_0"]["moe"]
    for name in ("w_gate", "w_up", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(moe_s[f"{name}_q"]), np.asarray(moe_r[f"{name}_q"])
        )
        np.testing.assert_array_equal(
            np.asarray(moe_s[f"{name}_scale"]),
            np.asarray(moe_r[f"{name}_scale"]),
        )
    # and the quantized tree actually runs
    qcfg = LlamaConfig.tiny(num_experts=4, num_selected=2, quantized=True)
    logits = Llama(qcfg).apply(
        {"params": streamed}, jnp.zeros((1, 4), jnp.int32)
    )
    assert logits.shape == (1, 4, cfg.vocab_size)


# ---------------------------------------------------------------------------
# ViT mapping


def test_vit_roundtrip_bit_exact(tmp_path):
    from unionml_tpu.models import ViT, ViTConfig
    from unionml_tpu.models.convert import (
        export_vit_safetensors,
        load_vit_checkpoint,
    )

    cfg = ViTConfig.tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "qkv_bias": True, "dtype": "float32"})
    module = ViT(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    )["params"]
    export_vit_safetensors(params, cfg, str(tmp_path))
    loaded, loaded_cfg = load_vit_checkpoint(
        str(tmp_path), num_classes=cfg.num_classes, dtype=jnp.float32,
        image_size=cfg.image_size, patch_size=cfg.patch_size,
    )
    assert loaded_cfg.qkv_bias and loaded_cfg.hidden_dim == cfg.hidden_dim
    _assert_trees_equal(params, loaded)


def test_vit_biasfree_roundtrip(tmp_path):
    """The zoo's default (qkv_bias=False) ViT round-trips too — bias
    specs are emitted only when the config carries biases."""
    from unionml_tpu.models import ViT, ViTConfig
    from unionml_tpu.models.convert import (
        export_vit_safetensors,
        load_vit_checkpoint,
    )

    cfg = ViTConfig.tiny()
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": "float32"})
    module = ViT(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    )["params"]
    export_vit_safetensors(params, cfg, str(tmp_path))
    loaded, loaded_cfg = load_vit_checkpoint(
        str(tmp_path), num_classes=cfg.num_classes, dtype=jnp.float32,
        image_size=cfg.image_size, patch_size=cfg.patch_size,
    )
    assert not loaded_cfg.qkv_bias
    _assert_trees_equal(params, loaded)


def test_llama_export_preserves_rope_scaling_and_eps(tmp_path):
    cfg = LlamaConfig.tiny(
        rope_scaling=(8.0, 1.0, 4.0, 32), norm_eps=1e-6, dtype="float32"
    )
    params = Llama(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    export_llama_safetensors(params, cfg, str(tmp_path))
    _, loaded_cfg = load_llama_checkpoint(str(tmp_path), dtype=jnp.float32)
    assert loaded_cfg.rope_scaling == (8.0, 1.0, 4.0, 32)
    assert loaded_cfg.norm_eps == 1e-6
