"""Attention-family and MoE op tests: every implementation is checked
against the full-score XLA reference (SURVEY.md §4.3 strategy: numerical
equivalence on the CPU-simulated mesh)."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.ops.attention import attention, blockwise_attention, mha_reference
from unionml_tpu.ops.flash_attention import flash_attention
from unionml_tpu.ops.ring_attention import ring_attention
from unionml_tpu.ops.ulysses import ulysses_attention
from unionml_tpu.parallel import make_mesh


def make_qkv(batch=2, seq=64, q_heads=4, kv_heads=4, dim=16, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (batch, seq, q_heads, dim), dtype)
    k = jax.random.normal(ks[1], (batch, seq, kv_heads, dim), dtype)
    v = jax.random.normal(ks[2], (batch, seq, kv_heads, dim), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("kv_heads", [4, 2])
def test_blockwise_matches_reference(causal, kv_heads):
    q, k, v = make_qkv(kv_heads=kv_heads)
    ref = mha_reference(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_ragged_kv():
    # kv length not a multiple of the block size
    q, k, v = make_qkv(seq=50)
    ref = mha_reference(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = make_qkv(seq=128, dim=32)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_kv=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gqa_and_ragged():
    q, k, v = make_qkv(seq=72, q_heads=4, kv_heads=2, dim=32)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_kv_valid_start_matches_masked_reference():
    """Per-row left-pad masking (generation prefill): kv positions below
    kv_valid_start are invisible; fully-padded query rows return zeros."""
    q, k, v = make_qkv(batch=3, seq=96, q_heads=4, kv_heads=2, dim=32)
    pads = jnp.asarray([0, 17, 90], jnp.int32)

    from unionml_tpu.ops.attention import _repeat_kv

    kr, vr = _repeat_kv(k, 4), _repeat_kv(v, 4)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, kr) * (32 ** -0.5)
    qpos = jnp.arange(96)[None, None, :, None]
    kpos = jnp.arange(96)[None, None, None, :]
    mask = (kpos <= qpos) & (kpos >= pads[:, None, None, None])
    p = jax.nn.softmax(jnp.where(mask, s, -1e30), axis=-1)
    rowvalid = (jnp.arange(96)[None, :] >= pads[:, None])[:, :, None, None]
    ref = jnp.einsum("bhqk,bkhd->bqhd", p, vr) * rowvalid

    out = flash_attention(
        q, k, v, causal=True, kv_valid_start=pads, block_q=32, block_kv=32
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # pad = 0 everywhere must equal the plain causal kernel exactly
    out0 = flash_attention(
        q, k, v, causal=True, kv_valid_start=jnp.zeros(3, jnp.int32),
        block_q=32, block_kv=32,
    )
    plain = flash_attention(q, k, v, causal=True, block_q=32, block_kv=32)
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(plain))


def test_flash_gradients_match_reference():
    q, k, v = make_qkv(seq=64, dim=16)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=32, block_kv=32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_matches_reference(causal):
    from unionml_tpu.ops.fused_attention import fused_attention

    q, k, v = make_qkv(seq=72, dim=32)  # ragged: 72 not tile-aligned
    ref = mha_reference(q, k, v, causal=causal)
    out = fused_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_fused_gradients_match_reference_gqa():
    from unionml_tpu.ops.fused_attention import fused_attention

    q, k, v = make_qkv(seq=72, q_heads=4, kv_heads=2, dim=32)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_fused(q, k, v):
        return jnp.sum(fused_attention(q, k, v, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fused):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_fused_rejects_long_sequences():
    from unionml_tpu.ops.fused_attention import fused_attention

    q, k, v = make_qkv(batch=1, seq=2048, q_heads=1, dim=8)
    with pytest.raises(ValueError, match="short sequences"):
        fused_attention(q, k, v)


def test_fused_rejects_unequal_lengths():
    from unionml_tpu.ops.fused_attention import fused_attention

    q, k, v = make_qkv(seq=32, dim=16)
    with pytest.raises(ValueError, match="q_len == kv_len"):
        fused_attention(q[:, :16], k, v)


def test_flash_causal_cross_length_bottom_right_aligned():
    """Decode convention: with q_len < kv_len the queries are the LAST
    q_len positions — flash must match the reference's alignment in both
    forward and gradients."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(ks[0], (2, 8, 4, 16))
    k = jax.random.normal(ks[1], (2, 40, 4, 16))
    v = jax.random.normal(ks[2], (2, 40, 4, 16))
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_kv=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16, block_kv=16) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_flash_gradients_gqa_cross_length():
    # KV prefix longer than q (decode-style): GQA group-sum must reshape
    # with kv_len, not q_len
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (1, 16, 4, 8))
    k = jax.random.normal(ks[1], (1, 32, 2, 8))
    v = jax.random.normal(ks[2], (1, 32, 2, 8))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=False, block_q=16, block_kv=16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=False) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_flash_gradients_gqa_ragged():
    # GQA (group-summed dk/dv) + ragged tail blocks in the Pallas backward
    q, k, v = make_qkv(seq=72, q_heads=4, kv_heads=2, dim=32)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=False) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=False, block_q=32, block_kv=32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh({"sequence": 8})
    q, k, v = make_qkv(seq=64)
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gqa():
    mesh = make_mesh({"sequence": 8})
    q, k, v = make_qkv(seq=64, q_heads=8, kv_heads=2)
    ref = mha_reference(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gradients_match_reference():
    # sequence-parallel TRAINING: the backward differentiates through the
    # ppermute rotation (AD of collectives)
    mesh = make_mesh({"sequence": 8})
    q, k, v = make_qkv(seq=64)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True, block_size=8) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_ulysses_gradients_match_reference():
    mesh = make_mesh({"sequence": 4, "tensor": 2})
    q, k, v = make_qkv(seq=32, q_heads=8, kv_heads=8)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_uly(q, k, v):
        return jnp.sum(
            ulysses_attention(q, k, v, mesh, axis="sequence", causal=True) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(causal):
    mesh = make_mesh({"sequence": 4, "tensor": 2})
    q, k, v = make_qkv(seq=32, q_heads=8, kv_heads=8)
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, axis="sequence", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_attention_dispatcher():
    q, k, v = make_qkv(seq=32)
    for impl in ("xla", "blockwise", "flash", "fused", "auto"):
        out = attention(q, k, v, impl=impl, causal=True)
        assert out.shape == q.shape
    with pytest.raises(ValueError, match="unknown attention impl"):
        attention(q, k, v, impl="nope")


def test_attention_auto_routes_by_length():
    # short → fused; long or cross-length → flash (both numerically checked
    # against the reference elsewhere; here we check the routing decision
    # by matching each candidate's output exactly)
    from unionml_tpu.ops.flash_attention import flash_attention
    from unionml_tpu.ops.fused_attention import fused_attention

    q, k, v = make_qkv(seq=48, dim=16)
    np.testing.assert_array_equal(
        np.asarray(attention(q, k, v, impl="auto")),
        np.asarray(fused_attention(q, k, v)),
    )
    ql, kl, vl = make_qkv(batch=1, seq=1056, q_heads=2, kv_heads=2, dim=16)
    np.testing.assert_array_equal(
        np.asarray(attention(ql, kl, vl, impl="auto")),
        np.asarray(flash_attention(ql, kl, vl)),
    )


# ------------------------------------------------------------------ MoE


def test_moe_forward_and_balance():
    from unionml_tpu.ops.moe import MoEMlp, top_k_routing

    module = MoEMlp(num_experts=4, num_selected=2, hidden_dim=32, model_dim=16,
                    dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = module.init(jax.random.PRNGKey(1), x)
    out, aux = module.apply(params, x)
    assert out.shape == x.shape
    assert np.isfinite(float(aux))

    logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
    weights, indices, aux = top_k_routing(logits, 2)
    assert weights.shape == (64, 2) and indices.shape == (64, 2)
    np.testing.assert_allclose(np.asarray(weights.sum(-1)), 1.0, atol=1e-5)


def test_moe_differentiable():
    from unionml_tpu.ops.moe import MoEMlp

    module = MoEMlp(num_experts=4, num_selected=1, hidden_dim=16, model_dim=8,
                    dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 8))
    params = module.init(jax.random.PRNGKey(1), x)

    def loss(p):
        out, aux = module.apply(p, x)
        return jnp.sum(out**2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    assert any(np.any(np.asarray(l) != 0) for l in leaves)


def test_causal_decode_alignment_bottom_right():
    """q_len < kv_len causal (KV-cache decode): queries are the LAST q_len
    positions. Regression: the mask offset was applied to kv instead of q,
    masking everything for the final query row."""
    q, k, v = make_qkv(seq=16)
    full = mha_reference(q, k, v, causal=True)
    # last 4 queries against the full KV prefix must match the full result
    tail = mha_reference(q[:, -4:], k, v, causal=True)
    np.testing.assert_allclose(np.asarray(full[:, -4:]), np.asarray(tail), rtol=1e-5, atol=1e-5)
    # single-token decode: must attend to ALL kv (not be fully masked)
    one = mha_reference(q[:, -1:], k, v, causal=True)
    np.testing.assert_allclose(np.asarray(full[:, -1:]), np.asarray(one), rtol=1e-5, atol=1e-5)
    # blockwise agrees with the same convention
    bw = blockwise_attention(q[:, -4:], k, v, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(tail), np.asarray(bw), rtol=1e-4, atol=1e-4)


def test_partition_rule_tuple_entries_and_fallbacks():
    """Tuple spec entries shard one dim over multiple axes; axes missing
    from the mesh or not dividing the dim are dropped, not erroring."""
    from unionml_tpu.parallel import PartitionRule, ShardingConfig

    cfg = ShardingConfig(
        data=2, fsdp=2, tensor=2,
        rules=(
            PartitionRule(r"big/kernel", (("fsdp", "tensor"), None)),
            PartitionRule(r"odd/kernel", (None, "tensor")),
            PartitionRule(r"gone/kernel", ("expert", None)),
        ),
    )
    big = np.zeros((8, 4))
    spec = cfg.param_pspec("big/kernel", big)
    assert spec == jax.sharding.PartitionSpec(("fsdp", "tensor"), None)
    odd = np.zeros((4, 3))  # 3 not divisible by tensor=2 → dropped
    assert cfg.param_pspec("odd/kernel", odd) == jax.sharding.PartitionSpec(None, None)
    gone = np.zeros((4, 4))  # expert axis not in mesh → dropped
    assert cfg.param_pspec("gone/kernel", gone) == jax.sharding.PartitionSpec(None, None)


# --------------------------------------------------------------------- #
# ring flash attention (Pallas local compute + lse merge)
# --------------------------------------------------------------------- #

from unionml_tpu.ops.ring_attention import ring_flash_attention  # noqa: E402


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_reference(causal):
    q, k, v = make_qkv(seq=32)
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_flash_attention(q, k, v, mesh, causal=causal, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_ring_flash_gqa():
    q, k, v = make_qkv(seq=32, q_heads=4, kv_heads=2)
    mesh = make_mesh({"sequence": 2}, devices=jax.devices()[:2])
    ref = mha_reference(q, k, v, causal=True)
    out = ring_flash_attention(q, k, v, mesh, causal=True, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


@pytest.mark.parametrize("kv_heads", [4, 2])
def test_ring_flash_gradients_match_reference(kv_heads):
    q, k, v = make_qkv(seq=16, q_heads=4, kv_heads=kv_heads, dim=8)
    mesh = make_mesh({"sequence": 2}, devices=jax.devices()[:2])

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_flash_attention(q, k, v, mesh, causal=True, block_size=8) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=3e-4)


def test_quantized_cache_attention_blockwise_matches_full():
    """The online-softmax block scan (long-context VMEM guard) must equal
    the single-fusion form up to float reduction order — including GQA,
    masked (bias) slots, per-position scales, and a non-dividing block."""
    import numpy as np

    from unionml_tpu.ops.attention import quantized_cache_attention

    rng = np.random.default_rng(0)
    B, S, Hq, Hk, D, Q = 2, 100, 4, 2, 16, 3
    q = jnp.asarray(rng.normal(size=(B, Q, Hq, D)), jnp.bfloat16)
    k_q = jnp.asarray(rng.integers(-127, 128, (B, S, Hk, D)), jnp.int8)
    v_q = jnp.asarray(rng.integers(-127, 128, (B, S, Hk, D)), jnp.int8)
    k_s = jnp.asarray(rng.uniform(0.5, 2.0, (B, S, Hk)), jnp.float32) / 127
    v_s = jnp.asarray(rng.uniform(0.5, 2.0, (B, S, Hk)), jnp.float32) / 127
    visible = jnp.asarray(rng.random((B, 1, Q, S)) < 0.8)
    bias = jnp.where(visible, 0.0, -1e30)
    # every query row must see at least one key
    bias = bias.at[..., 0].set(0.0)

    full = quantized_cache_attention(
        q, k_q, v_q, k_s, v_s, bias=bias, block_threshold=4096
    )
    blocked = quantized_cache_attention(
        q, k_q, v_q, k_s, v_s, bias=bias, block_threshold=32  # 100 -> 4 blocks, padded
    )
    np.testing.assert_allclose(
        np.asarray(full, np.float32), np.asarray(blocked, np.float32),
        atol=2e-2, rtol=2e-2,
    )
    # bias=None long path (decode without mask)
    full_nb = quantized_cache_attention(
        q, k_q, v_q, k_s, v_s, block_threshold=4096
    )
    blocked_nb = quantized_cache_attention(
        q, k_q, v_q, k_s, v_s, block_threshold=25
    )
    np.testing.assert_allclose(
        np.asarray(full_nb, np.float32), np.asarray(blocked_nb, np.float32),
        atol=2e-2, rtol=2e-2,
    )
