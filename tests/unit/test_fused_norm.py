"""Fused LayerNorm/RMSNorm kernels: values and grads must match the
plain XLA implementations (interpret mode on CPU), including through the
model-level switch (same params, same outputs)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.ops.fused_norm import (
    fused_add_layer_norm,
    fused_layer_norm,
    fused_rms_norm,
)


def _ref_ln(x, g, b, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, -1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), -1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _ref_rms(x, g, eps=1e-5):
    x32 = x.astype(jnp.float32)
    return (x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps) * g).astype(x.dtype)


@pytest.mark.parametrize("shape", [(4, 17, 128), (256, 256)])
def test_layer_norm_values_and_grads(shape):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape[-1]) + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=shape[-1]), jnp.float32)

    np.testing.assert_allclose(
        np.asarray(fused_layer_norm(x, g, b)), np.asarray(_ref_ln(x, g, b)),
        rtol=1e-5, atol=1e-5,
    )

    def loss_fused(x, g, b):
        return jnp.sum(jnp.sin(fused_layer_norm(x, g, b)))

    def loss_ref(x, g, b):
        return jnp.sum(jnp.sin(_ref_ln(x, g, b)))

    for got, want in zip(
        jax.grad(loss_fused, argnums=(0, 1, 2))(x, g, b),
        jax.grad(loss_ref, argnums=(0, 1, 2))(x, g, b),
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_rms_norm_values_and_grads():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(6, 9, 128)), jnp.float32)
    g = jnp.asarray(rng.normal(size=128) + 1.0, jnp.float32)

    np.testing.assert_allclose(
        np.asarray(fused_rms_norm(x, g)), np.asarray(_ref_rms(x, g)),
        rtol=1e-5, atol=1e-5,
    )
    got = jax.grad(lambda x, g: jnp.sum(jnp.cos(fused_rms_norm(x, g))), argnums=(0, 1))(x, g)
    want = jax.grad(lambda x, g: jnp.sum(jnp.cos(_ref_rms(x, g))), argnums=(0, 1))(x, g)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_add_layer_norm_matches_unfused():
    """(s, y) = add+LN fused == the two-op reference, values and grads —
    including the residual gradient folding (ds flows to both inputs)."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 16, 128)), jnp.float32)
    r = jnp.asarray(rng.normal(size=(8, 16, 128)), jnp.float32)
    g = jnp.asarray(rng.normal(size=128) + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=128), jnp.float32)

    s, y = fused_add_layer_norm(x, r, g, b)
    np.testing.assert_allclose(np.asarray(s), np.asarray(x + r), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_ref_ln(x + r, g, b)), rtol=1e-5, atol=1e-5
    )

    def loss_fused(x, r, g, b):
        s, y = fused_add_layer_norm(x, r, g, b)
        return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s))  # both outputs used

    def loss_ref(x, r, g, b):
        s = x + r
        return jnp.sum(jnp.sin(_ref_ln(s, g, b))) + jnp.sum(jnp.cos(s))

    for got, want in zip(
        jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, r, g, b),
        jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, g, b),
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_bf16_inputs_fp32_statistics():
    """bf16 activations: statistics in fp32, output cast once."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(32, 256)), jnp.bfloat16)
    g = jnp.asarray(rng.normal(size=256) + 1.0, jnp.float32)
    b = jnp.zeros(256, jnp.float32)
    got = fused_layer_norm(x, g, b)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(_ref_ln(x, g, b), np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_vit_fused_norm_matches_xla_impl():
    """The model-level switch: same params, same loss/grads either way."""
    from unionml_tpu.models import ViT, ViTConfig

    rng = np.random.default_rng(4)
    images = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
    # fp32 end to end so the comparison isolates the kernel math from
    # bf16 rounding-order differences
    cfg_x = ViTConfig(**{**ViTConfig.tiny().__dict__, "dtype": "float32"})
    cfg_f = ViTConfig(**{**cfg_x.__dict__, "norm_impl": "fused"})
    params = ViT(cfg_x).init(jax.random.PRNGKey(0), images)["params"]

    out_x = ViT(cfg_x).apply({"params": params}, images)
    out_f = ViT(cfg_f).apply({"params": params}, images)  # same param tree
    np.testing.assert_allclose(np.asarray(out_x), np.asarray(out_f), rtol=1e-4, atol=1e-4)

    def loss(cfg):
        def f(p):
            return jnp.sum(ViT(cfg).apply({"params": p}, images) ** 2)
        return jax.grad(f)(params)

    gx, gf = loss(cfg_x), loss(cfg_f)
    for a, b in zip(jax.tree_util.tree_leaves(gx), jax.tree_util.tree_leaves(gf)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), rtol=1e-3, atol=1e-3
        )


def test_llama_fused_rms_norm_matches():
    """RMSNorm impl switch on the Llama stack: same logits."""
    from unionml_tpu.models import Llama, LlamaConfig

    cfg_x = LlamaConfig.tiny(vocab_size=64)
    cfg_f = LlamaConfig(**{**cfg_x.__dict__, "norm_impl": "fused"})
    toks = jnp.asarray(np.arange(1, 17).reshape(2, 8), jnp.int32)
    params = Llama(cfg_x).init(jax.random.PRNGKey(0), toks)["params"]
    out_x = Llama(cfg_x).apply({"params": params}, toks)
    out_f = Llama(cfg_f).apply({"params": params}, toks)
    np.testing.assert_allclose(
        np.asarray(out_x), np.asarray(out_f), rtol=2e-2, atol=2e-2
    )


def test_non_divisible_row_counts():
    """Rows not divisible by the 256-row block (e.g. ViT's 64*197): the
    trailing partial block must not corrupt values or dgamma/dbeta."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(197 * 3, 128)), jnp.float32)
    g = jnp.asarray(rng.normal(size=128) + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=128), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fused_layer_norm(x, g, b)), np.asarray(_ref_ln(x, g, b)),
        rtol=1e-5, atol=1e-5,
    )
    got = jax.grad(lambda *a: jnp.sum(jnp.sin(fused_layer_norm(*a))), argnums=(0, 1, 2))(x, g, b)
    want = jax.grad(lambda *a: jnp.sum(jnp.sin(_ref_ln(*a))), argnums=(0, 1, 2))(x, g, b)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4)


def test_add_variant_non_divisible_rows():
    """The fused add+LN kernel on rows that leave a trailing partial
    block (the ViT-B production shape, B*197): both outputs and all
    grads must survive the masking."""
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(197, 128)), jnp.float32)
    r = jnp.asarray(rng.normal(size=(197, 128)), jnp.float32)
    g = jnp.asarray(rng.normal(size=128) + 1.0, jnp.float32)
    b = jnp.asarray(rng.normal(size=128), jnp.float32)
    s, y = fused_add_layer_norm(x, r, g, b)
    np.testing.assert_allclose(np.asarray(s), np.asarray(x + r), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(_ref_ln(x + r, g, b)), rtol=1e-5, atol=1e-5
    )

    def loss_fused(x, r, g, b):
        s, y = fused_add_layer_norm(x, r, g, b)
        return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s))

    def loss_ref(x, r, g, b):
        s = x + r
        return jnp.sum(jnp.sin(_ref_ln(s, g, b))) + jnp.sum(jnp.cos(s))

    for got, want in zip(
        jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, r, g, b),
        jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, g, b),
    ):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)
