"""Autoregressive generation tests: the scan-decode path must match
step-free full-recompute decoding, and left-padded prompts must generate
exactly what their unpadded versions do (pad masking + logical RoPE
positions)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator, make_lm_predictor


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = module.init(jax.random.PRNGKey(0), tokens)["params"]
    return module, params


def _reference_greedy(module, params, prompt, n_new):
    """Decode by re-running the full (growing) sequence each step — no
    cache, no scan. The gold standard the fused path must match."""
    toks = np.asarray(prompt)
    out = []
    for _ in range(n_new):
        logits = module.apply({"params": params}, jnp.asarray(toks))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1).astype(jnp.int32))
        out.append(nxt)
        toks = np.concatenate([toks, nxt[:, None]], axis=1)
    return np.stack(out, axis=1)


def test_scan_decode_matches_full_recompute(tiny_llama):
    module, params = tiny_llama
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(1, 97, size=(2, 6)), jnp.int32
    )
    gen = make_generator(module, max_new_tokens=5, max_len=32)
    got = np.asarray(gen(params, prompt))
    want = _reference_greedy(module, params, prompt, 5)
    np.testing.assert_array_equal(got, want)


def test_flash_prefill_matches_cached_prefill(tiny_llama):
    """``prefill_impl="flash"`` (monolithic long-prompt prefill through
    the Pallas kernel — no [B,H,S,max_len] score buffer) must generate
    the cached path's tokens on a ragged LEFT-PADDED batch. Exact here
    (fp32 interpret on CPU); on TPU the kernel's bf16 p@v cast makes it
    tolerance-equivalent, like the training flash path (measured 1.43-
    1.62x prefill speedup at 4k — BASELINE.md round 5)."""
    module, params = tiny_llama
    cfg_f = dataclasses.replace(module.config, prefill_impl="flash")
    fmod = Llama(cfg_f)
    rng = np.random.default_rng(3)
    toks = jnp.asarray(rng.integers(1, 97, size=(3, 24)), jnp.int32)
    mask = jnp.asarray(
        [[True] * 24, [False] * 5 + [True] * 19, [False] * 20 + [True] * 4]
    )
    toks = jnp.where(mask, toks, 0)

    gen_c = make_generator(module, max_new_tokens=6, max_len=64)
    gen_f = make_generator(fmod, max_new_tokens=6, max_len=64)
    out_c = np.asarray(gen_c(params, toks, prompt_mask=mask))
    out_f = np.asarray(gen_f(params, toks, prompt_mask=mask))
    np.testing.assert_array_equal(out_c, out_f)

    # a CHUNKED prefill under the flash config must not take the flash
    # path (the tail no longer covers the whole history) — still exact
    gen_fc = make_generator(fmod, max_new_tokens=6, max_len=64, prefill_chunk=8)
    np.testing.assert_array_equal(
        np.asarray(gen_fc(params, toks, prompt_mask=mask)), out_c
    )

    # composes with the int8 KV cache: flash prefill reads the EXACT
    # fresh k/v (decode still reads the quantized cache), so tokens may
    # differ from the cached path — deterministic and well-formed
    cfg_q = dataclasses.replace(cfg_f, kv_quant=True)
    gen_q = make_generator(Llama(cfg_q), max_new_tokens=6, max_len=64)
    out_q = np.asarray(gen_q(params, toks, prompt_mask=mask))
    np.testing.assert_array_equal(
        out_q, np.asarray(gen_q(params, toks, prompt_mask=mask))
    )
    assert out_q.shape == out_c.shape and (out_q < 97).all()

    # the prefix-cache build is the other monolithic full prefill: its
    # flash-built cache must match the cached-impl build (layer i's
    # attention output feeds layer i+1's k/v, so this checks the whole
    # stack, not just the write path)
    from unionml_tpu.models.generate import make_prefix_cache

    prefix = rng.integers(1, 97, size=12).tolist()
    pc_c = make_prefix_cache(module, params, prefix_tokens=prefix, max_len=64)
    pc_f = make_prefix_cache(fmod, params, prefix_tokens=prefix, max_len=64)
    for lc, lf in zip(pc_c.cache, pc_f.cache):
        for bc, bf in zip(lc, lf):
            # a few bf16 ulps: the two attention algorithms round
            # differently into the bf16 residual stream from layer 1 on
            np.testing.assert_allclose(
                np.asarray(bc, np.float32), np.asarray(bf, np.float32),
                atol=6e-2,
            )


def test_left_padded_prompts_match_unpadded(tiny_llama):
    module, params = tiny_llama
    rng = np.random.default_rng(1)
    p1 = rng.integers(1, 97, size=(1, 4)).astype(np.int32)
    p2 = rng.integers(1, 97, size=(1, 7)).astype(np.int32)

    gen7 = make_generator(module, max_new_tokens=4, max_len=32)
    # unpadded references, one at a time
    ref1 = np.asarray(gen7(params, jnp.asarray(p1)))
    ref2 = np.asarray(gen7(params, jnp.asarray(p2)))

    # batched with left-padding to 7 + mask
    batch = np.zeros((2, 7), np.int32)
    mask = np.zeros((2, 7), bool)
    batch[0, 3:] = p1[0]
    mask[0, 3:] = True
    batch[1, :] = p2[0]
    mask[1, :] = True
    got = np.asarray(
        gen7(params, jnp.asarray(batch), jax.random.PRNGKey(0), jnp.asarray(mask))
    )
    np.testing.assert_array_equal(got[0], ref1[0])
    np.testing.assert_array_equal(got[1], ref2[0])


def test_eos_freezes_sequence(tiny_llama):
    module, params = tiny_llama
    prompt = jnp.asarray([[5, 9, 11]], jnp.int32)
    gen = make_generator(module, max_new_tokens=6, max_len=32)
    plain = np.asarray(gen(params, prompt))[0]
    # use the first generated token as the eos id: everything after must pad
    eos = int(plain[0])
    gen_eos = make_generator(module, max_new_tokens=6, max_len=32, eos_id=eos, pad_id=0)
    got = np.asarray(gen_eos(params, prompt))[0]
    assert got[0] == eos
    assert np.all(got[1:] == 0)


def test_sampling_is_deterministic_per_key_and_varies_across_keys(tiny_llama):
    module, params = tiny_llama
    prompt = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    gen = make_generator(module, max_new_tokens=8, max_len=32, temperature=1.0, top_k=20)
    a = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    b = np.asarray(gen(params, prompt, jax.random.PRNGKey(7)))
    c = np.asarray(gen(params, prompt, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_sampling_without_key_rejected_and_mask_without_cache_rejected(tiny_llama):
    module, params = tiny_llama
    gen = make_generator(module, max_new_tokens=2, max_len=16, temperature=0.7)
    with pytest.raises(ValueError, match="PRNG key"):
        gen(params, jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(ValueError, match="kv_mask requires a KV cache"):
        module.apply(
            {"params": params}, jnp.zeros((1, 4), jnp.int32),
            kv_mask=jnp.ones((1, 4), bool),
        )


def test_lm_predictor_batch_bucketing(tiny_llama):
    module, params = tiny_llama

    class S:
        params = None

    s = S()
    s.params = params
    predictor = make_lm_predictor(
        module, max_new_tokens=2, max_len=64, bucket_lens=(16, 8)  # unsorted on purpose
    )
    # 3 prompts pad to a batch of 4 internally; results per row still exact
    out = predictor(s, [[1, 2], [3, 4, 5], [6]])
    assert len(out) == 3
    gen = make_generator(module, max_new_tokens=2, max_len=64)
    ref = np.asarray(gen(params, jnp.asarray([[0, 0, 0, 0, 0, 0, 1, 2]], jnp.int32),
                         None, jnp.asarray([[False] * 6 + [True] * 2])))
    np.testing.assert_array_equal(np.asarray(out[0]), ref[0])


def test_lm_predictor_sizes_cache_per_bucket(tiny_llama, monkeypatch):
    # decode attention reads the whole cache each step: the predictor must
    # build one generator per bucket with cache = bucket + max_new_tokens,
    # not one full-cfg.max_len cache for everything (measured ~4x p50)
    module, params = tiny_llama
    from unionml_tpu.models import generate as gen_mod

    seen = []
    real = gen_mod.make_generator

    def spy(mod, **kwargs):
        seen.append(kwargs["max_len"])
        return real(mod, **kwargs)

    monkeypatch.setattr(gen_mod, "make_generator", spy)
    predictor = gen_mod.make_lm_predictor(
        module, max_new_tokens=4, bucket_lens=(8, 16, 64)
    )
    assert sorted(seen) == [12, 20, 68]
    # bucketed-cache results still match a full-cache generator
    out = predictor(params, [[1, 2, 3]])
    full = real(module, max_new_tokens=4, max_len=module.config.max_len)
    ref = np.asarray(
        full(params, jnp.asarray([[0] * 5 + [1, 2, 3]], jnp.int32), None,
             jnp.asarray([[False] * 5 + [True] * 3]))
    )
    np.testing.assert_array_equal(np.asarray(out[0]), ref[0])


def test_top_p_sampling_restricts_to_nucleus(tiny_llama):
    module, params = tiny_llama
    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    # find the greedy continuation: with a tight nucleus every sampled
    # token must stay inside the few top-probability tokens
    greedy = make_generator(module, max_new_tokens=1, max_len=16)
    logits_top = int(np.asarray(greedy(params, prompt))[0, 0])

    gen = make_generator(
        module, max_new_tokens=1, max_len=16, temperature=1.0, top_p=1e-6
    )
    # top_p so tight only the argmax survives: sampling becomes greedy
    for seed in range(5):
        out = gen(params, prompt, jax.random.PRNGKey(seed))
        assert int(np.asarray(out)[0, 0]) == logits_top

    # permissive nucleus still yields valid tokens and varies across keys
    gen_loose = make_generator(
        module, max_new_tokens=4, max_len=16, temperature=1.0, top_p=0.9
    )
    outs = {
        tuple(np.asarray(gen_loose(params, prompt, jax.random.PRNGKey(s)))[0])
        for s in range(8)
    }
    assert len(outs) > 1  # actually sampling


def test_top_p_validation():
    from unionml_tpu.models import Llama, LlamaConfig

    module = Llama(LlamaConfig.tiny())
    with pytest.raises(ValueError, match="top_p"):
        make_generator(module, max_new_tokens=1, top_p=0.0)
    with pytest.raises(ValueError, match="top_p"):
        make_generator(module, max_new_tokens=1, top_p=1.5)


def test_serving_params_casts_floats_only():
    from unionml_tpu.models import serving_params

    tree = {"w": jnp.ones((2,), jnp.float32), "q": jnp.ones((2,), jnp.int8),
            "s": jnp.ones((2,), jnp.float32)}
    cast = serving_params(tree)
    assert cast["w"].dtype == jnp.bfloat16
    assert cast["s"].dtype == jnp.bfloat16
    assert cast["q"].dtype == jnp.int8


def test_serving_params_preserves_quantization_metadata():
    """Scales and the MoE router stay fp32 through the serving cast.

    The dequant contract applies the fp32 scale BEFORE the single cast
    down; bf16-rounding the scales (or the fp32 router master) would make
    quantize-then-cast disagree with the benchmarked cast-then-quantize
    order (ADVICE round 1: templates/llm_serving applies serving_params
    after quantize_params).
    """
    from unionml_tpu.models import serving_params

    tree = {
        "dense": {"kernel_q": jnp.ones((2, 2), jnp.int8),
                  "scale": jnp.ones((2,), jnp.float32)},
        "moe": {"w_gate_q": jnp.ones((2, 2, 2), jnp.int8),
                "w_gate_scale": jnp.ones((2, 2), jnp.float32),
                "router_kernel": jnp.ones((2, 4), jnp.float32)},
        "attn": {"kernel": jnp.ones((2, 2), jnp.float32)},
    }
    cast = serving_params(tree)
    assert cast["dense"]["scale"].dtype == jnp.float32
    assert cast["moe"]["w_gate_scale"].dtype == jnp.float32
    assert cast["moe"]["router_kernel"].dtype == jnp.float32
    assert cast["attn"]["kernel"].dtype == jnp.bfloat16
    assert cast["dense"]["kernel_q"].dtype == jnp.int8

    # a norm param also named "scale" has no int8 sibling -> it DOES cast
    norm_tree = {"norm": {"scale": jnp.ones((2,), jnp.float32),
                          "bias": jnp.zeros((2,), jnp.float32)}}
    assert serving_params(norm_tree)["norm"]["scale"].dtype == jnp.bfloat16
    # bare-array input (no containing dict) still casts
    assert serving_params(jnp.ones((3,), jnp.float32)).dtype == jnp.bfloat16
    # FrozenDict input is accepted
    import flax.core

    frozen = flax.core.freeze(tree)
    assert serving_params(frozen)["dense"]["scale"].dtype == jnp.float32


def test_generation_rejects_cache_overflow(tiny_llama):
    module, params = tiny_llama
    gen = make_generator(module, max_new_tokens=8, max_len=12)
    ok = gen(params, jnp.zeros((1, 4), jnp.int32))  # 4 + 8 == 12 fits
    assert ok.shape == (1, 8)
    with pytest.raises(ValueError, match="exceeds the KV cache"):
        gen(params, jnp.zeros((1, 5), jnp.int32))   # 5 + 8 > 12


def test_generation_under_tensor_parallel_sharding(tiny_llama):
    """Serving multi-chip path: params TP-sharded over the mesh, the
    jitted generate runs with GSPMD collectives, output identical to the
    unsharded run."""
    from unionml_tpu.models import LLAMA_PARTITION_RULES
    from unionml_tpu.parallel import ShardingConfig, shard_pytree

    module, params = tiny_llama
    prompt = jnp.asarray([[7, 3, 9, 2]], jnp.int32)
    gen = make_generator(module, max_new_tokens=4, max_len=32)
    ref = np.asarray(gen(params, prompt))

    cfg = ShardingConfig(data=-1, tensor=2, rules=LLAMA_PARTITION_RULES)
    sharded_params = shard_pytree(params, cfg)
    spec_leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: tuple(x.sharding.spec), sharded_params)
    )
    assert any("tensor" in str(s) for s in spec_leaves)  # actually sharded
    got = np.asarray(gen(sharded_params, prompt))
    np.testing.assert_array_equal(got, ref)


def test_flash_prefill_under_tensor_parallel_sharding(tiny_llama):
    """prefill_impl="flash" composes with TP-sharded serving params:
    GSPMD handles the Pallas prefill call without breaking compilation,
    and tokens match the unsharded flash run."""
    from unionml_tpu.models import LLAMA_PARTITION_RULES
    from unionml_tpu.parallel import ShardingConfig, shard_pytree

    module, params = tiny_llama
    fmod = Llama(dataclasses.replace(module.config, prefill_impl="flash"))
    prompt = jnp.asarray([[7, 3, 9, 2, 11, 5]], jnp.int32)
    gen = make_generator(fmod, max_new_tokens=4, max_len=32)
    ref = np.asarray(gen(params, prompt))

    cfg = ShardingConfig(data=-1, tensor=2, rules=LLAMA_PARTITION_RULES)
    got = np.asarray(gen(shard_pytree(params, cfg), prompt))
    np.testing.assert_array_equal(got, ref)


def test_remat_gradients_match_non_remat(tiny_llama):
    """remat recomputes, never changes math: grads must agree to the
    float32 reassociation floor."""
    module, params = tiny_llama
    cfg = module.config
    rm = Llama(dataclasses.replace(cfg, remat=True))
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(1, 97, size=(2, 12)), jnp.int32
    )

    def loss(m):
        def f(p):
            logits = m.apply({"params": p}, tokens)
            return jnp.mean(logits.astype(jnp.float32) ** 2)
        return f

    g_plain = jax.grad(loss(module))(params)
    g_remat = jax.grad(loss(rm))(params)
    # remat changes the graph XLA fuses, and tiny() runs bf16
    # activations (2^-8 ~ 4e-3 relative rounding): refusing vs reusing
    # an activation rounds it differently, so grad elements drift by
    # ~activation_eps * |grad| — measured up to 1.8e-4 absolute on this
    # geometry, with unbounded RELATIVE drift on near-zero elements
    # (sign flips; the old rtol=1e-5/atol=1e-6 flaked at clean HEAD).
    # atol=1e-3 is ~5x the measured bf16 floor; a real math change
    # (dropped term, wrong residual) moves grads at O(|grad|) and still
    # fails loudly.
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain), jax.tree_util.tree_leaves(g_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=1e-3)


def test_lm_predictor_ragged_prompts(tiny_llama):
    module, params = tiny_llama

    class S:  # predictor accepts raw params or state-like objects
        pass

    s = S()
    s.params = params
    predictor = make_lm_predictor(
        module, max_new_tokens=3, max_len=64, bucket_lens=(8, 16)
    )
    out = predictor(s, [[1, 2, 3], [4, 5, 6, 7, 8]])
    assert len(out) == 2 and all(len(row) == 3 for row in out)
    # per-row results equal the unpadded single-prompt generation
    gen = make_generator(module, max_new_tokens=3, max_len=64)
    ref = np.asarray(gen(params, jnp.asarray([[4, 5, 6, 7, 8]], jnp.int32)))
    np.testing.assert_array_equal(np.asarray(out[1]), ref[0])


def test_lm_predictor_warmup_compiles_all_shapes(tiny_llama):
    """warmup() pre-compiles every (bucket, power-of-two batch) executable
    so a live server never stalls a request behind a first-hit XLA
    compile (measured 17.9s p95 -> 0.3s on the 1.5B config, BASELINE.md)."""
    module, params = tiny_llama
    pred = make_lm_predictor(module, max_new_tokens=4, bucket_lens=(8, 16), max_len=32)
    n = pred.warmup(params, max_batch=4)
    assert n == 2 * 3  # buckets {8, 16} x batches {1, 2, 4}
    out = pred(params, [[1, 2, 3]])
    assert len(out) == 1 and len(out[0]) == 4


def test_warmup_rejects_unusable_bucket(tiny_llama):
    """A warmup bucket outside the usable set would silently compile the
    covering bucket instead — callers must get a ValueError, not a false
    belief that the shape was pre-compiled."""
    module, params = tiny_llama
    pred = make_lm_predictor(module, max_new_tokens=4, bucket_lens=(8, 16), max_len=32)
    with pytest.raises(ValueError, match="not in the usable bucket"):
        pred.warmup(params, max_batch=1, buckets=(64,))
    with pytest.raises(ValueError, match="empty bucket tuple"):
        pred.warmup(params, max_batch=1, buckets=())


# -- int8 KV cache -------------------------------------------------------- #


def test_kv_quant_cache_structure_and_memory():
    """kv_quant caches store int8 k/v + per-(pos, head) fp32 scales —
    about half the bytes of the bf16 form (the long-context bound)."""
    from unionml_tpu.models import init_cache

    cfg = LlamaConfig.tiny(vocab_size=97, kv_quant=True)
    cache = init_cache(cfg, batch=2, max_len=64)
    assert len(cache[0]) == 4
    k_q, v_q, k_s, v_s = cache[0]
    assert k_q.dtype == jnp.int8 and k_s.dtype == jnp.float32
    assert k_s.shape == k_q.shape[:-1]
    bf16 = init_cache(LlamaConfig.tiny(vocab_size=97), batch=2, max_len=64)
    bytes_q = sum(x.size * x.dtype.itemsize for layer in cache for x in layer)
    bytes_b = sum(x.size * x.dtype.itemsize for layer in bf16 for x in layer)
    # int8 bytes + one fp32 scale per head_dim values vs bf16: for this
    # tiny head_dim=16 that's (1 + 4/16)/2 = 0.625; at the zoo's
    # head_dim=128 it is (1 + 4/128)/2 ~ 0.516 — about half
    head_dim = cfg.head_dim
    assert bytes_q == pytest.approx((1 + 4 / head_dim) / 2 * bytes_b)


def test_kv_quant_attention_close_to_bf16_cache(tiny_llama):
    """Cached decode logits with the int8 cache stay within the int8
    grid's error of the bf16-cache logits (same params, same prompt)."""
    module, params = tiny_llama
    qcfg = dataclasses.replace(module.config, kv_quant=True)
    qmodule = Llama(qcfg)
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(1, 97, size=(2, 6)), jnp.int32
    )
    from unionml_tpu.models import init_cache

    out = {}
    for mod in (module, qmodule):
        cache = init_cache(mod.config, 2, 32)
        logits, cache = mod.apply(
            {"params": params}, prompt, cache=cache, cache_index=jnp.int32(0)
        )
        # one decode step reading the quantized prefix
        step_logits, _ = mod.apply(
            {"params": params},
            jnp.argmax(logits[:, -1:], -1).astype(jnp.int32),
            cache=cache, cache_index=jnp.int32(6),
        )
        out[mod.config.kv_quant] = np.asarray(step_logits, np.float32)
    err = np.abs(out[True] - out[False]).max()
    scale = np.abs(out[False]).max() + 1e-9
    assert err / scale < 0.03, err / scale


def test_kv_quant_generation_end_to_end(tiny_llama):
    """Full generate() + bucketed predictor run on the quantized cache;
    padding invariance holds exactly WITHIN the quantized path."""
    module, params = tiny_llama
    qmodule = Llama(dataclasses.replace(module.config, kv_quant=True))
    gen = make_generator(qmodule, max_new_tokens=5, max_len=32)
    prompt = jnp.asarray(
        np.random.default_rng(2).integers(1, 97, size=(2, 6)), jnp.int32
    )
    toks = np.asarray(gen(params, prompt))
    assert toks.shape == (2, 5)
    # greedy tokens from the quantized path agree with the exact path on
    # this tiny config (int8 KV error ~0.5% << the argmax margins here)
    exact = np.asarray(make_generator(module, max_new_tokens=5, max_len=32)(params, prompt))
    np.testing.assert_array_equal(toks, exact)

    pred = make_lm_predictor(qmodule, max_new_tokens=3, bucket_lens=(8, 16), max_len=32)
    out = pred(params, [[1, 2, 3], [4, 5, 6, 7, 8]])
    gen_ref = np.asarray(gen := make_generator(qmodule, max_new_tokens=3, max_len=64)(
        params, jnp.asarray([[4, 5, 6, 7, 8]], jnp.int32)
    ))
    np.testing.assert_array_equal(np.asarray(out[1]), gen_ref[0])


def test_chunked_prefill_matches_unchunked(tiny_llama):
    """prefill_chunk is a pure memory knob: same cache rows, same tokens
    — exactly — as one-shot prefill, including left-padded prompts and
    chunk sizes that do not divide the prompt length."""
    module, params = tiny_llama
    rng = np.random.default_rng(4)
    prompts = jnp.asarray(rng.integers(1, 97, size=(2, 12)), jnp.int32)
    want = np.asarray(
        make_generator(module, max_new_tokens=5, max_len=32)(params, prompts)
    )
    for chunk in (4, 5, 12):
        gen = make_generator(
            module, max_new_tokens=5, max_len=32, prefill_chunk=chunk
        )
        np.testing.assert_array_equal(np.asarray(gen(params, prompts)), want)
    # left-padded rows through the chunked path
    mask = jnp.asarray([[False] * 3 + [True] * 9, [True] * 12])
    padded = jnp.where(mask, prompts, 0)
    gen = make_generator(module, max_new_tokens=5, max_len=32, prefill_chunk=4)
    got = np.asarray(gen(params, padded, prompt_mask=mask))
    unchunked = np.asarray(
        make_generator(module, max_new_tokens=5, max_len=32)(
            params, padded, prompt_mask=mask
        )
    )
    np.testing.assert_array_equal(got, unchunked)


# -- shared-prefix (system prompt) serving -------------------------------- #


def test_prefix_cache_matches_concatenated_generation(tiny_llama):
    """Prefix-cached generation == prepending the prefix to every prompt,
    exactly (greedy), including left-padded rows and a chunked prefix
    build."""
    from unionml_tpu.models.generate import make_prefix_cache

    module, params = tiny_llama
    rng = np.random.default_rng(6)
    prefix = rng.integers(1, 97, 10).tolist()
    prompts = rng.integers(1, 97, (2, 6)).astype(np.int32)

    ref_gen = make_generator(module, max_new_tokens=5, max_len=64)
    cat = np.concatenate([np.tile(prefix, (2, 1)), prompts], axis=1)
    ref = np.asarray(ref_gen(params, jnp.asarray(cat, jnp.int32)))

    pc = make_prefix_cache(module, params, prefix, max_len=64)
    gen = make_generator(module, max_new_tokens=5, max_len=64, prefix_len=10)
    got = np.asarray(gen(params, jnp.asarray(prompts), prefix_cache=pc))
    np.testing.assert_array_equal(got, ref)

    # left-padded prompt rows: the reference is the LEFT-padded
    # concatenation (the plain generator's contract — pads first)
    mask = np.ones((2, 6), bool)
    mask[0, :2] = False
    padded = prompts.copy()
    padded[0, :2] = 0
    cat_p = np.zeros((2, 16), np.int32)
    cat_m = np.zeros((2, 16), bool)
    cat_p[0, 2:12], cat_p[0, 12:] = prefix, prompts[0, 2:]
    cat_m[0, 2:] = True
    cat_p[1, :10], cat_p[1, 10:] = prefix, prompts[1]
    cat_m[1, :] = True
    ref_p = np.asarray(
        ref_gen(params, jnp.asarray(cat_p), None, jnp.asarray(cat_m))
    )
    got_p = np.asarray(
        gen(params, jnp.asarray(padded), None, jnp.asarray(mask), prefix_cache=pc)
    )
    np.testing.assert_array_equal(got_p, ref_p)

    # chunked prefix build (non-dividing chunk) fills the same rows
    pc_chunked = make_prefix_cache(module, params, prefix, max_len=64, prefill_chunk=4)
    got_c = np.asarray(gen(params, jnp.asarray(prompts), prefix_cache=pc_chunked))
    np.testing.assert_array_equal(got_c, ref)


def test_prefix_cache_validations(tiny_llama):
    from unionml_tpu.models.generate import make_prefix_cache

    module, params = tiny_llama
    gen = make_generator(module, max_new_tokens=2, max_len=32, prefix_len=4)
    with pytest.raises(ValueError, match="prefix_cache must be passed"):
        gen(params, jnp.zeros((1, 4), jnp.int32))
    plain = make_generator(module, max_new_tokens=2, max_len=32)
    pc = make_prefix_cache(module, params, [1, 2, 3, 4], max_len=32)
    with pytest.raises(ValueError, match="prefix_cache must be passed"):
        plain(params, jnp.zeros((1, 4), jnp.int32), None, None, pc)
    with pytest.raises(ValueError, match="no cache room"):
        make_prefix_cache(module, params, list(range(1, 33)), max_len=32)


def test_lm_predictor_system_prefix(tiny_llama):
    """system_prefix through the bucketed predictor: per-row outputs equal
    prepending the prefix; the prefix cache is built once per params and
    reused across calls/buckets."""
    from unionml_tpu.models import generate as gen_mod

    module, params = tiny_llama
    rng = np.random.default_rng(7)
    prefix = rng.integers(1, 97, 8).tolist()

    calls = []
    real = gen_mod.make_prefix_cache

    def spy(*args, **kwargs):
        calls.append(kwargs.get("max_len"))
        return real(*args, **kwargs)

    gen_mod.make_prefix_cache = spy
    try:
        pred = gen_mod.make_lm_predictor(
            module, max_new_tokens=3, bucket_lens=(8, 16), max_len=64,
            system_prefix=prefix,
        )
        out = pred(params, [[5, 6, 7], [9, 10, 11, 12]])
        out2 = pred(params, [[5, 6, 7]])
    finally:
        gen_mod.make_prefix_cache = real
    assert len(calls) == 1  # memoized per (state, bucket)

    full = make_generator(module, max_new_tokens=3, max_len=64)
    for row, prompt in zip(out, [[5, 6, 7], [9, 10, 11, 12]]):
        ref = np.asarray(
            full(params, jnp.asarray([prefix + prompt], jnp.int32))
        )
        np.testing.assert_array_equal(np.asarray(row), ref[0])
    assert out2[0] == out[0]


def test_lm_predictor_system_prefix_memoizes_for_lora_state(tiny_llama):
    """The prefix memo keys on the STATE object: a LoRATrainState resolves
    to a freshly-merged param tree every call, so an id(params) key would
    re-prefill per request (the bug this test pins)."""
    from unionml_tpu.models import create_lora_train_state
    from unionml_tpu.models import generate as gen_mod

    module, params = tiny_llama
    lora_module = Llama(dataclasses.replace(module.config, lora_rank=2))
    state = create_lora_train_state(
        lora_module, jnp.zeros((1, 8), jnp.int32), base_params=params
    )

    calls = []
    real = gen_mod.make_prefix_cache

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    gen_mod.make_prefix_cache = spy
    try:
        pred = gen_mod.make_lm_predictor(
            lora_module, max_new_tokens=2, bucket_lens=(8,), max_len=32,
            system_prefix=[1, 2, 3],
        )
        first = pred(state, [[5, 6]])
        second = pred(state, [[5, 6]])
    finally:
        gen_mod.make_prefix_cache = real
    assert len(calls) == 1, "prefix re-prefilled per request for a LoRA state"
    assert first == second


def test_system_prefix_memo_warns_on_rewrapped_state():
    """Re-wrapping the same weight buffers in a fresh state object
    violates the memo's identity contract — the predictor must say so
    instead of silently re-prefilling the prefix per request. (The
    framework logger is propagate=False with a stream handler bound at
    import time, so attach a recording handler instead of capturing
    streams.)"""
    import logging

    from unionml_tpu._logging import logger as framework_logger

    cfg = LlamaConfig.tiny(vocab_size=53)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    predict = make_lm_predictor(
        module, max_new_tokens=4, bucket_lens=(8,), system_prefix=[5, 6, 7]
    )
    messages = []
    handler = logging.Handler()
    handler.emit = lambda record: messages.append(record.getMessage())
    framework_logger.addHandler(handler)
    try:
        predict(params, [[1, 2, 3]])
        assert not any("rebuilt" in m for m in messages)
        predict(dict(params), [[1, 2, 3]])  # same buffers, new wrapper
        assert any("rebuilt" in m for m in messages)
    finally:
        framework_logger.removeHandler(handler)
