"""Per-tenant usage metering tests (docs/observability.md "Usage
metering & cost attribution").

The contract under test: the ledger's per-tenant attributed
device-seconds and tokens explain >= 95% of engine totals under a
mixed multi-tenant stream (the attribution identity), exported
tenant-label cardinality is bounded by top_k + 1 no matter how many
distinct tenants appear, prefix-cache savings are credited to the
LEASING tenant, KV block-second hold windows close on abandon and
recovery, the tenant header round-trips through all three transports
(with a 422 boundary for hostile values), and the whole subsystem is
an off-switch away from zero overhead.
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import telemetry
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import FaultInjector, xla_oom_error
from unionml_tpu.serving.prefix_cache import RadixPrefixCache
from unionml_tpu.serving.usage import (
    DEFAULT_TENANT,
    OTHER_TENANT,
    UsageLedger,
    current_tenant,
    tenant_scope,
    validate_tenant,
)


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


@pytest.fixture
def trained_model(model):
    model.train(
        hyperparameters={"max_iter": 500}, sample_frac=1.0, random_state=123
    )
    return model


def _tenant_labels(registry):
    """Distinct tenant= label values across every exported
    unionml_tenant_* series (the cardinality the rollup bounds)."""
    values = set()
    for line in registry.exposition().splitlines():
        if line.startswith("unionml_tenant_") and 'tenant="' in line:
            values.add(line.split('tenant="', 1)[1].split('"', 1)[0])
    return values


# ---------------------------------------------------------------- unit


def test_validate_tenant_defaults_and_limits():
    assert validate_tenant(None) == DEFAULT_TENANT
    assert validate_tenant("") == DEFAULT_TENANT
    assert validate_tenant("acme-prod") == "acme-prod"
    assert validate_tenant("x" * 64) == "x" * 64
    with pytest.raises(ValueError, match="longer than 64"):
        validate_tenant("x" * 65)
    with pytest.raises(ValueError, match="non-printable"):
        validate_tenant("a\x00b")
    with pytest.raises(ValueError, match="non-printable"):
        validate_tenant("a\nb")


def test_tenant_scope_nesting_and_default():
    assert current_tenant() == DEFAULT_TENANT
    with tenant_scope("outer"):
        assert current_tenant() == "outer"
        with tenant_scope("inner"):
            assert current_tenant() == "inner"
        assert current_tenant() == "outer"
        with tenant_scope(None):  # no-op scope: outer stays visible
            assert current_tenant() == "outer"
    assert current_tenant() == DEFAULT_TENANT


def test_rollup_topk_other_bounds():
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=2)
    for tenant in ("a", "b", "c", "d", "other"):
        ledger.finish_request(tenant, queue_ms=1.0)
    # sticky slots for the first two; everyone else (and a tenant
    # literally named "other") rolls up
    assert ledger.label_for("a") == "a"
    assert ledger.label_for("b") == "b"
    assert ledger.label_for("c") == OTHER_TENANT
    assert ledger.label_for("other") == OTHER_TENANT
    labels = _tenant_labels(registry)
    assert labels == {"a", "b", OTHER_TENANT}
    assert len(labels) <= ledger.top_k + 1
    report = ledger.report()
    assert report["distinct_tenants"] == 5
    # exact vectors are still per-tenant (JSON, not label values)
    assert set(report["tenants"]) == {"a", "b", "c", "d", "other"}


def test_attribute_splits_by_token_share():
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    ledger.attribute(
        {"a": 3, "b": 1}, device_s=4.0, flops=8.0, slot_steps=8.0
    )
    report = ledger.report()
    assert report["tenants"]["a"]["device_seconds"] == pytest.approx(3.0)
    assert report["tenants"]["b"]["device_seconds"] == pytest.approx(1.0)
    assert report["tenants"]["a"]["flops"] == pytest.approx(6.0)
    assert report["tenants"]["b"]["flops"] == pytest.approx(2.0)
    assert report["tenants"]["a"]["decode_tokens"] == 3
    assert report["totals"]["device_seconds"] == pytest.approx(4.0)
    assert report["attribution"]["device_seconds_coverage"] == 1.0
    # an ownerless dispatch still counts toward the totals (the honest
    # identity denominator), attributed to nobody
    ledger.attribute({}, device_s=1.0)
    report = ledger.report()
    assert report["totals"]["device_seconds"] == pytest.approx(5.0)
    assert report["attribution"]["device_seconds_coverage"] == pytest.approx(
        4.0 / 5.0
    )


def test_capacity_headroom_estimate():
    ledger = UsageLedger(registry=telemetry.MetricsRegistry(), top_k=4)
    ledger.attribute({"a": 6, "b": 2}, device_s=1.0, slot_steps=16.0)
    cap = ledger.report()["capacity"]
    assert cap["slot_steps"] == 16.0
    assert cap["per_tenant"]["a"] == pytest.approx(6 / 16)
    assert cap["per_tenant"]["b"] == pytest.approx(2 / 16)
    assert cap["headroom"] == pytest.approx(0.5)


def test_drop_causes_are_a_closed_label_set():
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    ledger.record_drop("a", "abandoned")
    ledger.record_drop("a", "SomeExoticException")  # free-form -> error
    text = registry.exposition()
    assert 'cause="abandoned"' in text
    assert 'cause="error"' in text
    assert "SomeExoticException" not in text


def test_reset_keeps_label_slots_sticky():
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=1)
    ledger.finish_request("a")
    ledger.reset_stats()
    assert ledger.report()["tenants"] == {}
    # the slot survives the reset: a new tenant still rolls up, so the
    # exported series stay monotonic per label value
    ledger.finish_request("b")
    assert ledger.label_for("a") == "a"
    assert ledger.label_for("b") == OTHER_TENANT


def test_max_tenants_overflow_accumulates_into_other():
    ledger = UsageLedger(
        registry=telemetry.MetricsRegistry(), top_k=1, max_tenants=1
    )
    ledger.finish_request("a")
    ledger.finish_request("b")
    report = ledger.report()
    assert set(report["tenants"]) == {"a"}
    assert report["other"]["requests"] == 1


def test_max_tenants_bounds_remembered_ids():
    """A client minting a fresh (valid) tenant id per request must not
    grow host memory or the debug body: past max_tenants, unseen ids
    resolve to `other` without being remembered anywhere."""
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=2, max_tenants=4)
    for i in range(50):
        tenant = f"hostile-{i}"
        ledger.finish_request(tenant)
        ledger.attribute({tenant: 1}, device_s=0.01, slot_steps=2.0)
    report = ledger.report()
    assert len(ledger._labels) <= 4
    assert len(report["tenants"]) <= 4
    assert len(report["capacity"]["per_tenant"]) <= 4 + 1  # + other key
    assert report["distinct_tenants"] <= 4  # saturates at the bound
    # usage past the cap still lands in the `other` vector + label
    assert report["other"]["requests"] == 46
    assert _tenant_labels(registry) <= {
        "hostile-0", "hostile-1", OTHER_TENANT,
    }


def test_capacity_gauge_sums_rolled_up_tenants():
    """Several tenants sharing the `other` label must SUM into the
    capacity-fraction gauge, not overwrite each other."""
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=1)
    ledger.attribute(
        {"a": 100, "b": 50, "c": 10}, device_s=1.0, slot_steps=200.0
    )
    ledger.report()  # refreshes the gauges
    frac = {}
    for line in registry.exposition().splitlines():
        if line.startswith("unionml_tenant_capacity_fraction{"):
            label = line.split('tenant="', 1)[1].split('"', 1)[0]
            frac[label] = float(line.rsplit(" ", 1)[1])
    assert frac["a"] == pytest.approx(0.5)
    # b (0.25) and c (0.05) share `other`: the gauge carries their sum
    assert frac[OTHER_TENANT] == pytest.approx(0.3)


def test_capacity_counts_only_capacity_bearing_dispatches():
    """Prefill harvests and batcher rows pass slot_steps=0 — they are
    not decode capacity, so they must not inflate used slot-steps."""
    ledger = UsageLedger(registry=telemetry.MetricsRegistry(), top_k=4)
    ledger.attribute({"a": 1}, device_s=0.5)          # prefill-style
    ledger.attribute({"a": 4}, device_s=1.0, slot_steps=8.0)
    cap = ledger.report()["capacity"]
    assert cap["per_tenant"]["a"] == pytest.approx(4 / 8)
    assert cap["headroom"] == pytest.approx(0.5)


def test_lint_guard_flags_request_derived_labels(tmp_path):
    """The label-cardinality guard: a unionml_* metric taking a
    tenant/rid label OUTSIDE the ledger module fails lint."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "lint_basics",
        Path(__file__).resolve().parents[2] / "scripts" / "lint_basics.py",
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    pkg = tmp_path / "unionml_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        'R.counter("unionml_rogue_total", "x", ("engine", "tenant"))\n'
    )
    problems = lint.check_label_cardinality(pkg)
    assert len(problems) == 1 and "tenant" in problems[0]
    (pkg / "clean.py").write_text(
        'R.counter("unionml_ok_total", "x", ("engine", "reason"))\n'
    )
    assert len(lint.check_label_cardinality(pkg)) == 1  # clean file ok
    # the real ledger module is exempt (and the repo itself is clean)
    repo_pkg = Path(lint.ROOT) / "unionml_tpu"
    assert lint.check_label_cardinality(repo_pkg) == []


# ---------------------------------------------------- engine integration


def test_attribution_identity_mixed_three_tenant_stream(tiny_llama):
    """The acceptance identity: per-tenant attributed device-seconds
    and tokens sum to >= 95% of engine totals under a concurrent
    3-tenant stream with an uneven mix."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=8)
    engine = DecodeEngine(
        module, slots=4, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, registry=registry,
        tracer=telemetry.TraceRecorder(), usage=ledger,
    )
    try:
        engine.warmup(params)
        engine.reset_stats()
        rng = np.random.default_rng(0)
        mix = ["a", "a", "a", "b", "b", "c"]
        n_req = 24
        prompts = [rng.integers(1, 97, 8).tolist() for _ in range(n_req)]

        def client(idx0):
            for i in range(idx0, n_req, 4):
                with tenant_scope(mix[i % len(mix)]):
                    engine.generate(params, [prompts[i]])

        threads = [
            threading.Thread(target=client, args=(c,)) for c in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        report = ledger.report()
        # every request ran to its token budget (no eos): exact counts
        counts = {t: mix.count(t) * n_req // len(mix) for t in "abc"}
        for tenant, n in counts.items():
            vec = report["tenants"][tenant]
            assert vec["requests"] == n
            assert vec["decode_tokens"] == n * 8
            assert vec["device_seconds"] > 0
            assert vec["queue_ms"] >= 0
        assert report["attribution"]["device_seconds_coverage"] >= 0.95
        assert report["attribution"]["token_coverage"] >= 0.95
        assert report["totals"]["tokens"] == n_req * 8
        # flops attribution follows the tracker's cost analysis
        assert report["tenants"]["a"]["flops"] > 0
        # engine stats carry the compact view
        assert engine.stats()["usage"]["attribution"][
            "token_coverage"
        ] >= 0.95
    finally:
        engine.close()


def test_usage_off_switch_token_parity(tiny_llama):
    """usage=None (the default): no tenant series, no usage stats
    section, and bit-identical tokens to a metered engine."""
    module, params = tiny_llama
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 97, 6).tolist() for _ in range(4)]
    outs = {}
    for metered in (False, True):
        registry = telemetry.MetricsRegistry()
        engine = DecodeEngine(
            module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
            chunk_steps=2, registry=registry,
            tracer=telemetry.TraceRecorder(),
            usage=True if metered else None,
        )
        try:
            with tenant_scope("acme"):
                outs[metered] = engine.generate(params, prompts)
            text = registry.exposition()
            stats = engine.stats()
            if metered:
                assert "unionml_tenant_requests_total" in text
                assert stats["usage"]["distinct_tenants"] >= 1
                assert engine.usage is not None
            else:
                assert "unionml_tenant_" not in text
                assert "usage" not in stats
                assert engine.usage is None
        finally:
            engine.close()
    assert outs[False] == outs[True]


def test_usage_setter_toggles_metering_on_idle_engine(tiny_llama):
    """The ``engine.usage`` idle-swap seam (the serve_usage bench runs
    both overhead legs on ONE engine through it): toggling the ledger
    on meters exactly the requests served while attached, toggling it
    off stops accrual, and tokens are identical across toggles."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        chunk_steps=2, registry=registry,
        tracer=telemetry.TraceRecorder(), usage=None,
    )
    try:
        rng = np.random.default_rng(3)
        prompt = rng.integers(1, 97, 6).tolist()
        with tenant_scope("acme"):
            out_off = engine.generate(params, [prompt])
        assert ledger.report()["totals"]["tokens"] == 0
        engine.usage = ledger
        assert engine.usage is ledger
        with tenant_scope("acme"):
            out_on = engine.generate(params, [prompt])
        on_report = ledger.report()
        assert on_report["tenants"]["acme"]["decode_tokens"] == 6
        # the off-leg's idle gap must not inflate the first metered
        # window: attribution is clamped at each chunk's dispatch time
        assert on_report["tenants"]["acme"]["device_seconds"] < 30.0
        engine.usage = None
        with tenant_scope("acme"):
            out_off2 = engine.generate(params, [prompt])
        assert ledger.report()["totals"]["tokens"] == 6
        assert out_off == out_on == out_off2
    finally:
        engine.close()


def test_prefix_cache_savings_credited_to_leasing_tenant(tiny_llama):
    """Tenant A pays the cold prefill and inserts the blocks; tenant B
    reuses them — the cached_tokens credit lands on B (the lease
    holder whose prefill was skipped), not on A."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(16,),
        chunk_steps=2, registry=registry,
        tracer=telemetry.TraceRecorder(),
        prefix_cache=RadixPrefixCache(block_size=4, registry=registry),
        usage=ledger,
    )
    try:
        rng = np.random.default_rng(2)
        prompt = rng.integers(1, 97, 12).tolist()
        with tenant_scope("author"):
            out_a = engine.generate(params, [prompt])
        # the insert rides the async harvest pipeline: wait for it
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if engine.prefix_cache.stats()["entries"] > 0:
                break
            time.sleep(0.01)
        with tenant_scope("reuser"):
            out_b = engine.generate(params, [prompt])
        assert out_a == out_b  # cache parity rides along
        report = ledger.report()
        assert report["tenants"]["author"]["cached_tokens"] == 0
        # (12 - 1) // 4 = 2 usable blocks -> 8 tokens spliced
        assert report["tenants"]["reuser"]["cached_tokens"] == 8
        assert report["cache_savings_tokens"] == 8
        assert (
            report["tenants"]["reuser"]["prefill_tokens"]
            == 12 - 8
        )
    finally:
        engine.close()


def test_kv_block_seconds_closed_on_abandoned_stream(tiny_llama):
    """Paged mode: an abandoned stream's pool blocks free AND its hold
    window closes into the tenant's kv_block_seconds."""
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=32, prompt_buckets=(16,),
        chunk_steps=2, paged=True, registry=registry,
        tracer=telemetry.TraceRecorder(), usage=ledger,
    )
    try:
        rng = np.random.default_rng(3)
        with tenant_scope("ghost"):
            gen = engine.generate_stream(
                params, rng.integers(1, 97, 8).tolist()
            )
            next(gen)
            gen.close()  # client disconnect mid-decode
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = engine.stats()["kv_pool"]
            if st["blocks_in_use"] == 0 and st["blocks_reserved"] == 0:
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"pool never drained: {st}")
        report = ledger.report()
        assert report["tenants"]["ghost"]["kv_block_seconds"] > 0
        assert report["tenants"]["ghost"]["dropped"] == 1
        text = registry.exposition()
        assert "unionml_tenant_kv_block_seconds_total" in text
    finally:
        engine.close()


@pytest.mark.chaos
def test_kv_block_seconds_closed_on_recovery(tiny_llama):
    """Paged mode + chaos: a poisoned batch's hold windows close at
    recovery (before the pool resets under it) and the drops are
    billed to their tenants."""
    module, params = tiny_llama
    fi = FaultInjector()
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=8, prompt_buckets=(16,),
        chunk_steps=4, paged=True, registry=registry,
        tracer=telemetry.TraceRecorder(), usage=ledger,
        fault_injector=fi,
    )
    try:
        engine.warmup(params)
        engine.reset_stats()
        rng = np.random.default_rng(4)
        fi.arm("engine.dispatch", exc=xla_oom_error())

        def run(p):
            try:
                with tenant_scope("victim"):
                    engine.generate(params, [p])
            except Exception:
                pass  # the poisoned batch

        threads = [
            threading.Thread(
                target=run, args=(rng.integers(1, 97, 9).tolist(),)
            )
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert engine.stats()["robustness"]["recoveries"] >= 1
        report = ledger.report()
        vec = report["tenants"]["victim"]
        assert vec["kv_block_seconds"] > 0
        assert vec["dropped"] >= 1
        st = engine.stats()["kv_pool"]
        assert st["blocks_in_use"] == 0 and st["blocks_reserved"] == 0
    finally:
        engine.close()


def test_rejections_gain_tenant_dimension(tiny_llama):
    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(8,),
        chunk_steps=2, registry=registry,
        tracer=telemetry.TraceRecorder(), usage=ledger,
    )
    try:
        engine.drain(timeout=5)
        from unionml_tpu.serving.faults import EngineUnavailable

        with pytest.raises(EngineUnavailable):
            with tenant_scope("shed-me"):
                engine.generate(params, [[1, 2, 3]])
        report = ledger.report()
        assert report["tenants"]["shed-me"]["rejected"] == 1
        assert (
            'unionml_tenant_rejected_total{ledger="'
            f'{ledger.instance}",tenant="shed-me",reason="draining"}} 1'
        ) in registry.exposition()
    finally:
        engine.close()


def test_flight_events_tenant_tag_and_filter(tiny_llama):
    module, params = tiny_llama
    flight = telemetry.FlightRecorder()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(8,),
        chunk_steps=2, registry=telemetry.MetricsRegistry(),
        tracer=telemetry.TraceRecorder(), flight=flight, usage=True,
    )
    try:
        rng = np.random.default_rng(5)
        for tenant in ("red", "blue"):
            with tenant_scope(tenant):
                engine.generate(params, [rng.integers(1, 97, 5).tolist()])
        red = flight.dump(tenant="red")
        assert red and all(e["tenant"] == "red" for e in red)
        kinds = {e["kind"] for e in red}
        assert {"submit", "prefill", "finish"} <= kinds
        assert flight.dump(tenant="nobody") == []
    finally:
        engine.close()


def test_batcher_usage_attribution_by_rows():
    from unionml_tpu.serving.batcher import MicroBatcher

    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)

    def predict(feats):
        return feats.sum(axis=1)

    batcher = MicroBatcher(
        predict, max_batch_size=16, max_wait_ms=50.0,
        registry=registry, tracer=telemetry.TraceRecorder(),
        usage=ledger,
    )
    try:
        results = {}

        def submit(tenant, rows):
            with tenant_scope(tenant):
                results[tenant] = batcher.submit(
                    np.full((rows, 4), 1.0)
                )

        threads = [
            threading.Thread(target=submit, args=("big", 3)),
            threading.Thread(target=submit, args=("small", 1)),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        report = ledger.report()
        assert report["tenants"]["big"]["requests"] == 1
        assert report["tenants"]["big"]["decode_tokens"] == 3
        assert report["tenants"]["small"]["decode_tokens"] == 1
        total = report["totals"]["device_seconds"]
        split = (
            report["tenants"]["big"]["device_seconds"]
            + report["tenants"]["small"]["device_seconds"]
        )
        # abs term: vector() rounds to nanoseconds, so the two-tenant
        # sum can differ from the total by up to 1e-9 even though the
        # unrounded identity is exact
        assert split == pytest.approx(total, rel=1e-6, abs=1e-8)
        # rows split 3:1 -> device share splits 3:1 when batched
        # together (the two may also land in separate batches; either
        # way the identity above holds)
        assert "usage" in batcher.stats()
    finally:
        batcher.close()


# ------------------------------------------------------- transports


def test_stdlib_transport_tenant_round_trip(trained_model):
    import httpx

    from unionml_tpu.serving.http import ServingApp

    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict",
            json={"features": [{"x": 1.0, "x2": 1.0}]},
            headers={"X-Tenant-ID": "acme"},
        )
        assert r.status_code == 200
        assert r.headers["x-tenant-id"] == "acme"
        assert r.headers.get("x-request-id")
        # default + echo on non-predict routes too
        h = httpx.get(f"{base}/health")
        assert h.headers["x-tenant-id"] == "anonymous"
        # hostile values: 422, never a label value
        bad = httpx.post(
            f"{base}/predict", json={"features": []},
            headers={"X-Tenant-ID": "x" * 65},
        )
        assert bad.status_code == 422
        # no ledger on this app -> /debug/usage is 422 like /debug/slo
        assert httpx.get(f"{base}/debug/usage").status_code == 422
    finally:
        app.shutdown()


def test_serving_app_batch_mode_forwards_ledger_to_batcher(trained_model):
    """ServingApp(batch=True, usage=) must hand the SAME ledger to the
    MicroBatcher it constructs — `usage` is consumed by the app for
    /debug/usage and cannot be reached through **batcher_kwargs, so
    without the forward the batched path silently meters nothing."""
    import httpx

    from unionml_tpu.serving.http import ServingApp

    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=4)
    app = ServingApp(
        trained_model, batch=True, registry=registry, usage=ledger,
        max_batch_size=4, max_wait_ms=1.0,
    )
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict",
            json={"features": [{"x": 1.0, "x2": 1.0}]},
            headers={"X-Tenant-ID": "acme"},
        )
        assert r.status_code == 200
        body = httpx.get(f"{base}/debug/usage").json()
        assert body["tenants"]["acme"]["requests"] == 1
        assert body["tenants"]["acme"]["decode_tokens"] == 1  # rows
        assert "acme" in _tenant_labels(registry)
    finally:
        app.shutdown()


def test_fastapi_transport_tenant_round_trip(trained_model):
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        r = client.post(
            "/predict", json={"features": [[0.1, 0.2]]},
            headers={"X-Tenant-ID": "acme"},
        )
        assert r.status_code == 200
        assert r.headers["x-tenant-id"] == "acme"
        h = client.get("/health")
        assert h.headers["x-tenant-id"] == "anonymous"
        bad = client.get("/health", headers={"X-Tenant-ID": "x" * 65})
        assert bad.status_code == 422
        assert client.get("/debug/usage").status_code == 422


def test_serverless_transport_tenant_round_trip(trained_model):
    from unionml_tpu.serving.serverless import gateway_handler

    handler = gateway_handler(trained_model)
    r = handler({
        "httpMethod": "POST", "path": "/predict",
        "headers": {"X-Tenant-ID": "acme"},
        "body": json.dumps({"features": [[0.1, 0.2]]}),
    })
    assert r["statusCode"] == 200
    assert r["headers"]["X-Tenant-ID"] == "acme"
    h = handler({"httpMethod": "GET", "path": "/health"})
    assert h["headers"]["X-Tenant-ID"] == "anonymous"
    bad = handler({
        "httpMethod": "GET", "path": "/health",
        "headers": {"X-Tenant-ID": "x" * 65},
    })
    assert bad["statusCode"] == 422
    assert handler({
        "httpMethod": "GET", "path": "/debug/usage",
    })["statusCode"] == 422


def test_debug_usage_endpoint_engine_backed(tiny_llama):
    """The full wiring: engine ledger -> ServingApp(usage=) ->
    GET /debug/usage serves per-tenant vectors; the flight filter
    narrows the postmortem to one tenant."""
    import httpx

    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.serving.http import ServingApp

    module, params = tiny_llama
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=6, prompt_buckets=(8,),
        chunk_steps=2, registry=registry,
        tracer=telemetry.TraceRecorder(), flight=flight, usage=True,
    )
    dataset = Dataset(name="usage_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    lm = Model(name="usage_lm", init=lambda: params, dataset=dataset)

    @lm.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @lm.predictor
    def predictor(p: dict, prompts: list) -> list:
        return engine.generate(p, prompts)

    lm.artifact = ModelArtifact(params, {}, {})
    app = ServingApp(
        lm, stats=engine.stats, health=engine.health,
        registry=registry, flight=flight, usage=engine.usage,
    )
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict", json={"features": [[1, 2, 3]]},
            headers={"X-Tenant-ID": "acme"}, timeout=120,
        )
        assert r.status_code == 200 and r.headers["x-tenant-id"] == "acme"
        usage = httpx.get(f"{base}/debug/usage", timeout=30).json()
        assert usage["tenants"]["acme"]["requests"] == 1
        assert usage["tenants"]["acme"]["decode_tokens"] == 6
        assert usage["attribution"]["token_coverage"] >= 0.95
        assert "capacity" in usage and "headroom" in usage["capacity"]
        flight_resp = httpx.get(
            f"{base}/debug/flight?tenant=acme", timeout=30
        ).json()
        assert flight_resp["events"]
        assert all(
            e.get("tenant") == "acme" for e in flight_resp["events"]
        )
        # /stats mirrors the compact usage section
        stats = httpx.get(f"{base}/stats", timeout=30).json()
        assert stats["usage"]["distinct_tenants"] >= 1
        # the scrape carries the bounded tenant series
        text = httpx.get(f"{base}/metrics", timeout=30).text
        assert 'tenant="acme"' in text
    finally:
        app.shutdown()
        engine.close()


def test_capacity_totals_cheap_read_matches_report():
    """capacity_totals() — the autoscaler's windowed-headroom read —
    returns the raw counters without assembling a report, and
    differencing consecutive samples isolates recent utilization."""
    ledger = UsageLedger(registry=telemetry.MetricsRegistry())
    assert ledger.capacity_totals() == (0.0, 0.0)
    assert ledger.capacity_headroom() == 1.0  # vacuous: no capacity yet
    ledger.attribute({"a": 30}, device_s=1.0, slot_steps=100.0)
    cap, used = ledger.capacity_totals()
    assert (cap, used) == (100.0, 30.0)
    assert ledger.capacity_headroom() == pytest.approx(0.7)
    assert ledger.report()["capacity"]["headroom"] == pytest.approx(0.7)
    # the delta window: a later busy burst reads busy even after a
    # long idle cumulative history
    ledger.attribute({"a": 95}, device_s=1.0, slot_steps=100.0)
    cap2, used2 = ledger.capacity_totals()
    d_headroom = 1.0 - (used2 - used) / (cap2 - cap)
    assert d_headroom == pytest.approx(0.05)
    ledger.reset_stats()
    assert ledger.capacity_totals() == (0.0, 0.0)
