"""SLO watchdog tier-1 tests (docs/observability.md "Distributed
tracing & SLOs"): deterministic multi-window burn-rate math on a
synthetic clock, the ``unionml_slo_*`` series, and the acceptance
path — a fault-injected slow prefill breaches a TTFT objective, flips
``GET /health`` to ``degraded`` (503) within the fast burn window, and
clears after recovery."""

import time

import httpx
import jax
import jax.numpy as jnp
import pytest

from unionml_tpu import telemetry
from unionml_tpu.slo import (
    AvailabilityObjective,
    GaugeObjective,
    LatencyObjective,
    SloWatchdog,
)
from unionml_tpu.telemetry import MetricsRegistry


# ------------------------------------------------------------ burn math


def _ttft_watchdog(reg, **kwargs):
    return SloWatchdog(
        [LatencyObjective(
            "ttft_p90", "unionml_engine_ttft_ms", threshold_ms=100.0,
            target=0.9, fast_burn=2.0, slow_burn=1.0,
        )],
        registry=reg, fast_window_s=10.0, slow_window_s=60.0, **kwargs,
    )


def test_latency_burn_rate_window_math():
    reg = MetricsRegistry()
    h = reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    r = wd.evaluate(now=0.0)
    assert r["breached"] == [] and r["objectives"][0]["windows"]["fast"][
        "burn_rate"] == 0.0

    # 20 good observations: bad fraction 0, burn 0
    for _ in range(20):
        h.labels("engine-0").observe(50.0)
    r = wd.evaluate(now=2.0)
    assert r["breached"] == []

    # 20 bad of 40 total in the window: bad fraction 0.5, budget 0.1,
    # burn 5.0 in both windows -> breach (>= 2.0 fast, >= 1.0 slow)
    for _ in range(20):
        h.labels("engine-0").observe(500.0)
    r = wd.evaluate(now=4.0)
    obj = r["objectives"][0]
    assert obj["windows"]["fast"]["burn_rate"] == pytest.approx(5.0)
    assert obj["windows"]["fast"]["bad_events"] == 20.0
    assert r["breached"] == ["ttft_p90"]

    # recovery: the fast window slides past the burst (clean traffic
    # only after t=4) while the slow window still remembers it — the
    # AND condition clears the breach on the fast window alone
    for _ in range(100):
        h.labels("engine-0").observe(50.0)
    wd.evaluate(now=5.0)
    r = wd.evaluate(now=16.0)   # fast window (6, 16]: only clean deltas
    obj = r["objectives"][0]
    assert obj["windows"]["fast"]["burn_rate"] == 0.0
    assert obj["windows"]["slow"]["burn_rate"] > 1.0
    assert r["breached"] == []

    # transition accounting: exactly one ok->breached edge so far
    assert wd._m_breaches.labels("ttft_p90").value == 1.0


def test_latency_burn_ignores_no_traffic_windows():
    reg = MetricsRegistry()
    reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    for now in (0.0, 1.0, 2.0):
        r = wd.evaluate(now=now)
    assert r["breached"] == []
    assert r["objectives"][0]["windows"]["fast"]["events"] == 0.0


def test_availability_burn_rate():
    reg = MetricsRegistry()
    total = reg.counter("unionml_http_requests_total", "t",
                        ("transport", "path", "status"))
    errors = reg.counter("unionml_http_errors_total", "e",
                         ("transport", "path"))
    wd = SloWatchdog(
        [AvailabilityObjective(
            "availability", total="unionml_http_requests_total",
            errors="unionml_http_errors_total", target=0.99,
            fast_burn=2.0, slow_burn=1.0,
        )],
        registry=reg, fast_window_s=10.0, slow_window_s=60.0,
    )
    wd.evaluate(now=0.0)
    for _ in range(95):
        total.labels("stdlib", "/predict", "200").inc()
    for _ in range(5):
        total.labels("stdlib", "/predict", "500").inc()
        errors.labels("stdlib", "/predict").inc()
    r = wd.evaluate(now=5.0)
    obj = r["objectives"][0]
    # 5% errors / 1% budget = burn 5.0
    assert obj["windows"]["fast"]["burn_rate"] == pytest.approx(5.0)
    assert r["breached"] == ["availability"]


def test_gauge_objective_needs_sustained_violation():
    reg = MetricsRegistry()
    g = reg.gauge("unionml_program_mfu_ratio", "mfu",
                  ("component", "program"))
    wd = SloWatchdog(
        [GaugeObjective("decode_mfu", "unionml_program_mfu_ratio",
                        min_value=0.2,
                        label_filter={"program": "engine.decode"})],
        registry=reg, fast_window_s=10.0, slow_window_s=30.0,
    )
    # unresolved gauge (0.0) is skipped, not a breach
    g.labels("engine-0", "engine.decode").set(0.0)
    assert wd.evaluate(now=0.0)["breached"] == []
    # healthy level
    g.labels("engine-0", "engine.decode").set(0.5)
    assert wd.evaluate(now=2.0)["breached"] == []
    # sustained low MFU across both windows
    g.labels("engine-0", "engine.decode").set(0.05)
    for now in (12.0, 20.0, 28.0, 36.0, 44.0):
        r = wd.evaluate(now=now)
    assert r["breached"] == ["decode_mfu"]
    assert r["objectives"][0]["windows"]["fast"]["value"] == pytest.approx(0.05)
    # a different program's gauge is invisible to the filter
    g.labels("engine-0", "engine.prefill").set(0.9)
    assert wd.evaluate(now=46.0)["breached"] == ["decode_mfu"]


def test_burn_score_is_max_fast_window_burn():
    """burn_score() — the router's load-shifting scalar — is the max
    fast-window burn across objectives from the LAST evaluation, 0.0
    before any sampling (no hidden evaluate: the health-probe cadence
    is the refresh cadence)."""
    reg = MetricsRegistry()
    h = reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    assert wd.burn_score() == 0.0  # never evaluated
    for _ in range(20):
        h.labels("engine-0").observe(50.0)
    wd.evaluate(now=0.0)
    wd.evaluate(now=2.0)
    assert wd.burn_score() == 0.0  # healthy traffic
    for _ in range(20):
        h.labels("engine-0").observe(500.0)
    wd.evaluate(now=4.0)
    # the window delta vs the now=0 baseline is 20 bad / 20 total:
    # bad fraction 1.0 over the 0.1 budget -> burn 10.0
    assert wd.burn_score() == pytest.approx(10.0)


def test_watchdog_publishes_slo_series_and_rejects_duplicates():
    reg = MetricsRegistry()
    reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    wd.evaluate(now=0.0)
    text = reg.exposition()
    assert 'unionml_slo_burn_rate{objective="ttft_p90",window="fast"}' in text
    assert 'unionml_slo_breached{objective="ttft_p90"}' in text
    assert "unionml_slo_breaches_total" in text
    with pytest.raises(ValueError, match="duplicate"):
        wd.add_objective(LatencyObjective(
            "ttft_p90", "unionml_engine_ttft_ms", threshold_ms=1.0,
        ))


def test_watchdog_validates_windows_and_targets():
    with pytest.raises(ValueError, match="shorter"):
        SloWatchdog(registry=MetricsRegistry(),
                    fast_window_s=60.0, slow_window_s=10.0)
    with pytest.raises(ValueError, match="target"):
        LatencyObjective("x", "h", 1.0, target=1.0)
    with pytest.raises(ValueError, match="exactly one"):
        GaugeObjective("x", "g")


def test_history_trimming_keeps_slow_baseline():
    reg = MetricsRegistry()
    h = reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    for i in range(200):
        h.labels("engine-0").observe(50.0)
        wd.evaluate(now=float(i))
    # bounded: roughly the slow window's worth of samples is retained,
    # including one at/before the horizon as the baseline
    assert len(wd._history) <= 63
    assert wd._history[0][0] <= 199.0 - 60.0


# ------------------------------------------------ acceptance: TTFT breach


@pytest.fixture(scope="module")
def tiny_llama():
    from unionml_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


class _EngineModel:
    """ServingApp double whose predictor is the decode engine."""

    name = "slo-engine"
    _predict_step_options: dict = {}

    class _DS:
        def get_features(self, f):
            return f

    def __init__(self, engine, params):
        self.engine, self.params = engine, params
        self.dataset = self._DS()

        class _Art:
            model_object = params
        self.artifact = _Art()

    def predict_from_features_workflow(self):
        return lambda model_object, features: self.engine.generate(
            model_object, features
        )


def test_ttft_breach_degrades_health_and_recovers(tiny_llama):
    """The acceptance bar: a fault-injected slow prefill pushes TTFT
    over the objective, GET /health flips to degraded (503) within the
    fast burn window, and clears after recovery."""
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.faults import FaultInjector
    from unionml_tpu.serving.http import ServingApp

    module, params = tiny_llama
    reg = MetricsRegistry()
    tracer = telemetry.TraceRecorder(registry=reg)
    fi = FaultInjector()
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=4, prompt_buckets=(8,),
        chunk_steps=2, registry=reg, tracer=tracer,
        flight=telemetry.FlightRecorder(), fault_injector=fi,
    )
    watchdog = SloWatchdog(
        [LatencyObjective(
            "ttft_p90", "unionml_engine_ttft_ms", threshold_ms=100.0,
            target=0.9, min_events=1, fast_burn=1.0, slow_burn=1.0,
        )],
        registry=reg, fast_window_s=3.0, slow_window_s=120.0,
    )
    app = ServingApp(
        _EngineModel(engine, params), registry=reg, tracer=tracer,
        health=engine.health, stats=engine.stats, slo=watchdog,
        flight=telemetry.FlightRecorder(),
    )
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    try:
        engine.warmup(params)
        # healthy traffic first: fast TTFT, health ok
        for _ in range(4):
            r = httpx.post(f"{url}/predict",
                           json={"features": [[1, 2, 3]]})
            assert r.status_code == 200
        h = httpx.get(f"{url}/health")
        assert h.status_code == 200 and h.json()["status"] == "ok"
        assert h.json()["slo_breached"] == []

        # fault-injected slow prefill: every admission stalls 150 ms,
        # so TTFT lands far over the 100 ms objective
        fi.arm("engine.prefill", count=8, delay_s=0.15)
        for _ in range(4):
            assert httpx.post(
                f"{url}/predict", json={"features": [[1, 2, 3]]}
            ).status_code == 200
        fi.disarm()
        # the breach must surface within the fast window (3 s): the
        # very next probe evaluates over a window containing the burst
        h = httpx.get(f"{url}/health")
        assert h.status_code == 503, h.text
        body = h.json()
        assert body["status"] == "degraded"
        assert body["slo_breached"] == ["ttft_p90"]
        text = httpx.get(f"{url}/metrics").text
        assert 'unionml_slo_breached{objective="ttft_p90"} 1' in text

        # recovery: clean traffic, and once the fast window slides past
        # the burst the breach clears and health returns to 200/ok
        deadline = time.monotonic() + 30.0
        status = None
        while time.monotonic() < deadline:
            httpx.post(f"{url}/predict", json={"features": [[1, 2, 3]]})
            h = httpx.get(f"{url}/health")
            status = (h.status_code, h.json()["status"])
            if status == (200, "ok"):
                break
            time.sleep(0.25)
        assert status == (200, "ok"), f"breach never cleared: {status}"
        assert httpx.get(f"{url}/debug/slo").json()["breached"] == []
    finally:
        app.shutdown()
        engine.close()


def test_burn_scores_reads_both_windows():
    """burn_scores() — the autoscaler's sustained-burn signal — reads
    the fast AND slow windows from the last evaluation; a burst inside
    the fast window alone must show slow < fast (the multiwindow
    discipline that keeps a blip from buying hardware)."""
    reg = MetricsRegistry()
    h = reg.histogram("unionml_engine_ttft_ms", "ttft", ("engine",))
    wd = _ttft_watchdog(reg)
    assert wd.burn_scores() == {"fast": 0.0, "slow": 0.0}
    # long healthy history fills the slow window
    for t in range(0, 50, 2):
        for _ in range(4):
            h.labels("engine-0").observe(50.0)
        wd.evaluate(now=float(t))
    # then a fast-window burst of slow requests
    for _ in range(8):
        h.labels("engine-0").observe(500.0)
    wd.evaluate(now=52.0)
    scores = wd.burn_scores()
    assert scores["fast"] > scores["slow"] > 0.0
    assert scores["fast"] == wd.burn_score("fast") == wd.burn_score()
    assert scores["slow"] == wd.burn_score("slow")
    with pytest.raises(ValueError, match="window"):
        wd.burn_score("medium")
