"""Two-tier trainer API: @model.train_step compiled over a mesh.

This is the TPU-native hot path (SURVEY.md §3.1: "the hot loop ... becomes
a pjit-compiled step function"), exercised end-to-end through the same
Dataset/Model spec surface the reference uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn
from flax.training import train_state

from unionml_tpu import Dataset, Model
from unionml_tpu.parallel import ShardingConfig

# NOTE: this module runs with the persistent compilation cache OFF —
# see _PERSISTENT_CACHE_UNSAFE in tests/conftest.py (warm-cache runs
# intermittently return garbage in the donated `step` counter).


class MLP(nn.Module):
    hidden: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(2)(x)


def make_app(sharding=None):
    dataset = Dataset(name="blobs", test_size=0.25, shuffle=True, random_state=7)

    @dataset.reader
    def reader(n: int = 256) -> dict:
        rng = np.random.default_rng(0)
        half = n // 2
        x = np.concatenate(
            [
                rng.normal(loc=-2.0, size=(half, 4)),
                rng.normal(loc=2.0, size=(n - half, 4)),
            ]
        ).astype(np.float32)
        y = np.concatenate([np.zeros(half), np.ones(n - half)]).astype(np.int32)
        order = rng.permutation(n)
        return {"features": x[order], "targets": y[order]}

    @dataset.splitter
    def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
        n = len(data["features"])
        k = int(n * (1 - test_size))
        return (
            {"features": data["features"][:k], "targets": data["targets"][:k]},
            {"features": data["features"][k:], "targets": data["targets"][k:]},
        )

    @dataset.parser
    def parser(data: dict, features, targets):
        return (data["features"], data["targets"])

    def init_state(learning_rate: float = 0.05) -> train_state.TrainState:
        module = MLP()
        params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 4)))["params"]
        return train_state.TrainState.create(
            apply_fn=module.apply, params=params, tx=optax.adam(learning_rate)
        )

    model = Model(name="mlp", init=init_state, dataset=dataset)

    @model.train_step(sharding=sharding)
    def train_step(state, batch):
        x, y = batch

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), {"loss": loss}

    @model.predictor(jit=True)
    def predictor(state: train_state.TrainState, features: np.ndarray) -> jnp.ndarray:
        logits = state.apply_fn({"params": state.params}, features)
        return jnp.argmax(logits, axis=-1)

    @model.evaluator
    def evaluator(state: train_state.TrainState, features: np.ndarray, targets: np.ndarray) -> float:
        logits = state.apply_fn({"params": state.params}, features)
        return float((jnp.argmax(logits, axis=-1) == targets).mean())

    return dataset, model


def test_train_step_single_device():
    _, model = make_app(sharding=None)
    state, metrics = model.train(
        hyperparameters={"learning_rate": 0.05},
        trainer_kwargs={"num_epochs": 5, "batch_size": 32},
        n=256,
    )
    assert metrics["train"] > 0.95
    assert metrics["test"] > 0.95
    preds = model.predict(features=np.full((3, 4), 2.0, dtype=np.float32))
    assert preds.shape == (3,)
    assert all(p == 1 for p in preds)


def test_train_step_dp_mesh():
    """Same app, data-parallel over the 8-device simulated mesh."""
    _, model = make_app(sharding=ShardingConfig(data=-1))
    state, metrics = model.train(
        hyperparameters={"learning_rate": 0.05},
        trainer_kwargs={"num_epochs": 5, "batch_size": 64},
        n=512,
    )
    assert metrics["test"] > 0.95


def test_train_step_fsdp_mesh():
    _, model = make_app(sharding=ShardingConfig(data=2, fsdp=4))
    state, metrics = model.train(
        hyperparameters={"learning_rate": 0.05},
        trainer_kwargs={"num_epochs": 4, "batch_size": 64},
        n=512,
    )
    assert metrics["test"] > 0.9


def test_pytree_artifact_roundtrip(tmp_path):
    _, model = make_app()
    model.train(
        hyperparameters={"learning_rate": 0.05},
        trainer_kwargs={"num_epochs": 2, "batch_size": 32},
        n=128,
    )
    path = tmp_path / "model.utpu"
    model.save(path)

    _, fresh = make_app()
    loaded = fresh.load(path)
    orig_leaves = jax.tree_util.tree_leaves(model.artifact.model_object.params)
    new_leaves = jax.tree_util.tree_leaves(loaded.params)
    for a, b in zip(orig_leaves, new_leaves):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------- #
# streaming trainers (execution.run_step_trainer, SURVEY.md §7.4)
# --------------------------------------------------------------------- #

def _stream_problem():
    from unionml_tpu.models import Mlp, MlpConfig, classification_step, create_train_state

    module = Mlp(MlpConfig(hidden_dims=(16,), num_classes=2))
    rng = np.random.default_rng(0)
    x = np.concatenate([rng.normal(-2, 1, (64, 4)), rng.normal(2, 1, (64, 4))]).astype(np.float32)
    y = np.concatenate([np.zeros(64), np.ones(64)]).astype(np.int32)
    state = create_train_state(module, jnp.asarray(x[:1]), learning_rate=0.05)
    return classification_step(module), state, x, y


def test_streaming_trainer_callable_per_epoch():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()

    def epoch_stream():
        for i in range(0, 128, 32):
            yield (jnp.asarray(x[i:i + 32]), jnp.asarray(y[i:i + 32]))

    out = run_step_trainer(
        step_fn=step, state=state, features=epoch_stream, num_epochs=4,
    )
    logits = out.apply_fn({"params": out.params}, jnp.asarray(x))
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(y)).mean())
    assert acc > 0.9


def test_streaming_trainer_one_shot_iterator():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()
    stream = ((jnp.asarray(x[i:i + 32]), jnp.asarray(y[i:i + 32]))
              for i in range(0, 128, 32))
    out = run_step_trainer(step_fn=step, state=state, features=stream)
    assert out.step == 4  # consumed exactly the four streamed batches


def test_streaming_trainer_rejections():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()
    stream = iter([(jnp.asarray(x[:32]), jnp.asarray(y[:32]))])
    with pytest.raises(ValueError, match="cannot be replayed"):
        run_step_trainer(step_fn=step, state=state, features=stream, num_epochs=2)
    with pytest.raises(ValueError, match="streaming trainers"):
        run_step_trainer(
            step_fn=step, state=state, features=iter([]), targets=np.zeros(4),
        )


def test_streaming_trainer_reiterable_loader_multi_epoch():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()

    class Loader:  # DataLoader-like: __iter__ only, fresh pass each time
        def __iter__(self):
            for i in range(0, 128, 32):
                yield (jnp.asarray(x[i:i + 32]), jnp.asarray(y[i:i + 32]))

    out = run_step_trainer(step_fn=step, state=state, features=Loader(), num_epochs=3)
    assert out.step == 12


def test_streaming_trainer_exhausted_callable_raises():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()
    gen = ((jnp.asarray(x[i:i + 32]), jnp.asarray(y[i:i + 32]))
           for i in range(0, 64, 32))
    with pytest.raises(ValueError, match="FRESH iterable"):
        run_step_trainer(step_fn=step, state=state, features=lambda: gen, num_epochs=3)


def test_streaming_trainer_empty_stream_raises():
    from unionml_tpu.execution import run_step_trainer

    step, state, x, y = _stream_problem()
    with pytest.raises(ValueError, match="no batches in epoch 1"):
        run_step_trainer(step_fn=step, state=state, features=iter([]))


def test_adamw_bf16_first_moment():
    """mu_dtype=bfloat16 quarters adam-state bytes; the trajectory stays
    close to fp32 (m is momentum — low-precision-tolerant; v stays fp32)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import Mlp, MlpConfig, classification_step, create_train_state
    from unionml_tpu.models.train import adamw

    module = Mlp(MlpConfig(num_classes=2, hidden_dims=(16,)))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
    y = jnp.asarray((np.asarray(x).sum(1) > 0).astype(np.int32))
    step = jax.jit(classification_step(module))

    losses = {}
    for name, dtype in (("fp32", None), ("bf16", jnp.bfloat16)):
        state = create_train_state(
            module, x[:1], optimizer=adamw(1e-2, mu_dtype=dtype)
        )
        if dtype is not None:
            mus = [
                leaf
                for leaf in jax.tree_util.tree_leaves(state.opt_state)
                if hasattr(leaf, "dtype") and leaf.dtype == jnp.bfloat16
            ]
            assert mus, "first moment not stored in bf16"
        for _ in range(20):
            state, metrics = step(state, (x, y))
        losses[name] = float(metrics["loss"])
    assert losses["bf16"] < 0.5  # actually trains
    assert abs(losses["bf16"] - losses["fp32"]) < 0.15
