"""Async checkpointing (checkpoint/async_writer.py): caller stalls for
the host snapshot only, the background commit is atomic (rename + commit
marker), a kill mid-commit always leaves the previous checkpoint
restorable, and restore refuses torn dirs. The elastic trainer adopts
the writer through make_checkpoint_manager — preemption/resume stays
bit-identical with the async backend, double-buffered prefetch, and
overlapped gradients all on (docs/performance.md "Overlapped
training")."""

import pathlib
import threading

import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier
pytestmark = pytest.mark.quick

import jax
import jax.numpy as jnp

from unionml_tpu.checkpoint import AsyncCheckpointManager, make_checkpoint_manager
from unionml_tpu.checkpoint.async_writer import AsyncCheckpointWriter, is_committed
from unionml_tpu.telemetry import MetricsRegistry

# NOTE: this module runs with the persistent compilation cache OFF —
# see _PERSISTENT_CACHE_UNSAFE in tests/conftest.py (warm-cache reads
# crash the donated elastic-step executables on jax 0.4.37/CPU).


def _state(scale: float = 1.0):
    return {"w": jnp.arange(8, dtype=jnp.float32) * scale,
            "b": jnp.full((2, 2), scale)}


def _target():
    return {"w": jnp.zeros(8, jnp.float32), "b": jnp.zeros((2, 2))}


def test_roundtrip_and_rotation(tmp_path):
    reg = MetricsRegistry()
    with AsyncCheckpointManager(tmp_path, max_to_keep=2, registry=reg) as mgr:
        for s in (1, 2, 3, 4):
            mgr.save(s, _state(float(s)))
        mgr.wait()
        assert mgr._steps() == [3, 4]  # rotation kept the newest two
        restored = mgr.restore(_target())
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(8, dtype=np.float32) * 4
        )
        older = mgr.restore(_target(), step=3)
        np.testing.assert_array_equal(
            np.asarray(older["b"]), np.full((2, 2), 3.0)
        )
    # every committed dir carries the marker
    for p in pathlib.Path(tmp_path).glob("step_*"):
        assert is_committed(p)


def test_resave_same_step_overwrites_committed_dir(tmp_path):
    """Re-saving an already-committed step (manual manager use, or a
    rolled-back run re-reaching the step number) must commit the NEW
    state — os.replace alone cannot replace a non-empty directory, so
    this used to die with ENOTEMPTY and kill the run."""
    reg = MetricsRegistry()
    with AsyncCheckpointManager(tmp_path, registry=reg) as mgr:
        mgr.save(1, _state(1.0))
        mgr.wait()
        mgr.save(1, _state(7.0))   # same step, new contents
        mgr.wait()                 # raises on commit failure
        restored = mgr.restore(_target(), step=1)
        np.testing.assert_array_equal(
            np.asarray(restored["w"]), np.arange(8, dtype=np.float32) * 7
        )
    # no stale move-aside dirs left behind; the step dir is committed
    assert not list(pathlib.Path(tmp_path).glob("*.tmp-*"))
    assert is_committed(pathlib.Path(tmp_path) / "step_1")


def test_save_returns_before_commit_and_metrics_split(tmp_path):
    """The caller-stall/commit split (docs/observability.md "Checkpoint
    I/O"): save() returns while the commit is still in flight — the
    pending gauge is up, latest_step still names the previous step —
    and save_ms/commit_ms land as separate series."""
    reg = MetricsRegistry()
    with AsyncCheckpointManager(tmp_path, registry=reg) as mgr:
        mgr.save(10, _state(1.0))
        mgr.wait()
        gate = threading.Event()
        mgr_gated = AsyncCheckpointManager(
            tmp_path, registry=reg, commit_hook=lambda p: gate.wait(10)
        )
        mgr_gated.save(20, _state(2.0))  # returns with the commit gated
        assert mgr_gated.latest_step() == 10
        snap = reg.snapshot()
        assert snap["unionml_checkpoint_pending"][""] == 1.0
        # the caller stall was observed even though the commit is open
        assert snap["unionml_checkpoint_save_ms"]["kind=async"]["count"] == 2
        gate.set()
        mgr_gated.wait()
        assert mgr_gated.latest_step() == 20
        snap = reg.snapshot()
        assert snap["unionml_checkpoint_pending"][""] == 0.0
        assert snap["unionml_checkpoint_commit_ms"]["kind=async"]["count"] == 2
        assert snap["unionml_checkpoint_save_bytes_total"]["kind=async"] > 0
        mgr_gated.close()


def test_kill_mid_commit_restores_previous_step(tmp_path):
    """The chaos contract: a commit that dies before the atomic rename
    leaves no step dir, latest_step/restore fall back to the previous
    committed checkpoint, and the failure surfaces on the strict wait()
    barrier (close() only logs — safe in trainer finally blocks)."""
    reg = MetricsRegistry()
    with AsyncCheckpointManager(tmp_path, registry=reg) as mgr:
        mgr.save(10, _state(1.0))
        mgr.wait()

    def die(final_path):
        raise OSError("simulated kill mid-commit")

    chaos = AsyncCheckpointManager(tmp_path, registry=reg, commit_hook=die)
    chaos.save(20, _state(2.0))
    with pytest.raises(RuntimeError, match="previous checkpoint"):
        chaos.wait()
    assert chaos.latest_step() == 10
    restored = chaos.restore(_target())
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(8, dtype=np.float32)
    )
    # no half-written final dir, no tmp leftovers after the cleanup
    assert not list(pathlib.Path(tmp_path).glob("step_20*"))


def test_restore_refuses_torn_checkpoint(tmp_path):
    """A step dir without its commit marker (external interference: a
    partial copy, a crashed rsync) is skipped by latest_step and
    REFUSED by an explicit restore — torn state never loads."""
    reg = MetricsRegistry()
    mgr = AsyncCheckpointManager(tmp_path, registry=reg)
    mgr.save(5, _state(1.0))
    mgr.wait()
    torn = pathlib.Path(tmp_path) / "step_9"
    torn.mkdir()
    (torn / "state.msgpack").write_bytes(b"partial garbage")
    assert mgr.latest_step() == 5
    with pytest.raises(ValueError, match="torn checkpoint"):
        mgr.restore(_target(), step=9)
    restored = mgr.restore(_target())  # falls back to the committed step
    np.testing.assert_array_equal(
        np.asarray(restored["w"]), np.arange(8, dtype=np.float32)
    )
    mgr.close()


def test_stale_tmp_dirs_swept_on_construction(tmp_path):
    leftover = pathlib.Path(tmp_path) / "step_7.tmp-123-1"
    leftover.mkdir(parents=True)
    (leftover / "state.msgpack").write_bytes(b"junk from a dead process")
    mgr = AsyncCheckpointManager(tmp_path)
    assert not leftover.exists()
    assert mgr.latest_step() is None
    mgr.close()


def test_writer_restore_preserves_device_placement(tmp_path):
    """Restore re-places leaves per the target's sharding — the elastic
    resume path hands in the freshly compiled (placed) state."""
    from unionml_tpu.parallel import ShardingConfig

    cfg = ShardingConfig(data=8)
    writer = AsyncCheckpointWriter(registry=MetricsRegistry())
    state = jax.device_put(
        {"w": jnp.arange(16, dtype=jnp.float32)},
        {"w": cfg.batch_sharding()},
    )
    writer.save(tmp_path / "step_1", state)
    writer.wait()
    target = jax.device_put(
        {"w": jnp.zeros(16, jnp.float32)}, {"w": cfg.batch_sharding()}
    )
    out = writer.restore(tmp_path / "step_1", target)
    np.testing.assert_array_equal(
        np.asarray(out["w"]), np.arange(16, dtype=np.float32)
    )
    assert out["w"].sharding.is_equivalent_to(cfg.batch_sharding(), 1)


def test_forced_async_backend_refuses_orbax_format_dir(tmp_path):
    """A FORCED async/sync backend over a marker-less (Orbax-format)
    directory must refuse at construction instead of seeing zero
    committed steps and silently restarting the run from step 0
    (backend='auto' detects the format and picks Orbax instead)."""
    orbax_style = pathlib.Path(tmp_path) / "step_12"
    orbax_style.mkdir()
    (orbax_style / "array_data").write_bytes(b"orbax-era payload")
    with pytest.raises(ValueError, match="backend='orbax'"):
        AsyncCheckpointManager(tmp_path)
    with pytest.raises(ValueError, match="backend='orbax'"):
        make_checkpoint_manager(tmp_path, backend="sync")
    # …but a dir that ALSO holds a committed async step is ours: the
    # marker-less stray is a torn external copy, skipped per the
    # restore contract (see test_restore_refuses_torn_checkpoint)
    ours = pathlib.Path(tmp_path) / "ours"
    with AsyncCheckpointManager(ours) as mgr:
        mgr.save(1, _state(1.0))
    (ours / "step_2").mkdir()
    mgr2 = AsyncCheckpointManager(ours)
    assert mgr2.latest_step() == 1
    mgr2.close()


def test_make_checkpoint_manager_sticks_with_orbax_dirs(tmp_path):
    """auto must not silently restart an existing Orbax-format run from
    scratch: marker-less step dirs pin the Orbax backend."""
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    with CheckpointManager(str(tmp_path), async_save=False) as mgr:
        mgr.save(3, {"w": jnp.ones((4,))})
    picked = make_checkpoint_manager(tmp_path, backend="auto")
    assert isinstance(picked, CheckpointManager)
    assert picked.latest_step() == 3
    picked.close()
    # a fresh dir single-process picks the async writer
    fresh = make_checkpoint_manager(tmp_path / "fresh", backend="auto")
    assert isinstance(fresh, AsyncCheckpointManager)
    fresh.close()
    with pytest.raises(ValueError, match="backend"):
        make_checkpoint_manager(tmp_path, backend="nope")


def _make_problem():
    from flax import linen as nn

    from unionml_tpu.models.train import classification_step, create_train_state

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

    module = Mlp()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=(128,)).astype(np.int32)
    step = classification_step(module, accumulate_steps=2)
    state = create_train_state(module, x[:4], learning_rate=1e-2, seed=1)
    return step, state, x, y


def test_elastic_async_preemption_resume_bit_identical(tmp_path):
    """The full overlapped stack — async checkpoint backend,
    double-buffered donated prefetch, overlap_grads — still satisfies
    the elastic contract: kill + relaunch reaches the bit-identical
    final state of an uninterrupted run (replay-after-preemption works
    because resumed feeds rebuild fresh donated buffers from the
    deterministic (seed, epoch) order)."""
    from unionml_tpu.elastic import Preemption, run_elastic_trainer
    from unionml_tpu.parallel import ShardingConfig

    def run(d, state, step, fault=None):
        return run_elastic_trainer(
            step_fn=step, state=state, arrays=[x, y],
            checkpoint_dir=str(d), num_epochs=2, batch_size=8,
            accumulate_steps=2, checkpoint_every=4, seed=3,
            sharding=ShardingConfig(data=2, fsdp=2, devices=jax.devices()[:4]),
            overlap_grads=True, double_buffer=True, fault_hook=fault,
        )

    step, state0, x, y = _make_problem()
    ref_state, ref_steps = run(tmp_path / "ref", state0, step)

    step2, state1, _, _ = _make_problem()

    def bomb(global_step):
        if global_step == 6:
            raise Preemption("simulated")

    with pytest.raises(Preemption):
        run(tmp_path / "run", state1, step2, fault=bomb)
    # the kill landed past the step-4 checkpoint: async commit already
    # durable, resume point is step 4
    assert make_checkpoint_manager(tmp_path / "run").latest_step() == 4

    step3, state2, _, _ = _make_problem()
    out_state, out_steps = run(tmp_path / "run", state2, step3)
    assert out_steps == ref_steps
    for a, b in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(out_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_elastic_kill_mid_commit_resumes_previous(tmp_path):
    """Chaos at the manager level THROUGH the trainer: the commit of
    the step-8 checkpoint dies mid-write; the trainer's finally-path
    close() only logs, and a relaunch resumes from the intact step-4
    checkpoint instead of a torn step-8."""
    from unionml_tpu.elastic import run_elastic_trainer

    step, state0, x, y = _make_problem()
    boom = {"armed": False}

    def flaky_commit(final_path):
        if final_path.name == "step_8" and not boom["armed"]:
            boom["armed"] = True
            raise OSError("power loss mid-commit")

    # run the loop manually against a chaos manager: monkeypatching via
    # the backend factory would hide which save failed
    mgr = AsyncCheckpointManager(tmp_path, commit_hook=flaky_commit)
    import jax as _jax

    compiled = _jax.jit(step, donate_argnums=())
    from unionml_tpu.execution import to_microbatches

    state = state0
    for i in range(8):
        xb = x[i * 16:(i + 1) * 16]
        yb = y[i * 16:(i + 1) * 16]
        batch = to_microbatches((xb, yb), 2, 8)
        state, _ = compiled(state, batch)
        if (i + 1) % 4 == 0:
            mgr.save(i + 1, state)
    mgr.close()  # drains; the step_8 failure was logged, not raised
    assert mgr.latest_step() == 4

    # relaunch through the trainer: resumes at 4, finishes, and the
    # terminal checkpoint commits cleanly this time
    step2, state1, _, _ = _make_problem()
    out, steps = run_elastic_trainer(
        step_fn=step2, state=state1, arrays=[x, y],
        checkpoint_dir=str(tmp_path), num_epochs=1, batch_size=8,
        accumulate_steps=2, checkpoint_every=4, seed=0,
    )
    assert steps == 8
    assert make_checkpoint_manager(tmp_path).latest_step() == 8
