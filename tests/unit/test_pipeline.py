"""Pipeline parallelism: SPMD GPipe schedule must be numerically identical
to serial stage application, for forward AND gradients (SURVEY.md §4.3:
equivalence testing on the CPU-simulated mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params

N_STAGES = 4
DIM = 8


def stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def make_stages(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_STAGES)
    return [
        {
            "w": jax.random.normal(k, (DIM, DIM)) / np.sqrt(DIM),
            "b": jnp.zeros((DIM,)),
        }
        for k in ks
    ]


def serial_apply(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.mark.parametrize("num_microbatches", [4, 8])
def test_pipeline_matches_serial_forward(num_microbatches):
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stages = make_stages()
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, DIM))

    out = jax.jit(
        lambda p, x: pipeline_apply(
            stage_fn, p, x, mesh=mesh, num_microbatches=num_microbatches
        )
    )(stacked, x)
    expected = serial_apply(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_serial():
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stages = make_stages(seed=2)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, DIM))
    y = jax.random.normal(jax.random.PRNGKey(4), (8, DIM))

    def pipe_loss(p):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh, num_microbatches=4)
        return jnp.mean((out - y) ** 2)

    def serial_loss(p):
        out = x
        for s in range(N_STAGES):
            out = stage_fn(jax.tree_util.tree_map(lambda a: a[s], p), out)
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked)
    g_serial = jax.grad(serial_loss)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_serial,
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stacked = stack_stage_params(make_stages())
    x = jnp.zeros((16, DIM))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(stage_fn, stacked, x, mesh=mesh, num_microbatches=5)
    with pytest.raises(ValueError, match="bubble"):
        pipeline_apply(stage_fn, stacked, x, mesh=mesh, num_microbatches=2)


# --------------------------------------------------------------------- #
# pipelined Llama (models/pipeline_lm.py)
# --------------------------------------------------------------------- #

def _llama_setup(dtype="float32", vocab=64, layers=2):
    from unionml_tpu.models import Llama, LlamaConfig

    cfg = LlamaConfig.tiny(vocab_size=vocab, num_layers=layers, dtype=dtype)
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, vocab)
    flat = module.init(jax.random.PRNGKey(1), tokens[:1])["params"]
    return cfg, module, tokens, flat


def test_pipelined_llama_logits_match_serial():
    from unionml_tpu.models import pipelined_lm_apply, to_pipeline_params

    cfg, module, tokens, flat = _llama_setup()
    mesh = make_mesh({"pipeline": 2, "data": -1})
    pp = to_pipeline_params(flat, cfg, num_stages=2)
    ref = module.apply({"params": flat}, tokens)
    out = jax.jit(
        lambda p, t: pipelined_lm_apply(
            p, t, cfg, 2, mesh=mesh, num_microbatches=2
        )
    )(pp, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pipelined_lm_step_matches_serial_step():
    from unionml_tpu.models import (
        create_train_state, lm_step, pipelined_lm_step, to_pipeline_params,
    )
    from unionml_tpu.models.train import TrainState, adamw

    cfg, module, tokens, flat = _llama_setup()
    mesh = make_mesh({"pipeline": 2, "data": -1})

    serial_state = create_train_state(module, tokens[:1], learning_rate=1e-2)
    serial_state = serial_state.replace(params=flat)
    _, serial_metrics = jax.jit(lm_step(module))(serial_state, tokens)

    pp_state = TrainState.create(
        apply_fn=None, params=to_pipeline_params(flat, cfg, 2), tx=adamw(1e-2)
    )
    step = jax.jit(pipelined_lm_step(cfg, 2, mesh=mesh, num_microbatches=2))
    pp_state, pp_metrics = step(pp_state, tokens)
    np.testing.assert_allclose(
        float(pp_metrics["loss"]), float(serial_metrics["loss"]), rtol=1e-4
    )


def test_pipelined_lm_step_composes_with_dp():
    from unionml_tpu.models import create_pipelined_lm_state, pipelined_lm_step

    cfg, _, tokens, _ = _llama_setup()
    mesh = make_mesh({"pipeline": 2, "data": 2}, devices=jax.devices()[:4])
    state = create_pipelined_lm_state(cfg, 2, tokens[:1], learning_rate=1e-2)
    step = jax.jit(
        pipelined_lm_step(cfg, 2, mesh=mesh, num_microbatches=2, data_axis="data")
    )
    first = None
    for _ in range(5):
        state, metrics = step(state, tokens)
        first = first if first is not None else float(metrics["loss"])
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["loss"]) < first


def test_pipelined_rejects_moe_and_bad_split():
    from unionml_tpu.models import LlamaConfig, create_pipelined_lm_state

    cfg = LlamaConfig.tiny(num_layers=3)
    with pytest.raises(ValueError, match="not divisible"):
        create_pipelined_lm_state(cfg, 2, jnp.zeros((1, 4), jnp.int32))
    moe = LlamaConfig.tiny(num_experts=4)
    with pytest.raises(NotImplementedError, match="pipelined MoE"):
        create_pipelined_lm_state(moe, 2, jnp.zeros((1, 4), jnp.int32))


def test_pipeline_partition_rules_shard_state_via_compile_step():
    from unionml_tpu.models import (
        PIPELINE_PARTITION_RULES, create_pipelined_lm_state, pipelined_lm_step,
    )
    from unionml_tpu.models import LlamaConfig
    from unionml_tpu.parallel import ShardingConfig, compile_step

    cfg = LlamaConfig.tiny(vocab_size=64, num_layers=2, dtype="float32")
    tokens = jnp.zeros((8, 16), jnp.int32)
    state = create_pipelined_lm_state(cfg, 2, tokens[:1])
    sharding = ShardingConfig(
        data=-1, pipeline=2, rules=PIPELINE_PARTITION_RULES
    )
    step_fn = pipelined_lm_step(
        cfg, 2, mesh=sharding.mesh(), num_microbatches=2, data_axis="data"
    )
    step, placed = compile_step(step_fn, state, sharding=sharding)
    # stage params AND their adam moments shard over the pipeline axis
    assert "pipeline" in jax.tree_util.tree_leaves(
        placed.params["stages"],
        is_leaf=lambda x: hasattr(x, "sharding"),
    )[0].sharding.spec
    mu = placed.opt_state[0].mu["stages"]
    assert "pipeline" in jax.tree_util.tree_leaves(mu)[0].sharding.spec
    placed, metrics = step(placed, jnp.zeros((8, 16), jnp.int32))
    assert np.isfinite(float(metrics["loss"]))


def test_to_pipeline_params_validates_divisibility():
    from unionml_tpu.models import LlamaConfig, to_pipeline_params

    cfg = LlamaConfig.tiny(num_layers=3)
    with pytest.raises(ValueError, match="not divisible"):
        to_pipeline_params({}, cfg, 2)
    with pytest.raises(NotImplementedError, match="quantization"):
        to_pipeline_params({}, LlamaConfig.tiny(quantized=True), 2)
