"""Pipeline parallelism: SPMD GPipe schedule must be numerically identical
to serial stage application, for forward AND gradients (SURVEY.md §4.3:
equivalence testing on the CPU-simulated mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.parallel import make_mesh, pipeline_apply, stack_stage_params

N_STAGES = 4
DIM = 8


def stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def make_stages(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_STAGES)
    return [
        {
            "w": jax.random.normal(k, (DIM, DIM)) / np.sqrt(DIM),
            "b": jnp.zeros((DIM,)),
        }
        for k in ks
    ]


def serial_apply(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.mark.parametrize("num_microbatches", [4, 8])
def test_pipeline_matches_serial_forward(num_microbatches):
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stages = make_stages()
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, DIM))

    out = jax.jit(
        lambda p, x: pipeline_apply(
            stage_fn, p, x, mesh=mesh, num_microbatches=num_microbatches
        )
    )(stacked, x)
    expected = serial_apply(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_serial():
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stages = make_stages(seed=2)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (8, DIM))
    y = jax.random.normal(jax.random.PRNGKey(4), (8, DIM))

    def pipe_loss(p):
        out = pipeline_apply(stage_fn, p, x, mesh=mesh, num_microbatches=4)
        return jnp.mean((out - y) ** 2)

    def serial_loss(p):
        out = x
        for s in range(N_STAGES):
            out = stage_fn(jax.tree_util.tree_map(lambda a: a[s], p), out)
        return jnp.mean((out - y) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked)
    g_serial = jax.grad(serial_loss)(stacked)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        g_pipe,
        g_serial,
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh({"pipeline": N_STAGES, "data": -1})
    stacked = stack_stage_params(make_stages())
    x = jnp.zeros((16, DIM))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(stage_fn, stacked, x, mesh=mesh, num_microbatches=5)
    with pytest.raises(ValueError, match="bubble"):
        pipeline_apply(stage_fn, stacked, x, mesh=mesh, num_microbatches=2)
