"""Fleet observability plane (docs/observability.md "Fleet
observability"): cross-hop trace stitching under one trace id with the
replica's server spans parented to the router attempt that caused
them, metrics federation that degrades — never errors — when a replica
dies, merged flight rings, fleet SLO/usage views, the /debug/fleet
operator dashboard, and OTLP span events for the router/autoscaler
lifecycle."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu import telemetry
from unionml_tpu.exporters import OtlpCollectorStub, OtlpExporter
from unionml_tpu.models import Llama, LlamaConfig
from unionml_tpu.models.generate import make_generator
from unionml_tpu.serving.autoscaler import (
    AutoscalerPolicy,
    FleetAutoscaler,
    ReplicaProvisioner,
)
from unionml_tpu.serving.engine import DecodeEngine
from unionml_tpu.serving.faults import (
    EngineUnavailable,
    FaultInjector,
    xla_oom_error,
)
from unionml_tpu.serving.router import (
    EngineReplica,
    FleetRouter,
    HttpReplica,
    ReplicaHandle,
    RouterPolicy,
    make_router_app,
)
from unionml_tpu.serving.usage import UsageLedger

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return module, params


def _solo(module, params, prompt, n_new, max_len=128):
    # Oracle discipline: pass max_len=engine.cache_len when comparing
    # against an engine.  A padded-length mismatch reorders the padded
    # attention reductions, and a bf16 near-tie argmax can flip on that
    # alone -- which a parity assert reads as lost token parity.
    gen = make_generator(module, max_new_tokens=n_new, max_len=max_len)
    return np.asarray(gen(params, jnp.asarray([prompt], jnp.int32)))[0].tolist()


def _wait_for(cond, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    pytest.fail(f"timed out waiting for {what}")


class FakeReplica(ReplicaHandle):
    """Scriptable replica: serves ``tokens`` in 2-token chunks,
    failing the first ``fail_times`` dispatches."""

    def __init__(self, name, tokens=(1, 2, 3, 4), *, fail_times=0):
        self.name = name
        self.tokens = list(tokens)
        self.fail_times = fail_times
        self.dispatches = 0

    def generate_stream(self, prompt, *, max_new_tokens=None):
        self.dispatches += 1
        if self.dispatches <= self.fail_times:
            raise EngineUnavailable(f"{self.name} down", reason="test")
        for i in range(0, len(self.tokens), 2):
            yield self.tokens[i:i + 2]

    def health(self):
        return {"status": "ok", "queue_depth": 0}


def _router(replicas, tracer=None, registry=None, flight=None, **policy_kw):
    policy_kw.setdefault("health_ttl_s", 0.0)
    policy_kw.setdefault("jitter_s", 0.0)
    policy_kw.setdefault("backoff_base_s", 0.0)
    return FleetRouter(
        replicas,
        policy=RouterPolicy(**policy_kw),
        registry=registry if registry is not None
        else telemetry.MetricsRegistry(),
        flight=flight if flight is not None else telemetry.FlightRecorder(),
        tracer=tracer if tracer is not None else telemetry.TraceRecorder(),
        sleep=lambda s: None,
    )


# ------------------------------------------------ exposition merging


def test_merge_expositions_injects_replica_label():
    local = (
        "# HELP unionml_router_live_replicas r\n"
        "# TYPE unionml_router_live_replicas gauge\n"
        "unionml_router_live_replicas 2\n"
    )
    replica = (
        "# HELP unionml_engine_requests_total r\n"
        "# TYPE unionml_engine_requests_total counter\n"
        'unionml_engine_requests_total{engine="engine-0"} 5\n'
        "unionml_up 1\n"
    )
    merged = telemetry.merge_expositions(local, {"r0": replica})
    # local body untouched; replica samples labeled; bare samples too
    assert "unionml_router_live_replicas 2" in merged
    assert (
        'unionml_engine_requests_total{replica="r0",engine="engine-0"} 5'
        in merged
    )
    assert 'unionml_up{replica="r0"} 1' in merged
    # HELP/TYPE once per family even when both sources share one
    both = telemetry.merge_expositions(
        replica, {"r1": replica},
    )
    assert both.count("# TYPE unionml_engine_requests_total counter") == 1
    assert 'unionml_engine_requests_total{engine="engine-0"} 5' in both
    assert (
        'unionml_engine_requests_total{replica="r1",engine="engine-0"} 5'
        in both
    )


def test_merge_expositions_keeps_existing_replica_label():
    """A federated sub-router's body already carries replica labels —
    its (more specific) names win over a second injection: routers
    compose."""
    sub = 'unionml_router_requests_total{replica="leaf-3",outcome="ok"} 7\n'
    merged = telemetry.merge_expositions("", {"mid": sub})
    assert (
        'unionml_router_requests_total{replica="leaf-3",outcome="ok"} 7'
        in merged
    )
    assert 'replica="mid"' not in merged


def test_merge_expositions_degrades_on_garbage():
    merged = telemetry.merge_expositions(
        "ok_metric 1\n", {"r0": "%%% not an exposition at all"},
    )
    assert "ok_metric 1" in merged
    assert "%%%" not in merged


# --------------------------------------------- recorder span events


def test_record_event_exports_everywhere():
    from unionml_tpu.exporters import encode_spans

    tracer = telemetry.TraceRecorder()
    rid = tracer.new_request("fleet")
    tracer.record_event(rid, "eject", replica="r0", cause="Overloaded")
    tracer.finish_request(rid)
    payload = encode_spans(tracer._all_requests(), {}, 0.0)
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    (root,) = spans
    assert root["name"] == "fleet"
    (event,) = root["events"]
    assert event["name"] == "eject"
    keys = {a["key"]: a["value"] for a in event["attributes"]}
    assert keys["replica"] == {"stringValue": "r0"}
    # chrome + jsonl carry the instant too
    chrome = tracer.export_chrome()
    assert any(
        e.get("ph") == "i" and e["name"] == "eject"
        for e in chrome["traceEvents"]
    )
    assert '"event": true' in tracer.export_jsonl()


# -------------------------------------------- router decision spans


def test_router_records_decision_spans_one_trace():
    tracer = telemetry.TraceRecorder()
    router = _router(
        [FakeReplica("r0", fail_times=1), FakeReplica("r1")],
        tracer=tracer,
    )
    out = [t for c in router.generate_stream([1, 2, 3]) for t in c]
    assert out == [1, 2, 3, 4]
    (rid, meta, spans) = tracer._done[-1]
    assert meta["kind"] == "route"
    names = [s["name"] for s in spans]
    # failover story: pick → failed attempt → backoff → pick → attempt
    assert names == ["pick", "attempt", "backoff", "pick", "attempt"]
    attempts = [s for s in spans if s["name"] == "attempt"]
    assert attempts[0]["args"]["outcome"] == "error"
    assert attempts[1]["args"]["outcome"] == "ok"
    assert {a["args"]["replica"] for a in attempts} == {"r0", "r1"}
    # rid doubles as the routing rid: the flight route event matches
    assert router._flight.dump(kind="route")[-1]["rid"] == rid
    assert tracer.find_trace_id(rid) == meta["trace_id"]


def test_tracer_swap_mid_stream_finishes_in_opening_recorder():
    """A mid-stream tracer swap must close the timeline in the
    recorder it was OPENED in — re-reading the property at finish
    time would leak the request live in the old recorder forever."""
    tracer = telemetry.TraceRecorder()
    router = _router([FakeReplica("r0")], tracer=tracer)
    stream = router.generate_stream([1, 2])
    next(stream)
    router.tracer = None  # the bench's plane-off toggle, mid-stream
    for _ in stream:
        pass
    assert tracer._live == {}, "timeline leaked live across the swap"
    assert len(tracer._done) == 1
    router.tracer = tracer


def test_fleet_flight_merge_is_wall_anchored():
    """Merged flight events carry EPOCH-anchored t_ms: per-host
    monotonic readings are rebased by each body's wall_offset_ms, so
    a long-uptime replica host cannot sort after everything the
    router recorded (and an ?n= cut cannot silently drop the
    router's own events)."""
    import time as _time

    ring = telemetry.FlightRecorder()
    ring.record("submit", rid="x")
    router = _router([FlightReplica("a", ring)])
    app = make_router_app(
        router, registry=router._registry, flight=router._flight,
    )
    assert router.generate([1]) == [1, 2, 3, 4]
    merged = app.debug_flight(n=None)
    assert merged["wall_offset_ms"] == 0.0  # events are pre-anchored
    now_ms = _time.time() * 1e3
    for event in merged["events"]:
        assert abs(event["t_ms"] - now_ms) < 600_000, (
            "merged t_ms is not epoch-anchored"
        )
    # the per-process surface exports the anchor the merge rebases by
    import unionml_tpu.serving.http  # noqa: F401 — route home

    local_offset = telemetry.wall_clock_offset_ms()
    raw = router._flight.dump(kind="route")[-1]["t_ms"]
    anchored = next(
        e for e in merged["events"] if e["kind"] == "route"
    )["t_ms"]
    assert abs((raw + local_offset) - anchored) < 1.0
    tracer = telemetry.TraceRecorder()
    router = _router([FakeReplica("r0")], tracer=tracer)
    router.tracer = None  # the bench's plane-off seam
    assert router.generate([1, 2]) == [1, 2, 3, 4]
    assert tracer._done == [] and tracer._live == {}
    router.tracer = tracer
    assert router.generate([1, 2]) == [1, 2, 3, 4]
    assert len(tracer._done) == 1


def test_hedge_lane_spans_and_win_lose_events():
    slow, fast = FakeReplica("slow"), FakeReplica("fast")

    def slow_stream(prompt, *, max_new_tokens=None):
        slow.dispatches += 1
        yield [1, 2]
        time.sleep(0.5)
        yield [3, 4]

    slow.generate_stream = slow_stream
    tracer = telemetry.TraceRecorder()
    router = _router(
        [slow, fast], tracer=tracer,
        hedge=True, hedge_min_s=0.05, hedge_warmup=1,
    )
    # warm the latency window so the hedge delay is the observed p95
    router._latency.add(0.05)
    out = router.generate([7, 8])
    assert out == [1, 2, 3, 4]
    (rid, meta, spans) = tracer._done[-1]
    lanes = [s for s in spans if s["name"] == "hedge-lane"]
    assert len(lanes) == 2
    outcomes = {s["args"]["replica"]: s["args"]["outcome"] for s in lanes}
    assert outcomes["fast"] == "ok"
    assert outcomes["slow"] in ("abandoned", "ok")
    events = {e["name"]: e["args"]["replica"] for e in meta["events"]}
    assert events == {"hedge_win": "fast", "hedge_lose": "slow"}


# ------------------------------------- fleet timeline span events


def test_fleet_timeline_carries_lifecycle_and_scale_events():
    tracer = telemetry.TraceRecorder()
    bad = FakeReplica("bad", fail_times=10 ** 6)
    ok = FakeReplica("ok")
    router = _router([bad, ok], tracer=tracer, eject_consecutive=1)
    assert router.generate([1]) == [1, 2, 3, 4]  # bad fails → ejected

    class NoProvisioner(ReplicaProvisioner):
        def provision(self, name):
            raise RuntimeError("no capacity")

    auto = FleetAutoscaler(
        router, NoProvisioner(),
        policy=AutoscalerPolicy(min_replicas=3, max_replicas=4),
        flight=telemetry.FlightRecorder(),
        registry=telemetry.MetricsRegistry(),
        clock=lambda: 0.0,
    )
    assert router.autoscaler is auto  # /debug/fleet link
    decision = auto.evaluate(now=0.0)
    assert decision == {
        **decision, "decision": "scale_hold", "reason": "provision_failed",
    }
    router._close_fleet_timeline()
    fleet = [
        (rid, meta, spans) for rid, meta, spans in tracer._done
        if meta.get("kind") == "fleet"
    ]
    assert len(fleet) == 1
    names = [e["name"] for e in fleet[0][1]["events"]]
    assert "eject" in names and "scale_hold" in names
    eject = next(e for e in fleet[0][1]["events"] if e["name"] == "eject")
    assert eject["args"]["replica"] == "bad"


# --------------------------------------------- stitched /debug/trace


def test_debug_trace_rid_and_trace_contract():
    tracer = telemetry.TraceRecorder()
    router = _router([FakeReplica("r0")], tracer=tracer)
    app = make_router_app(
        router, registry=router._registry, tracer=tracer,
        flight=router._flight,
    )
    with pytest.raises(ValueError):
        app.debug_trace(rid="nope-not-a-rid")
    doc, content_type = app.debug_trace(trace="f" * 32)
    assert content_type == "application/json"
    assert doc["spans"] == [] and doc["request_ids"] == []
    # plain formats still answer (and still 422 on garbage)
    body, ct = app.debug_trace("jsonl")
    assert ct == "application/x-ndjson"
    with pytest.raises(ValueError):
        app.debug_trace("nope")


def test_stitched_failover_single_trace_e2e(tiny_llama):
    """THE acceptance: a mid-stream failover request, queried back by
    the X-Request-ID the client actually received, comes back as ONE
    stitched timeline — one trace id, router pick/retry spans, both
    replicas' engine timelines parented under the attempts that
    dispatched to them — and the same trace reaches the
    OtlpCollectorStub intact."""
    httpx = pytest.importorskip("httpx")
    module, params = tiny_llama
    n_new = 24
    fis = [FaultInjector(), FaultInjector()]
    tracer = telemetry.TraceRecorder()
    registry = telemetry.MetricsRegistry()
    engines = [
        DecodeEngine(
            module, slots=2, max_new_tokens=n_new, prompt_buckets=(8,),
            chunk_steps=2, fault_injector=fis[i], tracer=tracer,
            registry=registry,
        )
        for i in range(2)
    ]
    router = FleetRouter(
        [EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)],
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.0,
        ),
        registry=registry,
        flight=telemetry.FlightRecorder(),
        tracer=tracer,
    )
    stub = OtlpCollectorStub()
    exporter = OtlpExporter(
        stub.endpoint, registry=registry, tracer=tracer,
        interval_s=3600.0, export_metrics=False,
    )
    app = make_router_app(router, registry=registry, tracer=tracer)
    host, port = app.serve(port=0, blocking=False)
    prompt = [3, 1, 4, 1, 5]
    try:
        victim = 0  # idle-tie round-robin break: first pick is r0
        fis[victim].arm("engine.dispatch", nth=2, exc=xla_oom_error())
        streamed = []
        with httpx.stream(
            "POST", f"http://{host}:{port}/predict/stream",
            json={"features": prompt}, timeout=120,
        ) as resp:
            assert resp.status_code == 200
            rid = resp.headers["x-request-id"]
            for line in resp.iter_lines():
                if line.startswith("data: "):
                    import json as _json

                    event = _json.loads(line[len("data: "):])
                    if not event.get("done"):
                        streamed.extend(event["tokens"])
        assert streamed == _solo(module, params, prompt, n_new, max_len=engines[0].cache_len)
        assert fis[victim].injected("engine.dispatch") == 1

        # ---- the one-call stitched timeline ----
        def doc():
            body, _ = app.debug_trace(rid=rid)
            return body

        _wait_for(
            lambda: sum(
                1 for s in doc()["spans"]
                if s.get("root") and s["kind"] == "stream"
            ) == 2,
            what="both replicas' engine timelines in the stitch",
        )
        body = doc()
        trace_id = body["trace_id"]
        assert trace_id and len(trace_id) == 32
        by_id = {s["span_id"]: s for s in body["spans"]}
        names = [s["name"] for s in body["spans"]]
        assert "route" in names and "pick" in names
        attempts = [s for s in body["spans"] if s["name"] == "attempt"]
        assert {a["replica"] for a in attempts} == {"r0", "r1"}
        assert attempts[0]["outcome"] == "error"  # the failover is visible
        # mid-stream replay is visible on the retry attempt
        retry = next(a for a in attempts if a["outcome"] == "ok")
        assert retry["replayed"] > 0
        # the engine timelines nest under the attempt that caused them
        attempt_ids = {a["span_id"] for a in attempts}
        stream_roots = [
            s for s in body["spans"]
            if s.get("root") and s["kind"] == "stream"
        ]
        assert len(stream_roots) == 2
        for root in stream_roots:
            assert root["parent_span_id"] in attempt_ids
        # the route root parents to the transport server timeline
        route_root = next(
            s for s in body["spans"] if s.get("root") and s["kind"] == "route"
        )
        http_root = next(
            s for s in body["spans"] if s.get("root") and s["kind"] == "http"
        )
        assert route_root["parent_span_id"] == http_root["span_id"]
        assert http_root["request_id"] == rid
        # engine decode spans from the victim AND the survivor made it
        assert any(n.startswith("decode-chunk[") for n in names)

        # ---- the same trace arrives at the collector intact ----
        exporter.flush()
        otlp_spans = [
            s
            for _, payload in stub.requests
            for rs in payload.get("resourceSpans", ())
            for ss in rs.get("scopeSpans", ())
            for s in ss.get("spans", ())
            if s["traceId"] == trace_id
        ]
        otlp_ids = {s["spanId"] for s in otlp_spans}
        assert len(otlp_spans) >= len(body["spans"])
        for span in otlp_spans:
            parent = span.get("parentSpanId")
            assert parent is None or parent in otlp_ids, (
                f"dangling parent {parent} for {span['name']}"
            )
        assert {s["name"] for s in otlp_spans} >= {
            "http", "route", "pick", "attempt", "stream",
        }
        # stitched view and collector agree span-for-span
        assert {s["span_id"] for s in body["spans"]} <= otlp_ids
    finally:
        exporter.close(flush=False)
        stub.close()
        app.shutdown()
        for e in engines:
            e.close()


def test_cross_hop_parent_over_stdlib_http():
    """Satellite: over a REAL stdlib HTTP hop, the remote transport's
    server span carries the router attempt's span id as parent — the
    traceparent the attempt scope emits is what the remote timeline
    roots to — and the fetched remote spans land in the outer stitched
    document under the replica's tag."""
    remote_tracer = telemetry.TraceRecorder()
    remote_router = _router([FakeReplica("leaf")], tracer=remote_tracer)
    remote_app = make_router_app(
        remote_router, registry=remote_router._registry,
        tracer=remote_tracer, flight=remote_router._flight,
    )
    host, port = remote_app.serve(port=0, blocking=False)
    outer_tracer = telemetry.TraceRecorder()
    outer = FleetRouter(
        [HttpReplica(f"http://{host}:{port}", name="remote")],
        policy=RouterPolicy(health_ttl_s=0.0),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
        tracer=outer_tracer,
    )
    outer_app = make_router_app(
        outer, registry=outer._registry, tracer=outer_tracer,
        flight=outer._flight,
    )
    try:
        assert outer.generate([5, 6]) == [1, 2, 3, 4]
        (rid, meta, spans) = outer_tracer._done[-1]
        trace_id = meta["trace_id"]
        attempt = next(s for s in spans if s["name"] == "attempt")
        # the remote's own recorder holds a server timeline in OUR trace
        _wait_for(
            lambda: remote_tracer.requests_for_trace(trace_id),
            what="remote server timeline in the shared trace",
        )
        remote_reqs = remote_tracer.requests_for_trace(trace_id)
        http_meta = next(
            m for _, m, _ in remote_reqs if m["kind"] == "http"
        )
        assert http_meta["parent_span_id"] == attempt["span_id"]
        # and the stitched fetch pulls it across the hop
        doc, _ = outer_app.debug_trace(trace=trace_id)
        remote_http = [
            s for s in doc["spans"]
            if s.get("root") and s["kind"] == "http"
        ]
        assert len(remote_http) == 1
        assert remote_http[0]["parent_span_id"] == attempt["span_id"]
        assert remote_http[0]["replica"] == "remote"
        # the remote router's own route spans rode along too
        assert any(
            s["kind"] == "route" and s.get("replica") == "remote"
            for s in doc["spans"]
        )
    finally:
        remote_app.shutdown()


# ------------------------------------------------ metrics federation


class RegistryReplica(FakeReplica):
    """In-process replica with its OWN registry (the isolated-engine
    shape, without paying for an engine)."""

    def __init__(self, name, registry):
        super().__init__(name)
        self._registry = registry

    def metrics_registry(self):
        return self._registry

    def metrics_text(self):
        return self._registry.exposition()


def test_metrics_federation_e2e_and_kill_degradation():
    # remote replica behind a real stdlib transport
    remote_router = _router([FakeReplica("leaf")])
    remote_reg = remote_router._registry
    remote_app = make_router_app(
        remote_router, registry=remote_reg, flight=remote_router._flight,
    )
    host, port = remote_app.serve(port=0, blocking=False)
    # isolated in-process registry replica
    iso_reg = telemetry.MetricsRegistry()
    iso_reg.counter("unionml_engine_requests_total", "r", ("engine",)) \
        .labels("engine-7").inc(3)
    # a replica sharing the APP registry must NOT be federated twice
    app_reg = telemetry.MetricsRegistry()
    shared = RegistryReplica("shared", app_reg)
    dead = HttpReplica("http://127.0.0.1:9", name="dead", obs_timeout_s=0.3)
    remote = HttpReplica(
        f"http://{host}:{port}", name="remote", metrics_ttl_s=0.0,
        obs_timeout_s=5.0,
    )
    router = FleetRouter(
        [RegistryReplica("iso", iso_reg), shared, dead, remote],
        policy=RouterPolicy(health_ttl_s=0.0),
        registry=app_reg,
        flight=telemetry.FlightRecorder(),
        tracer=telemetry.TraceRecorder(),
    )
    app = make_router_app(router, registry=app_reg)
    try:
        body = app.metrics_text()
        # the router's own series, unlabeled
        assert "unionml_router_live_replicas 4" in body
        # isolated in-process replica: labeled
        assert (
            'unionml_engine_requests_total{replica="iso",engine="engine-7"} 3'
            in body
        )
        # remote replica: scraped and labeled (its router gauge)
        assert (
            'unionml_router_live_replicas{replica="remote"} 1' in body
        )
        # shared-registry replica NOT duplicated under a label
        assert 'replica="shared"' not in body
        # the dead replica degraded silently (absent, never an error)
        assert 'replica="dead"' not in body
        failures = app._m_federation_failures.labels("dead", "metrics")
        assert failures.value >= 1
        # kill the remote: the scrape DEGRADES to last-seen, not error
        # (the last-seen fallback lives inside HttpReplica, so the
        # app-side failure counter only moves for replicas that have
        # NOTHING cached — like "dead" above)
        remote_app.shutdown()
        body2 = app.metrics_text()
        assert (
            'unionml_router_live_replicas{replica="remote"} 1' in body2
        )
        # federation off restores the local body
        app.federate = False
        assert 'replica="iso"' not in app.metrics_text()
    finally:
        try:
            remote_app.shutdown()
        except Exception:
            pass


# ------------------------------------------------- fleet debug views


class SloReplica(FakeReplica):
    def __init__(self, name, fast, slow, breached=()):
        super().__init__(name)
        self._fast, self._slow = fast, slow
        self._breached = list(breached)

    def slo_report(self):
        return {
            "objectives": [{
                "name": f"{self.name}-obj",
                "windows": {
                    "fast": {"burn_rate": self._fast},
                    "slow": {"burn_rate": self._slow},
                },
                "breached": bool(self._breached),
            }],
            "breached": self._breached,
        }


def test_fleet_slo_view_aggregates_replicas():
    router = _router([
        SloReplica("a", 0.5, 0.2),
        SloReplica("b", 3.5, 1.5, breached=["b-obj"]),
        FakeReplica("c"),  # no watchdog: reported as null
    ])
    app = make_router_app(
        router, registry=router._registry, flight=router._flight,
    )
    view = app.debug_slo()
    assert view["fleet"]["burn"] == {"fast": 3.5, "slow": 1.5}
    assert view["fleet"]["breached"] == ["b-obj"]
    assert view["replicas"]["c"] is None
    assert view["router"] is None
    # nothing anywhere → 422 contract
    bare = make_router_app(
        _router([FakeReplica("x")]),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
    )
    with pytest.raises(ValueError):
        bare.debug_slo()


class LedgerReplica(FakeReplica):
    def __init__(self, name, ledger):
        super().__init__(name)
        self._ledger = ledger

    def usage_ledger(self):
        return self._ledger

    def usage_report(self):
        return self._ledger.report()


def test_fleet_usage_view_merges_and_dedups_shared_ledger():
    shared = UsageLedger(registry=telemetry.MetricsRegistry())
    own = UsageLedger(registry=telemetry.MetricsRegistry())
    shared.finish_request("acme", queue_ms=10.0)
    shared.attribute({"acme": 5}, device_s=0.5)
    own.finish_request("acme", queue_ms=2.0)
    own.attribute({"acme": 7}, device_s=0.25)
    own.finish_request("zeta", queue_ms=1.0)
    own.attribute({"zeta": 1}, device_s=0.125)
    router = _router([
        LedgerReplica("a", shared),
        LedgerReplica("b", shared),   # SAME ledger: merge once
        LedgerReplica("c", own),
        FakeReplica("d"),             # meters nothing
    ])
    app = make_router_app(
        router, registry=router._registry, flight=router._flight,
    )
    view = app.debug_usage()
    assert view["fleet"]["merged_reports"] == 2
    acme = view["fleet"]["tenants"]["acme"]
    assert acme["requests"] == 2
    assert acme["decode_tokens"] == 12  # 5 (shared, once) + 7 (own)
    assert view["fleet"]["tenants"]["zeta"]["decode_tokens"] == 1
    assert view["replicas"]["b"] == {"shared_ledger": True}
    assert view["replicas"]["d"] is None
    # no ledger anywhere → 422 contract
    bare = make_router_app(
        _router([FakeReplica("x")]),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
    )
    with pytest.raises(ValueError):
        bare.debug_usage()


class FlightReplica(FakeReplica):
    def __init__(self, name, ring):
        super().__init__(name)
        self._ring = ring

    def flight_recorder(self):
        return self._ring

    def flight_events(self, n=None):
        return self._ring.dump(n=n)


def test_fleet_flight_merge_tags_and_orders():
    ring_a = telemetry.FlightRecorder()
    ring_b = telemetry.FlightRecorder()
    ring_a.record("submit", rid="x1", tenant="acme")
    ring_b.record("preempt", rid="x2")
    router = _router([
        FlightReplica("a", ring_a), FlightReplica("b", ring_b),
    ])
    app = make_router_app(
        router, registry=router._registry, flight=router._flight,
    )
    assert router.generate([1]) == [1, 2, 3, 4]
    view = app.debug_flight()
    assert view["merged_replicas"] == ["a", "b"]
    kinds = {e["kind"] for e in view["events"]}
    assert {"route", "submit", "preempt"} <= kinds
    submit = next(e for e in view["events"] if e["kind"] == "submit")
    assert submit["replica"] == "a"
    # time-ordered by t_ms
    times = [e["t_ms"] for e in view["events"]]
    assert times == sorted(times)
    # filters apply across the merged stream
    only = app.debug_flight(tenant="acme")["events"]
    assert [e["kind"] for e in only] == ["submit"]
    # a replica sharing the app ring is not duplicated
    shared = FlightReplica("s", router._flight)
    router.add_replica(shared)
    n_before = len(app.debug_flight()["events"])
    again = app.debug_flight()
    assert "s" not in again["merged_replicas"]
    assert len(again["events"]) == n_before
    # filter-then-truncate: newer non-matching events must not displace
    # an older matching one out of a filtered+bounded query (the
    # replica fetch is only thinned by ?n= when NO filter is active)
    for _ in range(5):
        ring_a.record("route", rid="noise")
    bounded = app.debug_flight(n=1, kind="submit")["events"]
    assert [e["kind"] for e in bounded] == ["submit"]


def test_debug_fleet_dashboard():
    router = _router([FakeReplica("r0"), FakeReplica("r1")])
    app = make_router_app(
        router, registry=router._registry, flight=router._flight,
    )
    report = app.debug_fleet()
    assert report["status"] == "ok"
    assert set(report["replicas"]) == {"r0", "r1"}
    assert report["replicas"]["r0"]["queue_depth"] == 0
    assert "autoscaler" not in report  # none attached yet

    class NullProvisioner(ReplicaProvisioner):
        def provision(self, name):
            raise RuntimeError("unused")

    auto = FleetAutoscaler(
        router, NullProvisioner(),
        # min_replicas == live: neither direction wants an action, so
        # the first evaluation is a genuine steady hold
        policy=AutoscalerPolicy(min_replicas=2, max_replicas=4),
        flight=telemetry.FlightRecorder(),
        registry=telemetry.MetricsRegistry(),
        clock=lambda: 100.0,
    )
    auto.evaluate(now=100.0)
    report = app.debug_fleet()
    dash = report["autoscaler"]
    assert dash["last_decision"]["decision"] == "scale_hold"
    assert dash["last_decision"]["reason"] == "steady"
    assert dash["headroom"] == 1.0
    assert dash["policy"]["max_replicas"] == 4
    # the dashboard read is side-effect-free on the decision loop
    before = auto.stats()["last_decision"]
    app.debug_fleet()
    assert auto.stats()["last_decision"] == before
    # a plain (non-router) ServingApp has no fleet → 422 contract
    from unionml_tpu.serving.http import ServingApp

    with pytest.raises(ValueError):
        ServingApp.debug_fleet(object())
