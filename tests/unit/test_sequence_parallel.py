"""Sequence-parallel training: the sharded step must match the serial
lm_step numerically (loss AND updated params), SURVEY.md §4.3 strategy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from unionml_tpu.models import (
    Llama,
    LlamaConfig,
    create_train_state,
    lm_step,
    sequence_parallel_config,
    sequence_parallel_lm_step,
)
from unionml_tpu.parallel import make_mesh


def _setup(dtype="float32", vocab=64):
    cfg = LlamaConfig.tiny(vocab_size=vocab, dtype=dtype)
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, vocab)
    params = module.init(jax.random.PRNGKey(1), tokens[:1])["params"]
    return cfg, module, tokens, params


@pytest.mark.parametrize("attn", ["ring", "ring_flash", "ulysses"])
def test_sp_step_matches_serial(attn):
    import optax

    cfg, module, tokens, params = _setup()
    mesh = make_mesh({"data": 2, "sequence": 2}, devices=jax.devices()[:4])

    # SGD, not adam: updates are linear in grads, so the comparison
    # tests the grad plumbing itself (adam's g/sqrt(v) amplifies the
    # sharded reduction-order noise on near-zero grads into ~4e-4 param
    # diffs — observed on CPU shard_map — which no per-element atol can
    # separate from a real plumbing bug); same convention as
    # test_sp_moe_step_matches_serial below
    serial_state = create_train_state(
        module, tokens[:1], optimizer=optax.sgd(1e-2)
    )
    serial_state = serial_state.replace(params=params)
    # serial reference with the SAME loss convention (last position
    # masked): lm_step's shifted (inputs, targets) tuple form
    targets = np.concatenate(
        [np.asarray(tokens[:, 1:]), np.full((4, 1), -100)], axis=1
    ).astype(np.int32)
    serial_state, serial_metrics = jax.jit(lm_step(module))(
        serial_state, (tokens, jnp.asarray(targets))
    )

    sp_state = create_train_state(module, tokens[:1], optimizer=optax.sgd(1e-2))
    sp_state = sp_state.replace(params=params)
    step = jax.jit(sequence_parallel_lm_step(cfg, mesh=mesh, attn=attn))
    sp_state, sp_metrics = step(sp_state, tokens)

    np.testing.assert_allclose(
        float(sp_metrics["loss"]), float(serial_metrics["loss"]), rtol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(serial_state.params),
        jax.tree_util.tree_leaves(sp_state.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5)


def test_sp_loss_decreases_over_steps():
    cfg, module, tokens, params = _setup()
    mesh = make_mesh({"data": 2, "sequence": 2}, devices=jax.devices()[:4])
    state = create_train_state(module, tokens[:1], learning_rate=1e-2)
    step = jax.jit(sequence_parallel_lm_step(cfg, mesh=mesh))
    _, first = step(state, tokens)
    for _ in range(8):
        state, metrics = step(state, tokens)
    assert float(metrics["loss"]) < float(first["loss"])


def test_sp_rejects_bad_configs():
    cfg = LlamaConfig.tiny()
    with pytest.raises(ValueError, match="ring"):
        sequence_parallel_config(cfg, attn="flash")


def test_sp_moe_step_matches_serial():
    """Long-context x MoE composes: the SP step re-forms the load-balance
    loss from pmean'd token-mean fractions (ops/moe.py sows them into
    `moe_stats`), so loss, aux AND updated params match serial lm_step
    exactly — not a per-shard approximation."""
    import optax

    vocab = 64
    cfg = LlamaConfig.tiny(
        vocab_size=vocab, dtype="float32", num_experts=4, num_selected=2
    )
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, vocab)
    params = module.init(jax.random.PRNGKey(1), tokens[:1])["params"]
    mesh = make_mesh({"data": 2, "sequence": 2}, devices=jax.devices()[:4])

    # SGD: updates are linear in grads, so the comparison tests the grad
    # plumbing itself (adam's g/sqrt(v) amplifies fp-reassociation noise
    # on near-zero grads into ~1e-4 param diffs)
    serial_state = create_train_state(
        module, tokens[:1], optimizer=optax.sgd(1e-2)
    )
    serial_state = serial_state.replace(params=params)
    targets = np.concatenate(
        [np.asarray(tokens[:, 1:]), np.full((4, 1), -100)], axis=1
    ).astype(np.int32)
    serial_state, serial_metrics = jax.jit(lm_step(module))(
        serial_state, (tokens, jnp.asarray(targets))
    )

    sp_state = create_train_state(module, tokens[:1], optimizer=optax.sgd(1e-2))
    sp_state = sp_state.replace(params=params)
    step = jax.jit(sequence_parallel_lm_step(cfg, mesh=mesh, attn="ring"))
    sp_state, sp_metrics = step(sp_state, tokens)

    assert float(sp_metrics["aux_loss"]) > 0
    np.testing.assert_allclose(
        float(sp_metrics["loss"]), float(serial_metrics["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(sp_metrics["aux_loss"]),
        float(serial_metrics["aux_loss"]),
        rtol=1e-5,
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(serial_state.params),
        jax.tree_util.tree_leaves(sp_state.params),
    ):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5)


def test_sp_ulysses_head_divisibility_checked_eagerly():
    cfg = LlamaConfig.tiny()  # 4 q heads, 2 kv heads
    mesh = make_mesh({"data": 2, "sequence": 4}, devices=jax.devices()[:8])
    with pytest.raises(ValueError, match="kv heads"):
        sequence_parallel_lm_step(cfg, mesh=mesh, attn="ulysses")


def test_sp_sequence_only_mesh():
    cfg, module, tokens, params = _setup()
    mesh = make_mesh({"sequence": 4}, devices=jax.devices()[:4])
    state = create_train_state(module, tokens[:1], learning_rate=1e-2)
    step = jax.jit(
        sequence_parallel_lm_step(cfg, mesh=mesh, data_axis=None)
    )
    state, metrics = step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))


def test_sp_step_through_model_spec():
    """Full-stack: @dataset.reader -> @model.train_step(sequence-parallel)
    -> model.train() — the SP step is a plain (state, batch) step, so the
    spec-level trainer loop drives it unchanged."""
    from unionml_tpu import Dataset, Model

    cfg = LlamaConfig.tiny(vocab_size=97)
    module = Llama(cfg)
    mesh = make_mesh({"data": 2, "sequence": 2}, devices=jax.devices()[:4])

    dataset = Dataset(name="sp_tokens", targets=[])

    @dataset.reader
    def reader(n: int = 32) -> np.ndarray:
        rng = np.random.default_rng(0)
        return rng.integers(0, 97, size=(n, 32)).astype(np.int32)

    model = Model(
        name="sp_lm",
        init=lambda: create_train_state(
            module, jnp.zeros((1, 8), jnp.int32), learning_rate=5e-3
        ),
        dataset=dataset,
    )
    sp_step = sequence_parallel_lm_step(cfg, mesh=mesh)
    model.train_step(sp_step, donate_state=False)
    eval_step = jax.jit(sp_step)  # one jitted instance reused per eval call

    @model.evaluator
    def evaluator(state, features, targets=None) -> float:
        _, metrics = eval_step(state, jnp.asarray(features))
        return float(metrics["loss"])

    state, metrics = model.train(
        trainer_kwargs={"num_epochs": 4, "batch_size": 16}, n=32
    )
    assert np.isfinite(metrics["train"])
