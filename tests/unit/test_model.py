"""Model spec tests (reference: tests/unit/test_model.py)."""

import io
from dataclasses import is_dataclass

import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu import Model, ModelArtifact
from unionml_tpu.stage import Stage, Workflow


def test_decorator_registration(model):
    assert model._trainer is not None
    assert model._predictor is not None
    assert model._evaluator is not None


def test_hyperparameter_type_synthesis(dataset):
    def init_fn(C: float = 1.0, max_iter: int = 100) -> "object":
        ...

    model = Model(name="hp_model", init=init_fn, dataset=dataset)
    hp_type = model.hyperparameter_type
    assert is_dataclass(hp_type)
    hp = hp_type()
    assert hp.C == 1.0 and hp.max_iter == 100

    # unannotated init falls back to dict (reference: model.py:144-146)
    def untyped_init(C=1.0):
        ...

    model2 = Model(name="hp2", init=untyped_init, dataset=dataset)
    assert model2.hyperparameter_type is dict

    # explicit hyperparameter_config wins
    model3 = Model(
        name="hp3", init=untyped_init, hyperparameter_config={"C": float}, dataset=dataset
    )
    assert is_dataclass(model3.hyperparameter_type)


def test_artifact_hyperparameters_are_plain_picklable_data(dataset):
    """An annotated-init app's default hyperparameters must cross the
    artifact boundary as a plain dict: the synthesized dataclass has no
    importable home, so instances would break the remote runner's output
    pickle (found by the two-host transport test; reference analog:
    flytekit ships dataclasses as JSON, model.py:137-161)."""
    import pickle

    def init_fn(scale: float = 2.0) -> dict:
        return {"scale": scale}

    model = Model(name="hp_pickle_model", init=init_fn, dataset=dataset)

    @model.trainer
    def trainer(m: dict, features, target) -> dict:
        return m

    model.train()  # no hyperparameters passed: the default-synthesis path
    hp = model.artifact.hyperparameters
    assert hp == {"scale": 2.0}
    assert not is_dataclass(hp)
    pickle.loads(pickle.dumps(hp))

    # an init that mutates its hyperparameters dict must not corrupt
    # the recorded artifact (the artifact is a pre-init snapshot)
    def mutating_init(scale: float = 2.0) -> dict:
        ...

    model2 = Model(name="hp_mut_model", init=mutating_init, dataset=dataset)

    @model2.init
    def do_init(hyperparameters: dict) -> dict:
        return {"scale": hyperparameters.pop("scale")}

    @model2.trainer
    def trainer2(m: dict, features, target) -> dict:
        return m

    model2.train(hyperparameters={"scale": 3.0})
    assert model2.artifact.hyperparameters == {"scale": 3.0}


def test_task_interfaces(model):
    train_task = model.train_task()
    assert isinstance(train_task, Stage)
    assert list(train_task.input_types)[:2] == ["hyperparameters", "data"]
    predict_task = model.predict_task()
    assert "model_object" in predict_task.input_types
    pff_task = model.predict_from_features_task()
    assert list(pff_task.input_types) == ["model_object", "features"]


def test_local_train_and_predict(model):
    model_obj, metrics = model.train(
        hyperparameters={"C": 1.0, "max_iter": 1000}, sample_frac=1.0, random_state=123
    )
    assert set(metrics) == {"train", "test"}
    assert 0.0 <= metrics["test"] <= 1.0
    assert isinstance(model.artifact, ModelArtifact)

    preds = model.predict(sample_frac=1.0, random_state=123)
    assert isinstance(preds, list) and len(preds) == 100
    preds2 = model.predict(features=[{"x": 0.1, "x2": -0.2}])
    assert len(preds2) == 1


def test_train_with_kwargs_overrides(model):
    _, metrics = model.train(
        hyperparameters={"C": 0.1},
        splitter_kwargs={"test_size": 0.5, "shuffle": False},
        sample_frac=1.0,
        random_state=123,
    )
    assert set(metrics) == {"train", "test"}


def test_saver_loader_roundtrip(model, tmp_path):
    model.train(hyperparameters={"C": 1.0}, sample_frac=1.0, random_state=123)
    path = tmp_path / "model.joblib"
    model.save(path)

    fresh = Model(
        name="test_model",
        init=type(model.artifact.model_object),
        dataset=model.dataset,
    )
    loaded = fresh.load(path)
    np.testing.assert_array_equal(loaded.coef_, model.artifact.model_object.coef_)

    # file-object round trip (reference: tests/unit/test_model.py:126-142)
    buf = io.BytesIO()
    model.save(buf)
    buf.seek(0)
    loaded2 = fresh.load(buf)
    np.testing.assert_array_equal(loaded2.coef_, model.artifact.model_object.coef_)


def test_load_from_env(model, tmp_path, monkeypatch):
    model.train(hyperparameters={"C": 1.0}, sample_frac=1.0, random_state=123)
    path = tmp_path / "model.joblib"
    model.save(path)
    monkeypatch.setenv("UNIONML_MODEL_PATH", str(path))
    fresh = Model(
        name="test_model", init=type(model.artifact.model_object), dataset=model.dataset
    )
    loaded = fresh.load_from_env()
    assert loaded is fresh.artifact.model_object


def test_predict_requires_artifact(model):
    with pytest.raises(RuntimeError):
        model.predict(features=[{"x": 0.0, "x2": 0.0}])


def test_predict_requires_input(model):
    with pytest.raises(ValueError):
        model.predict()


def test_stage_interop_in_custom_workflow(model):
    """unionml stages composed in a user-authored workflow DAG
    (reference: tests/unit/test_model.py:145-196)."""
    model.train(hyperparameters={"C": 1.0}, sample_frac=1.0, random_state=123)

    wf = Workflow("custom")
    wf.add_input("sample_frac", float)
    wf.add_input("random_state", int)
    wf.add_input("model_object", object)
    ds_idx = wf.add_node(
        model.dataset.dataset_task(), {"sample_frac": "sample_frac", "random_state": "random_state"}
    )
    p_idx = wf.add_node(
        model.predict_task(), {"model_object": "model_object", "data": (ds_idx, None)}
    )
    wf.add_output("preds", p_idx, None)
    preds = wf(sample_frac=1.0, random_state=123, model_object=model.artifact.model_object)
    assert len(preds) == 100


def test_workflow_names(model):
    assert model.train_workflow_name == "test_model.train"
    assert model.predict_workflow_name == "test_model.predict"
    assert model.predict_from_features_workflow_name == "test_model.predict_from_features"
    assert repr(model.train_workflow())
