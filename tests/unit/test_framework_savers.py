"""Per-framework artifact savers/loaders (reference: model.py:931-988 and
the sklearn/pytorch/keras app matrix in tests/integration). The sklearn
and JAX-pytree paths are covered elsewhere (test_model.py,
test_train_step.py); THIS file covers the torch state_dict and keras
.save/load_model dispatch with full train -> save -> wipe -> load ->
predict roundtrips."""

import numpy as np
import pytest

# measured sub-minute module: part of the `-m quick` tier (Makefile
# test-quick) so iteration/CI sharding get a <5-min spec-path pass
pytestmark = pytest.mark.quick

from unionml_tpu import Dataset, Model


def _make_dataset(name):
    dataset = Dataset(name=name, test_size=0.25)

    @dataset.reader
    def reader(n: int = 48) -> dict:
        rng = np.random.default_rng(7)
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int64)
        return {"features": x, "targets": y}

    @dataset.splitter
    def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
        k = int(len(data["features"]) * (1 - test_size))
        return (
            {"features": data["features"][:k], "targets": data["targets"][:k]},
            {"features": data["features"][k:], "targets": data["targets"][k:]},
        )

    @dataset.parser
    def parser(data: dict, features, targets):
        return (data["features"], data["targets"])

    return dataset


def test_pytorch_artifact_roundtrip(tmp_path):
    torch = pytest.importorskip("torch", reason="torch not installed")

    class Net(torch.nn.Module):
        def __init__(self, hidden: int = 8):
            super().__init__()
            self.fc1 = torch.nn.Linear(4, hidden)
            self.fc2 = torch.nn.Linear(hidden, 2)

        def forward(self, x):
            return self.fc2(torch.relu(self.fc1(x)))

    model = Model(name="pt_model", init=Net, dataset=_make_dataset("pt_data"))

    @model.trainer
    def trainer(net: Net, features: np.ndarray, targets: np.ndarray) -> Net:
        torch.manual_seed(0)  # deterministic init -> stable assertions
        for layer in (net.fc1, net.fc2):
            layer.reset_parameters()
        opt = torch.optim.SGD(net.parameters(), lr=0.1)
        x = torch.as_tensor(features)
        y = torch.as_tensor(targets)
        for _ in range(30):
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
        return net

    @model.predictor
    def predictor(net: Net, features: np.ndarray) -> list:
        with torch.no_grad():
            return [int(i) for i in net(torch.as_tensor(features)).argmax(-1)]

    @model.evaluator
    def evaluator(net: Net, features: np.ndarray, targets: np.ndarray) -> float:
        with torch.no_grad():
            preds = net(torch.as_tensor(features)).argmax(-1).numpy()
        return float((preds == targets).mean())

    _, metrics = model.train(hyperparameters={"hidden": 8}, n=48)
    assert metrics["train"] > 0.7
    probe = np.array([[2.0, 2.0, 2.0, 2.0], [-2.0, -2.0, -2.0, -2.0]], np.float32)
    before = model.predict(features=probe)

    path = tmp_path / "model.pt"
    model.save(str(path))
    model.artifact = None
    with pytest.raises(RuntimeError, match="ModelArtifact not found"):
        model.predict(features=probe)
    # default loader rebuilds Net from the SAVED hyperparameters, then
    # load_state_dict (reference: model.py:965-980)
    loaded = model.load(str(path))
    assert isinstance(loaded, Net)
    assert model.predict(features=probe) == before == [1, 0]


def test_keras_artifact_roundtrip(tmp_path):
    keras = pytest.importorskip("tensorflow.keras", reason="keras not installed")

    # the return annotation IS the framework dispatch: model_type comes
    # from init (reference: model.py:920-922) and routes the keras saver
    def build(hidden: int = 8) -> keras.Model:
        m = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(hidden, activation="relu"),
            keras.layers.Dense(2),
        ])
        m.compile(optimizer="adam",
                  loss=keras.losses.SparseCategoricalCrossentropy(from_logits=True))
        return m

    model = Model(name="keras_model", init=build, dataset=_make_dataset("keras_data"))

    @model.trainer
    def trainer(net: keras.Model, features: np.ndarray, targets: np.ndarray) -> keras.Model:
        net.fit(features, targets, epochs=20, verbose=0)
        return net

    @model.predictor
    def predictor(net: keras.Model, features: np.ndarray) -> list:
        return [int(i) for i in net.predict(features, verbose=0).argmax(-1)]

    @model.evaluator
    def evaluator(net: keras.Model, features: np.ndarray, targets: np.ndarray) -> float:
        preds = net.predict(features, verbose=0).argmax(-1)
        return float((preds == targets).mean())

    model.train(hyperparameters={"hidden": 8}, n=48)
    probe = np.array([[2.0, 2.0, 2.0, 2.0], [-2.0, -2.0, -2.0, -2.0]], np.float32)
    before = model.predict(features=probe)

    path = tmp_path / "model.keras"
    model.save(str(path))
    model.artifact = None
    model.load(str(path))
    assert model.predict(features=probe) == before
