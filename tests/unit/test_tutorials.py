"""Executable-tutorial tests: every code cell in docs/tutorials runs.

Reference analog: the upstream project's MyST tutorials are executed in
docs CI; here each tutorial's code cells run in one shared namespace on
the CPU-simulated mesh, and the myst->ipynb converter round-trips them.
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[2]
TUTORIALS = sorted((REPO / "docs" / "tutorials").glob("*.md"))
sys.path.insert(0, str(REPO / "scripts"))

from myst_to_ipynb import split_cells, to_notebook  # noqa: E402


def _code_cells(path: Path):
    return [
        src for kind, src in split_cells(path.read_text(encoding="utf-8"))
        if kind == "code"
    ]


def test_tutorials_exist():
    names = {p.stem for p in TUTORIALS}
    assert {"mnist", "vision"} <= names


@pytest.mark.parametrize("path", TUTORIALS, ids=[p.stem for p in TUTORIALS])
def test_tutorial_code_cells_execute(path):
    cells = _code_cells(path)
    assert cells, f"{path} has no code cells"
    ns: dict = {}
    for i, src in enumerate(cells):
        try:
            exec(compile(src, f"{path.name}[cell {i}]", "exec"), ns)
        except Exception as e:  # pragma: no cover - failure formatting
            pytest.fail(f"{path.name} cell {i} failed: {e}\n---\n{src}")


@pytest.mark.parametrize("path", TUTORIALS, ids=[p.stem for p in TUTORIALS])
def test_converter_roundtrip(path, tmp_path):
    nb = to_notebook(path.read_text(encoding="utf-8"))
    kinds = [c["cell_type"] for c in nb["cells"]]
    assert "code" in kinds and "markdown" in kinds
    # code sources survive conversion verbatim
    converted = ["".join(c["source"]) for c in nb["cells"] if c["cell_type"] == "code"]
    assert converted == _code_cells(path)
    # the CLI writes valid nbformat-4 JSON
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "myst_to_ipynb.py"), str(path),
         "--out-dir", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    written = json.loads((tmp_path / f"{path.stem}.ipynb").read_text())
    assert written["nbformat"] == 4 and written["cells"]
    import nbformat

    nbformat.validate(nbformat.from_dict(written))


def test_converter_strips_cell_options():
    from myst_to_ipynb import split_cells

    doc = (
        "# T\n\n```{code-cell} python\n:tags: [hide-input]\n:label: x\n\n"
        "print(1)\n```\n"
    )
    cells = list(split_cells(doc))
    assert cells[-1] == ("code", "print(1)")
