"""Serving tests over a real socket
(reference analog: tests/integration/test_fastapi.py, stdlib transport)."""

import threading

import httpx
import numpy as np
import pytest

from unionml_tpu.serving.batcher import MicroBatcher
from unionml_tpu.serving.http import ServingApp


@pytest.fixture
def trained_model(model):
    model.train(hyperparameters={"max_iter": 500}, sample_frac=1.0, random_state=123)
    return model


@pytest.fixture
def server(trained_model):
    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    yield f"http://{host}:{port}", app
    app.shutdown()


def test_landing_and_health(server):
    url, _ = server
    r = httpx.get(f"{url}/")
    assert r.status_code == 200 and "unionml-tpu serving" in r.text
    r = httpx.get(f"{url}/health")
    assert r.status_code == 200
    assert r.json() == {
        "status": "ok", "model_loaded": True,
        "queue_depth": 0, "breaker_open": False,
    }


def test_predict_features_and_inputs(server):
    url, _ = server
    r = httpx.post(f"{url}/predict", json={"features": [{"x": 5.0, "x2": 5.0}]})
    assert r.status_code == 200
    assert isinstance(r.json(), list) and len(r.json()) == 1

    r = httpx.post(
        f"{url}/predict", json={"inputs": {"sample_frac": 0.1, "random_state": 1}}
    )
    assert r.status_code == 200
    assert len(r.json()) == 10


def test_predict_validation_errors(server):
    url, _ = server
    r = httpx.post(f"{url}/predict", json={})
    assert r.status_code == 422 and "exactly one" in r.json()["error"]
    r = httpx.post(
        f"{url}/predict",
        json={"features": [{"x": 1.0}], "inputs": {"sample_frac": 1.0}},
    )
    assert r.status_code == 422
    r = httpx.get(f"{url}/nope")
    assert r.status_code == 404


def test_serving_requires_artifact(model):
    app = ServingApp(model)
    with pytest.raises(RuntimeError, match="artifact unavailable"):
        app.setup_model()


def test_model_path_env_loading(trained_model, tmp_path, monkeypatch, dataset):
    path = tmp_path / "m.joblib"
    trained_model.save(path)
    trained_model.artifact = None
    monkeypatch.setenv("UNIONML_MODEL_PATH", str(path))
    app = ServingApp(trained_model)
    app.setup_model()
    assert trained_model.artifact is not None


# ------------------------------------------------------------------ batcher


def test_fastapi_transport_parity(trained_model):
    """The FastAPI adapter must serve the same routes/payloads as the
    stdlib transport (reference: unionml/fastapi.py is the primary
    serving surface)."""
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        assert client.get("/health").json()["model_loaded"] is True
        root = client.get("/")
        assert root.status_code == 200 and "unionml" in root.text.lower()
        r = client.post("/predict", json={"features": [[0.1, 0.2], [1.5, -0.3]]})
        assert r.status_code == 200 and len(r.json()) == 2
        # same status the stdlib transport asserts for the identical payload
        bad = client.post("/predict", json={"features": [[0.1, 0.2]], "inputs": {}})
        assert bad.status_code == 422


def test_microbatcher_coalesces_requests():
    calls = []

    def predict(feats):
        calls.append(feats.shape[0])
        return feats.sum(axis=1)

    batcher = MicroBatcher(predict, max_batch_size=16, max_wait_ms=50.0)
    results = [None] * 8
    threads = []

    def submit(i):
        results[i] = batcher.submit(np.full((1, 4), float(i)))

    for i in range(8):
        t = threading.Thread(target=submit, args=(i,))
        threads.append(t)
        t.start()
    for t in threads:
        t.join()
    batcher.close()

    for i, r in enumerate(results):
        np.testing.assert_allclose(r, [4.0 * i])
    # requests were coalesced: fewer device calls than requests
    assert len(calls) < 8
    # padded to bucket sizes
    assert all(c in (1, 2, 4, 8, 16) for c in calls)


def test_microbatcher_error_propagation():
    def predict(feats):
        raise ValueError("boom")

    batcher = MicroBatcher(predict, max_batch_size=4, max_wait_ms=1.0)
    with pytest.raises(ValueError, match="boom"):
        batcher.submit(np.ones((1, 2)))
    batcher.close()


def test_batched_serving_end_to_end(trained_model):
    app = ServingApp(trained_model, batch=True, max_wait_ms=10.0)
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    try:
        # batcher path requires array features; DataFrame coalescing uses
        # numpy conversion under the hood via the default feature loader
        feats = np.array([[5.0, 5.0]])
        r = httpx.post(f"{url}/predict", json={"features": feats.tolist()})
        assert r.status_code == 200
    finally:
        app.shutdown()


def test_microbatcher_ragged_prompt_lists():
    """LLM-style predictors take a LIST of ragged token-id rows; the
    batcher must coalesce by list concat (no array padding) and split
    results per request."""
    from unionml_tpu.serving.batcher import MicroBatcher

    calls = []

    def predict(prompts):
        calls.append(len(prompts))
        # echo generation: per-row output depends only on that row
        return [[int(t) + 1 for t in row][-2:] for row in prompts]

    batcher = MicroBatcher(
        predict, max_batch_size=8, max_wait_ms=30.0, row_lists=True
    )
    try:
        # deterministic: ONE multi-row ragged request -> one bucketed
        # device call, results split per row
        out = batcher.submit([[1, 2, 3], [4, 5], [6, 7, 8, 9]])
        assert out == [[3, 4], [5, 6], [9, 10]]
        assert calls == [4]  # bucketed 3 -> 4 with a replicated pad row

        # concurrent ragged single-row requests: correct per-request splits
        import concurrent.futures as cf

        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
        with cf.ThreadPoolExecutor(4) as pool:
            futs = [pool.submit(batcher.submit, [p]) for p in prompts]
            results = [f.result(timeout=30) for f in futs]
        assert results == [[[3, 4]], [[5, 6]], [[9, 10]], [[11]]]
    finally:
        batcher.close()


def test_stats_endpoint_direct_and_batched(trained_model):
    """GET /stats: 'direct' when no batcher, queue/device split after
    batched traffic, and a custom stats callable (the engine hook)."""
    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    try:
        r = httpx.get(f"http://{host}:{port}/stats")
        assert r.status_code == 200 and r.json()["engine"] == "direct"
    finally:
        app.shutdown()

    app = ServingApp(trained_model, batch=True, max_wait_ms=5.0)
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    try:
        httpx.post(f"{url}/predict", json={"features": [[5.0, 5.0]]})
        s = httpx.get(f"{url}/stats").json()
        assert s["engine"] == "micro-batch"
        assert s["completed_requests"] >= 1 and s["batches"] >= 1
        assert s["queue_wait_ms"]["p50"] >= 0
        assert s["device_ms"]["p50"] > 0
    finally:
        app.shutdown()

    app = ServingApp(trained_model, stats=lambda: {"engine": "continuous", "x": 1})
    host, port = app.serve(port=0, blocking=False)
    try:
        s = httpx.get(f"http://{host}:{port}/stats").json()
        assert s == {"engine": "continuous", "x": 1}
    finally:
        app.shutdown()


def test_health_draining_503_stdlib(trained_model):
    """App-level drain: /health reports draining with a 503 (the
    readiness contract load balancers key on) and /predict stops
    admitting with a Retry-After; resume() reopens."""
    app = ServingApp(trained_model)
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    try:
        assert app.drain() is True
        r = httpx.get(f"{url}/health")
        assert r.status_code == 503 and r.json()["status"] == "draining"
        r = httpx.post(f"{url}/predict", json={"features": [{"x": 1.0, "x2": 2.0}]})
        assert r.status_code == 503
        assert r.json()["reason"] == "draining"
        assert int(r.headers["retry-after"]) >= 1
        app.resume()
        assert httpx.get(f"{url}/health").status_code == 200
        r = httpx.post(f"{url}/predict", json={"features": [{"x": 1.0, "x2": 2.0}]})
        assert r.status_code == 200
    finally:
        app.shutdown()


def test_health_draining_503_fastapi(trained_model):
    """Transport parity: the FastAPI adapter serves the same not-ready
    => 503 health contract as the stdlib server."""
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        h = client.get("/health")
        assert h.status_code == 200
        body = h.json()
        assert body["status"] == "ok"
        assert body["queue_depth"] == 0 and body["breaker_open"] is False
        core = app.state.unionml_tpu
        core.drain()
        try:
            h = client.get("/health")
            assert h.status_code == 503 and h.json()["status"] == "draining"
            r = client.post("/predict", json={"features": [[0.1, 0.2]]})
            assert r.status_code == 503
            assert int(r.headers["retry-after"]) >= 1
        finally:
            core.resume()
        assert client.get("/health").status_code == 200


def test_health_sourced_from_engine():
    """ServingApp(health=engine.health): /health carries the engine's
    queue/breaker state and drains through the engine hook."""
    app, engine = _lm_serving_app(stream=False)
    host, port = app.serve(port=0, blocking=False)
    url = f"http://{host}:{port}"
    try:
        h = httpx.get(f"{url}/health")
        assert h.status_code == 200
        body = h.json()
        assert body["status"] == "ok" and body["model_loaded"] is True
        assert body["queue_depth"] == 0 and body["breaker_open"] is False
        assert app.drain(timeout=30) is True      # delegates to engine.drain
        assert engine.health()["status"] == "draining"
        assert httpx.get(f"{url}/health").status_code == 503
        engine.resume()
        app.resume()
        assert httpx.get(f"{url}/health").status_code == 200
    finally:
        app.shutdown()
        engine.close()


def test_fastapi_stats_route_parity(trained_model):
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        s = client.get("/stats")
        assert s.status_code == 200 and s.json()["engine"] == "direct"


def test_metrics_endpoint_and_request_id_stdlib(server):
    """GET /metrics serves Prometheus text exposition and every response
    carries a generated X-Request-ID (stdlib transport)."""
    url, _ = server
    r = httpx.post(f"{url}/predict", json={"features": [{"x": 1.0, "x2": 2.0}]})
    rid = r.headers.get("x-request-id")
    assert rid and len(rid) == 16 and int(rid, 16) >= 0  # hex id
    m = httpx.get(f"{url}/metrics")
    assert m.status_code == 200
    assert m.headers["content-type"].startswith("text/plain")
    assert m.headers.get("x-request-id") != rid  # fresh id per response
    # the HTTP layer's own series cover the predict we just made
    assert "unionml_http_requests_total" in m.text
    assert 'transport="stdlib"' in m.text and 'path="/predict"' in m.text
    assert "unionml_http_request_ms_bucket" in m.text


def test_metrics_cover_engine_series_after_traffic():
    """After engine-backed traffic, one scrape covers HTTP-layer AND
    engine series (the unified-registry contract)."""
    app, engine = _lm_serving_app()
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict", json={"features": [[1, 2, 3]]}, timeout=120
        )
        assert r.status_code == 200 and r.headers.get("x-request-id")
        text = httpx.get(f"{base}/metrics", timeout=30).text
        for name in (
            "unionml_engine_requests_total",
            "unionml_engine_queue_wait_ms_bucket",
            "unionml_engine_slots_in_use",
            "unionml_http_requests_total",
        ):
            assert name in text, name
        # the engine's labeled series reports this request
        row = next(
            line for line in text.splitlines()
            if line.startswith("unionml_engine_requests_total{")
            and f'engine="{engine.instance}"' in line
        )
        assert row.rsplit(" ", 1)[1] == "1"
    finally:
        app.shutdown()
        engine.close()


# ---------------------------------------------------------------------------
# SSE token streaming (POST /predict/stream)


def _lm_serving_app(stream=True):
    import jax
    import jax.numpy as jnp

    from unionml_tpu import Dataset, Model
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    cfg = LlamaConfig.tiny(vocab_size=61)
    module = Llama(cfg)
    params = module.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    engine = DecodeEngine(
        module, slots=2, max_new_tokens=10, prompt_buckets=(8,), chunk_steps=4
    )
    dataset = Dataset(name="sse_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    lm = Model(name="sse_lm", init=lambda: params, dataset=dataset)

    @lm.trainer
    def trainer(p: dict, features: list) -> dict:
        return p

    @lm.predictor
    def predictor(p: dict, prompts: list) -> list:
        return engine.generate(p, prompts)

    lm.artifact = ModelArtifact(params, {}, {})
    # the full engine wiring: stats + health + drain hooks
    kwargs = dict(stats=engine.stats, health=engine.health, drain=engine.drain)
    if stream:
        kwargs["stream"] = lambda p, prompts: engine.generate_stream(p, prompts[0])
    return ServingApp(lm, **kwargs), engine


def _read_sse(resp):
    events = []
    for line in resp.iter_lines():
        if line.startswith("data: "):
            import json

            events.append(json.loads(line[len("data: "):]))
    return events


def test_predict_stream_sse_token_identity():
    app, engine = _lm_serving_app()
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    prompt = list(range(1, 8))
    try:
        full = httpx.post(
            f"{base}/predict", json={"features": [prompt]}, timeout=120
        ).json()
        with httpx.stream(
            "POST", f"{base}/predict/stream", json={"features": prompt},
            timeout=120,
        ) as resp:
            assert resp.status_code == 200
            assert resp.headers["content-type"].startswith("text/event-stream")
            events = _read_sse(resp)
        assert events[-1]["done"] is True
        streamed = [t for e in events[:-1] for t in e["tokens"]]
        assert streamed == full[0]
        assert events[-1]["n_tokens"] == len(streamed)
        assert len(events) >= 3  # incremental: prefill + >=1 decode chunk
        # the engine's stats now carry the TTFT percentile
        stats = httpx.get(f"{base}/stats", timeout=30).json()
        assert "ttft_ms" in stats
    finally:
        app.shutdown()
        engine.close()


def test_predict_stream_validation():
    app, engine = _lm_serving_app()
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        # two prompts in one stream request
        r = httpx.post(
            f"{base}/predict/stream",
            json={"features": [[1, 2], [3, 4]]}, timeout=60,
        )
        assert r.status_code == 422 and "one prompt" in r.json()["error"]
        # inputs form is not streamable
        r = httpx.post(
            f"{base}/predict/stream", json={"inputs": {}}, timeout=60
        )
        assert r.status_code == 422
        # empty prompt: the generator defers validation to first next();
        # the transport must still turn it into a 422, not a dropped
        # connection
        r = httpx.post(
            f"{base}/predict/stream", json={"features": []}, timeout=60
        )
        assert r.status_code == 422
    finally:
        app.shutdown()
        engine.close()


def test_debug_surface_on_engine_backed_app():
    """The introspection surface end to end over a live engine: the
    flight recorder dump names real requests, /debug/memory reports the
    CPU devices, and stats()["programs"] flows through GET /stats."""
    app, engine = _lm_serving_app(stream=False)
    host, port = app.serve(port=0, blocking=False)
    base = f"http://{host}:{port}"
    try:
        r = httpx.post(
            f"{base}/predict", json={"features": [[1, 2, 3]]}, timeout=120
        )
        assert r.status_code == 200
        stats = httpx.get(f"{base}/stats", timeout=30).json()
        assert "programs" in stats
        assert stats["programs"]["engine.decode"]["flops_per_call"] > 0
        fl = httpx.get(f"{base}/debug/flight?n=50", timeout=30).json()
        kinds = {e["kind"] for e in fl["events"]}
        assert {"submit", "prefill", "finish"} <= kinds
        mem = httpx.get(f"{base}/debug/memory", timeout=60).json()
        assert mem["devices"] and mem["devices"][0]["platform"] == "cpu"
        assert mem["live_arrays"]["count"] >= 1  # engine params resident
    finally:
        app.shutdown()
        engine.close()


def test_fastapi_debug_route_parity(trained_model):
    """The FastAPI adapter serves the same debug routes as the stdlib
    transport (shared ServingApp methods — they cannot drift)."""
    fastapi = pytest.importorskip("fastapi")
    from fastapi.testclient import TestClient

    app = fastapi.FastAPI()
    trained_model.serve(app)
    with TestClient(app) as client:
        r = client.get("/debug/memory")
        assert r.status_code == 200 and r.json()["devices"]
        r = client.get("/debug/flight", params={"n": 3})
        assert r.status_code == 200 and "events" in r.json()
        r = client.post("/debug/profile?seconds=0.02")
        assert r.status_code == 200 and "trace_dir" in r.json()


def test_predict_stream_disabled_is_422():
    app, engine = _lm_serving_app(stream=False)
    host, port = app.serve(port=0, blocking=False)
    try:
        r = httpx.post(
            f"http://{host}:{port}/predict/stream",
            json={"features": [1, 2, 3]}, timeout=60,
        )
        assert r.status_code == 422
        assert "not enabled" in r.json()["error"]
    finally:
        app.shutdown()
        engine.close()
