"""unionml_tpu: a TPU-native declarative ML-microservice framework.

A ground-up rebuild of the capability surface of UnionML
(reference: /root/reference/unionml/__init__.py:1-35) designed TPU-first:

- user functions registered on ``Dataset`` / ``Model`` compile into named,
  cached, resource-annotated **stages** (the flytekit-task analog, but with a
  JAX execution substrate instead of Flyte),
- trainer / predictor bodies can be jit/pjit-compiled over a
  ``jax.sharding.Mesh`` with first-class DP/FSDP/TP/SP/PP/EP strategies,
- the data path streams host batches to HBM with double buffering,
- serving batches requests on-device,
- the remote backend targets TPU VM slices with git-SHA app versioning and
  an execution-history model registry.

Public API mirrors the reference (`unionml/__init__.py:4-5`): the two core
objects are :class:`Dataset` and :class:`Model`.
"""

from unionml_tpu.dataset import Dataset
from unionml_tpu.model import Model, ModelArtifact, BaseHyperparameters

try:  # single-source the version from package metadata when installed
    from importlib.metadata import version as _version

    __version__ = _version("unionml_tpu")
except Exception:  # pragma: no cover - not installed as a distribution
    __version__ = "0.1.0"

__all__ = [
    "Dataset",
    "Model",
    "ModelArtifact",
    "BaseHyperparameters",
    "__version__",
]
