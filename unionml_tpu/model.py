"""Model: training, evaluation, prediction, artifacts, serving, remote.

Capability parity with reference unionml/model.py:55-988, redesigned
TPU-first. The key departure from the reference is a **two-tier trainer
API** (SURVEY.md §7 "hard parts"):

1. ``@model.trainer`` — the reference contract: any Python function
   ``(model_object, *data, **kwargs) -> model_object``. Runs host-side,
   opaque to the compiler (the user may call jax.jit themselves).
2. ``@model.train_step`` — the TPU-native contract: a **pure, jittable**
   per-batch function ``(state, batch) -> (state, metrics)``. The framework
   synthesizes the epoch/batch trainer loop around it, compiles the step
   with ``jax.jit`` over a ``jax.sharding.Mesh`` (sharding strategies from
   :mod:`unionml_tpu.parallel`), donates the state buffers, and streams
   batches to HBM with double buffering (:mod:`unionml_tpu.data`).

Everything else mirrors the reference surface: hyperparameter dataclass
synthesis (model.py:137-161), three compiled tasks (model.py:377-502),
three workflows (model.py:292-375), local train/predict (model.py:504-578),
artifact save/load (model.py:580-608), serving (model.py:610-623), and the
remote lifecycle (model.py:625-917).
"""

from __future__ import annotations

import copy
import inspect
import os
from dataclasses import asdict, field, is_dataclass, make_dataclass
from inspect import Parameter

from unionml_tpu.type_guards import signature
from typing import IO, Any, Callable, Dict, List, NamedTuple, Optional, Tuple, Type, Union

from unionml_tpu import type_guards
from unionml_tpu._logging import logger
from unionml_tpu.dataset import Dataset
from unionml_tpu.defaults import DEFAULT_DEVICE_RESOURCES, DEFAULT_RESOURCES
from unionml_tpu.stage import Stage, Workflow, stage_from_fn
from unionml_tpu.tracking import TrackedInstance


class BaseHyperparameters:
    """Base class for synthesized hyperparameter dataclasses
    (reference: model.py:31-40)."""


class ModelArtifact(NamedTuple):
    """Model artifact: trained object + hyperparameters + metrics
    (reference: model.py:42-52)."""

    model_object: Any
    hyperparameters: Optional[Union[BaseHyperparameters, dict]] = None
    metrics: Optional[Dict[str, Any]] = None


def is_pytorch_model(model_type: Any) -> bool:
    """Reference: unionml/utils.py:62-64."""
    try:
        import torch.nn

        return inspect.isclass(model_type) and issubclass(model_type, torch.nn.Module)
    except ImportError:
        return False


def is_keras_model(model_type: Any) -> bool:
    """Reference: unionml/utils.py:66-67."""
    try:
        from tensorflow import keras

        return inspect.isclass(model_type) and issubclass(model_type, keras.Model)
    except ImportError:
        return False


def is_sklearn_model(obj_or_type: Any) -> bool:
    try:
        import sklearn.base

        t = obj_or_type if inspect.isclass(obj_or_type) else type(obj_or_type)
        return issubclass(t, sklearn.base.BaseEstimator)
    except ImportError:
        return False


def is_jax_pytree(obj: Any) -> bool:
    """True when ``obj`` looks like a JAX pytree of arrays (flax TrainState,
    param dict, etc.) — the TPU-native model-object family."""
    import jax

    leaves = jax.tree_util.tree_leaves(obj)
    if not leaves:
        return False
    return all(hasattr(leaf, "dtype") and hasattr(leaf, "shape") for leaf in leaves)


class Model(TrackedInstance):
    """Declarative model spec (reference: unionml/model.py:55)."""

    def __init__(
        self,
        name: str = "model",
        *,
        init: Optional[Union[Type, Callable]] = None,
        hyperparameter_config: Optional[Dict[str, Type]] = None,
        dataset: Optional[Dataset] = None,
    ):
        super().__init__()
        self.name = name
        self._init_callable = init
        self._hyperparameter_config = hyperparameter_config
        self._dataset = dataset if dataset is not None else Dataset(f"{name}.dataset")
        if self._dataset.name is None:
            self._dataset.name = f"{name}.dataset"

        self._artifact: Optional[ModelArtifact] = None

        # registered components
        self._init: Callable = self._default_init
        self._trainer: Optional[Callable] = None
        self._predictor: Optional[Callable] = None
        self._evaluator: Optional[Callable] = None
        self._saver: Callable = self._default_saver
        self._loader: Callable = self._default_loader

        # TPU-native step API
        self._train_step: Optional[Callable] = None
        self._train_step_options: Dict[str, Any] = {}
        self._predict_step_options: Dict[str, Any] = {}

        # compiled stages (lazily built)
        self._train_task: Optional[Stage] = None
        self._predict_task: Optional[Stage] = None
        self._predict_from_features_task: Optional[Stage] = None

        self._train_task_kwargs: Optional[Dict[str, Any]] = None
        self._predict_task_kwargs: Dict[str, Any] = {}

        self._hyperparameter_type: Optional[Type] = None

        # deployment configuration (reference: model.py:96-102, 625-654)
        self._registry: Optional[str] = None
        self._image_name: Optional[str] = None
        self._config_file: Optional[str] = None
        self._dockerfile: Optional[str] = None
        self._project: Optional[str] = None
        self._domain: Optional[str] = None
        self._backend = None  # unionml_tpu.remote backend handle
        self._patch_destination_dir: Optional[str] = None

    # ------------------------------------------------------------------ #
    # properties
    # ------------------------------------------------------------------ #

    @property
    def artifact(self) -> Optional[ModelArtifact]:
        return self._artifact

    @artifact.setter
    def artifact(self, new_value: ModelArtifact):
        self._artifact = new_value

    @property
    def dataset(self) -> Dataset:
        return self._dataset

    @property
    def hyperparameter_type(self) -> Type:
        """Synthesize the hyperparameter dataclass from the ``init``
        signature or ``hyperparameter_config`` (reference: model.py:137-161).
        Falls back to ``dict`` when any init argument is unannotated."""
        if self._hyperparameter_type is not None:
            return self._hyperparameter_type

        hyperparameter_fields: List[Any] = []
        if self._hyperparameter_config is None:
            if self._init_callable is None:
                self._hyperparameter_type = dict
                return dict
            sig = signature(self._init_callable)
            if any(p.annotation is inspect.Parameter.empty for p in sig.parameters.values()):
                self._hyperparameter_type = dict
                return dict
            for hparam_name, hparam in sig.parameters.items():
                hyperparameter_fields.append(
                    (hparam_name, hparam.annotation, field(default=hparam.default))
                )
        else:
            for hparam_name, hparam_type in self._hyperparameter_config.items():
                hyperparameter_fields.append((hparam_name, hparam_type))

        self._hyperparameter_type = make_dataclass(
            "Hyperparameters", hyperparameter_fields, bases=(BaseHyperparameters,)
        )
        return self._hyperparameter_type

    @property
    def config_file(self) -> Optional[str]:
        return self._config_file

    @property
    def registry(self) -> Optional[str]:
        return self._registry

    @property
    def dockerfile(self) -> Optional[str]:
        return self._dockerfile

    @property
    def train_workflow_name(self) -> str:
        return f"{self.name}.train"

    @property
    def predict_workflow_name(self) -> str:
        return f"{self.name}.predict"

    @property
    def predict_from_features_workflow_name(self) -> str:
        return f"{self.name}.predict_from_features"

    @property
    def model_type(self) -> Any:
        """Model object type from init (reference: model.py:920-922)."""
        init = (
            self._init_callable
            if self._init == self._default_init
            else self._init or self._init_callable
        )
        if init is None:
            return Any
        return init if inspect.isclass(init) else signature(init).return_annotation

    # ------------------------------------------------------------------ #
    # registration decorators (reference: model.py:193-283)
    # ------------------------------------------------------------------ #

    def init(self, fn):
        """Register a model-object initializer (reference: model.py:193-196)."""
        self._init = fn
        self._hyperparameter_type = None
        return fn

    def _expected_data_types(self) -> Tuple[Any, ...]:
        """Types the parser hands to trainer/evaluator
        (reference: model.py:210-223 — DataFrame special-cased into
        features+targets frames)."""
        ds = self._dataset
        if ds._parser == ds._default_parser:
            try:
                dtype = ds.dataset_datatype["data"]
            except ValueError:
                return ()  # no reader yet: decoration-order tolerance
            try:
                import pandas as pd

                if dtype is pd.DataFrame:
                    return (dtype, dtype)
            except ImportError:
                pass
            # the default parser ALWAYS yields two outputs — (features,
            # targets-or-None) — so the guard must demand two data args or
            # the runtime call `trainer(model, *parsed)` breaks
            # (reference parity: dataset.py:472-487 returns [features, targets])
            return (dtype, Any)
        return ds.parser_return_types

    def trainer(self, fn: Optional[Callable] = None, **train_task_kwargs):
        """Register the trainer (reference: model.py:198-228).

        ``**train_task_kwargs`` forward stage knobs: ``cache``,
        ``cache_version``, ``resources``. Host-opaque tier — for the
        jit/pjit tier use :meth:`train_step`.
        """
        if fn is None:
            return lambda f: self.trainer(f, **train_task_kwargs)
        type_guards.guard_trainer(fn, self.model_type, self._expected_data_types())
        self._trainer = fn
        self._train_task_kwargs = {
            "resources": self._default_stage_resources(), **train_task_kwargs
        }
        self._train_task = None
        return fn

    def _default_stage_resources(self):
        """Host-only model families (sklearn / torch-cpu / keras classes)
        default to ``chips=0`` so their runner env gets the
        ``JAX_PLATFORMS=cpu`` guard :mod:`unionml_tpu.defaults` promises;
        everything else (JAX pytree apps, the two-tier ``train_step``
        path) advertises a chip. Override per stage with
        ``resources=Resources(...)``."""
        mt = self.model_type
        if is_sklearn_model(mt) or is_pytorch_model(mt) or is_keras_model(mt):
            return DEFAULT_RESOURCES
        return DEFAULT_DEVICE_RESOURCES

    def train_step(
        self,
        fn: Optional[Callable] = None,
        *,
        sharding: Any = None,
        donate_state: bool = True,
        accumulate_steps: int = 1,
        overlap_grads: bool = False,
        double_buffer: bool = False,
        donate_batch: Optional[bool] = None,
        checkpoint_dir: Optional[str] = None,
        save_every: int = 100,
        max_checkpoints: int = 3,
        checkpoint_backend: str = "auto",
        goodput: Any = None,
        measure_device_time: bool = False,
        **train_task_kwargs,
    ):
        """Register a TPU-native, jittable per-batch training step.

        Contract: ``step(state, batch) -> (state, metrics)`` where ``state``
        is a JAX pytree (e.g. flax TrainState) and ``batch`` is a pytree of
        arrays with a leading batch axis. The framework synthesizes the
        surrounding trainer (epochs, batching, device feed) and compiles the
        step with ``jax.jit`` under the mesh/shardings described by
        ``sharding`` (a :class:`unionml_tpu.parallel.ShardingConfig`).

        ``accumulate_steps=N``: gradient accumulation — the trainer feeds
        ``[N, batch_size, ...]`` microbatched batches and the step must
        scan them into one optimizer update (build it with a zoo factory's
        ``accumulate_steps`` or
        :func:`unionml_tpu.models.train.accumulated_value_and_grad`).
        The HBM knob for effective batch at long context.

        ``overlap_grads`` / ``double_buffer`` / ``donate_batch``
        (docs/performance.md "Overlapped training"): overlap the
        gradient all-reduce of microbatch *i* with the backward of
        *i+1* (loss-trajectory-identical to the serial accumulate),
        move the data feed — host batch pull + device-transfer
        dispatch — onto a background thread, and donate the fed batch
        buffers to the step. All three plumb through to whichever
        trainer loop the route below synthesizes.

        ``goodput``: training goodput accounting
        (docs/observability.md "Training goodput") — ``True`` or a
        :class:`unionml_tpu.goodput.GoodputTracker` attributes the
        synthesized loop's wall time into compute vs. badput buckets
        (data-wait, host→device, compile, checkpoint, preemption) on
        both the plain and checkpointed routes;
        ``measure_device_time=True`` adds a per-step sync so
        ``unionml_trainer_step_ms`` samples real device latency
        (plain route only — the elastic loop owns its own stepping).

        ``checkpoint_dir``: PREEMPTION SAFETY (SURVEY §5.3) — the
        synthesized trainer routes through
        :func:`unionml_tpu.elastic.run_elastic_trainer`: the state
        checkpoints every ``save_every`` optimizer steps (keeping
        ``max_checkpoints``), and a killed-and-relaunched run resumes
        from the newest checkpoint to the bit-identical final state of
        an uninterrupted run. A relative path resolves against the
        runner's working directory — stable across relaunches of the
        same deployed app version, which is what makes
        ``backend.execute(..., max_restarts=N)`` a preemption-recovery
        loop rather than a train-from-scratch retry. (The reference
        delegates retry semantics to Flyte; here restart-and-resume is
        a framework primitive.)

        No reference counterpart — this is the north-star TPU path
        (BASELINE.json: "trainer bodies compile to pjit'd XLA computations").
        """
        if fn is None:
            return lambda f: self.train_step(
                f, sharding=sharding, donate_state=donate_state,
                accumulate_steps=accumulate_steps,
                overlap_grads=overlap_grads, double_buffer=double_buffer,
                donate_batch=donate_batch,
                checkpoint_dir=checkpoint_dir, save_every=save_every,
                max_checkpoints=max_checkpoints,
                checkpoint_backend=checkpoint_backend, goodput=goodput,
                measure_device_time=measure_device_time,
                **train_task_kwargs
            )
        type_guards.guard_train_step(fn)
        self._train_step = fn
        self._train_step_options = {
            "sharding": sharding,
            "donate_state": donate_state,
            "accumulate_steps": accumulate_steps,
            "overlap_grads": overlap_grads,
            "double_buffer": double_buffer,
            "donate_batch": donate_batch,
            "checkpoint_dir": checkpoint_dir,
            "save_every": save_every,
            "max_checkpoints": max_checkpoints,
            "checkpoint_backend": checkpoint_backend,
            "goodput": goodput,
            "measure_device_time": measure_device_time,
        }
        self._trainer = self._make_step_trainer()
        self._train_task_kwargs = {"resources": DEFAULT_DEVICE_RESOURCES, **train_task_kwargs}
        self._train_task = None
        return fn

    def _make_step_trainer(self) -> Callable:
        """Synthesize an epoch/batch trainer loop around the registered
        ``train_step`` (the jit tier of the two-tier API)."""
        from unionml_tpu.execution import run_step_trainer

        model = self

        def trainer(
            model_object,
            features,
            targets=None,
            *,
            num_epochs: int = 1,
            batch_size: int = 32,
            seed: int = 0,
        ):
            opts = model._train_step_options
            checkpoint_dir = opts.get("checkpoint_dir")
            if checkpoint_dir:
                # preemption-safe route: periodic checkpoints + resume
                # from the newest one on relaunch (elastic.py's
                # deterministic (seed, epoch) data-order contract)
                import numpy as np

                from unionml_tpu.elastic import run_elastic_trainer
                from unionml_tpu.execution import is_stream

                common = dict(
                    step_fn=model._train_step,
                    state=model_object,
                    checkpoint_dir=checkpoint_dir,
                    checkpoint_every=opts.get("save_every", 100),
                    max_to_keep=opts.get("max_checkpoints", 3),
                    checkpoint_backend=opts.get("checkpoint_backend", "auto"),
                    batch_size=batch_size,
                    seed=seed,
                    sharding=opts.get("sharding"),
                    donate_state=opts.get("donate_state", True),
                    accumulate_steps=opts.get("accumulate_steps", 1),
                    overlap_grads=opts.get("overlap_grads", False),
                    double_buffer=opts.get("double_buffer", False),
                    donate_batch=opts.get("donate_batch"),
                    goodput=opts.get("goodput"),
                )
                if is_stream(features):
                    # resumable streams must be SEEKABLE or REPLAYABLE
                    # callables (run_elastic_trainer's contract) — a
                    # one-shot iterator cannot reproduce consumed
                    # batches after a preemption, and epochs don't apply
                    # to a step-indexed stream
                    if not callable(features):
                        raise ValueError(
                            "checkpoint_dir training needs a CALLABLE "
                            "stream — stream() replayable or "
                            "stream(start_step) seekable — so a "
                            "relaunch can resume; a one-shot iterator "
                            "cannot reproduce consumed batches"
                        )
                    if targets is not None:
                        raise ValueError(
                            "streaming trainers take batches from "
                            "`features` alone — yield (x, y) tuples "
                            "from the stream instead of passing targets"
                        )
                    if num_epochs != 1:
                        raise ValueError(
                            "a checkpointed stream is ONE step-indexed "
                            f"sequence (got num_epochs={num_epochs}); "
                            "bound it with the stream itself and keep "
                            "num_epochs=1"
                        )
                    state, _step = run_elastic_trainer(
                        stream=features, **common
                    )
                else:
                    arrays = [np.asarray(features)]
                    if targets is not None:
                        arrays.append(np.asarray(targets))
                    state, _step = run_elastic_trainer(
                        arrays=arrays, num_epochs=num_epochs, **common
                    )
                return state
            return run_step_trainer(
                step_fn=model._train_step,
                state=model_object,
                features=features,
                targets=targets,
                num_epochs=num_epochs,
                batch_size=batch_size,
                seed=seed,
                sharding=opts.get("sharding"),
                donate_state=opts.get("donate_state", True),
                accumulate_steps=opts.get("accumulate_steps", 1),
                overlap_grads=opts.get("overlap_grads", False),
                double_buffer=opts.get("double_buffer", False),
                donate_batch=opts.get("donate_batch"),
                goodput=opts.get("goodput"),
                measure_device_time=opts.get("measure_device_time", False),
            )

        trainer.__name__ = "synthesized_step_trainer"
        return trainer

    def predictor(self, fn: Optional[Callable] = None, **predict_task_kwargs):
        """Register the predictor (reference: model.py:230-252).

        TPU-native extras: ``jit=True`` compiles the predictor body with
        ``jax.jit`` for on-device serving; ``batch_axis`` hints at the
        micro-batching axis for the serving batcher.
        """
        if fn is None:
            return lambda f: self.predictor(f, **predict_task_kwargs)
        jit = predict_task_kwargs.pop("jit", False)
        batch_axis = predict_task_kwargs.pop("batch_axis", 0)
        type_guards.guard_predictor(fn, self.model_type, self._dataset.feature_type)
        self._predictor = fn
        self._predict_step_options = {"jit": jit, "batch_axis": batch_axis}
        self._predict_task_kwargs = {
            "resources": self._default_stage_resources(), **predict_task_kwargs
        }
        self._predict_task = None
        self._predict_from_features_task = None
        return fn

    def evaluator(self, fn):
        """Register the evaluator (reference: model.py:254-271)."""
        type_guards.guard_evaluator(fn, self.model_type, self._expected_data_types())
        self._evaluator = fn
        return fn

    def saver(self, fn):
        """Register a model-object serializer (reference: model.py:273-276)."""
        self._saver = fn
        return fn

    def loader(self, fn):
        """Register a model-object deserializer (reference: model.py:278-281)."""
        self._loader = fn
        return fn

    # ------------------------------------------------------------------ #
    # compiled stages (reference: model.py:377-502)
    # ------------------------------------------------------------------ #

    @property
    def trainer_params(self) -> Dict[str, Parameter]:
        """Keyword-only params of the trainer → workflow inputs
        (reference: model.py:284-291)."""
        if self._trainer is None:
            return {}
        return {
            name: param
            for name, param in signature(self._trainer).parameters.items()
            if param.kind == Parameter.KEYWORD_ONLY
        }

    def train_task(self) -> Stage:
        """Compile trainer+evaluator into the train stage
        (reference: model.py:377-443)."""
        if self._train_task is not None:
            return self._train_task
        if self._trainer is None:
            raise ValueError(
                f"Model {self.name!r} has no trainer. Register one with "
                "@model.trainer or @model.train_step."
            )

        [(data_arg_name, data_arg_type)] = self._dataset.dataset_datatype.items()
        hyperparam_param = Parameter(
            "hyperparameters", Parameter.KEYWORD_ONLY, annotation=self.hyperparameter_type
        )
        parameters = [
            hyperparam_param,
            Parameter(data_arg_name, Parameter.KEYWORD_ONLY, annotation=data_arg_type),
            *[
                Parameter(arg, Parameter.KEYWORD_ONLY, annotation=dict, default=None)
                for arg in ("loader_kwargs", "splitter_kwargs", "parser_kwargs")
            ],
            *self.trainer_params.values(),
        ]
        trainer_ret = signature(self._trainer).return_annotation
        eval_ret = (
            signature(self._evaluator).return_annotation if self._evaluator else Any
        )
        return_annotation = NamedTuple(
            "ModelArtifact",
            model_object=trainer_ret,
            # plain data on the way OUT (the synthesized dataclass is the
            # INPUT type only): see the normalization note at the return
            hyperparameters=Optional[dict],  # type: ignore[valid-type]
            metrics=Dict[str, eval_ret],  # type: ignore[valid-type]
        )

        def train_task(**kwargs):
            hyperparameters = kwargs["hyperparameters"]
            raw_data = kwargs[data_arg_name]
            trainer_kwargs = {p: kwargs[p] for p in self.trainer_params if p in kwargs}

            hp_dict = asdict(hyperparameters) if is_dataclass(hyperparameters) else hyperparameters
            # insulate BEFORE init runs: an init that mutates its
            # hyperparameters dict (even nested values) must corrupt
            # neither the recorded artifact nor the caller's own dict
            if isinstance(hp_dict, dict):
                hp_out = copy.deepcopy(hp_dict)
                hp_dict = copy.deepcopy(hp_dict)
            else:
                hp_out = hp_dict

            def dc_kwargs(key):
                v = kwargs.get(key)
                return asdict(v) if is_dataclass(v) else v

            training_data = self._dataset.get_data(
                raw_data,
                loader_kwargs=dc_kwargs("loader_kwargs"),
                splitter_kwargs=dc_kwargs("splitter_kwargs"),
                parser_kwargs=dc_kwargs("parser_kwargs"),
            )
            model_object = self._trainer(
                self._init(hyperparameters=hp_dict),
                *training_data["train"],
                **trainer_kwargs,
            )
            metrics = (
                {
                    split_key: self._evaluator(model_object, *training_data[split_key])
                    for split_key in training_data
                }
                if self._evaluator is not None
                else {}
            )
            # hyperparameters cross the artifact boundary as plain data:
            # the synthesized dataclass (hyperparameter_type) has no
            # importable home, so its instances cannot be pickled by the
            # remote runner's output dump — the reference has the same
            # normalization implicitly (flytekit ships dataclasses as
            # JSON and regenerates the type via the task resolver;
            # reference: model.py:137-161, task_resolver.py:16-31).
            return return_annotation(model_object, hp_out, metrics)

        self._train_task = stage_from_fn(
            train_task,
            owner=self,
            name=f"{self.name}.train_task",
            parameters=parameters,
            return_annotation=return_annotation,
            stage_method="train_task",
            **(self._train_task_kwargs or {}),
        )
        return self._train_task

    def predict_task(self) -> Stage:
        """Compile the predictor over reader output
        (reference: model.py:445-474)."""
        if self._predict_task is not None:
            return self._predict_task
        if self._predictor is None:
            raise ValueError(
                f"Model {self.name!r} has no predictor. Register one with @model.predictor."
            )

        predictor_sig = signature(self._predictor)
        model_param, *_ = predictor_sig.parameters.values()
        model_param = model_param.replace(name="model_object", kind=Parameter.KEYWORD_ONLY)
        [(data_arg_name, data_arg_type)] = self._dataset.dataset_datatype.items()
        data_param = Parameter(data_arg_name, Parameter.KEYWORD_ONLY, annotation=data_arg_type)

        def predict_task(**kwargs):
            model_object = kwargs["model_object"]
            parsed = self._dataset._parser(kwargs[data_arg_name], **self._dataset.parser_kwargs)
            features = parsed[self._dataset._parser_feature_key]
            return self._call_predictor(model_object, features)

        self._predict_task = stage_from_fn(
            predict_task,
            owner=self,
            name=f"{self.name}.predict_task",
            parameters=[model_param, data_param],
            return_annotation=predictor_sig.return_annotation,
            stage_method="predict_task",
            **self._predict_task_kwargs,
        )
        return self._predict_task

    def predict_from_features_task(self) -> Stage:
        """Compile the predictor over raw features
        (reference: model.py:476-502)."""
        if self._predict_from_features_task is not None:
            return self._predict_from_features_task
        if self._predictor is None:
            raise ValueError(
                f"Model {self.name!r} has no predictor. Register one with @model.predictor."
            )

        predictor_sig = signature(self._predictor)
        model_param, features_param = list(predictor_sig.parameters.values())[:2]
        model_param = model_param.replace(name="model_object", kind=Parameter.KEYWORD_ONLY)
        features_param = Parameter(
            "features", Parameter.KEYWORD_ONLY, annotation=features_param.annotation
        )

        def predict_from_features_task(**kwargs):
            return self._call_predictor(kwargs["model_object"], kwargs["features"])

        self._predict_from_features_task = stage_from_fn(
            predict_from_features_task,
            owner=self,
            name=f"{self.name}.predict_from_features_task",
            parameters=[model_param, features_param],
            return_annotation=predictor_sig.return_annotation,
            stage_method="predict_from_features_task",
            **self._predict_task_kwargs,
        )
        return self._predict_from_features_task

    def _call_predictor(self, model_object, features):
        """Dispatch to the (optionally jit-compiled) predictor."""
        if self._predict_step_options.get("jit"):
            from unionml_tpu.execution import jit_predictor

            compiled = jit_predictor(self._predictor)
            return compiled(model_object, features)
        return self._predictor(model_object, features)

    # ------------------------------------------------------------------ #
    # workflows (reference: model.py:292-375)
    # ------------------------------------------------------------------ #

    def train_workflow(self) -> Workflow:
        """reader → train stage, wired as a named DAG
        (reference: model.py:292-338)."""
        dataset_task = self._dataset.dataset_task()
        train_task = self.train_task()

        wf = Workflow(self.train_workflow_name)
        wf.add_input("hyperparameters", self.hyperparameter_type)
        for arg in ("loader_kwargs", "splitter_kwargs", "parser_kwargs"):
            wf.add_input(arg, dict, default=None)
        for arg, param in dataset_task.__signature__.parameters.items():
            default = param.default if param.default is not Parameter.empty else Workflow._EMPTY
            wf.add_input(arg, param.annotation, default=default)
        for arg, param in self.trainer_params.items():
            default = param.default if param.default is not Parameter.empty else Workflow._EMPTY
            wf.add_input(arg, param.annotation, default=default)

        ds_idx = wf.add_node(dataset_task, {k: k for k in dataset_task.input_types})
        [(data_arg_name, _)] = self._dataset.dataset_datatype.items()
        train_bindings: Dict[str, Any] = {
            "hyperparameters": "hyperparameters",
            data_arg_name: (ds_idx, None),
            "loader_kwargs": "loader_kwargs",
            "splitter_kwargs": "splitter_kwargs",
            "parser_kwargs": "parser_kwargs",
        }
        for arg in self.trainer_params:
            train_bindings[arg] = arg
        tr_idx = wf.add_node(train_task, train_bindings)

        wf.add_output("model_object", tr_idx, lambda r: r.model_object)
        wf.add_output("hyperparameters", tr_idx, lambda r: r.hyperparameters)
        wf.add_output("metrics", tr_idx, lambda r: r.metrics)
        return wf

    def predict_workflow(self) -> Workflow:
        """reader → predict stage (reference: model.py:340-361)."""
        dataset_task = self._dataset.dataset_task()
        predict_task = self.predict_task()

        wf = Workflow(self.predict_workflow_name)
        wf.add_input("model_object", predict_task.input_types["model_object"])
        for arg, param in dataset_task.__signature__.parameters.items():
            default = param.default if param.default is not Parameter.empty else Workflow._EMPTY
            wf.add_input(arg, param.annotation, default=default)

        ds_idx = wf.add_node(dataset_task, {k: k for k in dataset_task.input_types})
        [(data_arg_name, _)] = self._dataset.dataset_datatype.items()
        p_idx = wf.add_node(
            predict_task, {"model_object": "model_object", data_arg_name: (ds_idx, None)}
        )
        wf.add_output("predictions", p_idx, None)
        return wf

    def predict_from_features_workflow(self) -> Workflow:
        """raw features → predict stage (reference: model.py:363-375)."""
        predict_task = self.predict_from_features_task()
        wf = Workflow(self.predict_from_features_workflow_name)
        for arg, annotation in predict_task.input_types.items():
            wf.add_input(arg, annotation)
        p_idx = wf.add_node(predict_task, {k: k for k in predict_task.input_types})
        wf.add_output("predictions", p_idx, None)
        return wf

    # ------------------------------------------------------------------ #
    # local execution (reference: model.py:504-578)
    # ------------------------------------------------------------------ #

    def train(
        self,
        hyperparameters: Optional[Dict[str, Any]] = None,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
        trainer_kwargs: Optional[Dict[str, Any]] = None,
        **reader_kwargs,
    ) -> Tuple[Any, Any]:
        """Train locally through the compiled workflow
        (reference: model.py:504-547)."""
        trainer_kwargs = trainer_kwargs or {}
        hp_type = self.hyperparameter_type
        hp_value = (
            hp_type(**(hyperparameters or {})) if hp_type is not dict else (hyperparameters or {})
        )
        result = self.train_workflow()(
            hyperparameters=hp_value,
            loader_kwargs=self._dataset.loader_kwargs_type(**(loader_kwargs or {})),
            splitter_kwargs=self._dataset.splitter_kwargs_type(**(splitter_kwargs or {})),
            parser_kwargs=self._dataset.parser_kwargs_type(**(parser_kwargs or {})),
            **{**reader_kwargs, **trainer_kwargs},
        )
        model_obj = result["model_object"]
        hp = result["hyperparameters"]
        metrics = result["metrics"]
        self.artifact = ModelArtifact(model_obj, hp, metrics)
        return model_obj, metrics

    def predict(self, features: Any = None, **reader_kwargs):
        """Predict locally from features or reader kwargs
        (reference: model.py:549-578)."""
        if features is None and not reader_kwargs:
            raise ValueError("At least one of features or **reader_kwargs must be provided")
        if self.artifact is None:
            raise RuntimeError(
                "ModelArtifact not found. Train a model first with the `train` method "
                "before generating predictions."
            )
        if features is None:
            return self.predict_workflow()(
                model_object=self.artifact.model_object, **reader_kwargs
            )
        return self.predict_from_features_workflow()(
            model_object=self.artifact.model_object,
            features=self._dataset.get_features(features),
        )

    # ------------------------------------------------------------------ #
    # artifact save/load (reference: model.py:580-608, 931-988)
    # ------------------------------------------------------------------ #

    def save(self, file: Union[str, os.PathLike, IO], *args, **kwargs):
        if self.artifact is None:
            raise AttributeError(
                "`artifact` property is None. Call the `train` method to train a model first"
            )
        return self._saver(
            self.artifact.model_object, self.artifact.hyperparameters, file, *args, **kwargs
        )

    def load(self, file: Union[str, os.PathLike, IO], *args, **kwargs):
        self.artifact = ModelArtifact(self._loader(file, *args, **kwargs))
        return self.artifact.model_object

    def load_from_env(self, env_var: str = "UNIONML_MODEL_PATH", *args, **kwargs):
        model_path = os.getenv(env_var)
        # empty string counts as unset (containers often export VAR="")
        if not model_path:
            raise ValueError(f"env var for model path {env_var} doesn't exist.")
        return self.load(model_path, *args, **kwargs)

    def _default_init(self, hyperparameters: dict) -> Any:
        if self._init_callable is None:
            raise ValueError(
                "When using the default init, you must specify the init argument "
                "to the Model constructor."
            )
        return self._init_callable(**hyperparameters)

    def _default_saver(
        self,
        model_obj: Any,
        hyperparameters: Union[dict, BaseHyperparameters, None],
        file: Union[str, os.PathLike, IO],
        *args,
        **kwargs,
    ) -> Any:
        """Framework-dispatch saver (reference: model.py:931-963) with a
        JAX-pytree branch first: pytree artifacts serialize via flax
        msgpack (sharded Orbax checkpoints live in
        :mod:`unionml_tpu.checkpoint`)."""
        hp = (
            asdict(hyperparameters)
            if hyperparameters is not None and is_dataclass(hyperparameters)
            else hyperparameters
        )
        if is_sklearn_model(model_obj):
            import joblib

            return joblib.dump({"model_obj": model_obj, "hyperparameters": hp}, file, *args, **kwargs)
        model_type = self.model_type
        if is_pytorch_model(model_type):
            import torch

            torch.save({"model_obj": model_obj.state_dict(), "hyperparameters": hp}, file)
            return file
        if is_keras_model(model_type):
            model_obj.save(file, *args, **kwargs)
            return file
        if is_jax_pytree(model_obj):
            from unionml_tpu.checkpoint import save_pytree

            save_pytree(model_obj, hp, file)
            return file
        raise NotImplementedError(
            f"Default saver not defined for type {type(model_obj)}. Use the "
            "Model.saver decorator to define one."
        )

    def _default_loader(self, file: Union[str, os.PathLike, IO], *args, **kwargs) -> Any:
        """Framework-dispatch loader (reference: model.py:965-988)."""
        model_type = self.model_type
        if inspect.isclass(model_type) and is_sklearn_model(model_type):
            import joblib

            return joblib.load(file, *args, **kwargs)["model_obj"]
        if is_pytorch_model(model_type):
            import torch

            payload = torch.load(file, *args, **kwargs)
            if self._init_callable is not None:
                model = self._init(hyperparameters=payload["hyperparameters"] or {})
            else:
                model = model_type(**(payload["hyperparameters"] or {}))
            model.load_state_dict(payload["model_obj"])
            return model
        if is_keras_model(model_type):
            from tensorflow import keras

            return keras.models.load_model(file)
        # JAX-pytree branch: rebuild the target structure via init, then
        # restore leaves from the msgpack payload.
        from unionml_tpu.checkpoint import load_pytree

        def target_factory(hp):
            return self._init(hyperparameters=hp or {})

        return load_pytree(file, target_factory)

    # ------------------------------------------------------------------ #
    # serving (reference: model.py:610-623)
    # ------------------------------------------------------------------ #

    def serve(
        self,
        app=None,
        remote: bool = False,
        app_version: Optional[str] = None,
        model_version: str = "latest",
        batch: bool = False,
        **batcher_kwargs,
    ):
        """Mount serving endpoints (reference: model.py:610-623).

        ``app`` may be a FastAPI instance or ``None`` for the
        dependency-free stdlib HTTP server. ``batch=True`` enables the
        on-device micro-batcher (TPU-native addition). Returns the app.
        """
        from unionml_tpu.serving.fastapi import serving_app

        return serving_app(
            self,
            app,
            remote=remote,
            app_version=app_version,
            model_version=model_version,
            batch=batch,
            **batcher_kwargs,
        )

    def serve_gradio(self, **interface_kwargs):
        """Launchable Gradio interface over the predictor
        (reference parity: the mnist tutorial's Gradio integration,
        docs/source/tutorials/mnist.md:37). Optional dependency — raises
        with install guidance when gradio is absent.
        """
        try:
            import gradio
        except ImportError as e:
            raise ImportError(
                "model.serve_gradio() needs the optional gradio dependency: "
                "pip install gradio"
            ) from e
        if self.artifact is None:
            raise ValueError("no model artifact loaded — train or load first")

        def fn(features):
            return self.predict(features=features)

        interface_kwargs.setdefault("inputs", "json")
        interface_kwargs.setdefault("outputs", "json")
        return gradio.Interface(fn=fn, **interface_kwargs)

    # ------------------------------------------------------------------ #
    # remote lifecycle (reference: model.py:625-917)
    # ------------------------------------------------------------------ #

    def remote(
        self,
        registry: Optional[str] = None,
        image_name: Optional[str] = None,
        config_file: Optional[str] = None,
        project: Optional[str] = None,
        domain: Optional[str] = None,
        dockerfile: str = "Dockerfile",
        patch_destination_dir: str = "/root",
    ):
        """Configure the remote backend (reference: model.py:625-654)."""
        self._registry = registry
        self._image_name = image_name
        self._config_file = config_file
        self._project = project or self.name.replace("_", "-")
        self._domain = domain or "development"
        self._dockerfile = dockerfile
        self._patch_destination_dir = patch_destination_dir
        self._backend = None

    @property
    def _remote(self):
        """Lazily construct the backend handle (reference: model.py:657-670)."""
        if self._backend is not None:
            return self._backend
        from unionml_tpu.remote import get_backend

        self._backend = get_backend(
            config_file=self._config_file,
            project=self._project or self.name.replace("_", "-"),
            domain=self._domain or "development",
        )
        return self._backend

    def remote_deploy(
        self, app_version: Optional[str] = None, allow_uncommitted: bool = False, patch: bool = False
    ) -> str:
        """Package and register the app (reference: model.py:672-730)."""
        from unionml_tpu import remote as remote_module

        app_version = app_version or remote_module.get_app_version(allow_uncommitted)
        if patch:
            app_version = f"{app_version}-patch{remote_module.patch_suffix()}"
        self._remote.deploy(self, app_version=app_version, patch=patch)
        logger.info(f"deployed {self.name} version {app_version}")
        return app_version

    def remote_train(
        self,
        app_version: Optional[str] = None,
        wait: bool = True,
        *,
        hyperparameters: Optional[Dict[str, Any]] = None,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
        trainer_kwargs: Optional[Dict[str, Any]] = None,
        **reader_kwargs,
    ):
        """Launch training on the backend (reference: model.py:732-796)."""
        execution = self._remote.execute(
            self,
            workflow="train",
            app_version=app_version,
            inputs=dict(
                hyperparameters=hyperparameters or {},
                loader_kwargs=loader_kwargs,
                splitter_kwargs=splitter_kwargs,
                parser_kwargs=parser_kwargs,
                trainer_kwargs=trainer_kwargs or {},
                **reader_kwargs,
            ),
            wait=wait,
        )
        if wait:
            self.remote_load(execution)
            return self.artifact
        return execution

    def remote_predict(
        self,
        app_version: Optional[str] = None,
        model_version: Optional[str] = None,
        wait: bool = True,
        *,
        features: Any = None,
        **reader_kwargs,
    ):
        """Launch prediction on the backend (reference: model.py:798-864)."""
        workflow = "predict" if features is None else "predict_from_features"
        inputs: Dict[str, Any] = dict(reader_kwargs)
        if features is not None:
            inputs["features"] = features
        execution = self._remote.execute(
            self,
            workflow=workflow,
            app_version=app_version,
            model_version=model_version,
            inputs=inputs,
            wait=wait,
        )
        if wait:
            return self.remote_fetch_predictions(execution)
        return execution

    def remote_wait(self, execution, **kwargs):
        """Block until an execution completes (reference: model.py:866-870)."""
        return self._remote.wait(execution, **kwargs)

    def remote_load(self, execution):
        """Load the model artifact from an execution
        (reference: model.py:872-894)."""
        from unionml_tpu.remote.artifacts import decode_model_object

        execution = self._remote.wait(execution)
        outputs = self._remote.fetch_outputs(execution)
        self.artifact = ModelArtifact(
            decode_model_object(self, outputs.get("model_object")),
            outputs.get("hyperparameters"),
            outputs.get("metrics"),
        )
        return self.artifact

    def remote_list_model_versions(self, app_version: Optional[str] = None, limit: int = 10):
        """Model versions = successful train executions
        (reference: model.py:896-906)."""
        return self._remote.list_model_versions(self, app_version=app_version, limit=limit)

    def remote_fetch_predictions(self, execution):
        """Fetch predictions from an execution (reference: model.py:908-917)."""
        execution = self._remote.wait(execution)
        outputs = self._remote.fetch_outputs(execution)
        return outputs.get("predictions")
