"""Device data path: host batching, shard-aware placement, double buffering."""

from unionml_tpu.data.pipeline import DeviceFeed, prefetch_to_device

__all__ = ["DeviceFeed", "prefetch_to_device"]
