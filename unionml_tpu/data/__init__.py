"""Device data path: native host batching, shard-aware placement, double
buffering. Batch assembly (shuffle + gather) is C++ (``_native/``) with a
determinism-equivalent numpy fallback."""

from unionml_tpu.data.native import BatchLoader, epoch_permutation
from unionml_tpu.data.pipeline import (
    DeviceFeed,
    local_batches,
    prefetch_to_device,
    process_batch_slice,
)

__all__ = [
    "BatchLoader",
    "DeviceFeed",
    "epoch_permutation",
    "local_batches",
    "prefetch_to_device",
    "process_batch_slice",
]
