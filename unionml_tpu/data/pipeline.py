"""Host→HBM data feed with shard-aware placement and double buffering.

TPU-native version of the reference's loader→splitter→parser pipeline
output hand-off (reference: unionml/dataset.py:294-334 materializes splits
in host memory and passes them to the trainer in-process). Here the hot
training loop consumes an iterator whose batches are already resident in
HBM: ``prefetch_to_device`` keeps ``buffer_size`` batches in flight so the
host→device DMA of batch N+1 overlaps the compute of batch N — JAX
dispatch is async, so a buffer of 2 suffices to hide transfer latency.
``double_buffer=True`` goes further and runs the whole feed (host batch
pull + transfer dispatch) on a background thread, so even the host-side
cost overlaps compute and the yielded buffers are safe to donate to the
step (docs/performance.md "Overlapped training").

When a :class:`~unionml_tpu.parallel.ShardingConfig` is given, each batch
is placed with its data-axis NamedSharding. Multi-host execution
(``jax.process_count() > 1`` after
:func:`~unionml_tpu.parallel.multihost_initialize`) is first-class: each
process feeds ONLY the batch rows its addressable devices own —
:meth:`DeviceFeed.put` assembles the global array from process-local
shards via ``jax.make_array_from_process_local_data``, and
:func:`process_batch_slice` tells a data source which row range this
process must read. Validated by a real 2-process × 4-device
``jax.distributed`` run in ``tests/integration/test_multihost.py`` and
the ``multihost_dp_fsdp`` leg of ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import collections
import itertools
import queue as queue_mod
import threading
from typing import Any, Iterable, Iterator


def process_batch_slice(sharding: Any, global_batch: int) -> slice:
    """The half-open row range of a global batch that THIS process feeds.

    Computed from the sharding's device→index map restricted to this
    process's addressable devices, so it is correct for any mesh layout
    whose batch-dimension placement gives each process one contiguous
    block (the standard dp/fsdp-outermost layouts). Raises when rows are
    non-contiguous per process — feeding such a layout a contiguous
    slice would silently scramble example↔device placement.
    """
    index_map = sharding.devices_indices_map((global_batch,))
    rows = set()
    for device, index in index_map.items():
        if device.process_index != _process_index():
            continue
        sl = index[0]
        start = sl.start if sl.start is not None else 0
        stop = sl.stop if sl.stop is not None else global_batch
        rows.update(range(start, stop))
    if not rows:
        raise ValueError(
            "this process owns no rows of the batch sharding — was the "
            "mesh built over all processes' devices?"
        )
    lo, hi = min(rows), max(rows) + 1
    if rows != set(range(lo, hi)):
        raise ValueError(
            "this process's batch rows are non-contiguous under the given "
            "sharding; feed per-device shards explicitly instead of a "
            "contiguous process slice"
        )
    return slice(lo, hi)


def _process_index() -> int:
    import jax

    return jax.process_index()


class DeviceFeed:
    """Shard-aware device placement for host batches.

    Single-process: batches land via ``jax.device_put`` against the batch
    sharding (or a given device). Multi-process: ``put`` receives this
    process's LOCAL rows (see :func:`process_batch_slice`) and assembles
    the global jax.Array from every process's shards — no host ever
    materializes or transfers the full global batch.
    """

    def __init__(self, sharding: Any = None, device: Any = None):
        self._sharding = None
        self._device = device
        if sharding is not None:
            # accepts a ShardingConfig or a concrete jax Sharding
            self._sharding = (
                sharding.batch_sharding() if hasattr(sharding, "batch_sharding") else sharding
            )

    def put(self, batch: Any) -> Any:
        import jax

        if self._sharding is not None:
            if jax.process_count() > 1:
                import numpy as np

                sharding = self._sharding
                return jax.tree_util.tree_map(
                    lambda x: jax.make_array_from_process_local_data(
                        sharding, np.asarray(x)
                    ),
                    batch,
                )
            return jax.device_put(batch, self._sharding)
        if self._device is not None:
            return jax.device_put(batch, self._device)
        return jax.device_put(batch)


def local_batches(
    iterator: Iterable[Any], sharding: Any, global_batch: int
) -> Iterator[Any]:
    """Slice an iterator of GLOBAL batches down to this process's rows.

    For data sources that deterministically produce the same global batch
    on every host (seeded synthetic data, a shared filesystem read): each
    host keeps only its :func:`process_batch_slice` rows, which is what
    :meth:`DeviceFeed.put` expects under ``jax.process_count() > 1``.
    Sources that can seek (sharded files, SQL OFFSET) should read only
    their slice instead and skip this wrapper.
    """
    sharding = (
        sharding.batch_sharding() if hasattr(sharding, "batch_sharding") else sharding
    )
    sl = process_batch_slice(sharding, global_batch)

    def cut(x: Any) -> Any:
        return x[sl]

    import jax

    for batch in iterator:
        yield jax.tree_util.tree_map(cut, batch)


_EXHAUSTED = object()  # prefetch sentinel: next(it) default at stream end


def prefetch_to_device(
    iterator: Iterable[Any],
    *,
    buffer_size: int = 2,
    sharding: Any = None,
    device: Any = None,
    goodput: Any = None,
    double_buffer: bool = False,
) -> Iterator[Any]:
    """Yield device-resident batches, keeping ``buffer_size`` in flight.

    Multi-process contract: ``iterator`` yields PROCESS-LOCAL rows (wrap
    a global-batch source with :func:`local_batches`); placement then
    assembles global arrays per :class:`DeviceFeed`.

    ``goodput`` (a :class:`~unionml_tpu.goodput.GoodputTracker`, or any
    object with a ``phase(name)`` context manager) attributes the feed's
    wall time: pulling the host iterator lands in the ``data_wait``
    bucket (host input starvation — the producer was not ready) and
    :meth:`DeviceFeed.put` in ``host_to_device`` (the device_put
    *dispatch*; the DMA itself overlaps compute, which is the point of
    the prefetch — a transfer the compute had to wait on shows up as
    compute time, not here).

    ``double_buffer=True`` moves the whole feed — host-batch pull AND
    device-transfer dispatch — onto a background thread
    (docs/performance.md "Overlapped training"): while the current step
    runs, the feeder is already assembling and dispatching the next
    batch's host→device copy, so the consumer normally finds a
    device-resident batch waiting. Batch ORDER is identical to the
    synchronous mode, each yielded array is fresh (safe to donate to
    the step — no buffer is ever yielded twice), and a raising source
    re-raises in the consumer. Goodput accounting changes shape
    honestly: the feeder records nothing (its work overlaps compute by
    construction), and only the consumer's wait for a ready batch —
    true starvation, the feeder fell behind — lands in ``data_wait``;
    the ``host_to_device`` bucket drains to zero because the dispatch
    left the critical path.
    """
    if double_buffer:
        return _threaded_prefetch(
            iterator, buffer_size=max(2, buffer_size), sharding=sharding,
            device=device, goodput=goodput,
        )
    return _inline_prefetch(
        iterator, buffer_size=buffer_size, sharding=sharding,
        device=device, goodput=goodput,
    )


def _inline_prefetch(
    iterator: Iterable[Any], *, buffer_size: int, sharding: Any,
    device: Any, goodput: Any,
) -> Iterator[Any]:
    feed = DeviceFeed(sharding=sharding, device=device)
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def enqueue(k: int) -> None:
        if goodput is None:
            for item in itertools.islice(it, k):
                queue.append(feed.put(item))
            return
        for _ in range(k):
            with goodput.phase("data_wait"):
                item = next(it, _EXHAUSTED)
            if item is _EXHAUSTED:
                return
            with goodput.phase("host_to_device"):
                queue.append(feed.put(item))

    enqueue(buffer_size)
    while queue:
        yield queue.popleft()
        enqueue(1)


class _FeedError:
    """Producer-side failure envelope: re-raised at the consumer's next
    pull, so a raising data source behaves like the inline mode."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def _threaded_prefetch(
    iterator: Iterable[Any], *, buffer_size: int, sharding: Any,
    device: Any, goodput: Any,
) -> Iterator[Any]:
    feed = DeviceFeed(sharding=sharding, device=device)
    q: queue_mod.Queue = queue_mod.Queue(maxsize=buffer_size)
    stop = threading.Event()

    def offer(item: Any) -> bool:
        # bounded put that notices consumer abandonment: an abandoned
        # generator must not leave the feeder blocked forever (pinning
        # device buffers until process exit)
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def producer() -> None:
        try:
            for item in iterator:
                if not offer(feed.put(item)):
                    return
            offer(_EXHAUSTED)
        except BaseException as exc:  # re-raised at the consumer
            offer(_FeedError(exc))

    thread = threading.Thread(
        target=producer, name="prefetch-feed", daemon=True
    )
    thread.start()
    try:
        while True:
            if goodput is None:
                item = q.get()
            else:
                # only TRUE starvation lands in data_wait: the feeder
                # fell behind and the step loop is actually waiting
                with goodput.phase("data_wait"):
                    item = q.get()
            if item is _EXHAUSTED:
                return
            if isinstance(item, _FeedError):
                raise item.exc
            yield item
    finally:
        stop.set()
        thread.join(timeout=5.0)
