"""Host→HBM data feed with shard-aware placement and double buffering.

TPU-native version of the reference's loader→splitter→parser pipeline
output hand-off (reference: unionml/dataset.py:294-334 materializes splits
in host memory and passes them to the trainer in-process). Here the hot
training loop consumes an iterator whose batches are already resident in
HBM: ``prefetch_to_device`` keeps ``buffer_size`` batches in flight so the
host→device DMA of batch N+1 overlaps the compute of batch N — JAX
dispatch is async, so a buffer of 2 suffices to hide transfer latency.

When a :class:`~unionml_tpu.parallel.ShardingConfig` is given, each batch
is placed with its data-axis NamedSharding: every host feeds only its
addressable shards and XLA never re-lays the batch out.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Iterable, Iterator


class DeviceFeed:
    """Shard-aware device placement for host batches."""

    def __init__(self, sharding: Any = None, device: Any = None):
        self._sharding = None
        self._device = device
        if sharding is not None:
            # accepts a ShardingConfig or a concrete jax Sharding
            self._sharding = (
                sharding.batch_sharding() if hasattr(sharding, "batch_sharding") else sharding
            )

    def put(self, batch: Any) -> Any:
        import jax

        if self._sharding is not None:
            return jax.device_put(batch, self._sharding)
        if self._device is not None:
            return jax.device_put(batch, self._device)
        return jax.device_put(batch)


def prefetch_to_device(
    iterator: Iterable[Any],
    *,
    buffer_size: int = 2,
    sharding: Any = None,
    device: Any = None,
) -> Iterator[Any]:
    """Yield device-resident batches, keeping ``buffer_size`` in flight."""
    feed = DeviceFeed(sharding=sharding, device=device)
    queue: collections.deque = collections.deque()
    it = iter(iterator)

    def enqueue(k: int) -> None:
        for item in itertools.islice(it, k):
            queue.append(feed.put(item))

    enqueue(buffer_size)
    while queue:
        yield queue.popleft()
        enqueue(1)
