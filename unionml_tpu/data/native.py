"""ctypes bindings + Python fallback for the native host batch loader.

The native side (``_native/hostloader.cpp``) assembles shuffled batches
with worker threads into staging buffers; this module builds it on first
use with ``g++`` (no pybind11 — plain C ABI via ctypes), wraps the staging
pointers as numpy arrays without copying, and falls back to a pure-numpy
implementation with the *identical* determinism contract when no C++
toolchain is available.

Shared determinism contract (tested in tests/unit/test_native_loader.py):
the epoch permutation is ``argsort_u64(splitmix64(seed ^ (epoch+1)*PHI ^
row))`` with ties broken by row index, so C++ and numpy produce the same
batch stream and a run can resume from ``(epoch, step)`` on either.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("unionml_tpu")

_PHI = np.uint64(0x9E3779B97F4A7C15)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 — bit-identical to the C++ kernel."""
    with np.errstate(over="ignore"):
        x = (x + _PHI).astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def epoch_permutation(n_rows: int, seed: int, epoch: int, shuffle: bool = True) -> np.ndarray:
    """The loader's deterministic permutation (numpy reference)."""
    if not shuffle:
        return np.arange(n_rows, dtype=np.uint64)
    with np.errstate(over="ignore"):
        base = np.uint64(seed) ^ (np.uint64(epoch + 1) * _PHI)
    keys = splitmix64(base ^ np.arange(n_rows, dtype=np.uint64))
    return np.argsort(keys, kind="stable").astype(np.uint64)


# ------------------------------------------------------------------ build

_SRC = Path(__file__).parent / "_native" / "hostloader.cpp"
_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _lib_path() -> Path:
    cache = os.environ.get("UNIONML_TPU_CACHE_DIR", "~/.cache/unionml_tpu")
    d = Path(os.path.expanduser(cache)) / "native"
    d.mkdir(parents=True, exist_ok=True)
    return d / "libhostloader.so"


def _build_library() -> Optional[ctypes.CDLL]:
    so = _lib_path()
    try:
        if not so.exists() or so.stat().st_mtime < _SRC.stat().st_mtime:
            # compile to a unique temp path then atomically rename, so
            # concurrent builders (pytest workers, parallel trainers)
            # never load a half-written .so
            tmp = so.with_suffix(f".{os.getpid()}.tmp.so")
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
                   "-o", str(tmp), str(_SRC)]
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        lib = ctypes.CDLL(str(so))
    except (OSError, subprocess.SubprocessError) as e:
        logger.info(f"native hostloader unavailable ({e}); using numpy fallback")
        return None
    u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
    lib.hl_new.restype = ctypes.c_void_p
    lib.hl_new.argtypes = [u8pp, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
                           ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                           ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.hl_num_batches.restype = ctypes.c_uint64
    lib.hl_num_batches.argtypes = [ctypes.c_void_p]
    lib.hl_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.hl_next.restype = ctypes.c_uint64
    lib.hl_next.argtypes = [ctypes.c_void_p, u8pp, ctypes.POINTER(ctypes.c_void_p)]
    lib.hl_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.hl_free.argtypes = [ctypes.c_void_p]
    return lib


def get_library() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_FAILED
    with _LIB_LOCK:
        if _LIB is None and not _LIB_FAILED:
            _LIB = _build_library()
            _LIB_FAILED = _LIB is None
        return _LIB


# ------------------------------------------------------------------ loaders


class BatchLoader:
    """Deterministic shuffled-batch stream over row-aligned numpy arrays.

    Uses the native threaded loader when available, the numpy fallback
    otherwise — both produce the identical batch stream. Arrays must share
    the leading (row) dimension; each batch is a tuple of arrays in the
    same order. Supports mid-epoch resume via ``epochs(start_epoch,
    start_batch)`` (the elastic-training hook).
    """

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        *,
        batch_size: int,
        seed: int = 0,
        shuffle: bool = True,
        drop_remainder: bool = False,
        num_threads: int = 2,
        queue_depth: int = 4,
        use_native: Optional[bool] = None,
        copy: bool = True,
    ):
        """``copy=False`` yields zero-copy views into recycled staging
        buffers: each batch is only valid until the generator is advanced
        (safe for consume-then-advance loops like ``prefetch_to_device``,
        which ``device_put``s a batch before pulling the next one)."""
        if not arrays:
            raise ValueError("BatchLoader needs at least one array")
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        n = self.arrays[0].shape[0]
        for a in self.arrays:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the leading dimension")
        self.n_rows = n
        self.batch_size = batch_size
        self.seed = seed
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.copy = copy
        if drop_remainder:
            self.num_batches = n // batch_size
        else:
            self.num_batches = (n + batch_size - 1) // batch_size

        self._active_iter: Optional[object] = None
        lib = get_library() if (use_native is None or use_native) else None
        if use_native and lib is None:
            raise RuntimeError("native hostloader requested but unavailable")
        self._lib = lib
        self._handle = None
        if lib is not None:
            n_arr = len(self.arrays)
            ptrs = (ctypes.POINTER(ctypes.c_uint8) * n_arr)()
            row_bytes = (ctypes.c_uint64 * n_arr)()
            for i, a in enumerate(self.arrays):
                ptrs[i] = a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
                row_bytes[i] = a.dtype.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            self._handle = lib.hl_new(
                ptrs, row_bytes, n_arr, n, batch_size, seed,
                int(shuffle), int(drop_remainder), num_threads, queue_depth,
            )

    # -- iteration -----------------------------------------------------

    def epoch(self, epoch: int = 0, start_batch: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield the batches of one epoch, optionally resuming mid-epoch.

        Only one epoch iterator may be live per loader: the native side
        holds a single (permutation, queue) state per handle, so a second
        iterator would corrupt the first. Enforced uniformly (the numpy
        fallback could interleave, but the contract is "identical streams
        on either implementation"). Use separate loaders to interleave.
        """
        gen = (
            self._native_epoch(epoch, start_batch)
            if self._handle is not None
            else self._numpy_epoch(epoch, start_batch)
        )
        token = object()
        self._active_iter = token
        try:
            while True:
                if self._active_iter is not token:
                    raise RuntimeError(
                        "concurrent epoch() iterators on one BatchLoader are "
                        "not supported — create a separate loader per stream"
                    )
                try:
                    item = next(gen)
                except StopIteration:
                    return
                yield item
        finally:
            if self._active_iter is token:
                self._active_iter = None
            gen.close()

    def epochs(
        self, num_epochs: int, *, start_epoch: int = 0, start_batch: int = 0
    ) -> Iterator[Tuple[int, int, Tuple[np.ndarray, ...]]]:
        """Yield ``(epoch, batch_idx, batch)`` across epochs with resume."""
        for e in range(start_epoch, num_epochs):
            sb = start_batch if e == start_epoch else 0
            for i, batch in enumerate(self.epoch(e, sb)):
                yield e, sb + i, batch

    def _native_epoch(self, epoch: int, start_batch: int):
        lib, h = self._lib, self._handle
        lib.hl_start_epoch(h, epoch, start_batch)
        n_arr = len(self.arrays)
        out_ptrs = (ctypes.POINTER(ctypes.c_uint8) * n_arr)()
        token = ctypes.c_void_p()
        pending = None  # token of the batch currently lent out (copy=False)
        try:
            while True:
                rows = lib.hl_next(h, out_ptrs, ctypes.byref(token))
                if pending is not None:
                    # the consumer advanced the generator, so the previous
                    # zero-copy batch is done — recycle its staging buffer
                    lib.hl_release(h, pending)
                    pending = None
                if rows == 0:
                    return
                out = []
                for i, a in enumerate(self.arrays):
                    shape = (rows,) + a.shape[1:]
                    nbytes = int(rows) * a.dtype.itemsize * int(
                        np.prod(a.shape[1:], dtype=np.int64)
                    )
                    buf = ctypes.cast(
                        out_ptrs[i], ctypes.POINTER(ctypes.c_uint8 * nbytes)
                    ).contents
                    view = np.frombuffer(buf, dtype=a.dtype).reshape(shape)
                    out.append(view.copy() if self.copy else view)
                if self.copy:
                    lib.hl_release(h, token)
                else:
                    pending = ctypes.c_void_p(token.value)
                yield tuple(out)
        finally:
            # re-check the live handle: close() may have freed the loader
            # while this generator was suspended (abandoned mid-epoch)
            if pending is not None and self._handle is not None:
                lib.hl_release(self._handle, pending)

    def _numpy_epoch(self, epoch: int, start_batch: int):
        perm = epoch_permutation(self.n_rows, self.seed, epoch, self.shuffle)
        for b in range(start_batch, self.num_batches):
            idx = perm[b * self.batch_size:(b + 1) * self.batch_size]
            yield tuple(a[idx] for a in self.arrays)

    # -- lifecycle -----------------------------------------------------

    def close(self):
        if self._handle is not None:
            self._lib.hl_free(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
