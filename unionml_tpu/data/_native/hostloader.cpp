// Native host data loader: threaded shuffled-batch assembly.
//
// The reference delegates its data path to pandas/sklearn in-process
// (reference: unionml/dataset.py:294-334); its "native layer" is whatever
// those libraries do internally. For the TPU rebuild the host data path is
// a real bottleneck surface (the chip eats batches faster than a Python
// gather loop can produce them), so batch assembly is native: worker
// threads gather permuted rows from the caller's arrays (zero-copy views
// of numpy buffers) into a pool of staging buffers, handed to Python
// through a bounded queue. Python wraps the staging pointers as numpy
// arrays (no copy) and releases them after jax.device_put.
//
// Determinism contract (shared with the Python fallback in
// unionml_tpu/data/native.py): the epoch permutation is
//   argsort_u64( splitmix64(seed ^ (epoch+1)*PHI ^ row_index) )
// with ties broken by row index — identical in C++ and numpy, so resuming
// from (epoch, step) reproduces the same batches on either implementation.
//
// Build: g++ -O3 -shared -fPIC -pthread -o libhostloader.so hostloader.cpp
// (no external dependencies; bound via ctypes, not pybind11).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <thread>
#include <vector>

namespace {

constexpr uint64_t kPhi = 0x9E3779B97F4A7C15ull;

inline uint64_t splitmix64(uint64_t x) {
  x += kPhi;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

struct Batch {
  uint64_t index = 0;   // batch index within the epoch
  uint64_t rows = 0;    // rows actually filled (last batch may be short)
  std::vector<std::vector<uint8_t>> buffers;  // one per array
};

class Loader {
 public:
  Loader(const uint8_t** arrays, const uint64_t* row_bytes, int num_arrays,
         uint64_t n_rows, uint64_t batch_size, uint64_t seed, bool shuffle,
         bool drop_remainder, int num_threads, int queue_depth)
      : n_rows_(n_rows),
        batch_size_(batch_size),
        seed_(seed),
        shuffle_(shuffle),
        drop_remainder_(drop_remainder),
        queue_depth_(std::max(queue_depth, 1)),
        num_threads_(std::max(num_threads, 1)) {
    for (int a = 0; a < num_arrays; ++a) {
      arrays_.push_back(arrays[a]);
      row_bytes_.push_back(row_bytes[a]);
    }
    num_batches_ = drop_remainder_ ? n_rows_ / batch_size_
                                   : (n_rows_ + batch_size_ - 1) / batch_size_;
  }

  ~Loader() { Stop(); }

  uint64_t num_batches() const { return num_batches_; }

  void StartEpoch(uint64_t epoch, uint64_t start_batch) {
    Stop();
    BuildPermutation(epoch);
    next_to_assemble_ = start_batch;
    next_to_emit_ = start_batch;
    stop_ = false;
    for (int t = 0; t < num_threads_; ++t) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  // Returns rows in the batch (0 = epoch exhausted). Caller owns the
  // returned batch until ReleaseBatch.
  Batch* Next() {
    std::unique_lock<std::mutex> lk(mu_);
    for (;;) {
      // emit strictly in batch order so resume is deterministic
      auto it = std::find_if(ready_.begin(), ready_.end(), [&](Batch* b) {
        return b->index == next_to_emit_;
      });
      if (it != ready_.end()) {
        Batch* b = *it;
        ready_.erase(it);
        ++next_to_emit_;
        cv_space_.notify_all();
        return b;
      }
      if (next_to_emit_ >= num_batches_) return nullptr;
      cv_ready_.wait(lk);
    }
  }

  void ReleaseBatch(Batch* b) {
    std::lock_guard<std::mutex> lk(mu_);
    pool_.push_back(b);
    cv_space_.notify_all();
  }

 private:
  void BuildPermutation(uint64_t epoch) {
    perm_.resize(n_rows_);
    std::iota(perm_.begin(), perm_.end(), 0);
    if (!shuffle_) return;
    std::vector<uint64_t> keys(n_rows_);
    const uint64_t base = seed_ ^ ((epoch + 1) * kPhi);
    for (uint64_t i = 0; i < n_rows_; ++i) keys[i] = splitmix64(base ^ i);
    std::stable_sort(perm_.begin(), perm_.end(),
                     [&](uint64_t a, uint64_t b) { return keys[a] < keys[b]; });
  }

  void WorkerLoop() {
    for (;;) {
      uint64_t my_batch;
      Batch* buf = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_space_.wait(lk, [&] {
          return stop_ || next_to_assemble_ >= num_batches_ ||
                 InFlight() < static_cast<uint64_t>(queue_depth_);
        });
        if (stop_ || next_to_assemble_ >= num_batches_) return;
        my_batch = next_to_assemble_++;
        buf = TakeBufferLocked();
      }
      FillBatch(my_batch, buf);
      {
        std::lock_guard<std::mutex> lk(mu_);
        ready_.push_back(buf);
      }
      cv_ready_.notify_all();
    }
  }

  uint64_t InFlight() const {
    // batches assembled or being assembled but not yet emitted
    return next_to_assemble_ - next_to_emit_;
  }

  Batch* TakeBufferLocked() {
    if (!pool_.empty()) {
      Batch* b = pool_.back();
      pool_.pop_back();
      return b;
    }
    all_batches_.emplace_back(new Batch());
    Batch* b = all_batches_.back().get();
    b->buffers.resize(arrays_.size());
    for (size_t a = 0; a < arrays_.size(); ++a) {
      b->buffers[a].resize(batch_size_ * row_bytes_[a]);
    }
    return b;
  }

  void FillBatch(uint64_t batch_idx, Batch* out) {
    const uint64_t start = batch_idx * batch_size_;
    const uint64_t rows = std::min(batch_size_, n_rows_ - start);
    out->index = batch_idx;
    out->rows = rows;
    for (size_t a = 0; a < arrays_.size(); ++a) {
      const uint64_t rb = row_bytes_[a];
      uint8_t* dst = out->buffers[a].data();
      const uint8_t* src = arrays_[a];
      for (uint64_t r = 0; r < rows; ++r) {
        std::memcpy(dst + r * rb, src + perm_[start + r] * rb, rb);
      }
    }
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    cv_ready_.notify_all();
    for (auto& w : workers_) w.join();
    workers_.clear();
    std::lock_guard<std::mutex> lk(mu_);
    for (Batch* b : ready_) pool_.push_back(b);
    ready_.clear();
  }

  std::vector<const uint8_t*> arrays_;
  std::vector<uint64_t> row_bytes_;
  uint64_t n_rows_, batch_size_, seed_;
  bool shuffle_, drop_remainder_;
  int queue_depth_, num_threads_;
  uint64_t num_batches_ = 0;

  std::vector<uint64_t> perm_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_ready_, cv_space_;
  std::deque<Batch*> ready_;
  std::vector<Batch*> pool_;
  std::vector<std::unique_ptr<Batch>> all_batches_;
  uint64_t next_to_assemble_ = 0, next_to_emit_ = 0;
  bool stop_ = false;
};

}  // namespace

extern "C" {

void* hl_new(const uint8_t** arrays, const uint64_t* row_bytes, int num_arrays,
             uint64_t n_rows, uint64_t batch_size, uint64_t seed, int shuffle,
             int drop_remainder, int num_threads, int queue_depth) {
  return new Loader(arrays, row_bytes, num_arrays, n_rows, batch_size, seed,
                    shuffle != 0, drop_remainder != 0, num_threads, queue_depth);
}

uint64_t hl_num_batches(void* handle) {
  return static_cast<Loader*>(handle)->num_batches();
}

void hl_start_epoch(void* handle, uint64_t epoch, uint64_t start_batch) {
  static_cast<Loader*>(handle)->StartEpoch(epoch, start_batch);
}

// Fills out_ptrs[a] with the address of array a's staging buffer and
// returns the row count (0 = epoch exhausted). out_token receives an
// opaque token to pass to hl_release.
uint64_t hl_next(void* handle, uint8_t** out_ptrs, void** out_token) {
  Loader* l = static_cast<Loader*>(handle);
  Batch* b = l->Next();
  if (b == nullptr) {
    *out_token = nullptr;
    return 0;
  }
  for (size_t a = 0; a < b->buffers.size(); ++a) out_ptrs[a] = b->buffers[a].data();
  *out_token = b;
  return b->rows;
}

void hl_release(void* handle, void* token) {
  if (token == nullptr) return;
  static_cast<Loader*>(handle)->ReleaseBatch(static_cast<Batch*>(token));
}

void hl_free(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
