"""Profiling, tracing, and numerical-debug toggles.

The reference has NO tracing/profiling subsystem (SURVEY.md §5.1: the
closest thing is console-URL surfacing, reference: unionml/model.py:785-789)
and no sanitizers (§5.2 — concurrency is owned by Flyte). On TPU those
gaps matter: regressions hide inside one fused XLA program, and a NaN born
in step 40k of a bf16 run surfaces as a silent accuracy cliff. This module
supplies the rebuild obligations:

- :class:`StepTimer` — honest per-step wall timing (a window ends with a
  host readback that is data-dependent on the step, because async dispatch
  through tunneled backends makes ``block_until_ready`` unreliable — see
  BASELINE.md), windowed samples/sec.
- :func:`trace` — ``jax.profiler`` trace context for TensorBoard, no-op
  when profiling is unsupported on the backend.
- :func:`nan_guard` / :func:`assert_finite` — jit-wide debug-NaN toggle
  and a pytree finiteness check that names the offending leaf path.
- :func:`describe_sharding` / :func:`assert_sharding` — inspect and assert
  the realized shardings of a pytree against expected PartitionSpecs
  (catches silent GSPMD re-layout and donation mismatches).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.telemetry import percentile_summary


class StepTimer:
    """Windowed samples/sec meter for a training loop.

    ``tick(batch_examples)`` once per step; every ``window`` steps the
    meter records a sample. ``summary()`` reports the median rate (robust
    to tunnel jitter). The caller is responsible for making timing honest
    — i.e. perform a host readback of a value data-dependent on the last
    step before reading ``summary()``.
    """

    def __init__(self, window: int = 50):
        self.window = window
        self._t0: Optional[float] = None
        self._steps = 0
        self._examples = 0
        self.rates: list = []
        self.total_steps = 0
        self.total_examples = 0

    def closes_window(self) -> bool:
        """True when the NEXT tick ends a window — the caller should do a
        host readback of the current step's output before that tick so
        the window measures compute, not async dispatch."""
        return self._steps + 1 >= self.window

    def tick(self, batch_examples: int) -> None:
        now = time.perf_counter()
        self.total_steps += 1
        self.total_examples += batch_examples
        if self._t0 is None:
            # the first tick only anchors the clock: counting its examples
            # without its duration would overstate the first window by
            # window/(window-1)
            self._t0 = now
            return
        self._steps += 1
        self._examples += batch_examples
        if self._steps >= self.window:
            dt = now - self._t0
            if dt > 0:
                self.rates.append(self._examples / dt)
            self._t0 = now
            self._steps = 0
            self._examples = 0

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "steps": float(self.total_steps),
            "examples": float(self.total_examples),
        }
        if self.rates:
            # the shared nearest-rank formula (telemetry.percentile
            # _summary) — same percentile semantics as every serving
            # stats() surface, so trainer and server numbers compare
            s = percentile_summary(self.rates)
            out["samples_per_sec_median"] = float(s["p50"])
            out["samples_per_sec_last"] = float(self.rates[-1])
            out["samples_per_sec"] = s
        return out


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """``jax.profiler.trace`` context (TensorBoard format).

    Falls back to a no-op (with a log line) when the backend doesn't
    support profiling — e.g. tunneled device plugins. Only profiler
    start/stop failures are swallowed; exceptions from the traced body
    propagate untouched.
    """
    import jax

    prof = None
    try:
        prof = jax.profiler.trace(log_dir)
        prof.__enter__()
    except Exception as e:  # pragma: no cover - backend-specific
        logger.info(f"profiler unavailable ({e}); continuing without trace")
        prof = None
    try:
        yield
    finally:
        if prof is not None:
            try:
                prof.__exit__(None, None, None)
                logger.info(f"profiler trace written to {log_dir}")
            except Exception as e:  # pragma: no cover - backend-specific
                logger.info(f"profiler trace failed ({e})")


@contextlib.contextmanager
def nan_guard(enable: bool = True) -> Iterator[None]:
    """Enable ``jax_debug_nans`` within a scope (jit-wide NaN detection).

    XLA re-runs the offending computation un-jitted to locate the origin;
    expensive, so scope it to repro runs, not production training.
    """
    import jax

    if not enable:
        yield
        return
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def _leaf_paths(tree: Any):
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in flat:
        yield jax.tree_util.keystr(path), leaf


def assert_finite(tree: Any, *, name: str = "pytree") -> None:
    """Raise ``FloatingPointError`` naming the first non-finite leaf."""
    for path, leaf in _leaf_paths(tree):
        arr = np.asarray(leaf)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        if not np.all(np.isfinite(arr)):
            bad = int(np.size(arr) - np.sum(np.isfinite(arr)))
            raise FloatingPointError(
                f"{name}{path} has {bad} non-finite value(s) "
                f"(shape {arr.shape}, dtype {arr.dtype})"
            )


def describe_sharding(tree: Any) -> Dict[str, str]:
    """Map each leaf path to a human-readable sharding description."""
    out: Dict[str, str] = {}
    for path, leaf in _leaf_paths(tree):
        sharding = getattr(leaf, "sharding", None)
        out[path] = repr(sharding) if sharding is not None else "<host>"
    return out


def assert_sharding(tree: Any, expected: Dict[str, Any], *, name: str = "pytree") -> None:
    """Assert realized leaf shardings match expected PartitionSpecs.

    ``expected`` maps leaf-path substrings to ``jax.sharding.PartitionSpec``
    (or to a callable ``spec -> bool``). Catches GSPMD silently choosing a
    different layout than the config intended (SURVEY.md §5.2 rebuild:
    sharding-mismatch checks).
    """
    checked = set()
    for path, leaf in _leaf_paths(tree):
        sharding = getattr(leaf, "sharding", None)
        for pattern, want in expected.items():
            if pattern in path:
                checked.add(pattern)
                spec = getattr(sharding, "spec", None)
                ok = want(spec) if callable(want) else spec == want
                if not ok:
                    raise AssertionError(
                        f"{name}{path}: realized sharding spec {spec!r} != "
                        f"expected {want!r}"
                    )
    missing = set(expected) - checked
    if missing:
        raise AssertionError(
            f"{name}: no leaves matched expected sharding pattern(s) {sorted(missing)}"
        )
