"""Device-mesh construction and multi-host bring-up.

TPU-native replacement for the reference's Flyte-container distribution
model (SURVEY.md §5.8): a training step is laid out over one
``jax.sharding.Mesh`` whose axes name the parallelism strategies; XLA
compiles collectives that ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np


def mesh_devices(n: Optional[int] = None):
    """The devices to build a mesh over (all visible by default)."""
    import jax

    devices = jax.devices()
    if n is not None:
        if n > len(devices):
            raise ValueError(
                f"requested {n} devices but only {len(devices)} are visible. "
                "For CPU-simulated meshes set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N."
            )
        devices = devices[:n]
    return devices


def make_mesh(
    axes: Dict[str, int],
    *,
    devices=None,
    dcn_axes: Optional[Dict[str, int]] = None,
):
    """Build a ``jax.sharding.Mesh`` with named ``axes``.

    At most one axis may be ``-1`` (inferred from the device count). With
    ``dcn_axes`` (multi-slice: axis × num_slices over the data-center
    network) the mesh is built with
    ``mesh_utils.create_hybrid_device_mesh`` so collectives on DCN axes
    cross slices and all other traffic stays on ICI.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    devices = list(devices) if devices is not None else mesh_devices()
    n = len(devices)

    axes = dict(axes)
    inferred = [k for k, v in axes.items() if v == -1]
    if len(inferred) > 1:
        raise ValueError(f"only one mesh axis may be -1, got {inferred}")
    known = int(np.prod([v for v in axes.values() if v != -1])) if axes else 1
    if inferred:
        if n % known:
            raise ValueError(f"device count {n} not divisible by fixed axes product {known}")
        axes[inferred[0]] = n // known
    total = int(np.prod(list(axes.values())))
    if total != n:
        raise ValueError(
            f"mesh axes {axes} require {total} devices but {n} are available"
        )

    if dcn_axes:
        # validate upfront so misconfigurations fail identically in
        # simulation and on multi-slice hardware
        for k, slices in dcn_axes.items():
            if k not in axes:
                raise ValueError(f"dcn_axes key {k!r} is not a mesh axis {tuple(axes)}")
            if slices <= 0 or axes[k] % slices:
                raise ValueError(
                    f"dcn_axes[{k!r}]={slices} must divide axis size {axes[k]}"
                )
        ici_shape = [axes[k] // dcn_axes.get(k, 1) for k in axes]
        if hasattr(devices[0], "slice_index"):
            # real multi-slice hardware: topology-aware placement; config
            # errors (wrong slice count, indivisible shapes) propagate
            mesh_arr = mesh_utils.create_hybrid_device_mesh(
                ici_shape, [dcn_axes.get(k, 1) for k in axes], devices=devices
            )
        else:
            # simulated/CPU devices carry no slice topology: plain reshape
            # (collectives still correct; ICI/DCN placement only exists on
            # hardware)
            mesh_arr = np.asarray(devices).reshape(tuple(axes.values()))
        return Mesh(mesh_arr, tuple(axes))

    try:
        mesh_arr = mesh_utils.create_device_mesh(tuple(axes.values()), devices=devices)
    except Exception:
        # CPU-simulated or partial-device meshes: plain reshape
        mesh_arr = np.asarray(devices).reshape(tuple(axes.values()))
    return Mesh(mesh_arr, tuple(axes))


def cpu_multiprocess_supported() -> bool:
    """Can THIS jax build run multi-process collectives on the CPU
    backend? True when the ``jax_cpu_collectives_implementation`` knob
    exists and jaxlib ships the Gloo TCP implementation
    :func:`multihost_initialize` selects. The multihost/tpuvm
    integration suites ``skipif`` on this, so an environment that
    genuinely cannot run them reports *skipped*, not a known-red
    failure set."""
    import jax

    if "jax_cpu_collectives_implementation" not in getattr(
        jax.config, "values", {}
    ):
        return False
    try:
        from jax._src.lib import xla_extension
    except Exception:
        return False
    return hasattr(xla_extension, "make_gloo_tcp_collectives")


def multihost_initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up the multi-host runtime (``jax.distributed.initialize``).

    This replaces the reference's Flyte control plane for multi-machine
    execution (SURVEY.md §5.8): on TPU VM slices arguments are autodetected
    from the metadata server; across DCN pass them explicitly. No-ops when
    already initialized or when running single-process.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu" and coordinator_address is None:
        return False  # single-process CPU simulation
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # a multi-process CPU run (the TPU-pod control plane minus the
        # hardware) needs an explicit cross-process collectives
        # implementation BEFORE the backend initializes — without it
        # every cross-process psum/allgather dies with "Multiprocess
        # computations aren't implemented on the CPU backend". Gloo is
        # the TCP implementation jaxlib ships; builds without the knob
        # (or without gloo) fall through and the caller's capability
        # probe (cpu_multiprocess_supported) should have skipped.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, ValueError):
            pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except (RuntimeError, ValueError):
        return False  # already initialized or single-process
