"""Sharding strategies: one mesh, named axes, partition rules.

The strategy vocabulary (SURVEY.md §2.4 TPU additions): **dp** (batch
sharding, gradient psum), **fsdp** (param/optimizer sharding à la ZeRO-3 —
XLA all-gathers just-in-time), **tp** (tensor parallelism via param
partition rules), **sp** (sequence axis for ring/Ulysses attention), **pp**
(pipeline stages), **ep** (expert parallelism for MoE). All are axes of a
single ``jax.sharding.Mesh``; a :class:`ShardingConfig` names the axis
sizes, how batches shard, and how parameters partition. ``compile_step``
then jit-compiles a ``(state, batch) -> (state, metrics)`` function with
NamedSharding in/out specs — GSPMD inserts the ICI/DCN collectives.

Axis order puts model axes innermost ("tensor" fastest-varying) so
tensor-parallel collectives land on adjacent ICI neighbors.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


from unionml_tpu.parallel.mesh import make_mesh

# outermost → innermost; DCN-friendly axes (pipeline, data) first
AXIS_ORDER = ("pipeline", "data", "fsdp", "expert", "sequence", "tensor")


@dataclass(frozen=True)
class PartitionRule:
    """Regex over the '/'-joined parameter path → PartitionSpec entries."""

    pattern: str
    spec: Tuple[Any, ...]

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


def _path_str(path) -> str:
    import jax

    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(p.name)
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


@dataclass
class ShardingConfig:
    """Declarative parallelism config attached to ``@model.train_step``.

    Axis sizes multiply to the device count; ``data=-1`` absorbs the
    remainder. ``rules`` map parameter paths to PartitionSpecs (tensor/
    expert parallelism); unmatched parameters fall back to FSDP sharding of
    their largest divisible axis when ``fsdp > 1``, else replication.
    """

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    pipeline: int = 1
    expert: int = 1
    rules: Sequence[PartitionRule] = ()
    batch_spec: Optional[Tuple[Any, ...]] = None  # default: dim0 over (data, fsdp)
    devices: Optional[Sequence[Any]] = None
    dcn_axes: Optional[Dict[str, int]] = None

    _mesh: Any = field(default=None, repr=False, compare=False)

    def axis_sizes(self) -> Dict[str, int]:
        sizes = {name: getattr(self, name) for name in AXIS_ORDER}
        # keep axes that are inferred (-1) or used (>1); always keep data
        return {
            k: v for k, v in sizes.items() if v == -1 or v > 1 or k == "data"
        }

    def mesh(self):
        if self._mesh is None:
            self._mesh = make_mesh(
                self.axis_sizes(), devices=self.devices, dcn_axes=self.dcn_axes
            )
        return self._mesh

    # -- batch sharding ------------------------------------------------- #

    def batch_pspec(self):
        from jax.sharding import PartitionSpec as P

        if self.batch_spec is not None:
            return P(*self.batch_spec)
        axes = [a for a in ("data", "fsdp") if a in self.axis_sizes()]
        return P(tuple(axes) if len(axes) > 1 else axes[0] if axes else None)

    def batch_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh(), self.batch_pspec())

    def microbatched(self) -> "ShardingConfig":
        """Copy whose batch spec carries a leading UNSHARDED microbatch
        axis (gradient accumulation: batches are [n_micro, batch, ...] and
        the scan axis must stay whole on every device while the per-step
        batch axis keeps the data/fsdp sharding)."""
        import dataclasses

        if self.batch_spec is not None:
            spec = (None,) + tuple(self.batch_spec)
        else:
            axes = [a for a in ("data", "fsdp") if a in self.axis_sizes()]
            spec = (None, tuple(axes) if len(axes) > 1 else axes[0] if axes else None)
        return dataclasses.replace(self, batch_spec=spec)

    # -- parameter sharding --------------------------------------------- #

    def param_pspec(self, path: str, leaf) -> Any:
        from jax.sharding import PartitionSpec as P

        shape = getattr(leaf, "shape", ())
        mesh_axes = dict(self.mesh().shape)
        def sanitize(entry, dim_size):
            # drop axes absent from the mesh (e.g. tensor=1 configs) or
            # that the dim can't divide (e.g. GQA kv heads < tensor);
            # tuple entries shard one dim over several axes jointly
            axes = entry if isinstance(entry, tuple) else (entry,)
            kept, divisor = [], 1
            for a in axes:
                if a in mesh_axes and dim_size % (divisor * mesh_axes[a]) == 0:
                    kept.append(a)
                    divisor *= mesh_axes[a]
            if not kept:
                return None
            return tuple(kept) if isinstance(entry, tuple) else kept[0]

        for rule in self.rules:
            if rule.matches(path):
                spec = [
                    sanitize(entry, shape[i]) if entry is not None and i < len(shape) else None
                    for i, entry in enumerate(rule.spec)
                ]
                return P(*spec)
        if self.fsdp > 1 and shape:
            # FSDP fallback: shard the largest divisible axis
            candidates = [
                (dim_size, i) for i, dim_size in enumerate(shape) if dim_size % self.fsdp == 0
            ]
            if candidates:
                _, dim = max(candidates)
                spec = [None] * len(shape)
                spec[dim] = "fsdp"
                return P(*spec)
        return P()

    def state_shardings(self, state: Any):
        """Pytree of NamedSharding matching ``state``'s structure."""
        import jax
        from jax.sharding import NamedSharding

        mesh = self.mesh()
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: NamedSharding(mesh, self.param_pspec(_path_str(path), leaf)),
            state,
        )


def named_sharding(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def state_shardings(config: ShardingConfig, state: Any):
    return config.state_shardings(state)


def shard_pytree(state: Any, config: ShardingConfig):
    """Place a pytree on the config's mesh per its partition rules."""
    import jax

    return jax.device_put(state, config.state_shardings(state))


def compile_step(
    step_fn: Callable,
    state: Any,
    *,
    sharding: ShardingConfig,
    donate_state: bool = True,
    donate_batch: bool = False,
) -> Tuple[Callable, Any]:
    """Compile ``step_fn(state, batch) -> (state, metrics)`` over the mesh.

    Returns ``(compiled_step, placed_state)``: the state is device_put per
    the partition rules (sharded init happens once, host→HBM), and the
    compiled step constrains state in/out shardings so XLA keeps parameters
    resident and inserts gradient collectives (psum over 'data'/'fsdp',
    all-gathers for fsdp params) automatically. State buffers are donated —
    parameter memory is updated in place. ``donate_batch`` additionally
    donates the batch argument (the double-buffered prefetch feeds each
    device batch exactly once, so XLA may recycle its buffer for step
    temporaries instead of holding consumed batches in HBM).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = sharding.mesh()
    ss = sharding.state_shardings(state)
    placed = jax.device_put(state, ss)
    bspec = sharding.batch_sharding()
    replicated = NamedSharding(mesh, PartitionSpec())

    donate = (0,) if donate_state else ()
    if donate_batch:
        donate = donate + (1,)
    compiled = jax.jit(
        step_fn,
        in_shardings=(ss, bspec),
        out_shardings=(ss, replicated),
        donate_argnums=donate,
    )

    if mesh.devices.flat[0].platform == "cpu":
        # CPU-simulated meshes (tests) deadlock when many N-participant
        # collective programs are dispatched async onto a thread pool
        # smaller than N (XLA rendezvous starvation on few-core hosts).
        # Synchronize per step there; real TPU keeps async dispatch.
        def synced(state, batch, _inner=compiled):
            out = _inner(state, batch)
            jax.block_until_ready(out)
            return out

        return synced, placed
    return compiled, placed
