"""JAX version-compatibility shims for the parallelism layer.

``shard_map`` moved twice across the JAX versions this repo must run
under: modern releases export it as ``jax.shard_map`` with a
``check_vma`` kwarg, while older ones only have
``jax.experimental.shard_map.shard_map`` with the same knob named
``check_rep``. Every shard_map kernel in this repo imports the wrapper
below instead of touching either location directly, so a JAX upgrade
(or downgrade) is a one-file change rather than a grep across ops/,
models/, and parallel/.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["axis_size", "shard_map"]

try:  # modern JAX: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map_impl

    _CHECK_KWARG = "check_vma"
except ImportError:  # older JAX: experimental module, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _CHECK_KWARG = "check_rep"


def axis_size(axis: str) -> int:
    """Size of a bound mesh axis, portable across JAX versions.

    Modern JAX has ``lax.axis_size``; older releases rely on the
    documented constant-fold of ``lax.psum(1, axis)`` (a Python int at
    trace time, so it stays usable in shape math and loop bounds).
    """
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def shard_map(
    f: Callable,
    mesh: Any = None,
    *,
    in_specs: Any,
    out_specs: Any,
    check_vma: Optional[bool] = None,
    check_rep: Optional[bool] = None,
    **kwargs: Any,
):
    """Version-portable ``shard_map``.

    Accepts the replication-check flag under either its modern name
    (``check_vma``) or its legacy name (``check_rep``) — passing both
    is an error — and forwards it under whichever spelling the
    installed JAX understands. ``mesh`` may be positional or keyword,
    matching both historical signatures.
    """
    if check_vma is not None and check_rep is not None:
        raise ValueError(
            "pass only one of check_vma/check_rep (they are the same "
            "flag under different JAX versions)"
        )
    check = check_vma if check_vma is not None else check_rep
    if check is not None:
        kwargs[_CHECK_KWARG] = check
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
