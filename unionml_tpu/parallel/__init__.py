"""Parallelism layer: device meshes, sharding strategies, collectives.

No reference counterpart (SURVEY.md §2: the reference's only "distribution"
is task-level Flyte orchestration). This package is the TPU-native
first-class replacement: strategies compose as axes of one
``jax.sharding.Mesh`` and XLA/GSPMD inserts the collectives over ICI/DCN.

- :mod:`unionml_tpu.parallel.mesh` — mesh construction (single-chip, slice,
  multi-slice with DCN axes), multi-host bring-up.
- :mod:`unionml_tpu.parallel.sharding` — :class:`ShardingConfig` with named
  strategies (dp/fsdp/tp/sp/pp/ep), partition rules, ``compile_step``.
- :mod:`unionml_tpu.parallel.collectives` — named collective wrappers for
  shard_map kernels.
- :mod:`unionml_tpu.parallel.pipeline` — pipeline-parallel stage executor.
"""

from unionml_tpu.parallel.collectives import bucketed_psum
from unionml_tpu.parallel.compat import shard_map
from unionml_tpu.parallel.mesh import (
    cpu_multiprocess_supported,
    make_mesh,
    mesh_devices,
    multihost_initialize,
)
from unionml_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_spmd,
    stack_stage_params,
)
from unionml_tpu.parallel.sharding import (
    PartitionRule,
    ShardingConfig,
    compile_step,
    named_sharding,
    shard_pytree,
    state_shardings,
)

__all__ = [
    "bucketed_psum",
    "cpu_multiprocess_supported",
    "shard_map",
    "make_mesh",
    "mesh_devices",
    "multihost_initialize",
    "pipeline_apply",
    "pipeline_spmd",
    "stack_stage_params",
    "PartitionRule",
    "ShardingConfig",
    "compile_step",
    "named_sharding",
    "shard_pytree",
    "state_shardings",
]
