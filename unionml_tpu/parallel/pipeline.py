"""Pipeline parallelism: GPipe-style microbatch schedule as one SPMD program.

No reference counterpart (SURVEY.md §5.7/§7 — PP is a TPU-native
first-class addition). Design is scaling-book-style SPMD pipelining rather
than a host-side scheduler: every pipeline stage lives on one slice of the
``pipeline`` mesh axis, the whole schedule (fill, steady state, drain) is a
single ``lax.scan`` inside ``shard_map``, and activations move between
neighbouring stages with ``lax.ppermute`` over ICI. Because the schedule is
one traced program, ``jax.grad`` differentiates straight through it —
backward ppermutes are the transposed forward ones — so pipeline-parallel
*training* needs no bespoke backward scheduler.

Memory: each stage rematerializes its microbatch activations on the
backward pass (``jax.checkpoint`` around the stage body), the standard
GPipe memory/compute trade.

Usage shape: stack per-stage parameters on a leading axis (stage s owns
``stacked_params[s]``), pick ``num_microbatches >= num_stages`` to keep the
bubble fraction at ``(n-1)/(m+n-1)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from unionml_tpu.parallel import compat
from jax import lax


def _unstack_local(tree: Any) -> Any:
    """Drop the singleton leading (stage) axis of a per-device param shard."""
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def pipeline_spmd(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    axis: str = "pipeline",
    remat: bool = True,
) -> jnp.ndarray:
    """Run the GPipe schedule *inside* shard_map.

    ``stage_fn(params, x) -> y`` is this stage's computation; ``stage_params``
    the local stage's params; ``microbatches`` [M, mb, ...] — the full
    microbatched input, identical on every stage (only stage 0 consumes it).
    Returns [M, mb, ...] outputs, valid on the LAST stage (zeros elsewhere —
    callers psum or mask; see :func:`pipeline_apply`).
    """
    n = compat.axis_size(axis)
    idx = lax.axis_index(axis)
    num_micro, mb = microbatches.shape[0], microbatches.shape[1:]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    ticks = num_micro + n - 1

    # state: the activation currently entering this stage
    state0 = jnp.zeros(mb, microbatches.dtype)
    out0 = jnp.zeros((num_micro,) + mb, microbatches.dtype)

    def tick(carry, t):
        state, out = carry
        # stage 0 ingests microbatch t during the fill/steady phase
        feed = microbatches[jnp.minimum(t, num_micro - 1)]
        state = jnp.where(idx == 0, feed.astype(state.dtype), state)
        y = fn(stage_params, state)
        # last stage banks microbatch t-(n-1) once the pipe is full
        done = t - (n - 1)
        out = lax.cond(
            done >= 0,
            lambda o: o.at[jnp.maximum(done, 0)].set(
                jnp.where(idx == n - 1, y.astype(o.dtype), o[jnp.maximum(done, 0)])
            ),
            lambda o: o,
            out,
        )
        state = lax.ppermute(y, axis, fwd_perm)
        return (state, out), None

    (_, out), _ = lax.scan(tick, (state0, out0), jnp.arange(ticks))
    # replicate the last stage's outputs to every stage so downstream
    # (loss) code is stage-agnostic: zeros elsewhere → psum == broadcast
    return lax.psum(jnp.where(idx == n - 1, out, jnp.zeros_like(out)), axis)


def pipeline_apply(
    stage_fn: Callable,
    stacked_params: Any,
    batch: jnp.ndarray,
    *,
    mesh,
    axis: str = "pipeline",
    num_microbatches: int,
    remat: bool = True,
    data_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Globally-shaped pipeline execution (jit-able, differentiable).

    ``stacked_params``: pytree with a leading stage axis of size
    ``mesh.shape[axis]``; ``batch``: [B, ...] with ``B`` divisible by
    ``num_microbatches``. Returns [B, ...] outputs replicated over ``axis``.

    ``data_axis`` composes PP x DP: the microbatch dimension shards over
    that mesh axis (each data shard runs its own pipeline over the same
    stage weights; ppermute/psum stay on the ``pipeline`` axis), so the
    per-device microbatch is ``B / num_microbatches / mesh.shape[data_axis]``.
    """
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    b = batch.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} not divisible by num_microbatches {num_microbatches}")
    if num_microbatches < n:
        raise ValueError(
            f"num_microbatches {num_microbatches} < pipeline stages {n}: "
            f"the bubble would dominate; use at least one microbatch per stage"
        )
    mb_rows = b // num_microbatches
    if data_axis is not None:
        if data_axis not in mesh.shape:
            raise ValueError(
                f"data_axis {data_axis!r} is not a mesh axis {tuple(mesh.shape)}"
            )
        if mb_rows % mesh.shape[data_axis]:
            raise ValueError(
                f"microbatch rows {mb_rows} not divisible by data axis size "
                f"{mesh.shape[data_axis]}"
            )

    micro = batch.reshape((num_microbatches, mb_rows) + batch.shape[1:])

    # the scan carry is one microbatch-shaped activation, so every stage
    # must map [mb, ...] -> same shape/dtype; fail here with a clear error
    # rather than deep inside shard_map tracing
    local_params = jax.eval_shape(
        lambda p: _unstack_local(p), stacked_params
    )
    mb_shape = jax.ShapeDtypeStruct(micro.shape[1:], micro.dtype)
    out_shape = jax.eval_shape(stage_fn, local_params, mb_shape)
    if out_shape.shape != mb_shape.shape or out_shape.dtype != mb_shape.dtype:
        raise ValueError(
            f"pipeline stage_fn must preserve activation shape/dtype "
            f"(scan carry): got {out_shape.shape}/{out_shape.dtype} from "
            f"{mb_shape.shape}/{mb_shape.dtype}. Fold projections/dtype "
            f"casts into the last stage's OUTPUT consumer instead, or pad "
            f"activations to a common shape."
        )

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    batch_spec = P(None, data_axis) if data_axis is not None else P()

    def body(params, mb):
        return pipeline_spmd(
            stage_fn, _unstack_local(params), mb, axis=axis, remat=remat
        )

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(stacked_params, micro)
    return out.reshape((b,) + out.shape[2:])


def stack_stage_params(per_stage: list) -> Any:
    """Stack a list of per-stage param pytrees along a new leading axis.

    Shard the result's leading axis over ``pipeline`` with
    ``unionml_tpu.models.PIPELINE_PARTITION_RULES`` (which targets only
    the ``stages/`` subtree, leaving embed/head alone).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)
